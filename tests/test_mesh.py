"""Multi-chip sharding: the kernel must produce identical bindings when the
node axis is sharded over an 8-device mesh (virtual CPU devices; see
conftest.py)."""

import random

import jax
import pytest

from kubernetes_tpu.models import Tensorizer
from kubernetes_tpu.ops.batch_kernel import schedule_batch_arrays
from kubernetes_tpu.parallel import make_mesh, schedule_batch_sharded
from kubernetes_tpu.scheduler import PriorityContext

from tests.test_parity import build_cluster, make_batch


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) >= 8, "conftest must provide 8 virtual devices"
    return make_mesh(8)


def _build(seed, n_nodes, n_pods):
    rng = random.Random(seed)
    m = build_cluster(rng, n_nodes, zones=3)
    pctx = PriorityContext(m)
    pods = make_batch(rng, n_pods)
    tz = Tensorizer(pad_multiple=8 * 16)  # divisible by mesh size
    static = tz.build_static(pods, m, pctx, balanced_weight=1, spread_weight=1)
    init = tz.initial_state(static, m, pctx, pods)
    return static, init


def test_sharded_matches_single_device(mesh):
    static, init = _build(21, 40, 200)
    chosen_single, rr_single = schedule_batch_arrays(static, init)
    chosen_sharded, rr_sharded = schedule_batch_sharded(static, init, mesh)
    assert (chosen_single == chosen_sharded).all()
    assert rr_single == rr_sharded


def test_sharded_various_mesh_sizes():
    static, init = _build(22, 24, 100)
    want, rr_want = schedule_batch_arrays(static, init)
    for n_dev in (2, 4):
        mesh = make_mesh(n_dev)
        got, rr = schedule_batch_sharded(static, init, mesh)
        assert (want == got).all(), f"mismatch at mesh size {n_dev}"
        assert rr == rr_want


# -- phase B under GSPMD -----------------------------------------------------
# The sharded [T, N] affinity domain counters, the [V, N] volume-occupancy
# scatters, and the same-domain commit masks (reference symmetry semantics,
# predicates.go:982,1065) must produce binding-for-binding the single-device
# kernel's output on every mesh size.

def _build_mixed(n_devices, n_nodes=32, n_pods=80, seed=7):
    import __graft_entry__ as ge

    return ge._build_mixed_problem(
        n_nodes=n_nodes, n_pods=n_pods, pad_multiple=n_devices * 8, seed=seed
    )


@pytest.mark.parametrize("n_dev", [2, 4, 8])
def test_sharded_phase_b_mixed_matches_single_device(n_dev):
    static, init = _build_mixed(n_dev)
    assert static.terms and static.use_vols  # the hard half is actually on
    want, rr_want = schedule_batch_arrays(static, init)
    mesh = make_mesh(n_dev)
    got, rr = schedule_batch_sharded(static, init, mesh)
    assert (want == got).all(), f"phase-B mismatch at mesh size {n_dev}"
    assert rr == rr_want
    assert (got >= 0).any()


def test_sharded_phase_b_volume_conflicts_respected():
    """Many pods sharing few disks: [V, N] occupancy must serialize them
    one-node-per-disk identically under sharding."""
    import random as _random

    from kubernetes_tpu.api import Volume
    from kubernetes_tpu.models import Tensorizer
    from kubernetes_tpu.scheduler import PriorityContext
    from kubernetes_tpu.testutil import make_pod

    rng = _random.Random(31)
    m = build_cluster(rng, 16, zones=3)
    pctx = PriorityContext(m)
    pods = [
        make_pod(f"v-{i}", cpu="100m",
                 labels={"app": "db"},
                 volumes=[Volume(name="v", disk_id=f"pd-{i % 3}",
                                 disk_kind="gce-pd")])
        for i in range(30)
    ]
    tz = Tensorizer(pad_multiple=8 * 4)
    static = tz.build_static(pods, m, pctx, balanced_weight=1, spread_weight=1)
    init = tz.initial_state(static, m, pctx, pods)
    assert static.use_vols
    want, _ = schedule_batch_arrays(static, init)
    for n_dev in (2, 8):
        got, _ = schedule_batch_sharded(static, init, make_mesh(n_dev))
        assert (want == got).all(), f"volume-conflict mismatch at mesh {n_dev}"


def test_sharded_scan_collective_structure(mesh):
    """The sharded program's collectives must be reductions/permutes —
    never a per-step all-gather of the [G,N]/[T,N] node-axis state (a
    silent sharding regression that re-materializes sharded state every
    step; r3 VERDICT Weak #7).  Exercises phase B (terms + volumes),
    whose chosen-column extraction is the tempting place to regress."""
    from kubernetes_tpu.parallel import assert_collective_structure, sharded_hlo

    static, init = _build(21, 32, 96)
    hlo = sharded_hlo(static, init, mesh)
    counts = assert_collective_structure(hlo, static)  # must not raise
    # the mesh is genuinely communicating: score normalization and the
    # cumsum tie-break need cross-shard reductions
    assert counts["all-reduce"] > 0, counts


def test_collective_structure_gate_rejects_state_allgather():
    """The gate itself has teeth: a synthetic HLO carrying a full-plane
    all-gather of [T, N] state must fail the assertion."""
    from kubernetes_tpu.parallel import assert_collective_structure

    static, _ = _build(22, 32, 32)
    # a full state plane: the gate's limit keys off the LARGEST of the
    # [G, N] / [T, N] planes, so size the synthetic gather accordingly
    # (term padding is tight now — [T, N] alone can be under the limit)
    g = int(static.static_ok.shape[0])
    t = int(static.term_matches_sig.shape[0])
    n = int(static.n_pad)
    bad_hlo = (
        "ENTRY %main {\n"
        f"  %ag = s32[{max(g, t, 2)},{n}]{{1,0}} all-gather(%x), dimensions={{1}}\n"
        "}\n")
    with pytest.raises(AssertionError, match="all-gathers node-axis state"):
        assert_collective_structure(bad_hlo, static)


# -- shard_map wave loop (ISSUE 18) ------------------------------------------
# The device-resident wave loop runs under shard_map with the node axis
# partitioned: in-loop psum/pmax/pmin reductions replace the per-chunk host
# hop, and the cross-shard argmax tie-breaks on (score, GLOBAL node index) so
# the round-robin rotation stays bit-exact vs the sequential CPU oracle.

import numpy as np

from kubernetes_tpu.models.snapshot import (
    frontier_seed,
    pad_segment_to_multiple,
)
from kubernetes_tpu.ops import TPUBatchBackend
from kubernetes_tpu.ops.batch_kernel import FrontierRun
from kubernetes_tpu.testutil import make_pod

from tests.test_frontier import assert_frontier_parity, tie_cluster


def _seeded(pods, nim):
    pctx = PriorityContext(nim)
    tz = Tensorizer()
    static = tz.build_static(pods, nim, pctx)
    init = tz.initial_state(static, nim, pctx, pods)
    frontier_seed(static, init)
    return static, init


@pytest.mark.parametrize("n_dev", [2, 4, 8])
def test_sharded_loop_forced_ties_and_compaction_parity(n_dev):
    """The capstone fixture under sharding: identical nodes tie on every
    score while staggered caps force mid-segment compactions — the
    sharded wave loop, the single-device loop, and the plain full-width
    scan must agree on bindings AND the tie counter at every mesh
    size."""
    nim = tie_cluster(16)
    pods = [make_pod(f"p-{i:03d}", cpu="100m", memory="128Mi",
                     labels={"app": "web"}) for i in range(110)]
    static, init = _seeded(pods, nim)
    pstatic, pinit = pad_segment_to_multiple(static, init, n_dev)
    run = FrontierRun(pstatic, pinit, device_loop=True, chunk_len=16,
                      min_width=8, mesh=make_mesh(n_dev))
    m_chosen, m_rr = run.finalize()
    single = FrontierRun(static, init, device_loop=True, chunk_len=16,
                         min_width=8)
    s_chosen, s_rr = single.finalize()
    p_chosen, p_rr = schedule_batch_arrays(static, init)
    # identity padding keeps real-node indices stable, so the sharded
    # chosen vector compares directly against the unpadded runs
    np.testing.assert_array_equal(m_chosen, s_chosen)
    np.testing.assert_array_equal(m_chosen, p_chosen)
    assert m_rr == s_rr == p_rr
    assert run.stats["compactions"] >= 1, "compaction never fired sharded"
    # per-shard compaction stats rode the existing spans
    assert run.stats.get("n_shards") == n_dev
    # the O(compactions + 1) sync budget survives sharding: reductions
    # happen IN the loop, never as a host hop per chunk
    assert run.stats["host_syncs"] <= run.stats["loop_runs"] + 1
    assert run.stats["loop_runs"] >= run.stats["compactions"] + 1


def test_sharded_loop_uneven_width_pads_no_phantom_columns():
    """An N that does not divide the shard count: padding must force the
    extra columns infeasible for every signature (no phantom feasible
    column can win any reduce) and the sharded run stays exact vs the
    unpadded plain scan."""
    import random as _random

    from tests.test_parity import build_cluster

    rng = _random.Random(91)
    nim = build_cluster(rng, 20, zones=3)
    pods = [make_pod(f"p-{i:03d}", cpu="100m", memory="128Mi",
                     labels={"app": "web"}) for i in range(90)]
    pctx = PriorityContext(nim)
    tz = Tensorizer(pad_multiple=2)  # n_pad=20: not divisible by 8
    static = tz.build_static(pods, nim, pctx)
    init = tz.initial_state(static, nim, pctx, pods)
    frontier_seed(static, init)
    assert int(static.n_pad) % 8 != 0
    pstatic, pinit = pad_segment_to_multiple(static, init, 8)
    assert int(pstatic.n_pad) % 8 == 0 and pstatic.n_pad > static.n_pad
    n = int(static.n_pad)
    # the padded tail is dead on arrival: no existence, no feasibility
    assert not pstatic.node_exists[n:].any()
    assert not np.asarray(pinit.still_ok)[:, n:].any()
    run = FrontierRun(pstatic, pinit, device_loop=True, chunk_len=16,
                      min_width=8, mesh=make_mesh(8))
    m_chosen, m_rr = run.finalize()
    p_chosen, p_rr = schedule_batch_arrays(static, init)
    np.testing.assert_array_equal(m_chosen, p_chosen)
    assert m_rr == p_rr
    assert not (m_chosen >= n).any(), "a phantom pad column was chosen"


def test_sharded_backend_oracle_parity_end_to_end():
    """Through the backend with ``frontier_mesh=True``: bindings and the
    round-robin counter match the per-pod CPU oracle, the segment is
    served in mesh mode with zero fallbacks, and the per-segment
    host_syncs stay O(compactions + 1)."""
    nim = tie_cluster(16)
    pods = [make_pod(f"p-{i:03d}", cpu="100m", memory="128Mi",
                     labels={"app": "web"}) for i in range(110)]
    backend = assert_frontier_parity(
        pods, nim,
        backend_kwargs=dict(frontier_chunk=16, frontier_min_width=8,
                            frontier_mesh=True))
    assert backend.stats["frontier_fallback_modes"].get("mesh", 0) == 0
    seg = backend.last_frontier[0]
    assert seg["mode"] == "mesh"
    assert seg["n_shards"] == 8  # conftest forces 8 virtual devices
    assert seg["compactions"] >= 1
    assert seg["host_syncs"] <= seg["compactions"] + 2
    # per-shard alive fractions rode the span attrs: one snapshot per
    # loop exit (>= one per compaction), each over all 8 shards
    assert len(seg["shard_alive_frac"]) > seg["compactions"] >= 1
    assert all(len(s) == 8 for s in seg["shard_alive_frac"])


def test_sharded_backend_mesh_failure_degrades_to_single_device():
    """Breaker-style fallback: a poisoned mesh build disables the mesh
    path for the backend's lifetime — segments serve through the
    single-device loop with parity intact, and the fallback is counted
    under its own mode."""
    nim = tie_cluster(16)
    pods = [make_pod(f"p-{i:03d}", cpu="100m", memory="128Mi",
                     labels={"app": "web"}) for i in range(110)]
    from kubernetes_tpu.scheduler import GenericScheduler

    from tests.test_frontier import oracle_batch

    pctx = PriorityContext(nim)
    a, b = GenericScheduler(), GenericScheduler()
    want = oracle_batch(pods, nim, pctx, a)
    backend = TPUBatchBackend(algorithm=b, frontier_chunk=16,
                              frontier_min_width=8, frontier_mesh=True,
                              mesh_devices=1)  # < 2: mesh build must fail
    got = backend.schedule_batch(pods, nim, pctx)
    assert [g for g in got] == want
    assert a._round_robin == b._round_robin
    assert backend._mesh_failed
    assert backend.stats["frontier_fallback_modes"].get("mesh", 0) >= 1
    assert backend.last_frontier[0]["mode"] == "loop"
