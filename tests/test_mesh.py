"""Multi-chip sharding: the kernel must produce identical bindings when the
node axis is sharded over an 8-device mesh (virtual CPU devices; see
conftest.py)."""

import random

import jax
import pytest

from kubernetes_tpu.models import Tensorizer
from kubernetes_tpu.ops.batch_kernel import schedule_batch_arrays
from kubernetes_tpu.parallel import make_mesh, schedule_batch_sharded
from kubernetes_tpu.scheduler import PriorityContext

from tests.test_parity import build_cluster, make_batch


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) >= 8, "conftest must provide 8 virtual devices"
    return make_mesh(8)


def _build(seed, n_nodes, n_pods):
    rng = random.Random(seed)
    m = build_cluster(rng, n_nodes, zones=3)
    pctx = PriorityContext(m)
    pods = make_batch(rng, n_pods)
    tz = Tensorizer(pad_multiple=8 * 16)  # divisible by mesh size
    static = tz.build_static(pods, m, pctx, balanced_weight=1, spread_weight=1)
    init = tz.initial_state(static, m, pctx, pods)
    return static, init


def test_sharded_matches_single_device(mesh):
    static, init = _build(21, 40, 200)
    chosen_single, rr_single = schedule_batch_arrays(static, init)
    chosen_sharded, rr_sharded = schedule_batch_sharded(static, init, mesh)
    assert (chosen_single == chosen_sharded).all()
    assert rr_single == rr_sharded


def test_sharded_various_mesh_sizes():
    static, init = _build(22, 24, 100)
    want, rr_want = schedule_batch_arrays(static, init)
    for n_dev in (2, 4):
        mesh = make_mesh(n_dev)
        got, rr = schedule_batch_sharded(static, init, mesh)
        assert (want == got).all(), f"mismatch at mesh size {n_dev}"
        assert rr == rr_want


# -- phase B under GSPMD -----------------------------------------------------
# The sharded [T, N] affinity domain counters, the [V, N] volume-occupancy
# scatters, and the same-domain commit masks (reference symmetry semantics,
# predicates.go:982,1065) must produce binding-for-binding the single-device
# kernel's output on every mesh size.

def _build_mixed(n_devices, n_nodes=32, n_pods=80, seed=7):
    import __graft_entry__ as ge

    return ge._build_mixed_problem(
        n_nodes=n_nodes, n_pods=n_pods, pad_multiple=n_devices * 8, seed=seed
    )


@pytest.mark.parametrize("n_dev", [2, 4, 8])
def test_sharded_phase_b_mixed_matches_single_device(n_dev):
    static, init = _build_mixed(n_dev)
    assert static.terms and static.use_vols  # the hard half is actually on
    want, rr_want = schedule_batch_arrays(static, init)
    mesh = make_mesh(n_dev)
    got, rr = schedule_batch_sharded(static, init, mesh)
    assert (want == got).all(), f"phase-B mismatch at mesh size {n_dev}"
    assert rr == rr_want
    assert (got >= 0).any()


def test_sharded_phase_b_volume_conflicts_respected():
    """Many pods sharing few disks: [V, N] occupancy must serialize them
    one-node-per-disk identically under sharding."""
    import random as _random

    from kubernetes_tpu.api import Volume
    from kubernetes_tpu.models import Tensorizer
    from kubernetes_tpu.scheduler import PriorityContext
    from kubernetes_tpu.testutil import make_pod

    rng = _random.Random(31)
    m = build_cluster(rng, 16, zones=3)
    pctx = PriorityContext(m)
    pods = [
        make_pod(f"v-{i}", cpu="100m",
                 labels={"app": "db"},
                 volumes=[Volume(name="v", disk_id=f"pd-{i % 3}",
                                 disk_kind="gce-pd")])
        for i in range(30)
    ]
    tz = Tensorizer(pad_multiple=8 * 4)
    static = tz.build_static(pods, m, pctx, balanced_weight=1, spread_weight=1)
    init = tz.initial_state(static, m, pctx, pods)
    assert static.use_vols
    want, _ = schedule_batch_arrays(static, init)
    for n_dev in (2, 8):
        got, _ = schedule_batch_sharded(static, init, make_mesh(n_dev))
        assert (want == got).all(), f"volume-conflict mismatch at mesh {n_dev}"


def test_sharded_scan_collective_structure(mesh):
    """The sharded program's collectives must be reductions/permutes —
    never a per-step all-gather of the [G,N]/[T,N] node-axis state (a
    silent sharding regression that re-materializes sharded state every
    step; r3 VERDICT Weak #7).  Exercises phase B (terms + volumes),
    whose chosen-column extraction is the tempting place to regress."""
    from kubernetes_tpu.parallel import assert_collective_structure, sharded_hlo

    static, init = _build(21, 32, 96)
    hlo = sharded_hlo(static, init, mesh)
    counts = assert_collective_structure(hlo, static)  # must not raise
    # the mesh is genuinely communicating: score normalization and the
    # cumsum tie-break need cross-shard reductions
    assert counts["all-reduce"] > 0, counts


def test_collective_structure_gate_rejects_state_allgather():
    """The gate itself has teeth: a synthetic HLO carrying a full-plane
    all-gather of [T, N] state must fail the assertion."""
    from kubernetes_tpu.parallel import assert_collective_structure

    static, _ = _build(22, 32, 32)
    # a full state plane: the gate's limit keys off the LARGEST of the
    # [G, N] / [T, N] planes, so size the synthetic gather accordingly
    # (term padding is tight now — [T, N] alone can be under the limit)
    g = int(static.static_ok.shape[0])
    t = int(static.term_matches_sig.shape[0])
    n = int(static.n_pad)
    bad_hlo = (
        "ENTRY %main {\n"
        f"  %ag = s32[{max(g, t, 2)},{n}]{{1,0}} all-gather(%x), dimensions={{1}}\n"
        "}\n")
    with pytest.raises(AssertionError, match="all-gathers node-axis state"):
        assert_collective_structure(bad_hlo, static)
