"""Multi-chip sharding: the kernel must produce identical bindings when the
node axis is sharded over an 8-device mesh (virtual CPU devices; see
conftest.py)."""

import random

import jax
import pytest

from kubernetes_tpu.models import Tensorizer
from kubernetes_tpu.ops.batch_kernel import schedule_batch_arrays
from kubernetes_tpu.parallel import make_mesh, schedule_batch_sharded
from kubernetes_tpu.scheduler import PriorityContext

from tests.test_parity import build_cluster, make_batch


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) >= 8, "conftest must provide 8 virtual devices"
    return make_mesh(8)


def _build(seed, n_nodes, n_pods):
    rng = random.Random(seed)
    m = build_cluster(rng, n_nodes, zones=3)
    pctx = PriorityContext(m)
    pods = make_batch(rng, n_pods)
    tz = Tensorizer(pad_multiple=8 * 16)  # divisible by mesh size
    static = tz.build_static(pods, m, pctx, balanced_weight=1, spread_weight=1)
    init = tz.initial_state(static, m, pctx, pods)
    return static, init


def test_sharded_matches_single_device(mesh):
    static, init = _build(21, 40, 200)
    chosen_single, rr_single = schedule_batch_arrays(static, init)
    chosen_sharded, rr_sharded = schedule_batch_sharded(static, init, mesh)
    assert (chosen_single == chosen_sharded).all()
    assert rr_single == rr_sharded


def test_sharded_various_mesh_sizes():
    static, init = _build(22, 24, 100)
    want, rr_want = schedule_batch_arrays(static, init)
    for n_dev in (2, 4):
        mesh = make_mesh(n_dev)
        got, rr = schedule_batch_sharded(static, init, mesh)
        assert (want == got).all(), f"mismatch at mesh size {n_dev}"
        assert rr == rr_want
