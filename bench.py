"""Benchmark: batched TPU scheduling throughput vs the reference scheduler.

The harness mirrors ``test/integration/scheduler_perf`` (SURVEY.md §4.4):
fake nodes + a flood of pending pods through the REAL scheduling path
(store → informers → cache snapshot → backend → bind writes), measuring
pods-scheduled/sec.  The reference's expected throughput on this harness is
100 pods/s (warn threshold, ``scheduler_perf/scheduler_test.go:35``; hard
floor 30) — ``vs_baseline`` is measured-value / 100.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Presets:
  smoke  —   200 nodes ×   1k pods (fast sanity)
  basic  —   500 nodes ×   2k pods (BASELINE.json configs[0], default)
  dense  —  1000 nodes ×  10k pods
  north  —  5000 nodes × 150k pods (the north-star scale)
"""

from __future__ import annotations

import argparse
import json
import random
import sys
import time


PRESETS = {
    "smoke": (200, 1_000),
    "basic": (500, 2_000),
    "dense": (1_000, 10_000),
    "north": (5_000, 150_000),
}


def build_cluster(clientset, n_nodes: int, rng: random.Random):
    from kubernetes_tpu.testutil import make_node

    for i in range(n_nodes):
        clientset.nodes.create(
            make_node(
                f"node-{i:05d}",
                cpu=rng.choice(["8", "16", "32"]),
                memory=rng.choice(["16Gi", "32Gi", "64Gi"]),
                pods=110,
                labels={
                    "kubernetes.io/hostname": f"node-{i:05d}",
                    "failure-domain.beta.kubernetes.io/zone": f"zone-{i % 3}",
                },
            )
        )


def make_pods(n_pods: int, rng: random.Random):
    from kubernetes_tpu.testutil import make_pod

    # RC-of-pods style flood (scheduler_perf creates pods via RCs): a few
    # homogeneous templates, like real workloads
    templates = [
        dict(cpu="100m", memory="128Mi", labels={"app": "web"}),
        dict(cpu="250m", memory="256Mi", labels={"app": "api"}),
        dict(cpu="500m", memory="512Mi", labels={"app": "db"}),
        dict(cpu="1", memory="1Gi", labels={"app": "batch"}),
    ]
    return [make_pod(f"pod-{i:06d}", **templates[i % len(templates)]) for i in range(n_pods)]


def run_once(n_nodes: int, n_pods: int, use_backend: bool, seed: int = 0) -> dict:
    from kubernetes_tpu.client import Clientset
    from kubernetes_tpu.ops import TPUBatchBackend
    from kubernetes_tpu.scheduler import GenericScheduler, Scheduler
    from kubernetes_tpu.store import Store

    rng = random.Random(seed)
    cs = Clientset(Store(event_log_window=max(200_000, 2 * (n_nodes + n_pods))))
    build_cluster(cs, n_nodes, rng)
    for pod in make_pods(n_pods, rng):
        cs.pods.create(pod)

    algo = GenericScheduler()
    backend = TPUBatchBackend(algorithm=algo) if use_backend else None
    sched = Scheduler(cs, algorithm=algo, backend=backend, emit_events=False)
    sched.start()

    start = time.perf_counter()
    if use_backend:
        bound, failed = sched.schedule_pending_batch()
    else:
        bound = sched.run_pending()
        failed = 0
    elapsed = time.perf_counter() - start
    return {
        "bound": bound,
        "failed": failed,
        "elapsed_s": elapsed,
        "pods_per_sec": bound / elapsed if elapsed > 0 else 0.0,
    }


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--preset", choices=PRESETS, default="basic")
    parser.add_argument("--nodes", type=int, default=None)
    parser.add_argument("--pods", type=int, default=None)
    parser.add_argument("--oracle", action="store_true", help="bench the CPU oracle path instead")
    parser.add_argument(
        "--compare", action="store_true", help="also run the oracle and report speedup to stderr"
    )
    args = parser.parse_args()
    n_nodes, n_pods = PRESETS[args.preset]
    if args.nodes:
        n_nodes = args.nodes
    if args.pods:
        n_pods = args.pods

    # warm-up at the same shapes: triggers all XLA compilation so the timed
    # run measures steady-state throughput (first TPU compile is ~20-40s)
    if not args.oracle:
        run_once(n_nodes, n_pods, use_backend=True, seed=1)

    result = run_once(n_nodes, n_pods, use_backend=not args.oracle, seed=0)
    if result["bound"] == 0:
        print(json.dumps({"metric": "pods-scheduled/sec", "value": 0, "unit": "pods/s", "vs_baseline": 0}))
        sys.exit(1)

    if args.compare:
        oracle = run_once(n_nodes, min(n_pods, 2_000), use_backend=False, seed=0)
        print(
            f"# oracle: {oracle['pods_per_sec']:.1f} pods/s on {min(n_pods, 2000)} pods; "
            f"backend speedup {result['pods_per_sec'] / max(oracle['pods_per_sec'], 1e-9):.1f}x",
            file=sys.stderr,
        )

    print(
        f"# {args.preset}: {result['bound']} bound / {result['failed']} failed "
        f"in {result['elapsed_s']:.2f}s on {n_nodes} nodes",
        file=sys.stderr,
    )
    # baseline: the reference harness's expected throughput (100 pods/s)
    print(
        json.dumps(
            {
                "metric": "pods-scheduled/sec",
                "value": round(result["pods_per_sec"], 1),
                "unit": "pods/s",
                "vs_baseline": round(result["pods_per_sec"] / 100.0, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
