"""Benchmark: batched TPU scheduling throughput vs the reference scheduler.

The harness mirrors ``test/integration/scheduler_perf`` (SURVEY.md §4.4):
fake nodes + a flood of pending pods through the REAL scheduling path
(store → informers → cache snapshot → backend → bind writes), measuring
pods-scheduled/sec.  The reference's expected throughput on this harness is
100 pods/s (warn threshold, ``scheduler_perf/scheduler_test.go:35``; hard
floor 30) — ``vs_baseline`` is measured-value / 100.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Presets:
  smoke  —   200 nodes ×   1k pods (fast sanity)
  basic  —   500 nodes ×   2k pods (BASELINE.json configs[0])
  dense  —  1000 nodes ×  10k pods
  mixed  —  1000 nodes ×  10k pods, mixed workload (default: ~20% affinity
            pods, ~10% volume pods, taints/zones/services — the honest
            preset; the phase-B kernel keeps all of it on device)
  north  —  5000 nodes × 150k pods (the north-star scale)

``--parity`` additionally runs the pure sequential CPU oracle over an
identical cluster and asserts assignment-for-assignment equality (the
"identical bindings" half of the north star), reporting it in the JSON.
"""

from __future__ import annotations

import argparse
import collections
import json
import os
import random
import sys
import time


PRESETS = {
    "smoke": (200, 1_000, "plain"),
    "basic": (500, 2_000, "plain"),
    "dense": (1_000, 10_000, "plain"),
    "mixed": (1_000, 10_000, "mixed"),
    "north": (5_000, 150_000, "mixed"),
}

ZONE = "failure-domain.beta.kubernetes.io/zone"


def make_nodes(n_nodes: int, rng: random.Random, workload: str):
    from kubernetes_tpu.api import Taint
    from kubernetes_tpu.testutil import make_node

    nodes = []
    for i in range(n_nodes):
        labels = {
            "kubernetes.io/hostname": f"node-{i:05d}",
            ZONE: f"zone-{i % 3}",
        }
        taints = []
        if workload == "mixed":
            if rng.random() < 0.3:
                labels["disk"] = rng.choice(["ssd", "hdd"])
            if rng.random() < 0.1:
                taints.append(Taint(key="dedicated", value="special", effect="NoSchedule"))
        nodes.append(
            make_node(
                f"node-{i:05d}",
                cpu=rng.choice(["8", "16", "32"]),
                memory=rng.choice(["16Gi", "32Gi", "64Gi"]),
                pods=110,
                labels=labels,
                taints=taints,
            )
        )
    return nodes


def make_services():
    from kubernetes_tpu.api import ObjectMeta, Service

    return [
        Service(meta=ObjectMeta(name=app), selector={"app": app})
        for app in ("web", "api", "db")
    ]


def make_pods(n_pods: int, rng: random.Random, workload: str):
    """Pending-pod flood.  ``plain``: 4 homogeneous RC-style templates.
    ``mixed``: adds ~20% affinity-bearing pods (soft zone co-location +
    required hostname anti-affinity — the reference's own hot spot,
    predicates.go:982), ~10% disk-volume pods, node selectors, and
    toleration-bearing pods for the tainted capacity."""
    from kubernetes_tpu.api import (
        Affinity,
        LabelSelector,
        PodAffinityTerm,
        Toleration,
        Volume,
        WeightedPodAffinityTerm,
    )
    from kubernetes_tpu.testutil import make_pod

    plain_templates = [
        dict(cpu="100m", memory="128Mi", labels={"app": "web"}),
        dict(cpu="250m", memory="256Mi", labels={"app": "api"}),
        dict(cpu="500m", memory="512Mi", labels={"app": "db"}),
        dict(cpu="1", memory="1Gi", labels={"app": "batch"}),
    ]
    if workload == "plain":
        return [
            make_pod(f"pod-{i:06d}", **plain_templates[i % len(plain_templates)])
            for i in range(n_pods)
        ]

    soft = Affinity(
        pod_affinity_preferred=[
            WeightedPodAffinityTerm(
                weight=10,
                term=PodAffinityTerm(
                    selector=LabelSelector.from_match_labels({"app": "web"}),
                    topology_key=ZONE,
                ),
            )
        ]
    )
    anti = Affinity(
        pod_anti_affinity_required=[
            PodAffinityTerm(
                selector=LabelSelector.from_match_labels({"app": "lonely"}),
                topology_key="kubernetes.io/hostname",
            )
        ]
    )
    pods = []
    for i in range(n_pods):
        r = rng.random()
        if r < 0.10:
            pods.append(
                make_pod(f"soft-{i:06d}", cpu="100m", memory="128Mi",
                         labels={"app": "web"}, affinity=soft)
            )
        elif r < 0.20:
            pods.append(
                make_pod(f"lonely-{i:06d}", cpu="100m", memory="128Mi",
                         labels={"app": "lonely"}, affinity=anti)
            )
        elif r < 0.30:
            pods.append(
                make_pod(
                    f"vol-{i:06d}", cpu="100m", memory="128Mi", labels={"app": "api"},
                    volumes=[Volume(name="v", disk_id=f"pd-{rng.randrange(2 * n_pods)}",
                                    disk_kind=rng.choice(["gce-pd", "aws-ebs"]))],
                )
            )
        elif r < 0.35:
            pods.append(
                make_pod(f"ssd-{i:06d}", cpu="250m", memory="256Mi",
                         labels={"app": "db"}, node_selector={"disk": "ssd"})
            )
        elif r < 0.40:
            pods.append(
                make_pod(
                    f"tol-{i:06d}", cpu="200m", memory="128Mi", labels={"app": "batch"},
                    tolerations=[Toleration(key="dedicated", operator="Exists")],
                )
            )
        else:
            pods.append(
                make_pod(f"pod-{i:06d}", **plain_templates[i % len(plain_templates)])
            )
    return pods


def _failure_reasons(cs, sched, assignments: dict, sample_cap: int = 500) -> dict:
    """Why pods stayed unbound: re-evaluate a sample of them against the
    final cluster state and histogram each pod's dominant predicate-failure
    reason (the per-node detail the oracle's FitError carries).  Off-clock;
    explains the unbound tail in the artifact instead of leaving it mute."""
    from kubernetes_tpu.scheduler.predicates import PredicateContext

    unbound = [k for k, v in assignments.items() if v is None]
    if not unbound:
        return {"unbound_total": 0, "sampled": 0, "reasons": {}}
    pods_by_key = {p.meta.key: p for p in cs.pods.list()[0]}
    snapshot = sched.snapshot()
    pctx = sched.priority_context(snapshot)
    ctx = PredicateContext(snapshot, pvcs=pctx.pvcs, pvs=pctx.pvs,
                           services=pctx.services)
    node_names = sorted(n for n, i in snapshot.items() if i.node is not None)
    hist: dict[str, int] = {}
    sample = unbound[:sample_cap]
    for key in sample:
        pod = pods_by_key.get(key)
        if pod is None:
            continue
        feasible, failures = sched.algorithm.find_nodes_that_fit(
            pod, node_names, snapshot, ctx
        )
        if feasible:
            # fits now (space freed since the run); call it out as such
            hist["fits-now (state changed since attempt)"] = (
                hist.get("fits-now (state changed since attempt)", 0) + 1)
            continue
        per_reason: dict[str, int] = {}
        for reasons in failures.values():
            for r in reasons:
                per_reason[r] = per_reason.get(r, 0) + 1
        if per_reason:
            dominant = max(per_reason, key=per_reason.get)
            hist[dominant] = hist.get(dominant, 0) + 1
    return {
        "unbound_total": len(unbound),
        "sampled": len(sample),
        "reasons": dict(sorted(hist.items(), key=lambda kv: -kv[1])),
    }


def run_once(
    n_nodes: int,
    n_pods: int,
    use_backend: bool,
    workload: str,
    seed: int = 0,
    emit_events: bool = False,
    want_failure_reasons: bool = False,
) -> dict:
    from kubernetes_tpu.client import Clientset
    from kubernetes_tpu.ops import TPUBatchBackend
    from kubernetes_tpu.scheduler import GenericScheduler, Scheduler
    from kubernetes_tpu.store import Store

    rng = random.Random(seed)
    cs = Clientset(Store(event_log_window=max(200_000, 2 * (n_nodes + n_pods))))
    for node in make_nodes(n_nodes, rng, workload):
        cs.nodes.create(node)
    if workload == "mixed":
        for svc in make_services():
            cs.services.create(svc)
    for pod in make_pods(n_pods, rng, workload):
        cs.pods.create(pod)

    algo = GenericScheduler()
    backend = TPUBatchBackend(algorithm=algo) if use_backend else None
    sched = Scheduler(cs, algorithm=algo, backend=backend, emit_events=emit_events)
    sched.start()
    drain_order: list[str] = []
    if use_backend:
        # record the queue-drain order for the prefix-parity gate (the
        # queue is fed from the store's name-sorted LIST, not creation
        # order); one list-extend per batch — negligible on the timed path
        orig_drain = sched.queue.drain

        def _recording_drain(max_n=None):
            drained = orig_drain(max_n)
            drain_order.extend(p.meta.key for p in drained)
            return drained

        sched.queue.drain = _recording_drain
    if emit_events:
        # production shape: the hot loop enqueues, the sink thread
        # correlates + writes concurrently with the timed work
        sched.broadcaster.start()

    start = time.perf_counter()
    if use_backend:
        bound, failed = sched.schedule_pending_batch()
    else:
        bound = sched.run_pending()
        failed = 0
    elapsed = time.perf_counter() - start
    result = {
        "bound": bound,
        "failed": failed,
        "elapsed_s": elapsed,
        "pods_per_sec": bound / elapsed if elapsed > 0 else 0.0,
    }
    if use_backend:
        result["backend_stats"] = dict(backend.stats)
    if emit_events:
        # drain the remaining queue off-clock, then report what the
        # correlator actually did during the run
        sched.broadcaster.stop(drain=True)
        result["event_stats"] = dict(sched.broadcaster.correlator.stats)
    # the three reference SLIs (metrics/metrics.go:26-50), p50/p99 in ms
    m = sched.metrics

    def _pq(h, q):
        v = h.quantile(q)
        return round(v / 1e3, 3) if v != float("inf") else None

    result["sli"] = {
        "e2e_scheduling_ms": {"p50": _pq(m.e2e_scheduling_latency, 0.5),
                              "p99": _pq(m.e2e_scheduling_latency, 0.99)},
        "binding_ms": {"p50": _pq(m.binding_latency, 0.5),
                       "p99": _pq(m.binding_latency, 0.99)},
    }
    # final pod→node assignment map, for parity comparison across runs
    pods, _ = cs.pods.list()
    result["assignments"] = {p.meta.key: p.spec.node_name or None for p in pods}
    if use_backend:
        result["batch_order"] = drain_order
    if want_failure_reasons:
        result["failure_reasons"] = _failure_reasons(cs, sched, result["assignments"])
    return result


def run_parity(backend_res: dict, n_nodes: int, n_pods: int, workload: str, seed: int) -> dict:
    """The north star's 'identical bindings' gate: the oracle runs over an
    identical cluster (same seed) through the full store→bind path; its
    assignment map must match the timed backend run key-for-key."""
    oracle_res = run_once(n_nodes, n_pods, use_backend=False, workload=workload, seed=seed)
    b, o = backend_res["assignments"], oracle_res["assignments"]
    assert set(b) == set(o), "pod sets diverged"
    mismatches = [(k, o[k], b[k]) for k in o if o[k] != b[k]]
    return {
        "checked": len(o),
        "mismatches": len(mismatches),
        "sample": mismatches[:5],
        "oracle_pods_per_sec": round(oracle_res["pods_per_sec"], 1),
        "backend_pods_per_sec": round(backend_res["pods_per_sec"], 1),
    }


CHURN_SLO_P99_MS = 5_000.0  # reference pod-startup SLO (metrics_util.go:46)
# regression floor for the NORTH-scale churn preset (5k nodes).  ISSUE 3's
# pipeline reached ~1282 pods/s; ISSUE 4's zero-copy ingest ~1434.7; the
# ISSUE 5 frontier scan (monotone prefilter + chunked still_ok + axis
# tightening + batched arrival/event txns + clone-on-write work map)
# lifted same-box medians to 1788.4 pods/s (BENCH_AB_frontier_scan.json:
# old 1390.2 -> new 1788.4, 4/4 interleaved pairs both orders, worktree
# method, per-wave oracle parity exact on both arms).  1300 sits ~27%
# under the demonstrated new level (this bench has ~±15-20% day drift)
# and 30% above the previous floor, so a regression to any pre-ISSUE-3/4
# path fails the gate loudly.
CHURN_FLOOR_PODS_PER_SEC = 1_300.0


def _oracle_replay_waves(drain_batches: list, final_assignments: dict,
                         n_nodes: int, total_pods: int, workload: str,
                         seed: int) -> dict:
    """Off-clock per-wave oracle parity for a churn run: replay the
    RECORDED drain batches, in drain order, through the per-pod CPU
    oracle on an identically seeded cluster, and compare each wave's
    bindings against the timed run's final map.  Exact by prefix-closure
    (sequential-greedy: pod i's placement depends only on the initial
    cluster and the pods scheduled before it) as long as no key was
    drained twice — a requeue re-decides under different queue state, so
    the exact replay degrades honestly to 'skipped'."""
    flat = [k for b in drain_batches for k in b]
    if len(set(flat)) != len(flat):
        return {"mode": "skipped (requeues present)",
                "checked": 0, "mismatches": -1, "round_robin": None}
    from kubernetes_tpu.client import Clientset
    from kubernetes_tpu.scheduler import GenericScheduler, Scheduler
    from kubernetes_tpu.store import Store

    rng = random.Random(seed)
    cs = Clientset(Store(event_log_window=max(200_000, 2 * (n_nodes + total_pods))))
    for node in make_nodes(n_nodes, rng, workload):
        cs.nodes.create(node)
    if workload == "mixed":
        for svc in make_services():
            cs.services.create(svc)
    pods_by_key = {p.meta.key: p for p in make_pods(total_pods, rng, workload)}
    sched = Scheduler(cs, algorithm=GenericScheduler(), backend=None,
                      emit_events=False)
    sched.start()
    checked = mismatches = 0
    sample = []
    for batch in drain_batches:
        for key in batch:
            cs.pods.create(pods_by_key[key])
        sched.pump()
        sched.run_pending()
        sched.pump()
        pods_now, _ = cs.pods.list()
        got = {p.meta.key: p.spec.node_name or None for p in pods_now}
        for key in batch:
            checked += 1
            if got.get(key) != final_assignments.get(key):
                mismatches += 1
                if len(sample) < 5:
                    sample.append((key, got.get(key),
                                   final_assignments.get(key)))
    # the oracle's final select_host tie-rotation counter: the sharded
    # loop's cross-shard tie-break must leave the timed run's counter at
    # exactly this value or every later tied choice lands one rotation
    # off (the --multichip ledger gates on the comparison)
    return {"mode": "exact per-wave replay", "checked": checked,
            "mismatches": mismatches, "sample": sample,
            "round_robin": sched.algorithm._round_robin}


def run_churn(n_nodes: int = 5_000, total_pods: int = 20_000, waves: int = 10,
              workload: str = "mixed", seed: int = 0, warmup: bool = True,
              pipeline: bool = True, lazy_ingest: bool = True,
              frontier: bool = True, watch_frames: bool = True,
              device_loop: bool = True, frontier_chunk: int = 512,
              verify_oracle: bool = False, trace=None,
              telemetry=None, mesh: bool = False,
              coalesce: float = 0.0) -> dict:
    """Steady-state arrival load (``test/e2e/scalability/density.go:
    316-318,474-475``): pods arrive from an ARRIVAL THREAD — wave w+1 is
    created the moment wave w leaves the queue, the density.go shape
    where creation clients are not the scheduler — and the scheduler
    serves them through ``Scheduler.run_batch_loop`` (min-batch/max-wait
    policy), so per-pod e2e scheduling latency is measured under
    continuous creation along with saturation throughput.

    Per-wave phase timers (pump / tensorize / dispatch / device-wait /
    commit / overlapped prep) and the overlap fraction (prep hidden in
    the device's shadow over total device wait) ride the result.

    ``pipeline=False`` is the A/B arm: lock-step ingest (no overlapped
    prep, no persistent node-static rows, no sticky shape buckets, no
    device-resident node state) on the SAME harness, isolating the
    ISSUE-3 pipeline from everything else.

    ``lazy_ingest=False`` is the ISSUE-4 A/B arm (``--ab-pump``): eager
    per-event ``from_dict`` and the classic item LIST (the dict
    compatibility oracle) instead of lazy decode-on-access views and the
    columnar store emit.  ``frontier=False`` is the ISSUE-5 A/B arm
    (``--ab-frontier``): the full-width plain scan instead of the
    frontier scan (monotone prefilter + chunked still_ok + mid-segment
    node-axis compaction).  ``watch_frames=False`` is the ISSUE-6 A/B
    arm (``--ab-watch``): per-event watch delivery and per-pod cache
    apply/bind confirm instead of column-packed frames, one-lock batch
    apply, and the columnar wave confirm.  ``device_loop=False`` is the
    ISSUE-11 A/B arm (``--ab-loop``): the chunked HOST loop (one
    blocking sync per chunk) instead of the device-resident
    ``lax.while_loop`` with donated carries and on-device compaction
    decisions; ``frontier_chunk`` sets the chunk width for both modes
    (the chunk-count axis of the host-sync scaling evidence).
    ``verify_oracle=True`` additionally replays
    the recorded drain batches through the per-pod CPU oracle off-clock
    and reports per-wave binding parity (``oracle_parity``).

    ``trace`` (ISSUE 7): truthy enables the wave tracer + flight
    recorder for the TIMED run only (the warm-up compiles untraced); a
    string value additionally writes the Chrome trace-event JSON
    artifact there (load into chrome://tracing / Perfetto), and the
    result carries a ``trace`` summary block either way.

    ``telemetry`` (ISSUE 13): truthy enables the continuous-telemetry
    stack for the TIMED run — the time-series scraper over the
    scheduler registry, the burn-rate SLO monitor over DEFAULT_SLOS,
    and the off-box shipper.  A string value ships the run's records
    (JSON-lines) to that path; truthy-non-string ships to ``os.devnull``
    (the A/B arm: full pipeline cost, no artifact).  The result carries
    a ``telemetry`` summary block with per-SLO burn-rate verdicts.

    The default preset is NORTH-scale churn (5,000 nodes — VERDICT r4
    directive 4): the returned dict carries an SLO verdict
    (``slo_pass``) gating e2e p99 ≤ 5s (the reference pod-startup SLO)
    and throughput ≥ the recorded floor; ``main`` exits 1 on failure."""
    import threading

    from kubernetes_tpu.api import lazy as lazy_mod
    from kubernetes_tpu.client import Clientset
    from kubernetes_tpu.models.snapshot import Tensorizer
    from kubernetes_tpu.ops import TPUBatchBackend
    from kubernetes_tpu.scheduler import GenericScheduler, Scheduler
    from kubernetes_tpu.store import Store

    from kubernetes_tpu.store import frames as frames_mod

    if warmup:  # compile the wave-sized segment buckets off the clock
        run_churn(n_nodes, 2 * (total_pods // waves), 2, workload, seed + 1,
                  warmup=False, pipeline=pipeline, lazy_ingest=lazy_ingest,
                  frontier=frontier, watch_frames=watch_frames,
                  device_loop=device_loop, frontier_chunk=frontier_chunk,
                  mesh=mesh)

    lazy_was = lazy_mod.ENABLED
    frames_was = frames_mod.ENABLED
    lazy_mod.ENABLED = lazy_ingest
    frames_mod.ENABLED = watch_frames
    tracer = None
    if trace:
        from kubernetes_tpu.utils import tracing

        tracer = tracing.enable(ring_waves=waves + 2)
    try:
        r = _run_churn_timed(n_nodes, total_pods, waves, workload, seed,
                             pipeline, lazy_ingest, frontier,
                             watch_frames, device_loop, frontier_chunk,
                             verify_oracle, telemetry, mesh, coalesce)
    finally:
        lazy_mod.ENABLED = lazy_was
        frames_mod.ENABLED = frames_was
        if tracer is not None:
            from kubernetes_tpu.utils import tracing

            tracing.disable()
        if telemetry:
            # belt and braces: the timed run disables these itself on
            # the happy path; a raise mid-run must not leak the globals
            from kubernetes_tpu.utils import telemetry as telemetry_mod
            from kubernetes_tpu.utils import timeseries as timeseries_mod

            telemetry_mod.disable()
            timeseries_mod.disable()
    if tracer is not None:
        doc = tracer.chrome_trace()
        r["trace"] = {
            "enabled": True,
            "events": len(doc["traceEvents"]),
            "waves_recorded": len(tracer.ring),
            "flight_dumps": len(tracer.dumps),
            "dump_reasons": sorted({d["reason"] for d in tracer.dumps}),
        }
        if isinstance(trace, str):
            with open(trace, "w") as f:
                json.dump(doc, f)
                f.write("\n")
            r["trace"]["artifact"] = trace
    return r


def _run_churn_timed(n_nodes, total_pods, waves, workload, seed, pipeline,
                     lazy_ingest, frontier, watch_frames, device_loop,
                     frontier_chunk, verify_oracle, telemetry=None,
                     mesh=False, coalesce=0.0) -> dict:
    import threading

    from kubernetes_tpu.api import lazy as lazy_mod
    from kubernetes_tpu.client import Clientset
    from kubernetes_tpu.models.snapshot import Tensorizer
    from kubernetes_tpu.ops import TPUBatchBackend
    from kubernetes_tpu.scheduler import GenericScheduler, Scheduler
    from kubernetes_tpu.store import Store

    rng = random.Random(seed)
    cs = Clientset(Store(event_log_window=max(200_000, 2 * (n_nodes + total_pods)),
                         coalesce_window_s=coalesce))
    for node in make_nodes(n_nodes, rng, workload):
        cs.nodes.create(node)
    if workload == "mixed":
        for svc in make_services():
            cs.services.create(svc)
    all_pods = make_pods(total_pods, rng, workload)

    algo = GenericScheduler()
    # mesh=True forces the sharded wave loop (frontier_mesh default is
    # "auto", which stays single-device on the CPU backend); everything
    # else about the harness is identical, so a mesh run is A/B-comparable
    backend = TPUBatchBackend(algorithm=algo, frontier=frontier,
                              frontier_device_loop=device_loop,
                              frontier_chunk=frontier_chunk,
                              frontier_mesh=(True if mesh else "auto"))
    if not pipeline:
        backend.tensorizer = Tensorizer(sticky_buckets=False,
                                        persistent_rows=False)
    sched = Scheduler(cs, algorithm=algo, backend=backend, emit_events=True)
    sched.overlap_ingest = pipeline
    sched.start()
    sched.broadcaster.start()

    # continuous telemetry over the TIMED run (ISSUE 13): scraper over
    # the scheduler registry, burn-rate monitor on the standing SLOs,
    # shipper to the artifact path (or devnull for the cost-only arm).
    # run_churn's finally tears these globals down on any raise.
    ts_store = slo_ev = shipper = None
    if telemetry:
        from kubernetes_tpu.utils import slo as slo_mod
        from kubernetes_tpu.utils import telemetry as telemetry_mod
        from kubernetes_tpu.utils import timeseries as timeseries_mod

        ts_store = timeseries_mod.enable(sched.metrics.registry,
                                         interval_s=0.25)
        slo_ev = slo_mod.monitor(store=ts_store)
        sink = telemetry_mod.FileSink(
            telemetry if isinstance(telemetry, str) else os.devnull)
        shipper = telemetry_mod.enable(sink,
                                       registry=sched.metrics.registry)
        ts_store.add_observer(telemetry_mod.timeseries_observer(shipper))

    per_wave = total_pods // waves
    # per-wave pump timing (the loop pumps internally; wrap to attribute)
    pump_acc = [0.0]
    orig_pump = sched.pump

    def timed_pump():
        t = time.perf_counter()
        n = orig_pump()
        pump_acc[0] += time.perf_counter() - t
        return n

    sched.pump = timed_pump

    # wave-drain detection feeds the arrival thread: wave w+1 is created
    # the moment wave w left the queue, so creation overlaps scheduling
    drained = [0]
    drain_batches: list[list[str]] = []  # per drain call, keys in order
    wave_drained = [threading.Event() for _ in range(waves)]
    orig_drain = sched.queue.drain

    def recording_drain(max_n=None):
        out = orig_drain(max_n)
        if out:
            drain_batches.append([p.meta.key for p in out])
        drained[0] += len(out)
        for w in range(waves):
            if drained[0] >= (w + 1) * per_wave:
                wave_drained[w].set()
        return out

    sched.queue.drain = recording_drain

    def arrivals():
        for w in range(waves):
            # ONE batch-create txn per wave (Store.create_many): the
            # arrival client's per-pod lock/fanout round-trips leave the
            # host budget; event order (and therefore queue/drain order
            # and binding parity) is identical to per-item creates
            cs.pods.create_many_nowait(all_pods[w * per_wave:(w + 1) * per_wave])
            if not wave_drained[w].wait(timeout=300):
                return  # scheduler wedged: the SLO gate will fail loudly

    lazy_pre = lazy_mod.stats_snapshot()
    t0 = time.perf_counter()
    arr = threading.Thread(target=arrivals, daemon=True)
    arr.start()
    bound = 0
    phase_timers: list[dict] = []
    for w in range(waves):
        pump_before = pump_acc[0]
        # pump-APPLICATION bracket at the wave level (ISSUE 6): the
        # bind-confirm frame of wave w is often digested by wave w+1's
        # pre-drain pumps, so per-wave apply time is deltaed around the
        # whole serving call, not just schedule_pending_batch
        apply_before = sched._pump_apply_stats()
        fb_before = sched.metrics.confirm_fallbacks.value
        b = sched.run_batch_loop(min_batch=per_wave, max_wait=30.0,
                                 max_waves=1, poll_interval=0.002)
        bound += b
        ph = {k: round(sched.last_batch_phases.get(k, 0.0), 4)
              for k in ("tensorize_s", "dispatch_s", "device_wait_s",
                        "commit_s", "prep_s", "decode_s")}
        ph["promotions"] = int(sched.last_batch_phases.get("promotions", 0))
        ph["pump_s"] = round(pump_acc[0] - pump_before, 4)
        apply_after = sched._pump_apply_stats()
        ph["apply_s"] = round(apply_after[0] - apply_before[0], 4)
        ph["frames"] = apply_after[1] - apply_before[1]
        ph["frame_events"] = apply_after[2] - apply_before[2]
        ph["confirm_fallbacks"] = int(
            sched.metrics.confirm_fallbacks.value - fb_before)
        # blocking device→host round-trips of the wave (ISSUE 11): fed by
        # the same backend seam device_wait uses; O(compactions + 1) per
        # segment in loop mode, O(chunks) in the chunked host loop
        ph["host_syncs"] = int(sched.last_batch_phases.get("host_syncs", 0))
        ph["bound"] = b
        fr = sched.last_batch_phases.get("frontier")
        if fr:
            # per-wave alive-union trajectory (the ISSUE 5 artifact):
            # prefilter width + per-chunk alive fractions per segment
            ph["frontier"] = fr
        mw = sched.last_batch_phases.get("mesh")
        if mw:
            # sharded-wave attribution (ISSUE 18): shard count, per-shard
            # upload fractions, and the alive-fraction skew of the wave
            ph["mesh"] = mw
        phase_timers.append(ph)
    elapsed = time.perf_counter() - t0
    arr.join(timeout=10)
    sched.broadcaster.stop(drain=True)
    # unbound from FINAL state, not failure events: a pod that failed a
    # wave re-queues after backoff and would be double-counted by events
    pods_final, _ = cs.pods.list()
    unbound = sum(1 for p in pods_final if not p.spec.node_name)
    m = sched.metrics

    def _pq(h, q):
        v = h.quantile(q)
        return round(v / 1e3, 3) if v != float("inf") else None

    pps = round(bound / elapsed, 1) if elapsed > 0 else 0.0
    p99 = _pq(m.e2e_scheduling_latency, 0.99)
    prep_total = sum(p["prep_s"] for p in phase_timers)
    wait_total = sum(p["device_wait_s"] for p in phase_timers)
    ncache = backend.device_node_cache.stats
    lazy_post = lazy_mod.stats_snapshot()
    pod_inf = sched.informers.informer("Pod").stats
    telem_block = None
    if ts_store is not None:
        from kubernetes_tpu.utils import telemetry as telemetry_mod
        from kubernetes_tpu.utils import timeseries as timeseries_mod

        ts_store.sample_once()  # one final scrape so the tail is in-ring
        telemetry_mod.disable()  # drains the queue through the sink
        timeseries_mod.disable()
        verdicts = {}
        for s in (slo_ev.slos if slo_ev is not None else []):
            fast = s.sli.bad_fraction(ts_store, s.fast_window_s)
            slow = s.sli.bad_fraction(ts_store, s.slow_window_s)
            verdicts[s.name] = {
                "breached": slo_ev.state(s.name)["breached"],
                "objective": s.objective,
                "fast_burn": round(fast / s.error_budget, 2)
                if fast is not None else None,
                "slow_burn": round(slow / s.error_budget, 2)
                if slow is not None else None,
            }
        telem_block = {
            "enabled": True,
            "artifact": telemetry if isinstance(telemetry, str) else None,
            "scrapes": ts_store.scrapes,
            "tracks": len(ts_store.tracks()),
            "shipper": shipper.stats(),
            "breaches_fired": slo_ev.breaches_fired if slo_ev else 0,
            "slo_verdicts": verdicts,
        }

    oracle_parity = None
    if verify_oracle:
        oracle_parity = _oracle_replay_waves(
            drain_batches, {p.meta.key: p.spec.node_name or None
                            for p in pods_final},
            n_nodes, total_pods, workload, seed)
        # rr tie-counter parity: the deterministic cross-shard tie-break
        # must advance the timed run's rotation counter exactly as the
        # sequential oracle does
        oracle_parity["round_robin_timed"] = algo._round_robin
        oracle_parity["round_robin_match"] = (
            oracle_parity["round_robin"] is not None
            and oracle_parity["round_robin"] == algo._round_robin)
    return {
        "nodes": n_nodes,
        "pods": total_pods,
        "waves": waves,
        "bound": bound,
        "unbound": unbound,
        "pods_per_sec": pps,
        "pipeline": pipeline,
        "e2e_scheduling_ms": {"p50": _pq(m.e2e_scheduling_latency, 0.5),
                              "p99": p99},
        "binding_ms": {"p50": _pq(m.binding_latency, 0.5),
                       "p99": _pq(m.binding_latency, 0.99)},
        "queue_wait_ms": {"p50": _pq(m.batch_queue_wait, 0.5),
                          "p99": _pq(m.batch_queue_wait, 0.99)},
        "phase_timers": phase_timers,
        # fraction of total device wait filled with overlapped host prep
        "overlap_fraction": round(prep_total / (prep_total + wait_total), 3)
        if prep_total + wait_total > 0 else 0.0,
        # device-resident node state: how much of the node axis was
        # actually re-uploaded (0 dirty cols on a quiet fleet)
        "node_upload": {
            "reuses": ncache["reuses"], "uploads": ncache["uploads"],
            "col_updates": ncache["col_updates"],
            "dirty_fraction": round(
                ncache["dirty_cols"] / max(ncache["cols_total"], 1), 4),
            # per-shard cumulative upload attribution (ISSUE 18): only
            # populated when the node cache served a sharded mesh
            **({"shard_dirty_cols": list(ncache["shard_dirty_cols"]),
                "shard_cols_total": list(ncache["shard_cols_total"]),
                "shard_upload_fractions": [
                    round(d / max(c, 1), 4)
                    for d, c in zip(ncache["shard_dirty_cols"],
                                    ncache["shard_cols_total"])]}
               if ncache.get("shard_cols_total") else {}),
        },
        # frontier scan (ISSUE 5): segments served, device compactions,
        # tensorize-time column drops, full-width retries
        "frontier": {
            "enabled": frontier,
            "segments": backend.stats["frontier_segments"],
            "compactions": backend.stats["frontier_compactions"],
            "prefilter_cols": backend.stats["frontier_prefilter_cols"],
            "fallbacks": backend.stats["frontier_fallbacks"],
            "loop_fallbacks": backend.stats["frontier_loop_fallbacks"],
            "fallback_modes": dict(backend.stats["frontier_fallback_modes"]),
        },
        # sharded wave loop (ISSUE 18): requested mode, observed shard
        # count, and the per-wave attribution attrs (also on each
        # phase_timers[w]["mesh"])
        "mesh": {
            "requested": bool(mesh),
            "n_shards": max((p["mesh"]["n_shards"] for p in phase_timers
                             if p.get("mesh")), default=0),
            "waves_sharded": sum(1 for p in phase_timers if p.get("mesh")),
        },
        # device-resident wave loop (ISSUE 11): blocking device→host
        # round-trips the run actually paid, per wave and in total
        "host_syncs": {
            "device_loop": device_loop,
            "chunk": frontier_chunk,
            "total": backend.stats["host_syncs"],
            "per_wave": [p["host_syncs"] for p in phase_timers],
        },
        "row_cache": dict(backend.tensorizer.node_rows_stats or {}),
        # zero-copy ingest (ISSUE 4): what the decode path actually did
        "ingest": {
            "lazy": lazy_ingest,
            "decoded_events": pod_inf["decoded_events"],
            "decode_s": round(pod_inf["decode_s"], 4),
            "decode_errors": pod_inf["decode_errors"],
            "wrapped": lazy_post["wrapped"] - lazy_pre["wrapped"],
            "promotions": (lazy_post["promotions"] + lazy_post["sections"]
                           - lazy_pre["promotions"] - lazy_pre["sections"]),
        },
        # batched watch frames (ISSUE 6): delivery + one-lock apply +
        # columnar confirm volume of the run
        "watch": {
            "frames_enabled": watch_frames,
            "frames": pod_inf["frames"],
            "frame_events": pod_inf["frame_events"],
            "batch_errors": pod_inf["batch_errors"],
            "apply_s": round(pod_inf["apply_s"], 4),
            "confirm_fallbacks": int(sched.metrics.confirm_fallbacks.value),
        },
        "oracle_parity": oracle_parity,
        # continuous-telemetry summary (ISSUE 13): scrape/ship counters
        # and per-SLO burn-rate verdicts; None when the stack was off
        "telemetry": telem_block,
        "slo_p99_ms": CHURN_SLO_P99_MS,
        "floor_pods_per_sec": CHURN_FLOOR_PODS_PER_SEC,
        "slo_pass": bool(p99 is not None and p99 <= CHURN_SLO_P99_MS
                         and pps >= CHURN_FLOOR_PODS_PER_SEC),
    }


def run_churn_ab(n_nodes: int = 5_000, total_pods: int = 20_000,
                 waves: int = 10, pairs: int = 2, seed: int = 0) -> dict:
    """Both-orders interleaved A/B of the steady-state pipeline: B (new) =
    overlapped ingest + persistent rows + sticky buckets + device-resident
    node state; A (old) = all four off, same harness, same seeds.  Writes
    the BENCH_AB_churn_pipeline.json ledger shape."""
    # pay each arm's XLA compiles off the books
    run_churn(n_nodes, 2 * (total_pods // waves), 2, seed=seed + 1,
              warmup=False, pipeline=True)
    run_churn(n_nodes, 2 * (total_pods // waves), 2, seed=seed + 1,
              warmup=False, pipeline=False)

    def one(pipe: bool) -> dict:
        return run_churn(n_nodes, total_pods, waves, seed=seed,
                         warmup=False, pipeline=pipe)

    ab_pairs, ba_pairs = [], []
    a_all, b_all = [], []
    bounds = set()
    for _ in range(pairs):
        b = one(True)
        a = one(False)
        ab_pairs.append({"B_new": b["pods_per_sec"], "A_old": a["pods_per_sec"]})
        b_all.append(b["pods_per_sec"])
        a_all.append(a["pods_per_sec"])
        bounds.update((a["bound"], b["bound"]))
        print(f"# ab-churn AB: B={b['pods_per_sec']} A={a['pods_per_sec']} "
              f"overlap={b['overlap_fraction']}", file=sys.stderr)
    for _ in range(pairs):
        a = one(False)
        b = one(True)
        ba_pairs.append({"A_old": a["pods_per_sec"], "B_new": b["pods_per_sec"]})
        a_all.append(a["pods_per_sec"])
        b_all.append(b["pods_per_sec"])
        bounds.update((a["bound"], b["bound"]))
        print(f"# ab-churn BA: A={a['pods_per_sec']} B={b['pods_per_sec']}",
              file=sys.stderr)
    a_med = sorted(a_all)[len(a_all) // 2]
    b_med = sorted(b_all)[len(b_all) // 2]
    won = sum(1 for p in ab_pairs + ba_pairs if p["B_new"] > p["A_old"])
    return {
        "claim": ("Steady-state scheduling pipeline: overlapped wave ingest "
                  "(prep in the device's shadow), incremental tensorize "
                  "(persistent node-static rows), sticky shape buckets (no "
                  "mid-run recompiles), device-resident node state"),
        "method": (f"Churn {n_nodes} nodes / {total_pods} mixed pods / "
                   f"{waves} waves, arrival thread + run_batch_loop serving "
                   "(both arms), events on; interleaved pairs in BOTH "
                   "orders, one shared process, per-arm warm-up compiles "
                   "paid up front; A = pipeline seams off (pre-ISSUE-3 "
                   "behavior), B = pipeline on"),
        "pairs_order_AB_first": ab_pairs,
        "pairs_order_BA_first": ba_pairs,
        "A_old_all": a_all,
        "B_new_all": b_all,
        "A_median": a_med,
        "B_median": b_med,
        "win_pct": round((b_med - a_med) / a_med * 100, 1) if a_med else None,
        "b_won_pairs": f"{won}/{len(ab_pairs) + len(ba_pairs)} (both orders)",
        "bound_counts": sorted(bounds),
    }


def run_pump_ab(n_nodes: int = 5_000, total_pods: int = 20_000,
                waves: int = 10, pairs: int = 2, seed: int = 0) -> dict:
    """Both-orders interleaved A/B of the zero-copy ingest path (ISSUE 4):
    B (new) = lazy decode-on-access watch/LIST views + the columnar store
    emit; A (old) = eager per-event ``from_dict`` + classic item LIST (the
    dict compatibility oracle), same harness, same seeds.  The first run
    of EACH arm additionally replays the recorded drain batches through
    the per-pod CPU oracle (off-clock) and reports per-wave binding
    parity.  Writes the BENCH_AB_pump_ingest.json ledger shape."""
    # pay the XLA compiles off the books (shape buckets are identical in
    # both arms — one warm-up covers the process-wide compile cache)
    run_churn(n_nodes, 2 * (total_pods // waves), 2, seed=seed + 1,
              warmup=False, lazy_ingest=True)

    parity = {}

    def one(lazy: bool, verify: bool = False) -> dict:
        r = run_churn(n_nodes, total_pods, waves, seed=seed, warmup=False,
                      lazy_ingest=lazy, verify_oracle=verify)
        if verify:
            parity["lazy" if lazy else "eager"] = r["oracle_parity"]
        return r

    ab_pairs, ba_pairs = [], []
    a_all, b_all = [], []
    bounds = set()
    for i in range(pairs):
        b = one(True, verify=(i == 0))
        a = one(False, verify=(i == 0))
        ab_pairs.append({"B_new": b["pods_per_sec"], "A_old": a["pods_per_sec"]})
        b_all.append(b["pods_per_sec"])
        a_all.append(a["pods_per_sec"])
        bounds.update((a["bound"], b["bound"]))
        print(f"# ab-pump AB: B={b['pods_per_sec']} A={a['pods_per_sec']} "
              f"decode_s A={a['ingest']['decode_s']} "
              f"B={b['ingest']['decode_s']}", file=sys.stderr)
    for _ in range(pairs):
        a = one(False)
        b = one(True)
        ba_pairs.append({"A_old": a["pods_per_sec"], "B_new": b["pods_per_sec"]})
        a_all.append(a["pods_per_sec"])
        b_all.append(b["pods_per_sec"])
        bounds.update((a["bound"], b["bound"]))
        print(f"# ab-pump BA: A={a['pods_per_sec']} B={b['pods_per_sec']}",
              file=sys.stderr)
    a_med = sorted(a_all)[len(a_all) // 2]
    b_med = sorted(b_all)[len(b_all) // 2]
    won = sum(1 for p in ab_pairs + ba_pairs if p["B_new"] > p["A_old"])
    return {
        "claim": ("Zero-copy ingest: lazy decode-on-access watch/LIST views "
                  "(typed fields materialize only when touched) + columnar "
                  "store LIST emit (shared-subtree views, identity/request/"
                  "signature columns) between store and tensorizer"),
        "method": (f"Churn {n_nodes} nodes / {total_pods} mixed pods / "
                   f"{waves} waves, arrival thread + run_batch_loop serving "
                   "(both arms), events on; interleaved pairs in BOTH "
                   "orders, one shared process, warm-up compiles paid up "
                   "front; A = eager from_dict per event + item LIST "
                   "(pre-ISSUE-4), B = lazy + columnar; first run of each "
                   "arm replayed off-clock through the per-pod CPU oracle "
                   "per drained wave"),
        "pairs_order_AB_first": ab_pairs,
        "pairs_order_BA_first": ba_pairs,
        "A_old_all": a_all,
        "B_new_all": b_all,
        "A_median": a_med,
        "B_median": b_med,
        "win_pct": round((b_med - a_med) / a_med * 100, 1) if a_med else None,
        "b_won_pairs": f"{won}/{len(ab_pairs) + len(ba_pairs)} (both orders)",
        "bound_counts": sorted(bounds),
        "oracle_parity": parity,
    }


def run_frontier_ab(n_nodes: int = 5_000, total_pods: int = 20_000,
                    waves: int = 10, pairs: int = 2, seed: int = 0) -> dict:
    """Both-orders interleaved A/B of the frontier scan (ISSUE 5):
    B (new) = frontier mode on (tensorize-time monotone prefilter,
    chunked still_ok scan, mid-segment node-axis compaction); A (old) =
    the full-width plain scan, same harness, same seeds.  The first pair
    replays both arms' recorded drain batches through the per-pod CPU
    oracle (off-clock) and reports per-wave binding parity.  Writes the
    BENCH_AB_frontier_scan.json ledger shape."""
    run_churn(n_nodes, 2 * (total_pods // waves), 2, seed=seed + 1,
              warmup=False, frontier=True)
    run_churn(n_nodes, 2 * (total_pods // waves), 2, seed=seed + 1,
              warmup=False, frontier=False)

    parity = {}

    def one(frontier: bool, verify: bool = False) -> dict:
        r = run_churn(n_nodes, total_pods, waves, seed=seed, warmup=False,
                      frontier=frontier, verify_oracle=verify)
        if verify:
            parity["frontier" if frontier else "plain"] = r["oracle_parity"]
        return r

    ab_pairs, ba_pairs = [], []
    a_all, b_all = [], []
    bounds = set()
    trajectories = None
    for i in range(pairs):
        b = one(True, verify=(i == 0))
        a = one(False, verify=(i == 0))
        if trajectories is None:
            trajectories = [p.get("frontier") for p in b["phase_timers"]]
        ab_pairs.append({"B_new": b["pods_per_sec"], "A_old": a["pods_per_sec"]})
        b_all.append(b["pods_per_sec"])
        a_all.append(a["pods_per_sec"])
        bounds.update((a["bound"], b["bound"]))
        print(f"# ab-frontier AB: B={b['pods_per_sec']} A={a['pods_per_sec']} "
              f"frontier={b['frontier']}", file=sys.stderr)
    for _ in range(pairs):
        a = one(False)
        b = one(True)
        ba_pairs.append({"A_old": a["pods_per_sec"], "B_new": b["pods_per_sec"]})
        a_all.append(a["pods_per_sec"])
        b_all.append(b["pods_per_sec"])
        bounds.update((a["bound"], b["bound"]))
        print(f"# ab-frontier BA: A={a['pods_per_sec']} B={b['pods_per_sec']}",
              file=sys.stderr)
    a_med = sorted(a_all)[len(a_all) // 2]
    b_med = sorted(b_all)[len(b_all) // 2]
    won = sum(1 for p in ab_pairs + ba_pairs if p["B_new"] > p["A_old"])
    return {
        "claim": ("Frontier scan: tensorize-time monotone node prefilter, "
                  "per-signature still_ok carry plane, and mid-segment "
                  "device node-axis compaction on the XLA scan path "
                  "(bit-exact oracle parity by construction)"),
        "method": (f"Churn {n_nodes} nodes / {total_pods} mixed pods / "
                   f"{waves} waves, arrival thread + run_batch_loop serving "
                   "(both arms), events on; interleaved pairs in BOTH "
                   "orders, one shared process, per-arm warm-up compiles "
                   "paid up front; A = frontier off (full-width plain "
                   "scan), B = frontier on; first pair of each arm "
                   "replayed off-clock through the per-pod CPU oracle per "
                   "drained wave"),
        "pairs_order_AB_first": ab_pairs,
        "pairs_order_BA_first": ba_pairs,
        "A_old_all": a_all,
        "B_new_all": b_all,
        "A_median": a_med,
        "B_median": b_med,
        "win_pct": round((b_med - a_med) / a_med * 100, 1) if a_med else None,
        "b_won_pairs": f"{won}/{len(ab_pairs) + len(ba_pairs)} (both orders)",
        "bound_counts": sorted(bounds),
        "oracle_parity": parity,
        "alive_trajectories_first_run": trajectories,
    }


def run_watch_ab(n_nodes: int = 5_000, total_pods: int = 20_000,
                 waves: int = 10, pairs: int = 2, seed: int = 0) -> dict:
    """Both-orders interleaved A/B of batched watch frames (ISSUE 6):
    B (new) = column-packed watch frames + one-lock informer batch apply
    + the scheduler's columnar wave confirm; A (old) = per-event watch
    delivery and per-pod cache apply/bind confirm, same harness, same
    seeds.  The first pair replays both arms' recorded drain batches
    through the per-pod CPU oracle (off-clock) and reports per-wave
    binding parity.  Writes the BENCH_AB_watch_frames.json ledger shape
    (the recorded ledger uses the worktree method; this flag A/B
    isolates the feature seam on one tree)."""
    run_churn(n_nodes, 2 * (total_pods // waves), 2, seed=seed + 1,
              warmup=False, watch_frames=True)
    run_churn(n_nodes, 2 * (total_pods // waves), 2, seed=seed + 1,
              warmup=False, watch_frames=False)

    parity = {}

    def one(framed: bool, verify: bool = False) -> dict:
        r = run_churn(n_nodes, total_pods, waves, seed=seed, warmup=False,
                      watch_frames=framed, verify_oracle=verify)
        if verify:
            parity["frames" if framed else "per_event"] = r["oracle_parity"]
        return r

    ab_pairs, ba_pairs = [], []
    a_all, b_all = [], []
    a_apply, b_apply = [], []
    bounds = set()
    for i in range(pairs):
        b = one(True, verify=(i == 0))
        a = one(False, verify=(i == 0))
        ab_pairs.append({"B_new": b["pods_per_sec"], "A_old": a["pods_per_sec"]})
        b_all.append(b["pods_per_sec"])
        a_all.append(a["pods_per_sec"])
        b_apply.append(b["watch"]["apply_s"])
        a_apply.append(a["watch"]["apply_s"])
        bounds.update((a["bound"], b["bound"]))
        print(f"# ab-watch AB: B={b['pods_per_sec']} A={a['pods_per_sec']} "
              f"apply_s A={a['watch']['apply_s']} B={b['watch']['apply_s']}",
              file=sys.stderr)
    for _ in range(pairs):
        a = one(False)
        b = one(True)
        ba_pairs.append({"A_old": a["pods_per_sec"], "B_new": b["pods_per_sec"]})
        a_all.append(a["pods_per_sec"])
        b_all.append(b["pods_per_sec"])
        a_apply.append(a["watch"]["apply_s"])
        b_apply.append(b["watch"]["apply_s"])
        bounds.update((a["bound"], b["bound"]))
        print(f"# ab-watch BA: A={a['pods_per_sec']} B={b['pods_per_sec']}",
              file=sys.stderr)
    a_med = sorted(a_all)[len(a_all) // 2]
    b_med = sorted(b_all)[len(b_all) // 2]
    won = sum(1 for p in ab_pairs + ba_pairs if p["B_new"] > p["A_old"])
    return {
        "claim": ("Batched watch frames: column-packed event delivery "
                  "(one frame per correlated store txn), one-lock informer "
                  "batch apply, and the scheduler's columnar wave confirm "
                  "(prev-revision fence) from store to bind confirm"),
        "method": (f"Churn {n_nodes} nodes / {total_pods} mixed pods / "
                   f"{waves} waves, arrival thread + run_batch_loop serving "
                   "(both arms), events on; interleaved pairs in BOTH "
                   "orders, one shared process, per-arm warm-up compiles "
                   "paid up front; A = frames seam off (per-event delivery "
                   "+ per-pod apply/confirm, pre-ISSUE-6), B = frames on; "
                   "first pair of each arm replayed off-clock through the "
                   "per-pod CPU oracle per drained wave"),
        "pairs_order_AB_first": ab_pairs,
        "pairs_order_BA_first": ba_pairs,
        "A_old_all": a_all,
        "B_new_all": b_all,
        "A_median": a_med,
        "B_median": b_med,
        "win_pct": round((b_med - a_med) / a_med * 100, 1) if a_med else None,
        "b_won_pairs": f"{won}/{len(ab_pairs) + len(ba_pairs)} (both orders)",
        "bound_counts": sorted(bounds),
        "apply_s_per_run": {"A_old": a_apply, "B_new": b_apply},
        "oracle_parity": parity,
    }


def _rss_mb() -> float:
    """Current resident set (VmRSS) in MiB — current, not peak, so the
    second arm of an A/B is not poisoned by the first arm's high-water
    mark the way ``ru_maxrss`` would be."""
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return round(int(line.split()[1]) / 1024.0, 1)
    except OSError:
        pass
    return 0.0


def _fleet_arm(arm_b: bool, n_watchers: int, seed_pods: int, churn_ops: int,
               http_watchers: int, selector_watchers: int, n_informers: int,
               pump_threads: int, coalesce_window_s: float, seed: int,
               slo_probe: bool, drain_timeout_s: float = 120.0) -> dict:
    """One arm of the hollow-watcher fleet bench: B = coalescing window +
    framed delivery + shared encode, A = per-event delivery (the
    pre-serving-tier broadcaster), same harness, same seeded churn.

    The fleet is kubemark applied to the WATCH axis: ``n_watchers``
    in-process hollow watchers (no thread each — a pump pool drives
    slices), a small HTTP cohort on real apiserver streams (selector
    watchers among them exercising column-level sub-frame packing), and
    a few real ``SharedInformer``s with ``compact_on_resync`` for the
    RSS point.  Throughput is LOGICAL fan-out: every churn event must
    reach every full watcher (a coalesced fold counts — the client holds
    the newest state that event produced), so events/s =
    churn_ops x full_watchers / drain wall."""
    import dataclasses
    import threading

    from kubernetes_tpu.apiserver import APIServer
    from kubernetes_tpu.client import Clientset
    from kubernetes_tpu.client.informer import SharedInformer
    from kubernetes_tpu.client.remote import RemoteStore
    from kubernetes_tpu.kubelet.hollow import HollowWatcher, HollowWatcherFleet
    from kubernetes_tpu.store import Store
    from kubernetes_tpu.store import frames as frames_mod
    from kubernetes_tpu.utils import tracing
    from kubernetes_tpu.utils.fanout import WatchFanoutTracker
    from kubernetes_tpu.utils.metrics import (DEFAULT_STORE_METRICS,
                                              ClientMetrics, Registry)
    from kubernetes_tpu.utils.slo import BurnRateEvaluator, serving_slos
    from kubernetes_tpu.utils.timeseries import TimeSeriesStore

    frames_was, shenc_was = frames_mod.ENABLED, frames_mod.SHARED_ENCODE
    frames_mod.ENABLED = arm_b
    frames_mod.SHARED_ENCODE = arm_b
    sm = DEFAULT_STORE_METRICS
    sm0 = (sm.coalesce_flushes.value, sm.coalesced_events.value,
           sm.coalesce_fallbacks.value)
    store = Store(event_log_window=max(200_000, 8 * (seed_pods + churn_ops)),
                  coalesce_window_s=(coalesce_window_s if arm_b else 0.0))
    server = None
    stop = threading.Event()
    stall = threading.Event()
    threads: list[threading.Thread] = []
    tracer = tracing.enable(ring_waves=4) if slo_probe else None
    try:
        rng = random.Random(seed)
        cs = Clientset(store)

        def pod(i):
            return {"metadata": {"name": f"fp-{i:05d}", "namespace": "default",
                                 "labels": {"tier": "hot" if i % 2 == 0
                                            else "cold"}},
                    "spec": {}, "status": {"phase": "Pending"}}

        for i in range(seed_pods):
            store.create("Pod", pod(i))
        seed_head = store.revision

        metrics = ClientMetrics(Registry())
        tracker = WatchFanoutTracker(metrics)
        fleet = HollowWatcherFleet(store, n_watchers, kind="Pod",
                                   frames=arm_b, tracker=tracker,
                                   from_revision=seed_head)
        server = APIServer(store)
        server.start()
        remote = RemoteStore(server.url)
        http_fleet = HollowWatcherFleet(remote, http_watchers, kind="Pod",
                                        frames=arm_b, tracker=tracker,
                                        prefix="http",
                                        from_revision=seed_head)
        sel_watchers = [
            HollowWatcher(
                f"sel-{i:03d}",
                remote.watch("Pod", from_revision=seed_head, frames=arm_b,
                             label_selector="tier=hot"))
            for i in range(selector_watchers)
        ]
        informers = [SharedInformer(cs.pods, compact_on_resync=True)
                     for _ in range(n_informers)]
        for inf in informers:
            inf.start_manual()

        # -- pump pool: slices of the hollow fleet + one aux driver --------
        def pump_slice(ws):
            while not stop.is_set():
                if stall.is_set():
                    time.sleep(0.002)
                    continue
                n = 0
                for w in ws:
                    n += w.pump()
                if n == 0:
                    time.sleep(0.001)

        def pump_aux():
            while not stop.is_set():
                if stall.is_set():
                    time.sleep(0.002)
                    continue
                n = http_fleet.pump_all()
                for w in sel_watchers:
                    n += w.pump()
                for inf in informers:
                    n += inf.pump()
                if n == 0:
                    time.sleep(0.001)

        step = max(1, n_watchers // pump_threads)
        for j in range(0, n_watchers, step):
            t = threading.Thread(target=pump_slice,
                                 args=(fleet.watchers[j:j + step],),
                                 daemon=True, name=f"fleet-pump-{j}")
            threads.append(t)
        threads.append(threading.Thread(target=pump_aux, daemon=True,
                                        name="fleet-pump-aux"))

        # staleness sampler: per-tick p50/p99 revision lag across the
        # hollow fleet (plain int reads — watcher applied_rev is a word)
        lag_p50: list[int] = []
        lag_p99: list[int] = []

        def sampler():
            while not stop.is_set():
                head = store.revision
                tracker.observe_head(head)
                lags = sorted(head - w.applied_rev for w in fleet.watchers)
                lag_p50.append(lags[len(lags) // 2])
                lag_p99.append(lags[(len(lags) * 99) // 100])
                tracker.sample()
                time.sleep(0.02)

        threads.append(threading.Thread(target=sampler, daemon=True,
                                        name="fleet-sampler"))
        for t in threads:
            t.start()

        # -- the measured churn: singles (the coalescer's diet) ------------
        alive = set(range(seed_pods))
        hot = list(range(0, seed_pods, 2))
        touched: set = set()
        t0 = time.perf_counter()
        for op in range(churn_ops):
            i = rng.choice(hot)
            touched.add(i)
            r = rng.random()
            if i in alive and r < 0.12:
                store.delete("Pod", "default", f"fp-{i:05d}")
                alive.discard(i)
            elif i not in alive:
                store.create("Pod", pod(i))
                alive.add(i)
            else:
                obj = store.get("Pod", "default", f"fp-{i:05d}")
                obj["status"] = {"phase": f"Running-{op}"}
                store.update("Pod", obj)
        head = store.revision
        deadline = time.perf_counter() + drain_timeout_s
        while (fleet.converged(head) < n_watchers
               or http_fleet.converged(head) < http_watchers):
            if time.perf_counter() > deadline:
                break
            time.sleep(0.005)
        wall = time.perf_counter() - t0
        # grace for the selector cohort (its applied_rev tops out at the
        # last MATCHING revision, not head) and the informers
        time.sleep(0.25)

        full_clients = n_watchers + http_watchers
        logical = churn_ops * full_clients
        delivered = sum(w.event_units for w in fleet.watchers + http_fleet.watchers)
        deliveries = sum(w.deliveries for w in fleet.watchers + http_fleet.watchers)

        # -- state-equivalence gate (over the keys the watchers SAW:
        # the fleet watches from the seed head, so only churned keys
        # have deliveries to agree on) --------------------------------------
        expected = {}
        for i in touched:
            key = f"default/fp-{i:05d}"
            if i in alive:
                expected[key] = int(
                    store.get("Pod", "default", f"fp-{i:05d}")
                    ["metadata"]["resourceVersion"])
            else:
                expected[key] = None
        mismatches = gapped = 0
        for w in fleet.watchers + http_fleet.watchers:
            if w.gaps:
                gapped += 1
                continue
            for key, rev in expected.items():
                if w.cache.get(key) != rev and not (rev is None
                                                    and key not in w.cache):
                    mismatches += 1
                    break
        sel_bad = sel_mismatch = 0
        for w in sel_watchers:
            if any(not k.split("/", 1)[1].startswith("fp-") or
                   int(k.split("fp-")[1]) % 2 != 0 for k in w.cache):
                sel_bad += 1
            for key, rev in expected.items():
                if rev is not None and w.cache.get(key) != rev:
                    sel_mismatch += 1
                    break
        for inf in informers:
            inf.relist()  # resync -> compact_on_resync sweep (the RSS point)
        inf_lag = [head - inf.last_revision for inf in informers]
        rss = _rss_mb()

        # -- SLO probe: stall the pumps, burn, drain, recover --------------
        slo_block = None
        if slo_probe:
            tracker.attach_breach_context()
            clk = [0.0]
            ts = TimeSeriesStore(metrics.registry, interval_s=0.5,
                                 capacity=600, clock=lambda: clk[0])
            slos = [dataclasses.replace(s, fast_window_s=1.0,
                                        slow_window_s=3.0, recovery_evals=2)
                    for s in serving_slos(worst_lag_revisions=40.0)]
            ev = BurnRateEvaluator(slos=slos, store=ts)
            events: list[dict] = []

            def tick():
                clk[0] += 0.5
                tracker.observe_head(store.revision)
                tracker.sample()
                ts.sample_once()
                events.extend(ev.evaluate())

            stall.set()
            for op in range(120):  # lag builds while nobody pumps
                i = rng.choice(hot)
                if i in alive:
                    obj = store.get("Pod", "default", f"fp-{i:05d}")
                    obj["status"] = {"phase": f"stall-{op}"}
                    store.update("Pod", obj)
            store.flush_coalesced()
            for _ in range(30):
                tick()
                if any(e["type"] == "breach" for e in events):
                    break
                time.sleep(0.02)
            stall.clear()
            shead = store.revision
            sdl = time.perf_counter() + 30.0
            while (fleet.converged(shead) < n_watchers
                   and time.perf_counter() < sdl):
                time.sleep(0.005)
            for _ in range(40):
                tick()
                if any(e["type"] == "recovered" for e in events):
                    break
                time.sleep(0.02)
            dump_ctx = None
            for d in (tracer.dumps if tracer is not None else []):
                if d["reason"].startswith("slo:watch_fanout_worst_client"):
                    dump_ctx = d["attrs"].get("context")
            slo_block = {
                "slo": "watch_fanout_worst_client_staleness",
                "breached": any(e["type"] == "breach" for e in events),
                "recovered": any(e["type"] == "recovered" for e in events),
                "breach_dump_top_laggards": (
                    len(dump_ctx["top_laggards"]) if dump_ctx else 0),
                "events": events,
            }

        return {
            "arm": "B_coalesced_shared" if arm_b else "A_per_event",
            "wall_s": round(wall, 3),
            "fanout_events_per_s": int(logical / wall) if wall else None,
            "logical_events": logical,
            "delivered_units": delivered,
            "deliveries": deliveries,
            "staleness_p50_revisions": (sorted(lag_p50)[len(lag_p50) // 2]
                                        if lag_p50 else 0),
            "staleness_p99_revisions": (sorted(lag_p99)[len(lag_p99) // 2]
                                        if lag_p99 else 0),
            "rss_mb": rss,
            "coalesce": {
                "flushes": int(sm.coalesce_flushes.value - sm0[0]),
                "folded": int(sm.coalesced_events.value - sm0[1]),
                "fallbacks": int(sm.coalesce_fallbacks.value - sm0[2]),
            },
            "equiv": {"clients": full_clients, "mismatches": mismatches,
                      "gapped": gapped},
            "selector": {"clients": selector_watchers,
                         "non_matching_keys": sel_bad,
                         "mismatches": sel_mismatch},
            "informers": {"count": n_informers,
                          "compact_on_resync": True,
                          "lag_after_relist": inf_lag,
                          "compactions": sum(i.stats["compactions"]
                                             for i in informers)},
            "slo": slo_block,
        }
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=5)
        try:
            fleet.stop_all()
            http_fleet.stop_all()
            for w in sel_watchers:
                w.stop()
            for inf in informers:
                inf.stop()
        except Exception:
            pass
        if server is not None:
            server.stop()
        store.close()
        if tracer is not None:
            tracing.disable()
        frames_mod.ENABLED = frames_was
        frames_mod.SHARED_ENCODE = shenc_was


def run_watch_fleet(n_watchers: int = 10_000, seed_pods: int = 400,
                    churn_ops: int = 600, http_watchers: int = 24,
                    selector_watchers: int = 8, n_informers: int = 4,
                    pump_threads: int = 8, coalesce_window_s: float = 0.005,
                    seed: int = 0, parity: bool = True) -> dict:
    """The hollow-watcher fleet bench (ISSUE 19): ``n_watchers``
    concurrent watch clients against ONE broadcaster under single-event
    churn, A/B-ing the serving tier (B = time-window coalescing + framed
    delivery + single-encode fan-out; A = per-event delivery).

    Ships the BENCH_watch_fleet.json evidence: logical fan-out events/s
    per arm (every churn event reaching every client), per-client
    staleness p50/p99 in revisions, RSS with ``compact_on_resync``
    informers riding along, a zero-mismatch state-equivalence gate over
    every client's final cache, the per-CLIENT staleness SLO burning and
    recovering mid-run (with the top-K laggard breach dump), and — with
    ``parity`` — the north-preset churn replayed through the per-pod CPU
    oracle with the coalescing window ON."""
    a = _fleet_arm(False, n_watchers, seed_pods, churn_ops, http_watchers,
                   selector_watchers, n_informers, pump_threads,
                   coalesce_window_s, seed, slo_probe=False)
    print(f"# watch-fleet A: {a['fanout_events_per_s']} ev/s "
          f"wall={a['wall_s']}s equiv={a['equiv']}", file=sys.stderr)
    b = _fleet_arm(True, n_watchers, seed_pods, churn_ops, http_watchers,
                   selector_watchers, n_informers, pump_threads,
                   coalesce_window_s, seed, slo_probe=True)
    print(f"# watch-fleet B: {b['fanout_events_per_s']} ev/s "
          f"wall={b['wall_s']}s equiv={b['equiv']} slo={b['slo']}",
          file=sys.stderr)
    ratio = (round(b["fanout_events_per_s"] / a["fanout_events_per_s"], 2)
             if a["fanout_events_per_s"] else None)

    parity_block = None
    if parity:
        print("# watch-fleet: north-preset oracle parity with coalescing on",
              file=sys.stderr)
        r = run_churn(5_000, 20_000, 10, seed=seed, verify_oracle=True,
                      coalesce=coalesce_window_s)
        parity_block = dict(r["oracle_parity"],
                            coalesce_window_s=coalesce_window_s,
                            pods_per_sec=r["pods_per_sec"])

    mism = (a["equiv"]["mismatches"] + b["equiv"]["mismatches"]
            + a["selector"]["mismatches"] + b["selector"]["mismatches"]
            + a["selector"]["non_matching_keys"]
            + b["selector"]["non_matching_keys"])
    gapped = a["equiv"]["gapped"] + b["equiv"]["gapped"]
    slo_ok = bool(b["slo"] and b["slo"]["breached"] and b["slo"]["recovered"]
                  and b["slo"]["breach_dump_top_laggards"] > 0)
    verdict = {
        "pass": bool(ratio is not None and ratio >= 3.0 and mism == 0
                     and gapped == 0 and slo_ok
                     and (parity_block is None
                          or parity_block["mismatches"] == 0)),
        "fanout_ratio_B_over_A": ratio,
        "min_ratio": 3.0,
        "state_mismatches": mism,
        "dropped_state_clients": gapped,
        "slo_burned_and_recovered": slo_ok,
        "oracle_parity_mismatches": (parity_block["mismatches"]
                                     if parity_block else None),
    }
    return {
        "claim": ("Heavy-traffic serving tier: a bounded time-window "
                  "coalescing seam at the broadcaster (per-key latest-wins "
                  "folds into synthetic watch frames), column-level "
                  "selector sub-frames, and single-encode fan-out — "
                  "measured as logical fan-out throughput against a "
                  "kubemark-style hollow-watcher fleet"),
        "method": (f"{n_watchers} hollow in-process watchers + "
                   f"{http_watchers} HTTP stream clients "
                   f"(+{selector_watchers} selector watchers, "
                   f"{n_informers} compact_on_resync informers) on one "
                   f"store; {churn_ops} single-object churn ops over "
                   f"{seed_pods} seeded pods; both arms same seeds, same "
                   "pump pool; throughput is logical fan-out (churn_ops x "
                   "full clients / drain wall); equivalence gates every "
                   "client's final cache against the store; the B arm "
                   "additionally stalls the pumps to burn and recover the "
                   "per-CLIENT staleness SLO"),
        "watchers": {"hollow": n_watchers, "http": http_watchers,
                     "selector": selector_watchers,
                     "informers": n_informers},
        "churn": {"seed_pods": seed_pods, "ops": churn_ops,
                  "coalesce_window_s": coalesce_window_s},
        "A": a,
        "B": b,
        "oracle_parity_coalesced": parity_block,
        "verdict": verdict,
    }


def run_loop_ab(n_nodes: int = 5_000, total_pods: int = 20_000,
                waves: int = 10, pairs: int = 2, seed: int = 0) -> dict:
    """Both-orders interleaved A/B of the device-resident wave loop
    (ISSUE 11): B (new) = the chunked frontier scan driven as ONE
    ``lax.while_loop`` dispatch per segment (donated carries, on-device
    compaction flag, all-G ``still_ok`` refresh at chunk boundaries);
    A (old) = the chunked HOST loop (one blocking sync per chunk), same
    frontier plane, same harness, same seeds.  The first pair replays
    both arms' recorded drain batches through the per-pod CPU oracle
    (off-clock) and reports per-wave binding parity.  An off-clock
    chunk-width sweep (512 → 128, a 4x chunk-count increase) records
    per-wave ``host_syncs`` for both modes — the loop's must stay flat
    (O(compactions + 1)) while the host loop's grow with chunk count.
    Writes the BENCH_AB_device_loop.json ledger shape (the recorded
    ledger uses the worktree method; this flag A/B isolates the loop
    seam on one tree)."""
    run_churn(n_nodes, 2 * (total_pods // waves), 2, seed=seed + 1,
              warmup=False, device_loop=True)
    run_churn(n_nodes, 2 * (total_pods // waves), 2, seed=seed + 1,
              warmup=False, device_loop=False)

    parity = {}
    syncs_first = {}

    def one(loop: bool, verify: bool = False) -> dict:
        r = run_churn(n_nodes, total_pods, waves, seed=seed, warmup=False,
                      device_loop=loop, verify_oracle=verify)
        if verify:
            parity["loop" if loop else "chunked_host"] = r["oracle_parity"]
            syncs_first["loop" if loop else "chunked_host"] = r["host_syncs"]
        return r

    ab_pairs, ba_pairs = [], []
    a_all, b_all = [], []
    bounds = set()
    for i in range(pairs):
        b = one(True, verify=(i == 0))
        a = one(False, verify=(i == 0))
        ab_pairs.append({"B_new": b["pods_per_sec"], "A_old": a["pods_per_sec"]})
        b_all.append(b["pods_per_sec"])
        a_all.append(a["pods_per_sec"])
        bounds.update((a["bound"], b["bound"]))
        print(f"# ab-loop AB: B={b['pods_per_sec']} A={a['pods_per_sec']} "
              f"syncs B={b['host_syncs']['total']} "
              f"A={a['host_syncs']['total']}", file=sys.stderr)
    for _ in range(pairs):
        a = one(False)
        b = one(True)
        ba_pairs.append({"A_old": a["pods_per_sec"], "B_new": b["pods_per_sec"]})
        a_all.append(a["pods_per_sec"])
        b_all.append(b["pods_per_sec"])
        bounds.update((a["bound"], b["bound"]))
        print(f"# ab-loop BA: A={a['pods_per_sec']} B={b['pods_per_sec']}",
              file=sys.stderr)
    # off-clock sync-scaling sweep: same workload, chunk 512 then 128
    # (4x the chunks per segment) in both modes — the recorded per-wave
    # host_syncs are the O(compactions + 1) flatness evidence
    sync_scaling = {}
    for label, loop_on, chunk in (("loop_chunk512", True, 512),
                                  ("loop_chunk128", True, 128),
                                  ("chunked_chunk512", False, 512),
                                  ("chunked_chunk128", False, 128)):
        r = run_churn(n_nodes, total_pods, waves, seed=seed, warmup=False,
                      device_loop=loop_on, frontier_chunk=chunk)
        sync_scaling[label] = {
            "per_wave_host_syncs": r["host_syncs"]["per_wave"],
            "total_host_syncs": r["host_syncs"]["total"],
            "segments": r["frontier"]["segments"],
            "compactions": r["frontier"]["compactions"],
        }
        print(f"# ab-loop sweep {label}: total={r['host_syncs']['total']} "
              f"per_wave={r['host_syncs']['per_wave']}", file=sys.stderr)
    a_med = sorted(a_all)[len(a_all) // 2]
    b_med = sorted(b_all)[len(b_all) // 2]
    won = sum(1 for p in ab_pairs + ba_pairs if p["B_new"] > p["A_old"])
    return {
        "claim": ("Device-resident wave loop: the chunked frontier scan "
                  "runs as ONE lax.while_loop dispatch per segment with "
                  "donated ScanState carries, an on-device compaction "
                  "flag (host re-entered only when a compaction fires), "
                  "and the all-G still_ok refresh at chunk boundaries — "
                  "host syncs per wave drop from O(chunks) to "
                  "O(compactions + 1)"),
        "method": (f"Churn {n_nodes} nodes / {total_pods} mixed pods / "
                   f"{waves} waves, arrival thread + run_batch_loop serving "
                   "(both arms), events on; interleaved pairs in BOTH "
                   "orders, one shared process, per-arm warm-up compiles "
                   "paid up front; A = chunked host loop (device_loop off, "
                   "pre-ISSUE-11), B = device-resident while_loop; first "
                   "pair of each arm replayed off-clock through the "
                   "per-pod CPU oracle per drained wave; off-clock chunk "
                   "sweep 512/128 records host-sync scaling in both modes"),
        "pairs_order_AB_first": ab_pairs,
        "pairs_order_BA_first": ba_pairs,
        "A_old_all": a_all,
        "B_new_all": b_all,
        "A_median": a_med,
        "B_median": b_med,
        "win_pct": round((b_med - a_med) / a_med * 100, 1) if a_med else None,
        "b_won_pairs": f"{won}/{len(ab_pairs) + len(ba_pairs)} (both orders)",
        "bound_counts": sorted(bounds),
        "oracle_parity": parity,
        "host_syncs_first_run": syncs_first,
        "host_sync_scaling": sync_scaling,
    }


def run_trace_ab(n_nodes: int = 5_000, total_pods: int = 20_000,
                 waves: int = 10, pairs: int = 2, seed: int = 0) -> dict:
    """Both-orders interleaved A/B pricing the wave tracer (ISSUE 7):
    A = tracing disabled (the production default — instrumented sites
    cost one global load + None check), B = tracer + flight recorder
    ENABLED for the whole timed run.  This is an overhead PRICE report,
    not a win claim: ``win_pct`` is the measured cost of enabling (≈0
    means the enabled path is free too; the DISABLED path's "within
    noise of pre-PR" claim uses the worktree ledger, not this flag A/B,
    because the instrumentation exists in both arms here)."""
    run_churn(n_nodes, 2 * (total_pods // waves), 2, seed=seed + 1,
              warmup=False)

    def one(traced: bool) -> dict:
        return run_churn(n_nodes, total_pods, waves, seed=seed,
                         warmup=False, trace=traced)

    ab_pairs, ba_pairs = [], []
    a_all, b_all = [], []
    trace_stats = []
    bounds = set()
    for i in range(pairs):
        b = one(True)
        a = one(False)
        ab_pairs.append({"B_on": b["pods_per_sec"], "A_off": a["pods_per_sec"]})
        b_all.append(b["pods_per_sec"])
        a_all.append(a["pods_per_sec"])
        trace_stats.append(b["trace"])
        bounds.update((a["bound"], b["bound"]))
        print(f"# ab-trace AB: on={b['pods_per_sec']} off={a['pods_per_sec']} "
              f"events={b['trace']['events']}", file=sys.stderr)
    for _ in range(pairs):
        a = one(False)
        b = one(True)
        ba_pairs.append({"A_off": a["pods_per_sec"], "B_on": b["pods_per_sec"]})
        a_all.append(a["pods_per_sec"])
        b_all.append(b["pods_per_sec"])
        trace_stats.append(b["trace"])
        bounds.update((a["bound"], b["bound"]))
        print(f"# ab-trace BA: off={a['pods_per_sec']} on={b['pods_per_sec']}",
              file=sys.stderr)
    a_med = sorted(a_all)[len(a_all) // 2]
    b_med = sorted(b_all)[len(b_all) // 2]
    return {
        "claim": ("Wave tracing + flight recorder: per-wave span trees, "
                  "store-txn correlation ids, dump-on-fault — priced "
                  "ENABLED vs disabled on the same tree (the disabled "
                  "path's no-regression claim is the worktree ledger)"),
        "method": (f"Churn {n_nodes} nodes / {total_pods} mixed pods / "
                   f"{waves} waves, arrival thread + run_batch_loop serving "
                   "(both arms), events on; interleaved pairs in BOTH "
                   "orders, one shared process, warm-up compiles paid up "
                   "front; A = tracing disabled, B = tracer + flight "
                   "recorder enabled for the whole timed run"),
        "pairs_order_AB_first": ab_pairs,
        "pairs_order_BA_first": ba_pairs,
        "A_off_all": a_all,
        "B_on_all": b_all,
        "A_median": a_med,
        "B_median": b_med,
        # the sign convention matches the other ledgers (B vs A), so a
        # NEGATIVE value here is the enabled-tracing slowdown
        "win_pct": round((b_med - a_med) / a_med * 100, 1) if a_med else None,
        "bound_counts": sorted(bounds),
        "trace_stats": trace_stats,
    }


def run_telemetry_ab(n_nodes: int = 5_000, total_pods: int = 20_000,
                     waves: int = 10, pairs: int = 2, seed: int = 0) -> dict:
    """Both-orders interleaved A/B pricing continuous telemetry (ISSUE
    13): A = scraper/monitor/shipper disabled (the production default —
    producer sites cost one global load + None check), B = the full
    stack ENABLED for the whole timed run: 0.25 s scrape cadence over
    the scheduler registry, burn-rate evaluation of the standing SLOs
    on every scrape, and the shipper draining every scrape delta through
    a devnull file sink.  Like ``--ab-trace`` this is an overhead PRICE
    report, not a win claim: the DISABLED path's "within noise of
    pre-PR" claim is the worktree ledger
    (BENCH_AB_telemetry_overhead.json), because the instrumentation
    exists in both arms here."""
    run_churn(n_nodes, 2 * (total_pods // waves), 2, seed=seed + 1,
              warmup=False)

    def one(enabled: bool) -> dict:
        return run_churn(n_nodes, total_pods, waves, seed=seed,
                         warmup=False, telemetry=enabled)

    ab_pairs, ba_pairs = [], []
    a_all, b_all = [], []
    telemetry_stats = []
    bounds = set()
    for _ in range(pairs):
        b = one(True)
        a = one(False)
        ab_pairs.append({"B_on": b["pods_per_sec"], "A_off": a["pods_per_sec"]})
        b_all.append(b["pods_per_sec"])
        a_all.append(a["pods_per_sec"])
        telemetry_stats.append(b["telemetry"])
        bounds.update((a["bound"], b["bound"]))
        print(f"# ab-telemetry AB: on={b['pods_per_sec']} "
              f"off={a['pods_per_sec']} "
              f"scrapes={b['telemetry']['scrapes']}", file=sys.stderr)
    for _ in range(pairs):
        a = one(False)
        b = one(True)
        ba_pairs.append({"A_off": a["pods_per_sec"], "B_on": b["pods_per_sec"]})
        a_all.append(a["pods_per_sec"])
        b_all.append(b["pods_per_sec"])
        telemetry_stats.append(b["telemetry"])
        bounds.update((a["bound"], b["bound"]))
        print(f"# ab-telemetry BA: off={a['pods_per_sec']} "
              f"on={b['pods_per_sec']}", file=sys.stderr)
    a_med = sorted(a_all)[len(a_all) // 2]
    b_med = sorted(b_all)[len(b_all) // 2]
    return {
        "claim": ("Continuous telemetry: registry scraper + burn-rate "
                  "SLO monitor + off-box shipper — priced ENABLED vs "
                  "disabled on the same tree (the disabled path's "
                  "no-regression claim is the worktree ledger)"),
        "method": (f"Churn {n_nodes} nodes / {total_pods} mixed pods / "
                   f"{waves} waves, arrival thread + run_batch_loop "
                   "serving (both arms), events on; interleaved pairs in "
                   "BOTH orders, one shared process, warm-up compiles "
                   "paid up front; A = telemetry disabled, B = scraper "
                   "(0.25 s cadence) + SLO monitor + devnull shipper "
                   "enabled for the whole timed run"),
        "pairs_order_AB_first": ab_pairs,
        "pairs_order_BA_first": ba_pairs,
        "A_off_all": a_all,
        "B_on_all": b_all,
        "A_median": a_med,
        "B_median": b_med,
        # sign convention matches the other ledgers (B vs A): a NEGATIVE
        # value here is the enabled-telemetry slowdown
        "win_pct": round((b_med - a_med) / a_med * 100, 1) if a_med else None,
        "bound_counts": sorted(bounds),
        "telemetry_stats": telemetry_stats,
    }


def run_preemption(n_nodes: int = 2_000) -> dict:
    """Priority-preemption workload (VERDICT r4 directive 6: measure
    preemption cost at all).  Saturate every node's CPU with priority-0
    fillers, then flood one batch of priority-100 preemptors that each
    need a victim evicted: the batch fails wholesale, the cohort
    PostFilter (scheduler._preempt_cohort — prefilter kernel + exact
    reprieve on the survivors) evicts minimal victim sets, and the next
    batch binds every preemptor into the freed space.

    Reports preemption throughput and per-attempt latency; parity of the
    decisions themselves is pinned by tests/test_preemption_batch.py's
    oracle table."""
    from kubernetes_tpu.client import Clientset
    from kubernetes_tpu.ops import TPUBatchBackend
    from kubernetes_tpu.scheduler import GenericScheduler, Scheduler
    from kubernetes_tpu.store import Store
    from kubernetes_tpu.testutil import make_node, make_pod

    n_fillers = 4 * n_nodes  # 4 x 2cpu fills each 8-cpu node
    n_preemptors = n_nodes // 2
    cs = Clientset(Store(event_log_window=max(200_000, 4 * (n_nodes + n_fillers))))
    for i in range(n_nodes):
        cs.nodes.create(make_node(
            f"node-{i:05d}", cpu="8", memory="32Gi", pods=110,
            labels={"kubernetes.io/hostname": f"node-{i:05d}",
                    ZONE: f"zone-{i % 3}"}))
    algo = GenericScheduler()
    sched = Scheduler(cs, algorithm=algo,
                      backend=TPUBatchBackend(algorithm=algo),
                      emit_events=True)
    sched.start()
    sched.broadcaster.start()
    for i in range(n_fillers):
        cs.pods.create(make_pod(f"filler-{i:06d}", cpu="2", memory="256Mi",
                                labels={"app": "filler"}))
    sched.pump()
    sched.schedule_pending_batch()
    for i in range(n_preemptors):
        p = make_pod(f"vip-{i:06d}", cpu="2", memory="256Mi",
                     labels={"app": "vip"})
        p.spec.priority = 100
        cs.pods.create(p)
    sched.pump()
    t0 = time.perf_counter()
    sched.schedule_pending_batch()  # fails -> cohort preemption
    preempt_elapsed = time.perf_counter() - t0
    m = sched.metrics
    # snapshot the counters HERE: the freed-space batch may run its own
    # cohort for stragglers, and those attempts are outside the window
    attempts = m.preemption_attempts.value
    victims = m.preemption_victims.value
    sched.pump()
    bound_after, _ = sched.schedule_pending_batch()  # into freed space
    total_elapsed = time.perf_counter() - t0
    sched.broadcaster.stop(drain=True)

    def _pq(h, q):
        v = h.quantile(q)
        return round(v / 1e3, 3) if v != float("inf") else None

    return {
        "nodes": n_nodes,
        "preemptors": n_preemptors,
        "attempts": attempts,
        "victims": victims,
        "preemptor_bound_after": bound_after,
        "preemptions_per_sec": round(attempts / preempt_elapsed, 1)
        if preempt_elapsed > 0 else 0.0,
        "e2e_preempt_and_bind_s": round(total_elapsed, 3),
        "preemption_latency_ms": {"p50": _pq(m.preemption_latency, 0.5),
                                  "p99": _pq(m.preemption_latency, 0.99)},
    }


def run_overload(n_nodes: int = 320, surge_mult: float = 3.0,
                 surge_pods_cap: int = 60_000, max_surge_s: float = 20.0,
                 goodput_deadline_s: float = 5.0, seed: int = 0,
                 fast_window_s: float = 0.5, slow_window_s: float = 1.5,
                 step_hold_s: float = 0.5) -> dict:
    """Overload-control surge bench (ISSUE 17): drive arrivals at
    ``surge_mult``x the measured drain capacity through the apiserver's
    create path and record what the degradation ladder does about it.

    Phases:

    1. **calibrate** — two direct-store batches through the serving loop
       (the first warms the wave-shape compiles); the second's rate is
       the drain capacity every other number is relative to.
    2. **surge** — three arrival threads (batch prio 0 / standard 5 /
       critical 9, at 50/30/20%) pace paced batch-creates through
       per-tier ``RemoteStore`` clients at ``surge_mult``x capacity.
       The ladder engages off the queue-depth gauge; rung 3 throttles
       the batch tier at the apiserver (429 + Retry-After, honored by
       the client, rejected when the budget runs out).  Per-pod e2e is
       stamped create-attempt -> bind (the wave-relative e2e histogram
       can't see queue backlog or throttle delay).
    3. **recover** — arrivals stop; the backlog drains; the run clocks
       how long the ladder takes to walk back to rung 0 (the gauge SLI
       keeps sampling at zero traffic, so recovery needs no probes).
    4. **steady-state parity** — a tail batch binds at rung 0 and is
       replayed through the per-pod CPU oracle seeded with the live
       world's bound state AND its select_host tie counter (scores are
       fixed-point integers, so ties are routine and the rotation
       offset matters), so the tail must match the oracle exactly —
       occupancy invariants are the verdict gate, the exact map rides
       along as evidence.

    The verdict block gates: ladder engaged (rung > 0), top-tier p99
    and goodput strictly better than the batch tier's, full recovery
    to rung 0, and post-recovery occupancy parity."""
    import threading

    from kubernetes_tpu.apiserver import APIServer
    from kubernetes_tpu.client import Clientset
    from kubernetes_tpu.client.remote import RemoteStore, RetryExhaustedError
    from kubernetes_tpu.ops import TPUBatchBackend
    from kubernetes_tpu.scheduler import GenericScheduler, Scheduler
    from kubernetes_tpu.store import Store
    from kubernetes_tpu.testutil import make_node, make_pod
    from kubernetes_tpu.utils import timeseries as timeseries_mod
    from kubernetes_tpu.utils.overload import (AdmissionThrottle,
                                               DegradationLadder,
                                               overload_slos)

    store = Store(event_log_window=400_000)
    server = APIServer(store)
    server.start()
    cs = Clientset(store)
    # distinct memories do NOT break score ties (scores are fixed-point
    # integers); exact replay instead relies on seeding the oracle with
    # the live select_host tie counter, captured at tail time below.
    # Generous per-node pod caps stretch the slot budget so the surge
    # can outlast the SLO windows even at high drain rates.
    pods_per_node = 200
    for i in range(n_nodes):
        cs.nodes.create(make_node(
            f"node-{i:05d}", cpu="8", memory=f"{16_384 + i}Mi",
            pods=pods_per_node,
            labels={"kubernetes.io/hostname": f"node-{i:05d}",
                    ZONE: f"zone-{i % 3}"}))
    algo = GenericScheduler()
    sched = Scheduler(cs, algorithm=algo,
                      backend=TPUBatchBackend(algorithm=algo),
                      emit_events=False)
    sched.start()

    t_create: dict[str, float] = {}
    t_bind: dict[str, float] = {}
    rejected: set[str] = set()
    drain_batches: list[list[str]] = []
    orig_drain = sched.queue.drain

    def recording_drain(max_n=None):
        out = orig_drain(max_n)
        if out:
            drain_batches.append([p.meta.name for p in out])
        return out

    sched.queue.drain = recording_drain
    orig_spb = sched.schedule_pending_batch

    def stamping_spb(max_batch=None):
        # probe only the pods this wave drained (a full list() per wave
        # holds the store lock long enough to starve the HTTP handlers
        # and the arrival threads behind them); failed pods re-queue and
        # get re-probed when a later wave re-drains them
        mark = len(drain_batches)
        r = orig_spb(max_batch)
        now = time.perf_counter()
        for batch in drain_batches[mark:]:
            for n in batch:
                if n in t_bind:
                    continue
                p = cs.pods.get(n)
                if p is not None and p.spec.node_name:
                    t_bind[n] = now
        return r

    sched.schedule_pending_batch = stamping_spb

    stop = threading.Event()
    max_batch = 384
    serve = threading.Thread(
        target=lambda: sched.run_batch_loop(
            min_batch=32, max_wait=0.05, poll_interval=0.002,
            max_batch=max_batch, stop=stop),
        daemon=True)
    serve.start()

    def _tmpl(name, prio=0):
        p = make_pod(name, cpu="10m", memory="16Mi")
        if prio:
            p.spec.priority = prio
        return p

    def _wait_all_bound(names, timeout):
        deadline = time.perf_counter() + timeout
        while time.perf_counter() < deadline:
            if all(n in t_bind for n in names):
                return True
            time.sleep(0.02)
        return False

    try:
        # -- phase 1: calibrate drain capacity (first batch warms XLA) --
        cal_rate = None
        for attempt in range(2):
            names = [f"cal{attempt}-{i:05d}" for i in range(768)]
            t0 = time.perf_counter()
            for n in names:
                t_create[n] = t0
            cs.pods.create_many_nowait([_tmpl(n) for n in names])
            assert _wait_all_bound(names, 120), "calibration never drained"
            cal_rate = len(names) / (max(t_bind[n] for n in names) - t0)
        print(f"# overload: drain capacity {cal_rate:.0f} pods/s",
              file=sys.stderr)

        # -- wire the ladder + throttle (absent during calibration) -----
        pending_threshold = max(32.0, cal_rate * 0.5)
        ts_store = timeseries_mod.enable(sched.metrics.registry,
                                         interval_s=0.1, capacity=4_096)
        ladder = DegradationLadder(
            slos=overload_slos(pending_threshold=pending_threshold,
                               fast_window_s=fast_window_s,
                               slow_window_s=slow_window_s,
                               recovery_evals=2),
            step_hold_s=step_hold_s, recover_hold_s=1.0)
        sched.attach_overload(ladder)
        ladder.attach(ts_store)
        server.admission_throttle = AdmissionThrottle(ladder,
                                                      retry_after_s=0.75)

        # -- phase 2: the surge ----------------------------------------
        # sized from a DURATION target, not a pod count: the gauge SLI
        # only breaches once the windowed means sustain past the slow
        # window plus the step holds, so a pod cap that silently
        # shortens the surge below that never engages the ladder.  The
        # per-node pod cap bounds how many arrivals can ever bind (the
        # calibration pods and the tail are already on the nodes).
        arrival_rate = surge_mult * cal_rate
        slot_budget = n_nodes * pods_per_node - 2 * 768 - 600
        surge_s_target = min(max_surge_s, slot_budget / arrival_rate)
        surge_pods = min(surge_pods_cap,
                         max(900, int(arrival_rate * surge_s_target)))
        print(f"# overload: surge {surge_pods} pods @ {arrival_rate:.0f}"
              f"/s (~{surge_pods / arrival_rate:.1f}s, slow window"
              f" {slow_window_s}s)", file=sys.stderr)
        tiers = {
            "batch": dict(prio=0, frac=0.5),
            "standard": dict(prio=5, frac=0.3),
            "critical": dict(prio=9, frac=0.2),
        }
        clients = {}
        per_tier_chunks = {}
        for tname, cfg in tiers.items():
            n = int(surge_pods * cfg["frac"])
            rs = RemoteStore(
                server.url, max_retries=2, retry_backoff=0.05,
                retry_backoff_max=1.0, retry_seed=seed + cfg["prio"])
            clients[tname] = rs
            rcs = Clientset(rs)
            pods = [_tmpl(f"{tname}-{i:05d}", cfg["prio"]) for i in range(n)]
            cfg["names"] = [p.meta.name for p in pods]
            per_tier_chunks[tname] = (rcs, [pods[i:i + 25]
                                            for i in range(0, n, 25)])
        # largest-deficit interleave: one shared chunk schedule keeps
        # the tier mix constant across the whole surge.  Per-tier
        # arrival threads don't — the un-throttled tiers flood in
        # early and eat the deepest backlog while the throttled tier's
        # retry sleeps push its pods into the drained aftermath, which
        # INVERTS the ordering the throttle exists to produce.
        schedule = []
        emitted = {t: 0 for t in tiers}
        total_chunks = sum(len(c) for _, c in per_tier_chunks.values())
        for k in range(total_chunks):
            pick = max(
                (t for t in tiers if emitted[t] < len(per_tier_chunks[t][1])),
                key=lambda t: tiers[t]["frac"] * (k + 1) - emitted[t])
            rcs, chunks = per_tier_chunks[pick]
            schedule.append((rcs, chunks[emitted[pick]]))
            emitted[pick] += 1
        next_idx = [0]
        idx_lock = threading.Lock()
        surge_t0 = time.perf_counter()

        def worker():
            while True:
                with idx_lock:
                    k = next_idx[0]
                    if k >= len(schedule):
                        return
                    next_idx[0] = k + 1
                rcs, chunk = schedule[k]
                target = surge_t0 + (k * 25) / arrival_rate
                now = time.perf_counter()
                if target > now:
                    time.sleep(target - now)
                stamp = time.perf_counter()
                for p in chunk:
                    t_create[p.meta.name] = stamp
                try:
                    rcs.pods.create_many(chunk)
                except RetryExhaustedError:
                    # throttled past the retry budget: load shed
                    for p in chunk:
                        rejected.add(p.meta.name)

        threads = [threading.Thread(target=worker, daemon=True)
                   for _ in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
        surge_end = time.perf_counter()

        # -- phase 3: recovery -----------------------------------------
        recovery_s = None
        deadline = surge_end + 180
        while time.perf_counter() < deadline:
            if ladder.rung == 0 and len(sched.queue) == 0:
                recovery_s = round(time.perf_counter() - surge_end, 2)
                break
            time.sleep(0.05)
        accepted = [n for cfg in tiers.values() for n in cfg["names"]
                    if n not in rejected]
        _wait_all_bound(accepted, 60)

        # -- phase 4: post-recovery steady state + oracle replay -------
        tail_mark = len(drain_batches)
        # all scores are fixed-point integers, so ties are common and
        # select_host rotates through them with a persistent counter
        # (reference lastNodeIndex).  The oracle must start its replay
        # from the live counter or every tied choice lands one rotation
        # off — captured here, before the tail waves advance it.
        rr_at_tail = algo._round_robin
        tail_names = [f"tail-{i:05d}" for i in range(300)]
        t0 = time.perf_counter()
        for n in tail_names:
            t_create[n] = t0
        cs.pods.create_many_nowait([_tmpl(n) for n in tail_names])
        tail_bound = _wait_all_bound(tail_names, 60)
        pods_live, _ = cs.pods.list()
        live_map = {p.meta.name: p.spec.node_name for p in pods_live}
    finally:
        stop.set()
        sched.queue.close()
        serve.join(timeout=30)
        timeseries_mod.disable()
        server.stop()

    # oracle replay of the tail waves over the live pre-tail state
    cs_o = Clientset(Store())
    for i in range(n_nodes):
        cs_o.nodes.create(make_node(
            f"node-{i:05d}", cpu="8", memory=f"{16_384 + i}Mi",
            pods=pods_per_node,
            labels={"kubernetes.io/hostname": f"node-{i:05d}",
                    ZONE: f"zone-{i % 3}"}))
    tail_set = set(tail_names)
    prebound = [(n, node) for n, node in live_map.items()
                if node and n not in tail_set]
    cs_o.pods.create_many_nowait(
        [make_pod(n, cpu="10m", memory="16Mi", node_name=node)
         for n, node in prebound])
    algo_o = GenericScheduler()
    algo_o._round_robin = rr_at_tail
    sched_o = Scheduler(cs_o, algorithm=algo_o, emit_events=False)
    sched_o.start()
    for batch in drain_batches[tail_mark:]:
        cs_o.pods.create_many_nowait(
            [_tmpl(n) for n in batch if n in tail_set])
        sched_o.pump()
        sched_o.run_pending()
    pods_o, _ = cs_o.pods.list()
    oracle_tail = {p.meta.name: p.spec.node_name for p in pods_o
                   if p.meta.name in tail_set}
    live_tail = {n: live_map.get(n) for n in tail_names}
    tail_counts = collections.Counter(live_tail.values())
    oracle_counts = collections.Counter(oracle_tail.values())
    occupancy_parity = (tail_bound and all(live_tail.values())
                        and tail_counts == oracle_counts)
    exact_parity = live_tail == oracle_tail

    def _tier_stats(cfg):
        names = cfg["names"]
        e2e = sorted(t_bind[n] - t_create[n] for n in names if n in t_bind)
        good = sum(1 for n in names
                   if n in t_bind
                   and t_bind[n] - t_create[n] <= goodput_deadline_s)
        return {
            "arrivals": len(names),
            "rejected": sum(1 for n in names if n in rejected),
            "bound": len(e2e),
            "goodput": round(good / max(len(names), 1), 4),
            "e2e_ms": {
                "p50": round(e2e[len(e2e) // 2] * 1e3, 1) if e2e else None,
                "p99": round(e2e[int(len(e2e) * 0.99)] * 1e3, 1)
                if e2e else None,
            },
        }

    tier_stats = {t: _tier_stats(cfg) for t, cfg in tiers.items()}
    crit, batch = tier_stats["critical"], tier_stats["batch"]
    tier_p99_ok = (crit["e2e_ms"]["p99"] is not None
                   and batch["e2e_ms"]["p99"] is not None
                   and crit["e2e_ms"]["p99"] < batch["e2e_ms"]["p99"])
    verdict = {
        "ladder_engaged": ladder.max_rung_seen > 0,
        "max_rung": ladder.max_rung_seen,
        "reached_throttle_rung": ladder.max_rung_seen >= 3,
        "tier_p99_ok": tier_p99_ok,
        "tier_goodput_ok": crit["goodput"] > batch["goodput"],
        "recovered": recovery_s is not None,
        "recovery_s": recovery_s,
        "post_recovery_occupancy_parity": occupancy_parity,
        "post_recovery_exact_parity": exact_parity,
    }
    verdict["pass"] = all((
        verdict["ladder_engaged"], verdict["tier_p99_ok"],
        verdict["tier_goodput_ok"], verdict["recovered"],
        verdict["post_recovery_occupancy_parity"]))
    throttle = server.admission_throttle.stats()
    return {
        "nodes": n_nodes,
        "drain_capacity_pods_per_sec": round(cal_rate, 1),
        "surge_mult": surge_mult,
        "surge_pods": surge_pods,
        "surge_s": round(surge_end - surge_t0, 2),
        "pending_threshold": pending_threshold,
        "goodput_deadline_s": goodput_deadline_s,
        "tiers": tier_stats,
        "rung_timeline": [(round(t, 3), r) for t, r in ladder.history()],
        "transitions": ladder.transitions,
        "degradation_transitions_total":
            sched.metrics.degradation_transitions.value,
        "score_plane_sheds": sched.metrics.score_plane_sheds.value,
        "admission": {
            "admitted": throttle["admitted"],
            "throttled": throttle["throttled"],
            "throttled_by_tier": {str(k): v for k, v in
                                  throttle["throttled_by_tier"].items()},
            "server_throttled_total": server.admission_throttled.value,
            "retry_after_honored": {
                t: clients[t].metrics.retry_after_honored.value
                for t in tiers},
        },
        "tail": {
            "pods": len(tail_names),
            "bound": sum(1 for v in live_tail.values() if v),
            "exact_mismatches": sum(1 for n in tail_names
                                    if live_tail.get(n) != oracle_tail.get(n)),
        },
        "verdict": verdict,
    }


MULTICHIP_DEVICE_COUNTS = (1, 2, 4, 8)


def run_multichip_child(cfg: dict) -> dict:
    """One ``--multichip`` measurement in a FRESH process: force an
    ``n_devices``-way virtual CPU platform before jax initializes (the
    parent also sets ``XLA_FLAGS``/``JAX_PLATFORMS`` in the child env —
    belt and braces), run the churn harness with the sharded wave loop
    forced on (n >= 2; n = 1 is the single-device loop baseline), and
    report the parity / host-sync / upload-attribution evidence the
    ledger gates on.  One process per device count is mandatory: the
    device count is fixed at jax initialization."""
    from kubernetes_tpu.utils.platform import force_virtual_cpu

    nd = int(cfg["n_devices"])
    force_virtual_cpu(nd)
    r = run_churn(n_nodes=int(cfg["nodes"]), total_pods=int(cfg["pods"]),
                  waves=int(cfg["waves"]),
                  workload=cfg.get("workload", "mixed"),
                  seed=int(cfg.get("seed", 0)),
                  frontier_chunk=int(cfg.get("chunk", 128)),
                  verify_oracle=True, mesh=(nd > 1))
    par = r["oracle_parity"] or {}
    return {
        "n_devices": nd,
        "pods_per_sec": r["pods_per_sec"],
        "bound": r["bound"],
        "unbound": r["unbound"],
        "mesh": r["mesh"],
        "host_syncs": r["host_syncs"],
        "frontier": r["frontier"],
        "node_upload": r["node_upload"],
        "oracle_parity": {k: par.get(k) for k in (
            "mode", "checked", "mismatches", "round_robin",
            "round_robin_timed", "round_robin_match")},
        "per_wave_mesh": [p.get("mesh") for p in r["phase_timers"]],
    }


def run_multichip(device_counts=MULTICHIP_DEVICE_COUNTS, n_nodes: int = 512,
                  total_pods: int = 4_000, waves: int = 5, chunk: int = 128,
                  seed: int = 0) -> dict:
    """The sharded-wave-loop churn ledger (ISSUE 18): run the churn
    harness at each device count in ``device_counts`` — one subprocess
    each, with ``--xla_force_host_platform_device_count=N`` on the CPU
    backend — and gate a single verdict on what the sharded loop must
    preserve:

    - **per-wave oracle parity, exact**, at every shard count, including
      the select_host tie-rotation counter (the deterministic cross-shard
      tie-break's observable);
    - **host syncs O(compactions + 1)** per segment (<= 2 per segment +
      1 per compaction — dispatch and the loop-exit cursor read), never
      O(chunks), at every shard count;
    - **per-shard upload attribution** present on every >= 2-device
      config (shard count == device count, non-empty per-shard upload
      fractions, zero mesh-mode fallbacks).

    This graduates MULTICHIP from the compile-and-collective dryrun
    shapes of earlier rounds to a real sharded *churn* ledger: the full
    store -> informer -> backend -> bind path under the mesh."""
    import subprocess

    configs = []
    for nd in device_counts:
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = (
            env.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={nd}").strip()
        cfg = {"n_devices": nd, "nodes": n_nodes, "pods": total_pods,
               "waves": waves, "chunk": chunk, "seed": seed}
        print(f"# multichip: {nd}-device child ({n_nodes} nodes x "
              f"{total_pods} pods x {waves} waves)", file=sys.stderr)
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--multichip-child", json.dumps(cfg)],
            env=env, capture_output=True, text=True, timeout=3_600)
        entry = {"n_devices": nd, "rc": proc.returncode,
                 "ok": proc.returncode == 0}
        if proc.returncode == 0:
            try:
                entry.update(json.loads(proc.stdout.strip().splitlines()[-1]))
            except (ValueError, IndexError) as e:
                entry["ok"] = False
                entry["tail"] = f"unparseable child stdout: {e}"
        else:
            entry["tail"] = proc.stderr[-2_000:]
        configs.append(entry)
        if entry["ok"]:
            par = entry["oracle_parity"]
            print(f"# multichip {nd}-device: {entry['pods_per_sec']} pods/s, "
                  f"parity {par['mismatches']}/{par['checked']} mismatches "
                  f"rr_match={par['round_robin_match']}, host_syncs="
                  f"{entry['host_syncs']['total']} (segments="
                  f"{entry['frontier']['segments']}, compactions="
                  f"{entry['frontier']['compactions']}), n_shards="
                  f"{entry['mesh']['n_shards']}", file=sys.stderr)
        else:
            print(f"# multichip {nd}-device: FAILED rc={entry['rc']}",
                  file=sys.stderr)

    def _gate(c: dict) -> list:
        if not c["ok"]:
            return ["child failed"]
        bad = []
        par = c["oracle_parity"]
        if par["mode"] != "exact per-wave replay" or par["mismatches"] != 0:
            bad.append("oracle parity not exact")
        if not par["round_robin_match"]:
            bad.append("rr tie counter diverged")
        fr = c["frontier"]
        if c["host_syncs"]["total"] > 2 * fr["segments"] + fr["compactions"]:
            bad.append("host syncs exceed O(compactions+1) budget")
        if c["n_devices"] >= 2:
            if c["mesh"]["n_shards"] != c["n_devices"]:
                bad.append("shard count != device count")
            if not c["node_upload"].get("shard_upload_fractions"):
                bad.append("no per-shard upload attribution")
            if "mesh" in fr["fallback_modes"]:
                bad.append("mesh-mode fallbacks fired")
        return bad

    failures = {str(c["n_devices"]): _gate(c) for c in configs}
    failures = {k: v for k, v in failures.items() if v}
    verdict = {
        "device_counts": list(device_counts),
        "parity_exact_all": all(
            c["ok"] and c["oracle_parity"]["mismatches"] == 0
            and c["oracle_parity"]["round_robin_match"] for c in configs),
        "host_sync_budget_all": all(
            c["ok"] and c["host_syncs"]["total"]
            <= 2 * c["frontier"]["segments"] + c["frontier"]["compactions"]
            for c in configs),
        "sharded_attribution_all": all(
            bool(c["ok"] and c["node_upload"].get("shard_upload_fractions")
                 and c["mesh"]["n_shards"] == c["n_devices"])
            for c in configs if c["n_devices"] >= 2),
        "failures": failures,
        "pass": not failures,
    }
    return {
        "claim": ("Sharded node axis: the device-resident wave loop runs "
                  "under shard_map over a 1-D node-axis mesh with in-loop "
                  "cross-shard reductions (psum/pmax alive + score "
                  "reduces, deterministic (score, global index) tie-break "
                  "with the cross-shard rotation prefix) — per-wave "
                  "bindings and the rr tie counter EXACT vs the CPU "
                  "oracle at every shard count, host syncs still "
                  "O(compactions + 1), per-shard upload attribution on "
                  "the node cache"),
        "method": (f"Churn {n_nodes} nodes / {total_pods} mixed pods / "
                   f"{waves} waves (arrival thread + run_batch_loop, "
                   f"events on, chunk {chunk}), one FRESH subprocess per "
                   f"device count {list(device_counts)} with "
                   "--xla_force_host_platform_device_count=N on the CPU "
                   "backend (mesh forced on at N >= 2; N = 1 is the "
                   "single-device loop baseline); every run's drained "
                   "waves replayed off-clock through the per-pod CPU "
                   "oracle"),
        "configs": configs,
        "verdict": verdict,
    }


PREFIX_PARITY_K = 2_000


def run_prefix_parity(backend_res: dict, n_nodes: int, n_pods: int,
                      workload: str, seed: int, k: int = PREFIX_PARITY_K) -> dict:
    """At-scale parity certification without at-scale oracle cost.

    Sequential-greedy is prefix-closed: pod i's placement depends only on
    the initial cluster and the pods scheduled before it (pending pods
    never influence predicates or priorities — only scheduled pods do).
    So the oracle replayed over just the FIRST ``k`` pods of the batch,
    in batch order, must match the kernel's first ``k`` assignments
    binding-for-binding.  This is exact, not statistical, and turns the
    north-scale "identical bindings" claim from extrapolated (certified
    at 10k) into certified at the timed scale itself.

    Batch order is the RECORDED queue-drain order of the timed run, not
    creation order (the queue is fed from the store's name-sorted LIST).
    A replay cluster holding exactly those ``k`` pods queues them in the
    same relative order — a restriction of a sorted sequence is sorted —
    so the oracle's ``k`` decisions are directly comparable.  Gates the
    exit code like the certify path
    (scheduler_perf/scheduler_test.go:83-88 fails, it doesn't just print).
    """
    from kubernetes_tpu.client import Clientset
    from kubernetes_tpu.scheduler import GenericScheduler, Scheduler
    from kubernetes_tpu.store import Store

    prefix_keys = backend_res["batch_order"][:k]
    rng = random.Random(seed)
    cs = Clientset(Store(event_log_window=max(200_000, 2 * (n_nodes + k))))
    for node in make_nodes(n_nodes, rng, workload):
        cs.nodes.create(node)
    if workload == "mixed":
        for svc in make_services():
            cs.services.create(svc)
    pods_by_key = {p.meta.key: p for p in make_pods(n_pods, rng, workload)}
    for key in prefix_keys:
        cs.pods.create(pods_by_key[key])
    sched = Scheduler(cs, algorithm=GenericScheduler(), backend=None)
    sched.start()
    t0 = time.perf_counter()
    bound = sched.run_pending()
    elapsed = time.perf_counter() - t0
    pods, _ = cs.pods.list()
    o = {p.meta.key: p.spec.node_name or None for p in pods}
    b = backend_res["assignments"]
    mismatches = [(key, o[key], b.get(key)) for key in o if o[key] != b.get(key)]
    return {
        "checked": len(o),
        "mismatches": len(mismatches),
        "sample": mismatches[:5],
        "oracle_pods_per_sec": round(bound / elapsed, 1) if elapsed > 0 else 0.0,
    }


def run_micro() -> dict:
    """Scheduler microbenchmark matrix (reference
    ``scheduler_perf/scheduler_bench_test.go:32-51``): latency of ONE
    ``Schedule()`` call over {100, 1000 nodes} x {0, 1000 scheduled
    pods}, for the CPU oracle, plus the TPU batch path's amortized
    per-pod cost at each cell (its per-call floor is the kernel launch,
    so the honest number is batched)."""
    from kubernetes_tpu.ops.backend import TPUBatchBackend
    from kubernetes_tpu.scheduler import GenericScheduler, PriorityContext
    from kubernetes_tpu.scheduler.nodeinfo import NodeInfo
    from kubernetes_tpu.testutil import make_node, make_pod

    results = {}
    for n_nodes in (100, 1000):
        for n_scheduled in (0, 1000):
            node_info_map = {}
            for i in range(n_nodes):
                node = make_node(
                    f"node-{i:04d}", cpu="32", memory="64Gi", pods=110,
                    labels={"kubernetes.io/hostname": f"node-{i:04d}",
                            ZONE: f"zone-{i % 3}"},
                )
                node_info_map[node.meta.name] = NodeInfo(node)
            for i in range(n_scheduled):
                pod = make_pod(f"sched-{i:05d}", cpu="100m", memory="128Mi",
                               labels={"app": "web"},
                               node_name=f"node-{i % n_nodes:04d}")
                node_info_map[pod.spec.node_name].add_pod(pod)
            algo = GenericScheduler()
            pctx = PriorityContext(node_info_map)
            probe = make_pod("probe", cpu="100m", memory="128Mi",
                             labels={"app": "web"})
            algo.schedule(probe, node_info_map, pctx)  # warm caches
            iters = 30 if n_nodes == 100 else 10
            t0 = time.perf_counter()
            for _ in range(iters):
                algo.schedule(probe, node_info_map, pctx)
            oracle_us = (time.perf_counter() - t0) / iters * 1e6

            # TPU path: amortized per-pod over a 1k-pod batch
            pending = [make_pod(f"p-{i:05d}", cpu="100m", memory="128Mi",
                                labels={"app": "web"}) for i in range(1000)]
            backend = TPUBatchBackend(algorithm=algo)
            backend.schedule_batch(pending, node_info_map, pctx)  # compile
            t0 = time.perf_counter()
            backend.schedule_batch(pending, node_info_map, pctx)
            tpu_us = (time.perf_counter() - t0) / len(pending) * 1e6
            key = f"{n_nodes}nodes/{n_scheduled}pods"
            results[key] = {"oracle_us_per_schedule": round(oracle_us, 1),
                            "tpu_us_per_pod_batched": round(tpu_us, 2)}
            print(f"# micro {key}: oracle {oracle_us:.0f}us/Schedule, "
                  f"tpu {tpu_us:.2f}us/pod (batched)", file=sys.stderr)
    return results


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--preset", choices=PRESETS, default="north")
    parser.add_argument("--nodes", type=int, default=None)
    parser.add_argument("--pods", type=int, default=None)
    parser.add_argument("--workload", choices=["plain", "mixed"], default=None)
    parser.add_argument("--events", dest="events", action="store_true", default=True,
                        help="emit Scheduled/FailedScheduling events on the timed run "
                        "(DEFAULT — the reference scheduler always emits them)")
    parser.add_argument("--no-events", dest="events", action="store_false")
    parser.add_argument("--trials", type=int, default=None,
                        help="timed-run repetitions; the MEDIAN is reported "
                        "with the min..max spread in the JSON (default 3 for "
                        "north, 1 otherwise) — this bench has ~±20%% "
                        "observed noise, a single trial proves nothing")
    parser.add_argument("--no-churn", dest="churn", action="store_false",
                        default=True,
                        help="skip the steady-state churn measurement that "
                        "rides along with the north preset")
    parser.add_argument("--no-preempt", dest="preempt", action="store_false",
                        default=True,
                        help="skip the priority-preemption workload that "
                        "rides along with the north preset")
    parser.add_argument("--no-certify", dest="certify", action="store_false",
                        default=True,
                        help="skip the default parity certification sub-run "
                        "(dense-mixed 1000 nodes x 10k pods vs the oracle)")
    parser.add_argument("--oracle", action="store_true", help="bench the CPU oracle path instead")
    parser.add_argument(
        "--parity",
        action="store_true",
        help="also run the sequential oracle over an identical cluster and "
        "assert identical bindings (reported in the JSON line)",
    )
    parser.add_argument(
        "--compare", action="store_true", help="also run the oracle and report speedup to stderr"
    )
    parser.add_argument(
        "--micro", action="store_true",
        help="Schedule()-latency matrix ({100,1000} nodes x {0,1000} pods)",
    )
    parser.add_argument(
        "--ab-churn", nargs="?", const="BENCH_AB_churn_pipeline.json",
        default=None, metavar="PATH",
        help="run the both-orders churn pipeline A/B (on vs off) and write "
        "the ledger JSON to PATH (default BENCH_AB_churn_pipeline.json); "
        "--nodes/--pods/--trials override scale and pair count",
    )
    parser.add_argument(
        "--ab-pump", nargs="?", const="BENCH_AB_pump_ingest.json",
        default=None, metavar="PATH",
        help="run the both-orders zero-copy-ingest A/B (lazy+columnar vs "
        "eager from_dict) and write the ledger JSON to PATH (default "
        "BENCH_AB_pump_ingest.json); --nodes/--pods/--trials override "
        "scale and pair count",
    )
    parser.add_argument(
        "--ab-frontier", nargs="?", const="BENCH_AB_frontier_scan.json",
        default=None, metavar="PATH",
        help="run the both-orders frontier-scan A/B (monotone prefilter + "
        "mid-segment node-axis compaction vs the full-width plain scan) "
        "and write the ledger JSON to PATH (default "
        "BENCH_AB_frontier_scan.json); --nodes/--pods/--trials override "
        "scale and pair count",
    )
    parser.add_argument(
        "--ab-watch", nargs="?", const="BENCH_AB_watch_frames.json",
        default=None, metavar="PATH",
        help="run the both-orders batched-watch-frames A/B (column-packed "
        "frames + one-lock batch apply + columnar confirm vs per-event "
        "delivery) and write the ledger JSON to PATH (default "
        "BENCH_AB_watch_frames.json); --nodes/--pods/--trials override "
        "scale and pair count",
    )
    parser.add_argument(
        "--ab-loop", nargs="?", const="BENCH_AB_device_loop.json",
        default=None, metavar="PATH",
        help="run the both-orders device-resident-wave-loop A/B "
        "(lax.while_loop with donated carries + on-device compaction "
        "decisions vs the chunked host loop) and write the ledger JSON "
        "to PATH (default BENCH_AB_device_loop.json); includes an "
        "off-clock chunk-width sweep recording host-sync scaling; "
        "--nodes/--pods/--trials override scale and pair count",
    )
    parser.add_argument(
        "--trace", nargs="?", const="BENCH_trace_churn.json",
        default=None, metavar="PATH",
        help="enable the wave tracer + flight recorder for the churn "
        "measurement and write its Chrome trace-event JSON to PATH "
        "(default BENCH_trace_churn.json); load into chrome://tracing "
        "or Perfetto",
    )
    parser.add_argument(
        "--ab-trace", nargs="?", const="BENCH_AB_trace_enabled.json",
        default=None, metavar="PATH",
        help="run the both-orders tracing-overhead A/B (tracer + flight "
        "recorder enabled vs disabled, same tree) and write the ledger "
        "JSON to PATH (default BENCH_AB_trace_enabled.json); a negative "
        "win_pct is the enabled-tracing slowdown — the disabled path's "
        "no-regression claim is the worktree ledger "
        "(BENCH_AB_trace_overhead.json); --nodes/--pods/--trials "
        "override scale and pair count",
    )
    parser.add_argument(
        "--telemetry", nargs="?", const="BENCH_telemetry_churn.ndjson",
        default=None, metavar="PATH",
        help="enable continuous telemetry for the churn measurement "
        "(time-series scraper + burn-rate SLO monitor + off-box "
        "shipper) and ship the run's records as JSON-lines to PATH "
        "(default BENCH_telemetry_churn.ndjson); the churn block gains "
        "per-SLO burn-rate verdicts, only quotable with the artifact "
        "behind them",
    )
    parser.add_argument(
        "--ab-telemetry", nargs="?",
        const="BENCH_AB_telemetry_enabled.json",
        default=None, metavar="PATH",
        help="run the both-orders telemetry-overhead A/B (scraper + SLO "
        "monitor + shipper enabled vs disabled, same tree) and write "
        "the ledger JSON to PATH (default "
        "BENCH_AB_telemetry_enabled.json); a negative win_pct is the "
        "enabled-telemetry slowdown — the disabled path's no-regression "
        "claim is the worktree ledger (BENCH_AB_telemetry_overhead."
        "json); --nodes/--pods/--trials override scale and pair count",
    )
    parser.add_argument(
        "--overload", nargs="?", const="BENCH_overload.json",
        default=None, metavar="PATH",
        help="run the overload-control surge bench (ISSUE 17): arrivals "
        "at 2-5x measured drain capacity through the apiserver, the "
        "degradation ladder engaging rung by rung, per-tier goodput/p99, "
        "post-surge recovery time, and a post-recovery oracle parity "
        "check; writes the artifact JSON to PATH (default "
        "BENCH_overload.json) — verdicts are only printed with the "
        "artifact behind them; --nodes overrides scale",
    )
    parser.add_argument(
        "--watch-fleet", nargs="?", const="BENCH_watch_fleet.json",
        default=None, metavar="PATH",
        help="run the hollow-watcher fleet bench (ISSUE 19): 10k+ "
        "concurrent watch clients against one broadcaster under churn, "
        "A/B-ing the serving tier (coalescing window + framed delivery "
        "+ single-encode fan-out vs per-event), with a zero-mismatch "
        "state-equivalence gate, the per-CLIENT staleness SLO burning "
        "and recovering mid-run, and a north-preset oracle-parity leg "
        "with coalescing on; writes the ledger JSON to PATH (default "
        "BENCH_watch_fleet.json) — verdicts only print with the "
        "artifact behind them",
    )
    parser.add_argument(
        "--fleet-watchers", type=int, default=10_000, metavar="N",
        help="hollow-watcher count for --watch-fleet (default 10000; "
        "the committed ledger requires >= 10000)",
    )
    parser.add_argument(
        "--fleet-no-parity", dest="fleet_parity", action="store_false",
        default=True,
        help="skip --watch-fleet's north-preset oracle-parity leg "
        "(minutes of churn) — fleet-only iteration",
    )
    parser.add_argument(
        "--multichip", nargs="?", const="MULTICHIP_churn.json",
        default=None, metavar="PATH",
        help="run the sharded-wave-loop churn ledger (ISSUE 18): the "
        "churn preset at 1/2/4/8 forced CPU devices (one subprocess "
        "each), gating per-wave oracle parity (incl. the rr tie "
        "counter), the O(compactions+1) host-sync budget, and per-shard "
        "upload attribution at every shard count; writes the ledger "
        "JSON to PATH (default MULTICHIP_churn.json) — verdicts are "
        "only printed with the artifact behind them; --nodes/--pods "
        "override scale",
    )
    parser.add_argument(
        "--multichip-child", default=None, metavar="JSON",
        help=argparse.SUPPRESS,  # internal: one forced-device-count run
    )
    parser.add_argument(
        "--overload-mult", type=float, default=3.0, metavar="X",
        help="surge arrival rate as a multiple of measured drain "
        "capacity for --overload (default 3.0; the verdict requires "
        ">= 2.0)",
    )
    args = parser.parse_args()

    if args.multichip_child is not None:
        # internal half of --multichip: ONE forced-device-count churn run
        # in this (fresh) process; the parent parses the JSON line below
        print(json.dumps(run_multichip_child(json.loads(args.multichip_child))))
        return

    if args.multichip is not None:
        import datetime

        kw = {}
        if args.nodes:
            kw["n_nodes"] = args.nodes
        if args.pods:
            kw["total_pods"] = args.pods
        ledger = run_multichip(**kw)
        ledger["date"] = datetime.date.today().isoformat()
        # the no-artifact-no-verdict guard (same contract as --overload
        # and --telemetry): if the JSON cannot be written, refuse to
        # print the verdict block and exit non-zero
        try:
            with open(args.multichip, "w") as f:
                json.dump(ledger, f, indent=1)
                f.write("\n")
        except OSError as e:
            print(f"# REFUSING to print multichip verdicts: artifact "
                  f"write to {args.multichip!r} failed ({e})",
                  file=sys.stderr)
            sys.exit(1)
        v = ledger["verdict"]
        print(json.dumps({
            "metric": "multichip-churn-verdict",
            "value": 1 if v["pass"] else 0,
            "unit": "pass",
            "vs_baseline": 1,
            "device_counts": v["device_counts"],
            "verdict": v,
            "artifact": args.multichip,
        }))
        sys.exit(0 if v["pass"] else 1)

    if args.watch_fleet is not None:
        import datetime

        ledger = run_watch_fleet(n_watchers=args.fleet_watchers,
                                 parity=args.fleet_parity)
        ledger["date"] = datetime.date.today().isoformat()
        # the no-artifact-no-verdict guard (same contract as --overload
        # and the A/B ledgers): if the JSON cannot be written, refuse to
        # print the verdict block and exit non-zero
        try:
            with open(args.watch_fleet, "w") as f:
                json.dump(ledger, f, indent=1)
                f.write("\n")
        except OSError as e:
            print(f"# REFUSING to print watch-fleet verdicts: artifact "
                  f"write to {args.watch_fleet!r} failed ({e})",
                  file=sys.stderr)
            sys.exit(1)
        v = ledger["verdict"]
        print(json.dumps({
            "metric": "watch-fleet-fanout-ratio",
            "value": v["fanout_ratio_B_over_A"],
            "unit": "x (B logical fan-out events/s vs A)",
            "vs_baseline": v["min_ratio"],
            "verdict": v,
            "artifact": args.watch_fleet,
        }))
        sys.exit(0 if v["pass"] else 1)

    if args.overload is not None:
        if args.overload_mult < 2.0:
            parser.error("--overload-mult must be >= 2.0 (the ladder "
                         "verdict is only meaningful past drain capacity)")
        res = run_overload(n_nodes=args.nodes or 320,
                           surge_mult=args.overload_mult)
        # the no-artifact-no-verdict guard (same contract as --telemetry
        # and the A/B ledgers): if the JSON cannot be written, refuse to
        # print the verdict block and exit non-zero — a quoted verdict
        # with nothing on disk behind it is not evidence
        try:
            with open(args.overload, "w") as f:
                json.dump(res, f, indent=1)
                f.write("\n")
        except OSError as e:
            print(f"# REFUSING to print overload verdicts: artifact "
                  f"write to {args.overload!r} failed ({e})",
                  file=sys.stderr)
            sys.exit(1)
        v = res["verdict"]
        t = res["tiers"]
        print(f"# overload: capacity={res['drain_capacity_pods_per_sec']} "
              f"pods/s, surge {res['surge_mult']}x for {res['surge_s']}s "
              f"({res['surge_pods']} pods), max_rung={v['max_rung']}, "
              f"recovery={v['recovery_s']}s", file=sys.stderr)
        for name in ("critical", "standard", "batch"):
            s = t[name]
            print(f"# overload tier {name}: goodput={s['goodput']} "
                  f"p99={s['e2e_ms']['p99']}ms rejected={s['rejected']}",
                  file=sys.stderr)
        print(f"# overload admission: throttled="
              f"{res['admission']['throttled']} "
              f"retry_after_honored={res['admission']['retry_after_honored']}",
              file=sys.stderr)
        print(json.dumps({
            "metric": "overload-verdict",
            "value": 1 if v["pass"] else 0,
            "unit": "pass",
            "vs_baseline": 1,
            "max_rung": v["max_rung"],
            "recovery_s": v["recovery_s"],
            "verdict": v,
            "artifact": args.overload,
        }))
        sys.exit(0 if v["pass"] else 1)

    if (args.ab_churn or args.ab_pump or args.ab_frontier or args.ab_watch
            or args.ab_loop or args.ab_trace or args.ab_telemetry):
        import datetime

        kw = {}
        if args.nodes:
            kw["n_nodes"] = args.nodes
        if args.pods:
            kw["total_pods"] = args.pods
        if args.trials:
            kw["pairs"] = args.trials
        runner = (run_telemetry_ab if args.ab_telemetry
                  else run_trace_ab if args.ab_trace
                  else run_loop_ab if args.ab_loop
                  else run_watch_ab if args.ab_watch
                  else run_frontier_ab if args.ab_frontier
                  else run_pump_ab if args.ab_pump else run_churn_ab)
        path = (args.ab_telemetry or args.ab_trace or args.ab_loop
                or args.ab_watch or args.ab_frontier or args.ab_pump
                or args.ab_churn)
        metric = ("telemetry-enabled-overhead-pct" if args.ab_telemetry
                  else "trace-enabled-overhead-pct" if args.ab_trace
                  else "device-loop-win-pct" if args.ab_loop
                  else "watch-frames-win-pct" if args.ab_watch
                  else "frontier-scan-win-pct" if args.ab_frontier
                  else "pump-ingest-win-pct" if args.ab_pump
                  else "churn-pipeline-win-pct")
        ledger = runner(**kw)
        ledger["date"] = datetime.date.today().isoformat()
        # the medians below are only quotable WITH the ledger artifact
        # behind them (ISSUE 11): if the JSON cannot be written, refuse
        # to print them and exit non-zero instead of reporting numbers
        # that nothing on disk substantiates
        try:
            with open(path, "w") as f:
                json.dump(ledger, f, indent=1)
                f.write("\n")
        except OSError as e:
            print(f"# REFUSING to print A/B medians: ledger write to "
                  f"{path!r} failed ({e})", file=sys.stderr)
            sys.exit(1)
        print(json.dumps({
            "metric": metric,
            "value": ledger["win_pct"],
            "unit": "% (B_median vs A_median)",
            "vs_baseline": round(ledger["B_median"] / 100.0, 2),
            "A_median": ledger["A_median"],
            "B_median": ledger["B_median"],
            "ledger": path,
        }))
        return

    if args.micro:
        matrix = run_micro()
        cell = matrix["1000nodes/1000pods"]
        print(json.dumps({
            "metric": "schedule-latency-us",
            "value": cell["oracle_us_per_schedule"],
            "unit": "us/Schedule@1000nodes/1000pods",
            "vs_baseline": 0,
            "matrix": matrix,
        }))
        return
    n_nodes, n_pods, workload = PRESETS[args.preset]
    if args.nodes:
        n_nodes = args.nodes
    if args.pods:
        n_pods = args.pods
    if args.workload:
        workload = args.workload

    if args.trials is not None and args.trials < 1:
        parser.error("--trials must be >= 1")  # before the minutes-long warm-up
    trials = args.trials or (3 if args.preset == "north" and not args.oracle else 1)

    # warm-up at the same scale (different seed): triggers XLA compilation of
    # every segment-shape bucket the timed run will hit, so the timed run
    # measures steady-state throughput (first TPU compile is ~5s per bucket)
    if not args.oracle:
        run_once(n_nodes, n_pods, use_backend=True, workload=workload, seed=1)
    runs = []
    for t in range(trials):
        runs.append(run_once(
            n_nodes, n_pods, use_backend=not args.oracle, workload=workload,
            seed=0, emit_events=args.events,
            want_failure_reasons=not args.oracle,
        ))
        if trials > 1:
            print(f"# trial {t + 1}/{trials}: "
                  f"{runs[-1]['pods_per_sec']:.1f} pods/s", file=sys.stderr)
    runs.sort(key=lambda r: r["pods_per_sec"])
    result = runs[len(runs) // 2]  # the median trial is the reported one
    if result["bound"] == 0:
        print(json.dumps({"metric": "pods-scheduled/sec", "value": 0, "unit": "pods/s", "vs_baseline": 0}))
        sys.exit(1)

    parity = None
    if args.parity:
        parity = run_parity(result, n_nodes, n_pods, workload, seed=0)
        print(
            f"# parity: {parity['checked']} pods checked, "
            f"{parity['mismatches']} mismatches "
            f"(oracle {parity['oracle_pods_per_sec']} pods/s)",
            file=sys.stderr,
        )

    if args.compare:
        oracle = run_once(
            n_nodes, min(n_pods, 2_000), use_backend=False, workload=workload, seed=0
        )
        print(
            f"# oracle: {oracle['pods_per_sec']:.1f} pods/s on {min(n_pods, 2000)} pods; "
            f"backend speedup {result['pods_per_sec'] / max(oracle['pods_per_sec'], 1e-9):.1f}x",
            file=sys.stderr,
        )

    # parity CERTIFICATION (default): dense-mixed preset, backend vs oracle
    # over identical clusters — the artifact carries the north star's
    # "identical bindings" evidence on every recorded run
    # (scheduler_perf/scheduler_test.go:83-88 gates, it doesn't just print)
    certify = None
    at_cert_scale = (n_nodes, n_pods, workload) == PRESETS["mixed"]
    if args.certify and not args.oracle and not (args.parity and at_cert_scale):
        cert_nodes, cert_pods, cert_workload = PRESETS["mixed"]
        # the timed run already IS the certification workload when the
        # preset matches — don't re-run identical multi-minute work
        cert_backend = result if at_cert_scale else run_once(
            cert_nodes, cert_pods, use_backend=True,
            workload=cert_workload, seed=0)
        certify = run_parity(cert_backend, cert_nodes, cert_pods, cert_workload, seed=0)
        print(
            f"# certify[dense-mixed]: {certify['checked']} pods checked, "
            f"{certify['mismatches']} mismatches "
            f"(backend {certify['backend_pods_per_sec']} vs oracle "
            f"{certify['oracle_pods_per_sec']} pods/s)",
            file=sys.stderr,
        )

    # north-prefix parity gate: when the timed run is BIGGER than the
    # certification scale, full-set oracle replay is infeasible (~45 min at
    # 150k) — replay the oracle over the first PREFIX_PARITY_K pods of the
    # SAME batch instead (prefix-closure makes this exact; docstring above)
    # churn: steady-state arrival-load measurement rides along with the
    # north preset (density.go's saturation throughput + per-pod latency
    # under continuous creation; VERDICT r3 Missing #5)
    churn = None
    if not args.oracle and args.preset == "north" and args.churn:
        churn = run_churn(seed=0, trace=args.trace,
                          telemetry=args.telemetry)
        if args.trace:
            tr = churn["trace"]
            print(f"# trace: {tr['events']} events over "
                  f"{tr['waves_recorded']} waves -> {tr['artifact']} "
                  f"({tr['flight_dumps']} flight dumps)", file=sys.stderr)
        if args.telemetry:
            # the no-ledger-no-numbers guard, extended to the SLO
            # verdict block (ISSUE 13): burn-rate verdicts are only
            # quotable with the shipped JSON-lines artifact behind them
            tb = churn.get("telemetry") or {}
            art = tb.get("artifact")
            shipped = (tb.get("shipper") or {}).get("shipped", 0)
            if not art or not os.path.exists(art) or shipped == 0:
                churn["telemetry"] = None
                print(f"# REFUSING to print SLO verdicts: telemetry "
                      f"artifact {art!r} missing or empty "
                      f"(shipped={shipped})", file=sys.stderr)
                sys.exit(1)
            verdicts = ", ".join(
                f"{name}={'BREACH' if v['breached'] else 'ok'}"
                for name, v in sorted(tb["slo_verdicts"].items()))
            print(f"# telemetry: {tb['scrapes']} scrapes over "
                  f"{tb['tracks']} tracks -> {art} (shipped {shipped}, "
                  f"dead {tb['shipper']['dead_lettered']}); "
                  f"verdicts: {verdicts}", file=sys.stderr)
        print(
            f"# churn[{churn['nodes']} nodes]: {churn['bound']} bound / "
            f"{churn['unbound']} unbound over "
            f"{churn['waves']} waves at {churn['pods_per_sec']} pods/s, "
            f"e2e p50={churn['e2e_scheduling_ms']['p50']}ms "
            f"p99={churn['e2e_scheduling_ms']['p99']}ms, "
            f"SLO(p99<={churn['slo_p99_ms']:.0f}ms, "
            f">={churn['floor_pods_per_sec']:.0f} pods/s): "
            f"{'PASS' if churn['slo_pass'] else 'FAIL'}",
            file=sys.stderr,
        )

    preemption = None
    if not args.oracle and args.preset == "north" and args.preempt:
        preemption = run_preemption()
        print(
            f"# preemption: {preemption['attempts']} attempts -> "
            f"{preemption['victims']} victims, "
            f"{preemption['preemptor_bound_after']}/{preemption['preemptors']} "
            f"preemptors bound, {preemption['preemptions_per_sec']} "
            f"preemptions/s, latency p50="
            f"{preemption['preemption_latency_ms']['p50']}ms p99="
            f"{preemption['preemption_latency_ms']['p99']}ms",
            file=sys.stderr,
        )

    prefix = None
    if not args.oracle and n_pods > PRESETS["mixed"][1]:
        prefix = run_prefix_parity(result, n_nodes, n_pods, workload, seed=0)
        print(
            f"# prefix-parity[{args.preset}]: oracle replay of the first "
            f"{prefix['checked']} batch pods, {prefix['mismatches']} mismatches "
            f"(oracle {prefix['oracle_pods_per_sec']} pods/s)",
            file=sys.stderr,
        )

    stats = result.get("backend_stats", {})
    print(
        f"# {args.preset}[{workload}]: {result['bound']} bound / {result['failed']} failed "
        f"in {result['elapsed_s']:.2f}s on {n_nodes} nodes "
        f"(kernel={stats.get('kernel_pods', 0)} oracle={stats.get('oracle_pods', 0)} "
        f"segments={stats.get('segments', 0)} "
        f"pallas_segments={stats.get('pallas_segments', 0)} "
        f"events={'on' if args.events else 'off'})",
        file=sys.stderr,
    )
    # baseline: the reference harness's expected throughput (100 pods/s).
    # The preset/scale ride along so recorded results across rounds are
    # comparable on their own terms (r1 default was 'basic'; the default
    # is now the north-star scale itself).
    line = {
        "metric": "pods-scheduled/sec",
        "value": round(result["pods_per_sec"], 1),
        "unit": "pods/s",
        "vs_baseline": round(result["pods_per_sec"] / 100.0, 2),
        "preset": args.preset,
        "nodes": n_nodes,
        "pods": result["bound"] + result["failed"],
        "workload": workload,
        "events": "on" if args.events else "off",
        "pallas_segments": stats.get("pallas_segments", 0),
        "kernel_pods": stats.get("kernel_pods", 0),
        "oracle_pods": stats.get("oracle_pods", 0),
        "sli": result.get("sli"),
    }
    if trials > 1:
        vals = [round(r["pods_per_sec"], 1) for r in runs]
        line["trials"] = trials
        line["trial_pods_per_sec"] = vals  # sorted; median is `value`
        line["spread_pct"] = round(
            (vals[-1] - vals[0]) / max(vals[len(vals) // 2], 1e-9) * 100, 1)
    if churn is not None:
        line["churn"] = churn
    if preemption is not None:
        line["preemption"] = preemption
    if "event_stats" in result:
        line["event_stats"] = result["event_stats"]
    if "failure_reasons" in result:
        line["failure_reasons"] = result["failure_reasons"]
    if certify is not None:
        line["parity_checked"] = certify["checked"]
        line["parity_mismatches"] = certify["mismatches"]
        line["parity_preset"] = "mixed"
    if parity is not None:
        # --parity: at-scale parity at the TIMED preset overrides the
        # certification sub-run's numbers
        line["parity_checked"] = parity["checked"]
        line["parity_mismatches"] = parity["mismatches"]
        line["parity_preset"] = args.preset
    if prefix is not None:
        # the at-scale prefix replay is the headline parity evidence; the
        # dense-mixed full-set certification rides along under its own keys
        if certify is not None:
            line["certify_checked"] = certify["checked"]
            line["certify_mismatches"] = certify["mismatches"]
            line["certify_preset"] = "mixed"
        if parity is None:
            line["parity_checked"] = prefix["checked"]
            line["parity_mismatches"] = prefix["mismatches"]
            line["parity_preset"] = f"{args.preset}-prefix"
        else:
            # an explicit --parity full-set run outranks the prefix gate
            # in the parity_* keys; keep the prefix result alongside
            line["prefix_checked"] = prefix["checked"]
            line["prefix_mismatches"] = prefix["mismatches"]
    print(json.dumps(line))
    mism = [p["mismatches"] for p in (parity, certify, prefix) if p is not None]
    if churn is not None and not churn["slo_pass"]:
        # the reference's pod-startup SLO, enforced at north scale — a
        # round that regresses past the floor must FAIL loudly
        print("# churn SLO gate FAILED", file=sys.stderr)
        sys.exit(1)
    if preemption is not None and (
            preemption["preemptor_bound_after"] < preemption["preemptors"]):
        # the workload is constructed so every preemptor has a victim set;
        # anything unbound means the PostFilter lost someone — gate on it
        print("# preemption gate FAILED: "
              f"{preemption['preemptor_bound_after']} of "
              f"{preemption['preemptors']} preemptors bound", file=sys.stderr)
        sys.exit(1)
    if any(mism):
        sys.exit(1)


if __name__ == "__main__":
    main()
