"""SLO enforcement: metric thresholds that FAIL tests on violation.

Capability of the reference's perf gatekeeping
(``test/e2e/framework/metrics_util.go:44-57`` — scrape component
metrics, compare against thresholds, fail the suite; and
``scheduler_perf/scheduler_test.go:35-38`` — per-interval
pods/s floors: fail < 30, warn < 100)."""

from __future__ import annotations

import logging
from typing import Optional

logger = logging.getLogger("kubernetes_tpu.testing.slo")

# the reference's scheduler_perf thresholds (scheduler_test.go:35-38)
MIN_THROUGHPUT_PODS_PER_SEC = 30.0
WARN_THROUGHPUT_PODS_PER_SEC = 100.0


class SLOViolation(AssertionError):
    pass


class SLOChecker:
    """Collects checks; ``assert_all`` raises SLOViolation listing every
    breach (the reference fails at suite teardown with the full list)."""

    def __init__(self):
        self.violations: list[str] = []
        self.warnings: list[str] = []

    # -- throughput (scheduler_perf) ---------------------------------------
    def check_throughput(self, pods_per_sec: float, minimum: float = MIN_THROUGHPUT_PODS_PER_SEC,
                         warn: float = WARN_THROUGHPUT_PODS_PER_SEC) -> None:
        if pods_per_sec < minimum:
            self.violations.append(
                f"throughput {pods_per_sec:.1f} pods/s below the {minimum:.0f} floor"
            )
        elif pods_per_sec < warn:
            self.warnings.append(
                f"throughput {pods_per_sec:.1f} pods/s below the {warn:.0f} warn line"
            )

    # -- latency quantiles (metrics_util) ----------------------------------
    def check_latency_quantile(self, name: str, histogram, q: float,
                               max_value: float) -> None:
        got = histogram.quantile(q)
        if got > max_value:
            self.violations.append(
                f"{name} p{int(q * 100)} = {got:.0f} exceeds {max_value:.0f}"
            )

    def check_counter_max(self, name: str, counter, max_value: int) -> None:
        if counter.value > max_value:
            self.violations.append(f"{name} = {counter.value} exceeds {max_value}")

    # -- verdict -----------------------------------------------------------
    def assert_all(self) -> None:
        for w in self.warnings:
            logger.warning("SLO warn: %s", w)
        if self.violations:
            raise SLOViolation("; ".join(self.violations))
