"""Test infrastructure: chaos injection + SLO enforcement (SURVEY.md §4.6)."""

from .chaos import ChaosMonkey, NodePartition, PodKiller, SchedulerRestart
from .slo import SLOChecker, SLOViolation
