"""Test infrastructure: chaos injection + SLO enforcement (SURVEY.md §4.6)."""

from .chaos import (
    ChaosMonkey,
    FaultInjection,
    NodePartition,
    PodKiller,
    SchedulerRestart,
)
from .slo import SLOChecker, SLOViolation
