"""Chaos injection for control-plane tests.

Capability of the reference's e2e chaos tooling:

- ``chaosmonkey.Do`` (``test/e2e/chaosmonkey/chaosmonkey.go:47,77``):
  register tests, start them, inject a disruption mid-flight, let the
  tests finish, assert.  ``ChaosMonkey.run`` is that protocol collapsed
  into a deterministic tick loop.
- ``network_partition.go``: a zone going silent — here, a subset of
  hollow kubelets simply stops ticking (no heartbeats, no pod status),
  which is exactly what a partition looks like to the control plane.
- component crash/restart (upgrade tests): throw a component away and
  rebuild it from the store — the checkpoint/resume property (SURVEY.md
  §5.3: the store IS the checkpoint).

The coarse disruptions above act from the OUTSIDE (remove a kubelet,
drop a scheduler).  :class:`FaultInjection` plugs the deterministic
fault framework (``kubernetes_tpu/faults``) into the same protocol: a
seeded :class:`~kubernetes_tpu.faults.FaultPlan` armed at ``inject_at``
and disarmed at ``recover_at`` makes a named INTERNAL seam misbehave —
bind CAS failures, watch-stream cuts, WAL tears — with exact replay.
"""

from __future__ import annotations

import random
from typing import Callable, Optional

from ..faults import FaultPlan


class Disruption:
    """begin() at the injection point, end() at recovery."""

    def begin(self) -> None:  # pragma: no cover - interface
        raise NotImplementedError

    def end(self) -> None:  # pragma: no cover - interface
        raise NotImplementedError


class NodePartition(Disruption):
    """A set of hollow kubelets goes silent (the network-partition
    analogue: heartbeats stop, pod statuses freeze)."""

    def __init__(self, fleet, node_names: set[str]):
        self.fleet = fleet
        self.node_names = set(node_names)
        self._removed = []

    def begin(self) -> None:
        self._removed = [k for k in self.fleet.kubelets if k.node_name in self.node_names]
        self.fleet.kubelets = [
            k for k in self.fleet.kubelets if k.node_name not in self.node_names
        ]

    def end(self) -> None:
        self.fleet.kubelets.extend(self._removed)
        for k in self._removed:
            k._last_heartbeat = -1e18  # heartbeat immediately on next tick
        self._removed = []


class SchedulerRestart(Disruption):
    """Kill the scheduler and rebuild it from the store (LIST+WATCH
    replay): nothing but the store may be needed to resume."""

    def __init__(self, holder: dict, factory: Callable[[], object]):
        self.holder = holder  # {"scheduler": Scheduler} — swapped in place
        self.factory = factory

    def begin(self) -> None:
        self.holder["scheduler"] = None  # the old instance is simply dropped

    def end(self) -> None:
        sched = self.factory()
        sched.start()
        sched.pump()
        self.holder["scheduler"] = sched


class PodKiller(Disruption):
    """Deletes random running pods while active (the reference's
    disruptive e2e pod churn)."""

    def __init__(self, clientset, rate: int = 1, seed: int = 0):
        self.clientset = clientset
        self.rate = rate
        self.rng = random.Random(seed)
        self.active = False
        self.killed = 0

    def begin(self) -> None:
        self.active = True

    def tick(self) -> None:
        if not self.active:
            return
        from ..store.store import NotFoundError

        pods, _ = self.clientset.pods.list()
        victims = [p for p in pods if p.status.phase == "Running"]
        self.rng.shuffle(victims)
        for p in victims[: self.rate]:
            try:
                self.clientset.pods.delete(p.meta.name, p.meta.namespace)
                self.killed += 1
            except NotFoundError:
                pass

    def end(self) -> None:
        self.active = False


class FaultInjection(Disruption):
    """A fault plan as a chaos disruption: the plan's policies are live
    between begin() and end().  Composes with the external disruptions —
    e.g. a node partition WHILE binds are failing — and inherits the
    plan's determinism (same seed, same misbehavior sequence)."""

    def __init__(self, plan: FaultPlan):
        self.plan = plan
        self._armed = None

    def begin(self) -> None:
        self._armed = self.plan.armed()
        self._armed.__enter__()

    def end(self) -> None:
        if self._armed is not None:
            self._armed.__exit__(None, None, None)
            self._armed = None


class ChaosMonkey:
    """chaosmonkey.Do: drive the workload, inject at ``inject_at``,
    recover at ``recover_at``, stop when ``done`` or ``max_ticks``."""

    def __init__(
        self,
        tick: Callable[[int], None],
        disruptions: list[Disruption],
        inject_at: int,
        recover_at: int,
        done: Optional[Callable[[], bool]] = None,
        max_ticks: int = 200,
    ):
        self.tick = tick
        self.disruptions = disruptions
        self.inject_at = inject_at
        self.recover_at = recover_at
        self.done = done or (lambda: False)
        self.max_ticks = max_ticks
        self.injected = False
        self.recovered = False

    def run(self) -> int:
        """Returns the tick count at completion.  Disruptions that began
        are ALWAYS ended — a tick() that raises mid-fault (likely, since
        faults make workloads throw) must not leak the disruption past
        the run: a still-armed FaultPlan would poison every later test
        in the process (and block the next ``armed()``)."""
        try:
            for t in range(self.max_ticks):
                if t == self.inject_at:
                    for d in self.disruptions:
                        d.begin()
                    self.injected = True
                if t == self.recover_at:
                    for d in self.disruptions:
                        d.end()
                    self.recovered = True
                self.tick(t)
                for d in self.disruptions:
                    tick_fn = getattr(d, "tick", None)
                    if tick_fn is not None:
                        tick_fn()
                if t > self.recover_at and self.done():
                    return t
            return self.max_ticks
        finally:
            if self.injected and not self.recovered:
                for d in self.disruptions:
                    d.end()
                self.recovered = True
