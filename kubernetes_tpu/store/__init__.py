"""Revisioned store + watch streams (SURVEY.md L0/L2)."""

from .store import (
    ADDED,
    DELETED,
    MODIFIED,
    AlreadyExistsError,
    ConflictError,
    ExpiredRevisionError,
    NotFoundError,
    Store,
    Watch,
    WatchEvent,
)
from .replication import (
    FollowerReplica,
    NoQuorumError,
    ReplicaDownError,
    ReplicatedStore,
)
