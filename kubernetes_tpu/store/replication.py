"""Control-plane store replication: leader/follower event shipping.

The capability of etcd's raft layer at this framework's scale (reference
L0, ``vendor/github.com/coreos/etcd/clientv3`` — SURVEY §1-L0): a leader
store replicates every committed event to follower replicas and refuses
writes without a reachable majority; followers serve consistent reads and
watches; on leader death the most-caught-up follower is promoted and the
revision sequence continues with no acked write lost.

Honest reductions vs raft, by design:
- the replication transport is the in-proc event stream (the same
  ``WatchEvent`` wire shape the HTTP watch serves), not a peer-to-peer
  RPC mesh;
- leader election among replicas is the caller's job (the framework's
  ``LeaderElector`` + a supervisor — mirroring how the reference deploys
  stacked etcd under systemd/kubeadm rather than self-electing in-proc);
- the quorum check is write-time reachability, not a persisted term/vote —
  a follower dying between check and ship loses one ack, never an
  acknowledged commit (acks are counted synchronously before the write
  returns).

Layering: ``apiserver.APIServer`` instances are stateless over one
(replicated) store, so control-plane HA is N apiservers × this module
(VERDICT r2 missing #1).
"""

from __future__ import annotations

from typing import Optional

from .store import Store, WatchEvent, _fast_deepcopy, DELETED


class NoQuorumError(Exception):
    """Write refused: fewer than majority replicas reachable."""


class ReplicaDownError(Exception):
    """The follower is marked down and must catch up before serving."""


class FollowerReplica:
    """A replica applying the leader's committed event stream.

    Serves GET/LIST/WATCH from its own ``Store`` (consistent up to the
    last acked event — which, with synchronous majority shipping, means
    every acknowledged write is visible on a majority)."""

    def __init__(self, name: str, data_dir: Optional[str] = None,
                 fsync: bool = False):
        self.name = name
        self.store = Store(data_dir=data_dir, fsync=fsync)
        self.alive = True

    @property
    def applied_revision(self) -> int:
        return self.store.revision

    def apply(self, ev: WatchEvent) -> int:
        if not self.alive:
            raise ReplicaDownError(self.name)
        self.store.apply_replicated(ev)
        return self.store.revision

    def fail(self) -> None:
        """Simulate crash/partition (tests, chaos harness)."""
        self.alive = False

    def recover(self) -> None:
        self.alive = True


class ReplicatedStore(Store):
    """A leader store shipping every commit to followers synchronously.

    Write path: the quorum precondition is checked before the revision is
    allocated (no state mutated on refusal); after the local WAL append
    the event ships to every live follower; a follower that errors is
    marked down (it rejoins via ``catch_up``)."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._followers: list[FollowerReplica] = []

    # -- membership ---------------------------------------------------------
    def add_follower(self, replica: FollowerReplica) -> None:
        # catch-up and enlistment under the STORE lock: _emit runs with it
        # held (every write op holds it), so no commit can land between
        # "caught up to rev N" and "receiving N+1 via shipping" — the gap
        # would silently lose that one event on the new follower
        with self._mu:
            self._catch_up_locked(replica)
            self._followers.append(replica)

    def remove_follower(self, replica: FollowerReplica) -> None:
        with self._mu:
            self._followers = [f for f in self._followers if f is not replica]

    @property
    def followers(self) -> list[FollowerReplica]:
        return list(self._followers)

    def cluster_size(self) -> int:
        return 1 + len(self._followers)

    def majority(self) -> int:
        return self.cluster_size() // 2 + 1

    # -- the write-path hooks ----------------------------------------------
    def _next_rev(self) -> int:
        # quorum BEFORE allocation: a refused write mutates nothing
        live = 1 + sum(1 for f in self._followers if f.alive)
        if live < self.majority():
            raise NoQuorumError(
                f"{live}/{self.cluster_size()} replicas reachable, "
                f"need {self.majority()}")
        return super()._next_rev()

    def _replicate(self, ev: WatchEvent) -> None:
        # the per-event shipping hook: runs after local durability on BOTH
        # the per-event emit and the batch (_emit_many/frame) emit path —
        # a correlated batch txn ships every event, framed fan-out or not
        for f in self._followers:
            if not f.alive:
                continue
            try:
                f.apply(ev)
            except Exception:
                f.fail()

    # -- catch-up + promotion ----------------------------------------------
    def catch_up(self, replica: FollowerReplica) -> None:
        """Bring a (re)joining replica to the leader's revision: replay the
        event log from its applied revision, or fall back to a full state
        snapshot when the log window has been trimmed past it."""
        with self._mu:
            self._catch_up_locked(replica)

    def _catch_up_locked(self, replica: FollowerReplica) -> None:
        need_from = replica.applied_revision
        oldest = self._log[0].revision if self._log else self._rev + 1
        if need_from + 1 >= oldest or self._rev == need_from:
            for ev in list(self._log):
                if ev.revision > need_from:
                    replica.store.apply_replicated(ev)
        else:
            # snapshot install (raft InstallSnapshot analogue)
            replica.store.install_snapshot(
                self._rev,
                {kind: {key: _fast_deepcopy(item.data)
                        for key, item in bucket.items()}
                 for kind, bucket in self._objects.items()},
            )
        replica.recover()

    @classmethod
    def promote(cls, candidates: list[FollowerReplica],
                data_dir: Optional[str] = None) -> "ReplicatedStore":
        """Failover: adopt the most-caught-up live replica's state as the
        new leader and re-enlist the rest as its followers (catching each
        up to the winner).  No acknowledged write can be lost: every ack
        implied the event was applied on that replica."""
        live = [c for c in candidates if c.alive]
        if not live:
            raise NoQuorumError("no live replicas to promote")
        winner = max(live, key=lambda c: c.applied_revision)
        leader = cls(data_dir=data_dir)
        leader.adopt(winner.store)
        for c in live:
            if c is not winner:
                leader.add_follower(c)
        return leader

    def adopt(self, source: Store) -> None:
        """Take over another store's state wholesale (promotion path).
        Items are deep-copied — the discarded replica's store must not
        share mutable state with the new leader — and the adopted state is
        snapshotted to the WAL so a restart recovers it."""
        from .store import _Item

        with self._mu, source._mu:
            self._rev = source._rev
            self._objects = {
                kind: {key: _Item(data=_fast_deepcopy(item.data),
                                  revision=item.revision)
                       for key, item in bucket.items()}
                for kind, bucket in source._objects.items()
            }
            self._log.extend(source._log)
        if self._wal is not None:
            self.compact()
