"""Column-packed watch frames: N correlated events as ONE delivery unit.

The last leg of the zero-copy contract (ROADMAP "batched watch frames"):
LIST went columnar in PR 4 (``store/columns.py``), but every watch event
still crossed the store→informer boundary — and the wire — one at a
time: one queue put, one JSON line, one informer lock acquisition, one
cache dict probe per event.  At churn scale a single ``bind_many`` wave
commits thousands of MODIFIED events back to back, and that per-event
pump APPLICATION (cache apply + bind confirm) was the largest remaining
host cost in the profile (~0.3-0.8s spikes per wave).

A :class:`WatchFrame` packs one correlated store batch — everything a
``create_many``/``bind_many`` txn committed under one store lock hold —
into parallel columns:

- **op/kind/identity columns**: ``types`` (ADDED/MODIFIED/DELETED),
  ``keys``, ``revisions`` as flat lists (one ``kind`` per frame — a
  store batch is single-kind by construction);
- **prev_revisions**: the revision each object held *before* this
  transition (-1 = unknown).  This is the columnar confirm fence: a
  scheduler that assumed a pod at revision r and sees a bind event with
  ``prev_revision == r`` knows, by CAS semantics, that NOTHING else
  changed in between — the whole containers/affinity equality check
  collapses to one integer compare per column entry;
- **shared raw-view payloads**: ``objects`` are the same shallow views /
  event copies the per-event path would have carried, shared-immutable
  (the informer contract: consumers never mutate wire payloads).

Consumers that predate frames are never broken: frames are **opt-in per
watcher** (``Store.watch(..., frames=True)``), the apiserver serves them
only to ``?frames=1`` clients (per-event JSON lines otherwise), and
``events()`` expands a frame back into the exact per-event sequence.

``ENABLED`` is the A/B seam: ``bench.py --ab-watch`` flips it to measure
framed vs per-event delivery on the same harness.
"""

from __future__ import annotations

from typing import Iterator, Optional

# module seam for the watch-frame A/B (bench.py --ab-watch): False
# restores per-event delivery everywhere (frame-aware consumers stay
# dormant — they only ever see plain WatchEvents)
ENABLED = True

# module seam for the single-encode fan-out A/B (bench.py --watch-fleet):
# True (default) serializes each frame/event wire payload ONCE and shares
# the encoded bytes across every HTTP watcher streaming it; False
# restores the pre-serving-tier shape where every client pays its own
# json.dumps per delivery.
SHARED_ENCODE = True

# WatchFrame.type value: a transport framing marker, not a state
# transition (like WATCH_GAP).  Consumers that dispatch on event type
# must expand the frame (``events()``) or apply it as a batch.
FRAME = "FRAME"


class FrameDecodeError(Exception):
    """A frame's columns are structurally broken (length mismatch,
    non-monotone revisions, malformed payloads).  A consumer cannot know
    WHICH events it lost — the only honest recovery is a gap + relist,
    never a silent partial apply."""


class WatchFrame:
    """One correlated batch of watch events, column-packed.

    Shared-immutable like :class:`~.store.WatchEvent`: one frame object
    is handed to the log consumers and every watcher; nobody mutates it.
    """

    __slots__ = ("kind", "types", "keys", "revisions", "prev_revisions",
                 "objects", "txn", "_node_names", "_wire_b")

    # duck-typed dispatch marker (``ev.type == FRAME``) for consumers
    # that pull mixed WatchEvent/WatchFrame items off one watch queue
    type = FRAME

    def __init__(self, kind: str, types: list, keys: list, revisions: list,
                 objects: list, prev_revisions: Optional[list] = None,
                 txn: Optional[str] = None):
        self.kind = kind
        self.types = types
        self.keys = keys
        self.revisions = revisions
        # -1 = unknown (creates, deletes, plain updates); >= 0 only where
        # the emitting txn knew the pre-transition revision (bind_many)
        self.prev_revisions = prev_revisions
        self.objects = objects
        # correlation id minted by the emitting store txn (ISSUE 7):
        # the same id appears on the store's txn span, this frame, the
        # informer's frame-apply span, and the scheduler's confirm span,
        # so one trace shows the store→informer→confirm propagation
        self.txn = txn
        self._node_names: Optional[list] = None
        self._wire_b: Optional[bytes] = None

    def __len__(self) -> int:
        return len(self.keys)

    @property
    def revision(self) -> int:
        """The frame's resourceVersion fence: a consumer that applied
        this frame has seen everything up to its LAST event."""
        return self.revisions[-1] if self.revisions else 0

    @property
    def node_names(self) -> list:
        """Per-event ``spec.nodeName`` column, computed on first touch —
        what the scheduler's columnar bind confirm compares against its
        assumed placements (one raw dict get per entry, no decode)."""
        got = self._node_names
        if got is None:
            got = self._node_names = [
                (o.get("spec") or {}).get("nodeName", "") if o else ""
                for o in self.objects]
        return got

    def select(self, indices: list) -> Optional["WatchFrame"]:
        """Column-level sub-frame: keep only the entries at ``indices``
        (ascending, as produced by a selector filter walk), sharing the
        payload dicts with this frame (shared-immutable, like every
        other consumer).  Revision order — and therefore the per-frame
        resourceVersion fence — is preserved by construction.  Returns
        None for an empty selection: an all-filtered frame must not
        reach the wire (``from_wire`` rejects empty frames; the client's
        fence advances on its next matching delivery instead)."""
        if not indices:
            return None
        if len(indices) == len(self.keys):
            return self  # every entry matched: share the packed frame
        prev = self.prev_revisions
        return WatchFrame(
            self.kind,
            [self.types[i] for i in indices],
            [self.keys[i] for i in indices],
            [self.revisions[i] for i in indices],
            [self.objects[i] for i in indices],
            prev_revisions=None if prev is None else [prev[i] for i in indices],
            txn=self.txn,
        )

    def wire_bytes(self) -> bytes:
        """The frame's encoded watch line (wire form + newline), computed
        once and shared across every streaming client (the single-encode
        fan-out leg) while :data:`SHARED_ENCODE` is on.  Benign race by
        design: two handler threads may both encode the same frame; the
        bytes are identical and the last assignment wins."""
        import json

        if not SHARED_ENCODE:
            return json.dumps(self.to_wire()).encode() + b"\n"
        got = self._wire_b
        if got is None:
            got = self._wire_b = json.dumps(self.to_wire()).encode() + b"\n"
        return got

    def events(self) -> Iterator:
        """Expand back into the exact per-event sequence (order, content,
        revisions) — the compatibility path for per-event consumers."""
        from .store import WatchEvent

        for i in range(len(self.keys)):
            yield WatchEvent(self.types[i], self.kind, self.keys[i],
                             self.revisions[i], self.objects[i])

    # -- wire form (the apiserver's ?frames=1 watch line) -------------------
    def to_wire(self) -> dict:
        out = {
            "type": FRAME,
            "kind": self.kind,
            "types": self.types,
            "keys": self.keys,
            "revisions": self.revisions,
            "objects": self.objects,
        }
        if self.prev_revisions is not None:
            out["prevRevisions"] = self.prev_revisions
        if self.txn is not None:
            out["txn"] = self.txn
        return out

    @classmethod
    def from_wire(cls, d: dict) -> "WatchFrame":
        """Decode + validate.  A structurally broken frame must fail HERE
        with :class:`FrameDecodeError` — the consumer turns it into a
        watch gap (relist), never a partial apply."""
        try:
            kind = d["kind"]
            types = d["types"]
            keys = d["keys"]
            revisions = [int(r) for r in d["revisions"]]
            objects = d["objects"]
            prev = d.get("prevRevisions")
            if prev is not None:
                prev = [int(r) for r in prev]
        except (KeyError, TypeError, ValueError) as e:
            raise FrameDecodeError(f"malformed frame: {e!r}") from None
        n = len(keys)
        if not (len(types) == len(revisions) == len(objects) == n) or (
                prev is not None and len(prev) != n):
            raise FrameDecodeError(
                f"frame column lengths diverge: keys={n} types={len(types)} "
                f"revisions={len(revisions)} objects={len(objects)}")
        if n == 0:
            raise FrameDecodeError("empty frame")
        if any(revisions[i] >= revisions[i + 1] for i in range(n - 1)):
            # one store txn commits strictly increasing revisions; a frame
            # violating that was corrupted in flight
            raise FrameDecodeError("frame revisions not strictly increasing")
        if any(o is not None and not isinstance(o, dict) for o in objects):
            raise FrameDecodeError("frame payloads must be dicts")
        txn = d.get("txn")
        if txn is not None and not isinstance(txn, str):
            raise FrameDecodeError("frame txn id must be a string")
        return cls(kind, list(types), list(keys), revisions, list(objects),
                   prev_revisions=prev, txn=txn)


def event_wire_bytes(ev) -> bytes:
    """Encoded watch line for one plain :class:`~.store.WatchEvent`
    (wire form + newline), computed once per event and shared across
    every streaming client while :data:`SHARED_ENCODE` is on.  The cache
    rides the event object itself (``object.__setattr__`` through the
    frozen dataclass): events are shared-immutable across all watcher
    queues, so the first client to encode pays and the rest reuse.
    Benign race: concurrent encoders produce identical bytes."""
    import json

    if SHARED_ENCODE:
        got = getattr(ev, "_wire_b", None)
        if got is not None:
            return got
    line = json.dumps({
        "type": ev.type,
        "kind": ev.kind,
        "key": ev.key,
        "revision": ev.revision,
        "object": ev.object,
    }).encode() + b"\n"
    if SHARED_ENCODE:
        object.__setattr__(ev, "_wire_b", line)
    return line
