"""Revisioned object store with CAS updates and watch streams.

The capability of the reference's L0+L2 (etcd3 +
``apiserver/pkg/storage/etcd3/store.go`` + the watch cache
``storage/cacher.go``) collapsed into one in-process component:

- a single monotonically increasing **revision** counter (etcd
  ``mod_revision`` analogue) stamped onto every write;
- **GuaranteedUpdate**: optimistic-concurrency read-modify-write that
  retries the caller's mutation function on revision conflict
  (``storage/etcd3/store.go:257``);
- **watch streams from a revision**: every watcher gets the exact ordered
  event sequence after its start revision, served from an in-memory event
  log (the watch-cache sliding window, ``storage/watch_cache.go``) — one
  writer fans out to any number of watchers (SURVEY.md P4).

Deliberate design point: the store holds **serialized dicts**, never live
objects, and deep-copies on every get/list/event — informer objects are
immutable by construction, which is what the reference enforces with its
cache mutation detector (``client-go/tools/cache/mutation_detector.go``).

The scheduler treats everything device-resident as a disposable cache of
this store, rebuildable from snapshot + watch replay (SURVEY.md §5.3).
"""

from __future__ import annotations

# (copy module no longer needed: JSON-shaped fast deepcopy below)
import collections
import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Iterator, Optional

from .. import faults
from ..api.meta import new_uid
from ..utils import tracing
from ..utils.metrics import DEFAULT_STORE_METRICS


def _py_fast_deepcopy(obj):
    """Deep copy for JSON-shaped data (dict/list/scalars only) — the store's
    wire form by construction.  ~3x faster than copy.deepcopy, which burns
    time on memo bookkeeping and type dispatch the shape can't need."""
    t = type(obj)
    if t is dict:
        return {k: _py_fast_deepcopy(v) for k, v in obj.items()}
    if t is list:
        return [_py_fast_deepcopy(v) for v in obj]
    return obj  # str/int/float/bool/None are immutable


def _fast_deepcopy(obj):
    """First call resolves the copier — the native C walk
    (csrc/fastcopy.c, another ~3x) when it builds, else the Python walk —
    and rebinds this name, so importing the store never triggers a
    compile and later calls pay zero dispatch overhead."""
    global _fast_deepcopy
    try:
        from ..native import get_fastcopy

        _fast_deepcopy = get_fastcopy() or _py_fast_deepcopy
    except Exception:  # noqa: BLE001 - the store must never lose its copier
        _fast_deepcopy = _py_fast_deepcopy
    return _fast_deepcopy(obj)


def object_key(namespace: str, name: str) -> str:
    """Canonical store/informer key — MUST match ``ObjectMeta.key``:
    cluster-scoped objects (empty namespace) use the bare name."""
    return f"{namespace}/{name}" if namespace else name


ADDED = "ADDED"
MODIFIED = "MODIFIED"
DELETED = "DELETED"
# Not a state transition: a watch transport's admission that continuity
# was lost (410 Gone on resume — the event-log window slid past the
# consumer's bookmark).  An informer receiving this must relist; there is
# no object payload to apply.
WATCH_GAP = "GAP"


class ConflictError(Exception):
    """CAS failure: the object's resourceVersion changed under the writer."""


class NotFoundError(KeyError):
    pass


class AlreadyExistsError(Exception):
    pass


@dataclass(frozen=True)
class WatchEvent:
    type: str  # ADDED | MODIFIED | DELETED
    kind: str
    key: str  # namespace/name
    revision: int
    object: dict  # serialized object (deep-copied per consumer)


@dataclass
class _Item:
    data: dict
    revision: int


class Watch:
    """One watch stream.  Iterate, or ``stop()`` to end.  Events are
    delivered in revision order with no gaps from ``start_revision``."""

    def __init__(self, store: "Store", q: "queue.Queue[Optional[WatchEvent]]"):
        self._store = store
        self._queue = q
        self._stopped = threading.Event()

    def stop(self) -> None:
        if not self._stopped.is_set():
            self._stopped.set()
            self._store._remove_watch(self._queue)
            self._queue.put(None)

    def __iter__(self) -> Iterator[WatchEvent]:
        while True:
            ev = self._queue.get()
            if ev is None:
                return
            yield ev

    def get(self, timeout: Optional[float] = None) -> Optional[WatchEvent]:
        try:
            return self._queue.get(timeout=timeout)
        except queue.Empty:
            return None


class _PendingBatch:
    """One open coalescing window at the broadcaster seam: per-key
    latest-wins fold of single-event churn awaiting one framed flush.

    ``latest`` maps (kind, key) → the newest buffered event for that
    object; a fold deletes-and-reinserts so dict order tracks each
    key's LATEST commit — the flush frame's revision column is strictly
    increasing by construction (the ``from_wire`` invariant).  WAL, the
    event log, and replication all stay per-event at commit time; ONLY
    live watcher delivery waits for the window."""

    __slots__ = ("latest", "deadline", "txn", "folded")

    def __init__(self, deadline: float, txn: str):
        self.latest: "collections.OrderedDict[tuple, WatchEvent]" = (
            collections.OrderedDict())
        self.deadline = deadline
        self.txn = txn
        self.folded = 0  # deliveries superseded inside this window


class Store:
    """In-process strongly-ordered object store (etcd3 + watch-cache analogue)."""

    def __init__(self, event_log_window: int = 100_000,
                 data_dir: Optional[str] = None, fsync: bool = False,
                 compact_every: int = 100_000, transformer=None,
                 coalesce_window_s: float = 0.0):
        self._mu = threading.RLock()
        self._rev = 0
        # kind -> {key -> _Item}
        self._objects: dict[str, dict[str, _Item]] = {}
        # ordered event log (the watch-cache window).  A deque: the window
        # trim must be O(1) — a front-slice del on a list memmoves the
        # whole window on EVERY write once it fills, which at a 300k
        # window costs more than the write itself.
        self._log: collections.deque[WatchEvent] = collections.deque(maxlen=event_log_window)
        self._log_window = event_log_window
        # (kind filter, queue, wants_frames): frame-aware watchers opted
        # in via watch(frames=True) receive one WatchFrame per correlated
        # batch txn; everyone else gets the per-event expansion
        self._watchers: list[tuple[Optional[str], "queue.Queue[Optional[WatchEvent]]", bool]] = []
        # time-window update coalescing (the serving-tier broadcaster
        # seam): 0.0 (default) = off, every event fans out at commit;
        # > 0 = single-event update/delete churn is folded per key
        # (latest wins) and flushed as ONE synthetic WatchFrame per kind
        # when the window closes.  Batch txns (_emit_many), new watcher
        # registration, and snapshot installs are ordering barriers that
        # flush the open window first.
        self._coalesce_window = float(coalesce_window_s or 0.0)
        self._coalesce_max_keys = 10_000
        self._pending: Optional[_PendingBatch] = None
        self._coalesce_closed = False
        self._coalesce_wake: Optional[threading.Event] = None
        self._coalesce_thread: Optional[threading.Thread] = None
        if self._coalesce_window > 0.0:
            self._coalesce_wake = threading.Event()
            self._coalesce_thread = threading.Thread(
                target=self._coalesce_loop, name="store-coalesce",
                daemon=True)
            self._coalesce_thread.start()
        # durability (the etcd WAL+snapshot analogue, store/wal.py):
        # with a data_dir every committed event is logged before the call
        # returns, and a fresh Store over the same dir recovers the state
        self._wal = None
        if data_dir is not None:
            from .wal import WriteAheadLog

            self._wal = WriteAheadLog(data_dir, compact_every=compact_every,
                                      fsync=fsync, transformer=transformer)
            rev, objects, _ = self._wal.recover()
            self._rev = rev
            for kind, bucket in objects.items():
                for key, data in bucket.items():
                    self._objects.setdefault(kind, {})[key] = _Item(
                        data=data,
                        revision=int(data.get("metadata", {}).get("resourceVersion", rev)),
                    )
            self._wal.open()

    def compact(self) -> None:
        """Write a snapshot and truncate the WAL (etcd compaction).  No
        copy needed: write_snapshot serializes synchronously while we
        hold the store lock, so the live dicts cannot mutate mid-encode."""
        if self._wal is None:
            return
        with self._mu:
            objects = {
                kind: {key: item.data for key, item in bucket.items()}
                for kind, bucket in self._objects.items()
            }
            self._wal.write_snapshot(self._rev, objects)

    def close(self) -> None:
        if self._coalesce_thread is not None:
            self._coalesce_closed = True
            self._coalesce_wake.set()
            self._coalesce_thread.join(timeout=5.0)
            self.flush_coalesced()  # nothing buffered outlives the store
        if self._wal is not None:
            self._wal.close()

    # -- revision ----------------------------------------------------------
    @property
    def revision(self) -> int:
        with self._mu:
            return self._rev

    def _next_rev(self) -> int:
        self._rev += 1
        return self._rev

    # -- writes ------------------------------------------------------------
    def create(self, kind: str, obj: dict, _trusted: bool = False) -> dict:
        """``_trusted`` marks ``obj`` as privately owned (the typed
        client's freshly built ``to_dict`` wire form), skipping the
        defensive deep copy — one of two per create on the hot arrival
        path (the other is the shared event/return copy below)."""
        # fault seam BEFORE the lock and any mutation: an injected commit
        # failure models apiserver/etcd overload — the write never starts
        faults.hit("store.commit", op="create", kind=kind)
        tr = tracing.current()
        with (tr.span("store.txn", cat="store", op="create", kind=kind)
              if tr is not None else tracing.NULL_SPAN), self._mu:
            meta = obj.setdefault("metadata", {})
            key = object_key(meta.get("namespace", "default"), meta.get("name", ""))
            bucket = self._objects.setdefault(kind, {})
            if key in bucket:
                raise AlreadyExistsError(f"{kind} {key} already exists")
            rev = self._next_rev()
            data = obj if _trusted else _fast_deepcopy(obj)
            m = data["metadata"]
            m.setdefault("namespace", "default")
            if not m.get("uid"):
                m["uid"] = new_uid()
            m["resourceVersion"] = rev
            m["creationRevision"] = rev
            bucket[key] = _Item(data=data, revision=rev)
            ev_copy = _fast_deepcopy(data)
            self._emit(WatchEvent(ADDED, kind, key, rev, ev_copy))
            # like update(): the event copy doubles as the caller's return
            # value — both are read-only by contract, and the stored dict
            # never escapes.  One deepcopy per create, not two (the create
            # flood is the churn bench's arrival path).
            return ev_copy

    def create_many(self, kind: str, objs: list[dict],
                    _trusted: bool = False) -> list[Optional[dict]]:
        """Batch create: every object commits under ONE lock acquisition
        (one revision run, one WAL stretch, one watch-fanout pass) — the
        txn shape for a churn wave's worth of arrivals or a whole bind
        wave's Events, where per-create lock round-trips and sink wake-ups
        are pure overhead.  Semantics per item are exactly :meth:`create`
        (same defaulting, same ADDED event, events in list order); a
        failed item (already exists / malformed) yields None in its slot
        and the REST of the batch still commits — the best-effort contract
        batch writers (the event sink) want, and loud enough for callers
        that care to check."""
        faults.hit("store.commit", op="create_many", kind=kind)
        # correlation id (ISSUE 7): minted per batch txn whether or not
        # tracing is on — it rides the watch frame to every consumer
        txn = tracing.next_txn("create_many")
        tr = tracing.current()
        with (tr.span("store.txn", cat="store", op="create_many", kind=kind,
                      txn=txn, n=len(objs))
              if tr is not None else tracing.NULL_SPAN) as sp:
            results: list[Optional[dict]] = []
            with self._mu:
                bucket = self._objects.setdefault(kind, {})
                events: list[WatchEvent] = []
                for obj in objs:
                    try:
                        meta = obj.setdefault("metadata", {})
                        key = object_key(meta.get("namespace", "default"),
                                         meta.get("name", ""))
                        if key in bucket:
                            results.append(None)
                            continue
                        rev = self._next_rev()
                        data = obj if _trusted else _fast_deepcopy(obj)
                        m = data["metadata"]
                        m.setdefault("namespace", "default")
                        if not m.get("uid"):
                            m["uid"] = new_uid()
                        m["resourceVersion"] = rev
                        m["creationRevision"] = rev
                        bucket[key] = _Item(data=data, revision=rev)
                        ev_copy = _fast_deepcopy(data)
                        events.append(WatchEvent(ADDED, kind, key, rev, ev_copy))
                        results.append(ev_copy)
                    except Exception:  # noqa: BLE001 - one bad item, not the batch
                        results.append(None)
                # the whole txn fans out as ONE column-packed frame per
                # frame-aware watcher (per-event to everyone else)
                self._emit_many(events, txn=txn)
            sp.set(committed=len(events))
            return results

    def update(
        self, kind: str, obj: dict, expect_rev: Optional[int] = None, _trusted: bool = False
    ) -> dict:
        """CAS write.  ``expect_rev`` defaults to obj.metadata.resourceVersion;
        pass 0/None there to force-write (last-write-wins).  ``_trusted``
        marks ``obj`` as privately owned (guaranteed_update's copy), skipping
        one defensive deep copy on the hot write path."""
        faults.hit("store.commit", op="update", kind=kind)
        tr = tracing.current()
        with (tr.span("store.txn", cat="store", op="update", kind=kind)
              if tr is not None else tracing.NULL_SPAN), self._mu:
            meta = obj.get("metadata") or {}
            key = object_key(meta.get("namespace", "default"), meta.get("name", ""))
            bucket = self._objects.setdefault(kind, {})
            item = bucket.get(key)
            if item is None:
                raise NotFoundError(f"{kind} {key}")
            if expect_rev is None:
                expect_rev = int(meta.get("resourceVersion", 0)) or None
            if expect_rev is not None and item.revision != expect_rev:
                raise ConflictError(
                    f"{kind} {key}: expected rev {expect_rev}, have {item.revision}"
                )
            rev = self._next_rev()
            data = obj if _trusted else _fast_deepcopy(obj)
            m = data["metadata"]
            m["uid"] = item.data["metadata"]["uid"]
            m["resourceVersion"] = rev
            m["creationRevision"] = item.data["metadata"].get("creationRevision", 0)
            # deletion tombstone is immutable once set (graceful deletion)
            prior_del = item.data["metadata"].get("deletionRevision")
            if prior_del is not None:
                m["deletionRevision"] = prior_del
                if not m.get("finalizers"):
                    # last finalizer cleared on a deleting object → finish the
                    # delete (store.go:977: deleteForEmptyFinalizers)
                    del bucket[key]
                    final = _fast_deepcopy(data)
                    self._emit(WatchEvent(DELETED, kind, key, rev, final))
                    return final
            bucket[key] = _Item(data=data, revision=rev)
            ev_copy = _fast_deepcopy(data)
            self._emit(WatchEvent(MODIFIED, kind, key, rev, ev_copy))
            # the event copy doubles as the caller's return value: both are
            # read-only by contract, and the stored dict never escapes
            return ev_copy

    def bind_many(self, items: list[tuple[str, str, str]]) -> list[Optional[str]]:
        """Batch placement commit: for each (namespace, name, node_name),
        CAS-set ``spec.nodeName`` under ONE lock acquisition — the etcd-txn
        analogue of issuing one BindingREST call per pod, shaped for the TPU
        batch path where hundreds of thousands of bindings land at once.

        Returns one entry per item: None on success, else an error string
        ("not found" / "conflict: <node>").  Per-pod watch events are still
        emitted (informers depend on them); their objects share the stored
        containers/status structures and own fresh spec/metadata dicts —
        the only fields this path ever mutates in place."""
        faults.hit("store.commit", op="bind_many", kind="Pod")
        txn = tracing.next_txn("bind_many")
        tr = tracing.current()
        with (tr.span("store.txn", cat="store", op="bind_many", kind="Pod",
                      txn=txn, n=len(items))
              if tr is not None else tracing.NULL_SPAN) as sp:
            return self._bind_many_locked(items, txn, sp)

    def _bind_many_locked(self, items, txn, sp) -> list[Optional[str]]:
        results: list[Optional[str]] = []
        with self._mu:
            bucket = self._objects.setdefault("Pod", {})
            events: list[WatchEvent] = []
            prev_revs: list[int] = []
            for namespace, name, node_name in items:
                key = object_key(namespace, name)
                # per-item seam: ONE pod's CAS fails while the rest of
                # the batch commits (the real-world partial-bind shape) —
                # surfaced as this item's error string, never an exception
                if faults.hit("scheduler.bind", pod=key, node=node_name,
                              via="bind_many") is not None:
                    results.append("injected: bind fault")
                    continue
                item = bucket.get(key)
                if item is None:
                    results.append("not found")
                    continue
                spec = item.data.setdefault("spec", {})
                cur = spec.get("nodeName", "")
                if cur and cur != node_name:
                    results.append(f"conflict: already bound to {cur}")
                    continue
                prev_rev = item.revision
                rev = self._next_rev()
                spec["nodeName"] = node_name
                item.data["metadata"]["resourceVersion"] = rev
                item.revision = rev
                ev_obj = {
                    **item.data,
                    "spec": dict(spec),
                    "metadata": dict(item.data["metadata"]),
                }
                events.append(WatchEvent(MODIFIED, "Pod", key, rev, ev_obj))
                # the columnar-confirm fence: the revision this pod held
                # BEFORE the bind CAS — a consumer that assumed the pod at
                # exactly this revision knows nothing else changed
                prev_revs.append(prev_rev)
                results.append(None)
            self._emit_many(events, prev_revisions=prev_revs, txn=txn)
        sp.set(committed=len(events),
               errors=sum(1 for r in results if r is not None))
        return results

    def guaranteed_update(
        self, kind: str, namespace: str, name: str, mutate: Callable[[dict], dict]
    ) -> dict:
        """Read-modify-write retry loop (``etcd3/store.go:257``).  ``mutate``
        receives a deep copy and returns the new object (or raises)."""
        while True:
            cur = self.get(kind, namespace, name)  # private deep copy already
            rev = int(cur["metadata"]["resourceVersion"])
            new = mutate(cur)
            try:
                return self.update(kind, new, expect_rev=rev, _trusted=True)
            except ConflictError:
                continue

    def delete(self, kind: str, namespace: str, name: str, expect_rev: Optional[int] = None) -> dict:
        """Delete, honoring finalizers (reference
        ``registry/generic/registry/store.go:977`` graceful deletion): while
        ``metadata.finalizers`` is non-empty the object is only *marked*
        deleting (``deletionRevision`` tombstone, MODIFIED event); the actual
        removal happens when an update clears the last finalizer."""
        faults.hit("store.commit", op="delete", kind=kind)
        tr = tracing.current()
        with (tr.span("store.txn", cat="store", op="delete", kind=kind)
              if tr is not None else tracing.NULL_SPAN), self._mu:
            key = object_key(namespace, name)
            bucket = self._objects.setdefault(kind, {})
            item = bucket.get(key)
            if item is None:
                raise NotFoundError(f"{kind} {key}")
            if expect_rev is not None and item.revision != expect_rev:
                raise ConflictError(f"{kind} {key}")
            rev = self._next_rev()
            if item.data["metadata"].get("finalizers"):
                item.data["metadata"]["deletionRevision"] = rev
                item.data["metadata"]["resourceVersion"] = rev
                item.revision = rev
                marked = _fast_deepcopy(item.data)
                self._emit(WatchEvent(MODIFIED, kind, key, rev, marked))
                return marked
            del bucket[key]
            final = _fast_deepcopy(item.data)
            final["metadata"]["deletionRevision"] = rev
            self._emit(WatchEvent(DELETED, kind, key, rev, final))
            return final

    # -- reads -------------------------------------------------------------
    def get(self, kind: str, namespace: str, name: str) -> dict:
        with self._mu:
            item = self._objects.get(kind, {}).get(object_key(namespace, name))
            if item is None:
                raise NotFoundError(f"{kind} {namespace}/{name}")
            return _fast_deepcopy(item.data)

    # -- replication apply (store/replication.py follower side) ------------
    def apply_replicated(self, ev: WatchEvent) -> None:
        """Apply a committed event from a leader verbatim: the state
        transition is taken as-is (no CAS re-check — it already won on the
        leader), the revision sequence follows the leader's, and local
        watchers/WAL observe it exactly like a local commit.  Idempotent:
        an event at or below the applied revision is a no-op (duplicate
        shipping during catch-up races)."""
        with self._mu:
            if ev.revision <= self._rev:
                return
            bucket = self._objects.setdefault(ev.kind, {})
            if ev.type == DELETED:
                bucket.pop(ev.key, None)
            else:
                bucket[ev.key] = _Item(data=_fast_deepcopy(ev.object),
                                       revision=ev.revision)
            self._rev = ev.revision
            self._emit(WatchEvent(ev.type, ev.kind, ev.key, ev.revision,
                                  _fast_deepcopy(ev.object)))

    def install_snapshot(self, rev: int, objects: dict) -> None:
        """Replace state wholesale (raft InstallSnapshot analogue): used
        when a rejoining replica is older than the leader's log window."""
        with self._mu:
            # pending events precede the snapshot: deliver them before
            # the state jump (watchers older than the snapshot relist)
            self._flush_pending_locked()
            self._objects = {
                kind: {key: _Item(data=_fast_deepcopy(data),
                                  revision=data["metadata"].get("resourceVersion", rev))
                       for key, data in bucket.items()}
                for kind, bucket in objects.items()
            }
            self._rev = rev
            self._log.clear()  # watchers older than the snapshot must relist
            if self._wal is not None:
                # durability must follow the state jump: the old WAL holds
                # pre-snapshot events that no longer compose with the new
                # revision line — snapshot it now or recovery diverges
                self.compact()

    def list(self, kind: str, namespace: Optional[str] = None) -> tuple[list[dict], int]:
        """Returns (objects, list_revision) — the revision to start a watch
        from, exactly the reflector's LIST-then-WATCH contract
        (``tools/cache/reflector.go:239``)."""
        with self._mu:
            out = []
            for key, item in self._objects.get(kind, {}).items():
                ns = item.data["metadata"].get("namespace", "")
                if namespace is None or ns == namespace:
                    out.append(_fast_deepcopy(item.data))
            out.sort(key=lambda d: (d["metadata"]["namespace"], d["metadata"]["name"]))
            return out, self._rev

    def list_columns(self, kind: str = "Pod", namespace: Optional[str] = None):
        """Columnar LIST fast path (Pod and Node): one packed batch of
        raw object views + parallel identity (and for pods request/
        signature) columns — see ``store/columns.py``.  The views share
        deep subtrees with the stored dicts (zero-copy): only the two
        levels the store ever mutates in place are copied, under the
        lock, so consumers get a consistent snapshot at the returned
        revision.  Consumers MUST treat the payloads as read-only (the
        informer contract).  Returns None for kinds without a columnar
        emitter — callers fall back to :meth:`list`."""
        from .columns import COLUMN_BATCH_KINDS, batch_from_views, shallow_object_view

        if kind not in COLUMN_BATCH_KINDS:
            return None
        with self._mu:
            rev = self._rev
            views = []
            for item in self._objects.get(kind, {}).values():
                if namespace is not None:
                    ns = item.data.get("metadata", {}).get("namespace", "")
                    if ns != namespace:
                        continue
                views.append(shallow_object_view(item.data))
        return batch_from_views(views, rev, kind=kind)

    # -- watch -------------------------------------------------------------
    def watch(self, kind: Optional[str] = None, from_revision: Optional[int] = None,
              frames: bool = False) -> Watch:
        """Watch events for ``kind`` (None = all kinds) strictly after
        ``from_revision`` (None = now).  Raises if the revision has fallen
        out of the event-log window ("too old resource version" — the
        reflector then relists).

        ``frames=True`` opts this watcher into column-packed delivery:
        a correlated batch txn (``create_many``/``bind_many``) arrives as
        ONE :class:`~.frames.WatchFrame` instead of N events (the log
        replay below stays per-event — only live batches frame)."""
        with self._mu:
            # ordering barrier: flush the open coalescing window before
            # the log replay below — otherwise the replay (which reads
            # the per-event log, where buffered events already live)
            # would be followed by a flush frame re-delivering them
            self._flush_pending_locked()
            q: "queue.Queue[Optional[WatchEvent]]" = queue.Queue()
            if from_revision is not None and from_revision < self._rev:
                oldest = self._log[0].revision if self._log else self._rev + 1
                if from_revision + 1 < oldest:
                    raise ExpiredRevisionError(
                        f"revision {from_revision} too old (oldest {oldest})"
                    )
                for ev in self._log:
                    if ev.revision > from_revision and (kind is None or ev.kind == kind):
                        q.put(ev)  # shared-immutable (see _emit)
            self._watchers.append((kind, q, frames))
            return Watch(self, q)

    def _remove_watch(self, q) -> None:
        with self._mu:
            self._watchers = [(k, w, f) for (k, w, f) in self._watchers
                              if w is not q]

    def _append_log(self, ev: WatchEvent) -> None:
        """Durability + watch-cache window for one event (no fan-out)."""
        if self._wal is not None:
            # durability BEFORE visibility: the record is on disk before
            # any watcher (or the caller) observes the commit
            self._wal.append(ev.type, ev.kind, ev.key, ev.revision, ev.object)
            if self._wal.needs_compaction():
                self.compact()  # RLock: safe to re-enter from the write path
        self._log.append(ev)  # deque maxlen trims the window in C

    def _replicate(self, ev: WatchEvent) -> None:
        """Per-event shipping hook (no-op here): ``ReplicatedStore``
        overrides it to ship to followers.  Called on BOTH the per-event
        and the batch emit path, after local durability."""

    def _emit(self, ev: WatchEvent) -> None:
        # WatchEvent.object is SHARED-IMMUTABLE: one private copy is made at
        # emit time and handed to the log and every watcher.  Consumers must
        # not mutate it (the informer parses it into fresh typed objects;
        # the mutation detector catches violations in tests).
        self._append_log(ev)
        self._replicate(ev)
        if self._coalesce_window > 0.0:
            # durability and the replay window are already per-event
            # (above); only LIVE delivery waits for the window.  Without
            # coalescing an event committed before watch() registration
            # is not delivered live either, so skipping the buffer when
            # nobody watches changes nothing (watch() replays the log).
            if self._watchers:
                self._buffer_event(ev)
            return
        for kind, q, _frames in self._watchers:
            if kind is None or kind == ev.kind:
                q.put(ev)

    # -- time-window coalescing (the serving-tier broadcaster seam) --------
    def _buffer_event(self, ev: WatchEvent) -> None:
        """Fold one committed event into the open window (opening one if
        needed).  Caller holds the store lock."""
        p = self._pending
        if p is None:
            p = self._pending = _PendingBatch(
                time.monotonic() + self._coalesce_window,
                tracing.next_txn("coalesce"))
            self._coalesce_wake.set()
        k = (ev.kind, ev.key)
        if k in p.latest:
            # latest wins: the superseded delivery is dropped, and the
            # key moves to the tail so the flush frame's revision column
            # stays strictly increasing (each key sorted by its LATEST
            # commit, which is also this window's arrival order)
            del p.latest[k]
            p.folded += 1
        p.latest[k] = ev
        # bounded: hard per-window key cap — the window flushes inline
        # before the pending dict can outgrow it
        if len(p.latest) >= self._coalesce_max_keys:
            self._flush_pending_locked()

    def flush_coalesced(self) -> None:
        """Deliver the open coalescing window NOW — the flusher thread's
        deadline path, an ordering barrier, or an explicit test/shutdown
        flush."""
        with self._mu:
            self._flush_pending_locked()

    def _flush_pending_locked(self) -> None:
        p = self._pending
        if p is None:
            return
        self._pending = None
        events = list(p.latest.values())
        if not events:
            return
        from . import frames as frames_mod

        m = DEFAULT_STORE_METRICS
        m.coalesce_flushes.inc()
        if p.folded:
            m.coalesced_events.inc(p.folded)
        by_kind: dict[str, list[WatchEvent]] = {}
        for ev in events:
            by_kind.setdefault(ev.kind, []).append(ev)
        # synthetic frames carry NO prev_revisions (fold hides the
        # intermediate transitions, so the pre-transition revision is
        # honestly unknown — consumers take the per-object fallback
        # compare, exactly the plain-update CAS semantics); the fence
        # (frame.revision = last entry) is exact as ever
        frames_by_kind: dict[str, object] = {}
        try:
            faults.hit("store.coalesce", n=len(events), folded=p.folded)
            if frames_mod.ENABLED:
                for kind, evs in by_kind.items():
                    if len(evs) > 1:
                        frames_by_kind[kind] = frames_mod.WatchFrame(
                            kind,
                            [e.type for e in evs],
                            [e.key for e in evs],
                            [e.revision for e in evs],
                            [e.object for e in evs],
                            prev_revisions=None,
                            txn=p.txn,
                        )
        except Exception:  # noqa: BLE001 - degrade, never drop state
            # flush-path failure (injected or real): this window falls
            # back to per-event delivery of the SAME folded events — the
            # state every consumer converges to is identical, only the
            # packing is lost
            frames_by_kind = {}
            m.coalesce_fallbacks.inc()
        for wkind, q, wants_frames in self._watchers:
            for kind, evs in by_kind.items():
                if wkind is not None and wkind != kind:
                    continue
                frame = frames_by_kind.get(kind) if wants_frames else None
                if frame is not None:
                    q.put(frame)
                else:
                    for ev in evs:
                        q.put(ev)

    def _coalesce_loop(self) -> None:
        """Daemon flusher: parked until a window opens, then sleeps out
        the deadline and flushes.  Never holds the store lock while
        sleeping."""
        while True:
            self._coalesce_wake.wait()  # blocking-ok — daemon flusher parked until a window opens
            self._coalesce_wake.clear()
            if self._coalesce_closed:
                return
            while not self._coalesce_closed:
                with self._mu:
                    p = self._pending
                    delay = 0.0 if p is None else p.deadline - time.monotonic()
                if p is None:
                    break
                if delay > 0:
                    time.sleep(delay)  # blocking-ok — outside the lock, bounded by coalesce_window_s
                    continue
                self.flush_coalesced()

    def _emit_many(self, events: list[WatchEvent],
                   prev_revisions: Optional[list[int]] = None,
                   txn: Optional[str] = None) -> None:
        """Fan one correlated batch out: WAL + log stay per-event (the
        replay window and durability framing are unchanged), but every
        frame-aware watcher receives ONE column-packed
        :class:`~.frames.WatchFrame` — one queue put, one informer lock
        hold, one handler fan-out for the whole txn.  Per-event watchers
        (kubectl -w, controllers, pre-frame clients) see the identical
        event sequence they always did."""
        if not events:
            return
        # ordering barrier: a batch txn fans out at commit, so anything
        # buffered in an open coalescing window must reach the queues
        # first — watchers see revisions in order, no fence violations
        self._flush_pending_locked()
        for ev in events:
            self._append_log(ev)
            self._replicate(ev)
        frame = None
        from . import frames as frames_mod

        want_frame = len(events) > 1 and frames_mod.ENABLED
        kind = events[0].kind  # batch txns are single-kind by construction
        for wkind, q, wants_frames in self._watchers:
            if wkind is not None and wkind != kind:
                continue
            if wants_frames and want_frame:
                if frame is None:  # built once, shared-immutable
                    frame = frames_mod.WatchFrame(
                        kind,
                        [ev.type for ev in events],
                        [ev.key for ev in events],
                        [ev.revision for ev in events],
                        [ev.object for ev in events],
                        prev_revisions=prev_revisions,
                        txn=txn,
                    )
                q.put(frame)
            else:
                for ev in events:
                    q.put(ev)


class ExpiredRevisionError(Exception):
    """Watch window compacted past the requested revision; caller must relist."""
