"""Packed column batches for the store→informer→tensorizer LIST path.

``Store.list`` deep-copies every object and callers then ``from_dict``
each one — O(object-size) twice per pod, which at 150k pods is most of a
cold seed.  ``Store.list_columns`` instead emits ONE batch:

- **raw views**: per object, the top two levels (object + metadata/spec)
  are fresh dicts; every deeper subtree is SHARED with the store.  This
  is safe because the store only ever mutates in place at those two
  levels (``bind_many`` sets ``spec.nodeName`` / ``metadata.
  resourceVersion``); every other write path installs a freshly
  deep-copied object.  Consumers inherit the informer contract: raw
  payloads are read-only.
- **identity columns**: keys, names, namespaces, node names as flat
  lists — what informer seeding reads, available without touching a
  single typed object;
- **signature ids**: ``sig_ids``/``sig_keys`` — the scheduling-
  equivalence grouping (``models.snapshot.pod_signature_key``) computed
  once at emit from the raw dicts; ``pods()`` pre-seeds each lazy pod's
  ``_sig_key`` memo so the backend's segmenter and ``build_static``
  never recompute it;
- **derived columns on demand**: resource-request units
  (``req_units``/``nonzero_units``, [P, R] int32 in the canonical
  fixed-point units through a content-memoized container table) and
  ``phases``/``owner_refs`` are cached properties — a seed/relist that
  only needs keys + signatures never pays for them.

The dict path (``Store.list`` + eager ``from_dict``) stays untouched as
the compatibility oracle; ``bench.py --ab-pump`` A/Bs the two.
"""

from __future__ import annotations

from typing import Optional

import numpy as np


class PodColumnBatch:
    """One LIST result as parallel columns + shared-subtree raw views."""

    kind = "Pod"

    def __init__(self, raw: list[dict], revision: int):
        from ..models.snapshot import raw_pod_signature_key

        self.raw = raw
        self.revision = revision
        n = len(raw)
        self.keys: list[str] = [""] * n
        self.names: list[str] = [""] * n
        self.namespaces: list[str] = [""] * n
        self.node_names: list[str] = [""] * n
        self.sig_ids = np.zeros(n, dtype=np.int32)
        self.sig_keys: list[tuple] = []
        sig_index: dict[tuple, int] = {}
        for i, d in enumerate(raw):
            meta = d.get("metadata") or {}
            ns = meta.get("namespace", "default")
            name = meta.get("name", "")
            self.names[i] = name
            self.namespaces[i] = ns
            self.keys[i] = f"{ns}/{name}" if ns else name
            self.node_names[i] = (d.get("spec") or {}).get("nodeName", "")
            key = raw_pod_signature_key(d)
            gid = sig_index.get(key)
            if gid is None:
                gid = sig_index[key] = len(self.sig_keys)
                self.sig_keys.append(key)
            self.sig_ids[i] = gid

    def __len__(self) -> int:
        return len(self.raw)

    # -- derived columns (computed on first touch, cached) ------------------
    @property
    def _request_cols(self):
        got = self.__dict__.get("_req_cols")
        if got is None:
            from ..scheduler.units import NUM_RESOURCES, raw_request_units

            n = len(self.raw)
            req = np.zeros((n, NUM_RESOURCES), dtype=np.int32)
            nz = np.zeros((n, 2), dtype=np.int32)
            for i, d in enumerate(self.raw):
                r, un = raw_request_units(d.get("spec") or {})
                req[i] = r
                nz[i, 0] = un[0]
                nz[i, 1] = un[1]
            got = self.__dict__["_req_cols"] = (req, nz)
        return got

    @property
    def req_units(self) -> np.ndarray:
        return self._request_cols[0]

    @property
    def nonzero_units(self) -> np.ndarray:
        return self._request_cols[1]

    @property
    def phases(self) -> list[str]:
        got = self.__dict__.get("_phases")
        if got is None:
            got = self.__dict__["_phases"] = [
                (d.get("status") or {}).get("phase", "") for d in self.raw]
        return got

    @property
    def owner_refs(self) -> list:
        got = self.__dict__.get("_owner_refs")
        if got is None:
            from ..api.lazy import raw_controller_ref

            got = self.__dict__["_owner_refs"] = [
                raw_controller_ref(d.get("metadata") or {}) for d in self.raw]
        return got

    def pods(self) -> list:
        """Lazy pod views over the raw columns, signature memos
        pre-seeded (the wire batch IS the tensorizer's grouping input)."""
        from ..api.lazy import LazyPod

        out = []
        sig_keys = self.sig_keys
        for i, d in enumerate(self.raw):
            pod = LazyPod(d)
            object.__setattr__(pod, "_sig_key", sig_keys[int(self.sig_ids[i])])
            out.append(pod)
        return out

    # the kind-agnostic accessor informer seeding uses
    objects = pods

    # -- wire form (the apiserver's ?columnar=1 LIST payload) ---------------
    def to_wire(self) -> dict:
        # ships ONLY the raw views: every column is recomputed client-side
        # from them (cheaper than paying identity arrays on the wire that
        # from_wire would rebuild anyway)
        return {
            "kind": "PodColumnBatch",
            "resourceVersion": self.revision,
            "raw": self.raw,
        }

    @classmethod
    def from_wire(cls, d: dict) -> "PodColumnBatch":
        return cls(d.get("raw") or [], int(d.get("resourceVersion", 0)))


class NodeColumnBatch:
    """One Node LIST as identity columns + shared-subtree raw views
    (ISSUE 5 satellite: ROADMAP named Node the next columnar candidate).

    Nodes are cluster-scoped (bare-name keys) and the store never mutates
    a stored Node in place (status heartbeats go through
    guaranteed_update, which installs a fresh deep copy), so the same
    top-two-levels-fresh view contract holds.  The identity columns —
    keys/names plus the zone label the spread priorities read — let
    informer seeding and the tensorizer's node-axis ordering run without
    decoding a single typed object; ``objects()`` yields ``LazyNode``
    views whose sections decode on first touch."""

    kind = "Node"

    def __init__(self, raw: list[dict], revision: int):
        self.raw = raw
        self.revision = revision
        n = len(raw)
        self.keys: list[str] = [""] * n
        self.names: list[str] = [""] * n
        self.zones: list[str] = [""] * n
        for i, d in enumerate(raw):
            meta = d.get("metadata") or {}
            name = meta.get("name", "")
            self.names[i] = name
            ns = meta.get("namespace", "")
            self.keys[i] = f"{ns}/{name}" if ns else name
            labels = meta.get("labels") or {}
            self.zones[i] = labels.get(
                "failure-domain.beta.kubernetes.io/zone", "")

    def __len__(self) -> int:
        return len(self.raw)

    def objects(self) -> list:
        from ..api.lazy import LazyNode

        return [LazyNode(d) for d in self.raw]

    def to_wire(self) -> dict:
        return {
            "kind": "NodeColumnBatch",
            "resourceVersion": self.revision,
            "raw": self.raw,
        }

    @classmethod
    def from_wire(cls, d: dict) -> "NodeColumnBatch":
        return cls(d.get("raw") or [], int(d.get("resourceVersion", 0)))


# kind -> batch class (the store's emitter registry; extend per kind)
COLUMN_BATCH_KINDS = {"Pod": PodColumnBatch, "Node": NodeColumnBatch}


def shallow_object_view(data: dict) -> dict:
    """The zero-copy emit unit: top two levels fresh, subtrees shared
    (see module docstring for why this is safe against store writes).
    MUST be called while the store lock is held — the two copied levels
    are exactly the ones ``bind_many`` mutates in place."""
    top = dict(data)
    if "metadata" in top:
        top["metadata"] = dict(top["metadata"])
    if "spec" in top:
        top["spec"] = dict(top["spec"])
    return top


def batch_from_views(views: list[dict], revision: int,
                     kind: str = "Pod"):
    """Sort to ``Store.list`` order (namespace, name) — queue/drain order,
    and therefore binding parity, must be identical on both LIST paths —
    then pack the columns (safe outside the store lock: only shared
    subtrees are read, and those are never mutated in place)."""
    views.sort(key=lambda d: (d["metadata"].get("namespace", ""),
                              d["metadata"].get("name", "")))
    return COLUMN_BATCH_KINDS[kind](views, revision)
