"""Encryption at rest for the durable store.

Capability of the reference's value-transformer stack
(``staging/src/k8s.io/apiserver/pkg/storage/value/`` — encrypt-on-write,
decrypt-on-read, multi-key chains for rotation, plaintext fallback for
migration).  Record bytes pass through a ``Transformer`` between the
store and disk: the WAL and snapshot hold ciphertext; the in-memory
store never sees it.

Primitive: an authenticated stream cipher built from the stdlib's HMAC
(no external crypto dependency in this image):

- keys: ``enc_key``/``auth_key`` derived from the configured secret via
  HMAC-SHA256 domain separation;
- keystream: HMAC(enc_key, nonce ‖ counter) blocks XORed over the
  payload (HMAC-as-PRF in counter mode — the construction PBKDF2/HKDF
  build on);
- integrity: HMAC(auth_key, header ‖ nonce ‖ ciphertext), verified
  before decryption (encrypt-then-MAC);
- fresh 16-byte ``os.urandom`` nonce per record.

Rotation mirrors the reference's provider config: a chain encrypts with
its FIRST transformer and decrypts with whichever key id a record names;
an ``identity`` tail reads (and optionally writes) plaintext, so turning
encryption on over an existing WAL is a rolling migration, exactly like
``EncryptionConfig`` with ``identity`` as the last provider.
"""

from __future__ import annotations

import hashlib
import hmac
import os
import struct

_MAGIC = b"ktpuenc1"  # 8 bytes, versioned
_NONCE_LEN = 16
_TAG_LEN = 32
_KEYID_LEN = struct.Struct(">H")


class DecryptionError(Exception):
    """Unreadable record: unknown key id, bad tag, or truncation."""


def _derive(secret: bytes, label: bytes) -> bytes:
    return hmac.new(secret, b"ktpu-store-" + label, hashlib.sha256).digest()


class HMACStreamTransformer:
    """One key: authenticated HMAC-CTR stream encryption."""

    def __init__(self, key_id: str, secret: bytes):
        if not secret:
            raise ValueError("empty secret")
        self.key_id = key_id.encode() if isinstance(key_id, str) else key_id
        if len(self.key_id) > 0xFFFF:
            raise ValueError("key id too long")
        self._enc_key = _derive(secret, b"encrypt")
        self._auth_key = _derive(secret, b"authenticate")

    def _keystream(self, nonce: bytes, n: int) -> bytes:
        out = bytearray()
        counter = 0
        while len(out) < n:
            out += hmac.new(self._enc_key,
                            nonce + struct.pack(">Q", counter),
                            hashlib.sha256).digest()
            counter += 1
        return bytes(out[:n])

    def encrypt(self, plaintext: bytes) -> bytes:
        nonce = os.urandom(_NONCE_LEN)
        ct = bytes(a ^ b for a, b in
                   zip(plaintext, self._keystream(nonce, len(plaintext))))
        header = _MAGIC + _KEYID_LEN.pack(len(self.key_id)) + self.key_id
        tag = hmac.new(self._auth_key, header + nonce + ct,
                       hashlib.sha256).digest()
        return header + nonce + tag + ct

    def decrypt(self, data: bytes) -> bytes:
        header_len = len(_MAGIC) + _KEYID_LEN.size + len(self.key_id)
        header = data[:header_len]
        rest = data[header_len:]
        if len(rest) < _NONCE_LEN + _TAG_LEN:
            raise DecryptionError("truncated record")
        nonce = rest[:_NONCE_LEN]
        tag = rest[_NONCE_LEN:_NONCE_LEN + _TAG_LEN]
        ct = rest[_NONCE_LEN + _TAG_LEN:]
        want = hmac.new(self._auth_key, header + nonce + ct,
                        hashlib.sha256).digest()
        if not hmac.compare_digest(tag, want):
            raise DecryptionError("integrity check failed")
        return bytes(a ^ b for a, b in
                     zip(ct, self._keystream(nonce, len(ct))))


class TransformerChain:
    """Encrypt with the first key; decrypt by the key id a record names;
    fall through to plaintext for unprefixed (pre-encryption) records."""

    def __init__(self, transformers: list[HMACStreamTransformer],
                 write_plaintext: bool = False):
        if not transformers and not write_plaintext:
            raise ValueError("no transformers and plaintext writes disabled")
        self._by_id = {t.key_id: t for t in transformers}
        self._primary = transformers[0] if transformers else None
        self.write_plaintext = write_plaintext

    @classmethod
    def from_keys(cls, keys: list[tuple[str, bytes]],
                  write_plaintext: bool = False) -> "TransformerChain":
        return cls([HMACStreamTransformer(kid, secret)
                    for kid, secret in keys], write_plaintext)

    def encrypt(self, plaintext: bytes) -> bytes:
        if self._primary is None or self.write_plaintext:
            return plaintext
        return self._primary.encrypt(plaintext)

    def decrypt(self, data: bytes) -> bytes:
        if not data.startswith(_MAGIC):
            return data  # pre-encryption plaintext record (migration)
        off = len(_MAGIC)
        (kid_len,) = _KEYID_LEN.unpack(data[off:off + _KEYID_LEN.size])
        kid = data[off + _KEYID_LEN.size:off + _KEYID_LEN.size + kid_len]
        t = self._by_id.get(kid)
        if t is None:
            raise DecryptionError(f"no key for id {kid!r}")
        return t.decrypt(data)


def identity() -> TransformerChain:
    return TransformerChain([], write_plaintext=True)
