"""Durable persistence for the store: write-ahead log + snapshot.

The reference's L0 is etcd: every write lands in a raft-replicated WAL
before it is acknowledged, and periodic snapshots bound replay time
(``vendor/github.com/coreos/etcd``; forked WAL code under
``third_party/forked/etcd221``).  This module gives the in-process store
the same durability contract on one node:

- every committed event appends a ``[len][crc32][payload]`` record to
  ``wal.bin`` (binary wire codec — the same serialization the HTTP layer
  negotiates),
- ``snapshot.bin`` holds a full state image at a revision; opening a
  store replays snapshot + WAL tail,
- compaction rewrites the snapshot and truncates the WAL once it grows
  past ``compact_every`` records,
- a torn final record (crash mid-append) is detected **structurally**
  (short length prefix / short payload) or by a CRC mismatch on the
  file's last record, and truncated on replay — exactly the record that
  was never acknowledged (etcd's ``wal.ReadAll`` tail repair),
- a CRC mismatch on a record that is *not* the tail is different in kind:
  acknowledged history was corrupted, and recovery refuses to guess
  (:class:`CorruptWALError`) rather than silently dropping everything
  after it.

Replication/HA remains by the reference's own split: the store process
is the etcd analogue; stateless apiservers above it restart freely, and
control-plane daemons fail over with leader election.
"""

from __future__ import annotations

import os
import struct
import threading
import zlib
from typing import Optional

from .. import faults
from ..api import wire
from ..utils import tracing

SNAPSHOT = "snapshot.bin"
WAL = "wal.bin"
_LEN = struct.Struct(">I")
_CRC = struct.Struct(">I")
_HEADER = _LEN.size + _CRC.size
# v2 file marker: CRC-framed records follow.  A log without it is the
# v1 ``[len][payload]`` format and is read that way — an upgrade must
# never misparse acknowledged history as corruption.  (No collision
# risk: a v1 file starts with a 4-byte record length, and b"KTPU" as a
# big-endian length would be a ~1.2 GB record.)
_MAGIC = b"KTPUWAL2"


class CorruptWALError(Exception):
    """A non-tail WAL record failed its checksum: acknowledged history is
    damaged (bad disk, truncation in the middle, wrong file).  Replay
    stops loudly — silently dropping acked records would un-commit writes
    that callers were told succeeded."""


class WriteAheadLog:
    def __init__(self, data_dir: str, compact_every: int = 100_000,
                 fsync: bool = False, transformer=None):
        os.makedirs(data_dir, exist_ok=True)
        self.dir = data_dir
        self.compact_every = compact_every
        self.fsync = fsync
        self._mu = threading.Lock()
        self._wal_path = os.path.join(data_dir, WAL)
        self._snap_path = os.path.join(data_dir, SNAPSHOT)
        self._f = None
        self._records_since_snapshot = 0
        # encryption at rest (store/encryption.py, the reference's
        # storage/value transformer seam): record/snapshot bytes pass
        # through here on the way to and from disk; None = plaintext
        self.transformer = transformer
        # what the last recover() observed — the crash-consistency audit
        # trail the fault matrix asserts on
        self.last_recovery: dict = {"replayed": 0, "truncated_bytes": 0,
                                    "torn_tail": False, "revision": 0}
        # detected on read (recover/open): False for a pre-CRC v1 file,
        # which keeps its framing until compaction rewrites it as v2
        self._crc_format = True

    def _detect_format(self) -> None:
        if os.path.exists(self._wal_path) and os.path.getsize(self._wal_path) > 0:
            with open(self._wal_path, "rb") as f:
                self._crc_format = f.read(len(_MAGIC)) == _MAGIC
        else:
            self._crc_format = True

    # -- recovery ----------------------------------------------------------
    def recover(self) -> tuple[int, dict, int]:
        """Returns (revision, {kind: {key: data}}, replayed_records)."""
        rev = 0
        objects: dict[str, dict[str, dict]] = {}
        if os.path.exists(self._snap_path):
            with open(self._snap_path, "rb") as f:
                raw = f.read()
            if self.transformer is not None:
                raw = self.transformer.decrypt(raw)
            snap = wire.decode(raw)
            rev = int(snap["rev"])
            objects = snap["objects"]
        replayed = 0
        self._detect_format()
        valid_end = len(_MAGIC) if (self._crc_format and os.path.exists(
            self._wal_path) and os.path.getsize(self._wal_path) > 0) else 0
        for rec, offset in self._read_wal():
            replayed += 1
            valid_end = offset
            rev = max(rev, int(rec["r"]))
            kind, key = rec["k"], rec["key"]
            bucket = objects.setdefault(kind, {})
            if rec["t"] == "DELETED":
                bucket.pop(key, None)
            else:
                bucket[key] = rec["o"]
        # drop the torn/corrupt tail NOW: future appends must follow the
        # last valid record, or they'd be unreachable behind the garbage
        truncated = 0
        if os.path.exists(self._wal_path):
            size = os.path.getsize(self._wal_path)
            if size > valid_end:
                truncated = size - valid_end
                with open(self._wal_path, "r+b") as f:
                    f.truncate(valid_end)
        self._records_since_snapshot = replayed
        self.last_recovery = {"replayed": replayed,
                              "truncated_bytes": truncated,
                              "torn_tail": truncated > 0,
                              "revision": rev}
        return rev, objects, replayed

    def _read_wal(self):
        """Yields (record, end_offset) for every intact record.

        Torn appends (a crash mid-write) are detected two ways, both
        confined to the file TAIL: the length prefix or payload comes up
        short (structural), or the last record's CRC disagrees with its
        payload (the bytes landed but not all of them were the write's).
        Either way that record was never acknowledged and the tail is
        dropped.  A CRC mismatch on a record with valid records *after*
        it — or a structurally complete record mid-file that fails
        decryption/decoding — is real corruption of acknowledged history
        and propagates loudly rather than silently truncating the log."""
        if not os.path.exists(self._wal_path):
            return
        size = os.path.getsize(self._wal_path)
        header_size = _HEADER if self._crc_format else _LEN.size
        with open(self._wal_path, "rb") as f:
            if self._crc_format and size > 0:
                f.read(len(_MAGIC))
            while True:
                head = f.read(header_size)
                if len(head) < header_size:
                    return  # clean EOF or torn header
                (n,) = _LEN.unpack(head[: _LEN.size])
                payload = f.read(n)
                if len(payload) < n:
                    return  # torn record: crash mid-append, never acked
                if self._crc_format:
                    (want_crc,) = _CRC.unpack(head[_LEN.size:])
                    if zlib.crc32(payload) != want_crc:
                        if f.tell() >= size:
                            return  # tail half-written: torn, drop it
                        raise CorruptWALError(
                            f"{self._wal_path}: CRC mismatch at offset "
                            f"{f.tell() - n - header_size} with valid "
                            "records after it — acknowledged history is "
                            "damaged")
                if self.transformer is not None:
                    payload = self.transformer.decrypt(payload)
                yield wire.decode(payload), f.tell()

    # -- append ------------------------------------------------------------
    def open(self) -> None:
        self._detect_format()
        fresh = (not os.path.exists(self._wal_path)
                 or os.path.getsize(self._wal_path) == 0)
        self._f = open(self._wal_path, "ab")
        if fresh:
            # new logs are v2; a surviving v1 log keeps its framing
            # until the next compaction rewrites it
            self._f.write(_MAGIC)
            self._f.flush()

    def append(self, ev_type: str, kind: str, key: str, rev: int,
               obj: dict) -> None:
        fault = faults.hit("store.wal.append", kind=kind, key=key)
        payload = wire.encode({"t": ev_type, "k": kind, "key": key,
                               "r": rev, "o": obj})
        if self.transformer is not None:
            payload = self.transformer.encrypt(payload)
        header = _LEN.pack(len(payload))
        if self._crc_format:
            header += _CRC.pack(zlib.crc32(payload))
        tr = tracing.current()
        # span covers lock wait + write + fsync: the durable-append cost
        # a slow disk charges every txn
        with (tr.span("wal.append", cat="store", kind=kind)
              if tr is not None else tracing.NULL_SPAN), self._mu:
            if self._f is None:
                self.open()
            if fault is not None and fault.mode == "torn":
                # crash mid-append: the header promises more bytes than
                # land.  Flush what DID land (the crash happens after the
                # page made it out) and die like the process would.
                cut = max(0, int(len(payload) * fault.value))
                self._f.write(header)
                self._f.write(payload[:cut])
                self._f.flush()
                if self.fsync:
                    # torn-write fault: flush the partial record like the
                    # dying process would, under the same lock hold
                    # blocking-ok — fault path mirrors the real append's durability point
                    os.fsync(self._f.fileno())
                raise faults.FaultInjected(
                    f"torn WAL append for {kind}/{key} (crash mid-write: "
                    f"{cut}/{len(payload)} payload bytes on disk)")
            self._f.write(header)
            self._f.write(payload)
            self._f.flush()
            if self.fsync:
                # no caller may observe this txn before its bytes are on
                # disk, so the fsync completes inside the append's lock hold
                # blocking-ok — WAL durability IS the commit point
                os.fsync(self._f.fileno())
            self._records_since_snapshot += 1

    def needs_compaction(self) -> bool:
        return self._records_since_snapshot >= self.compact_every

    # -- snapshot / compaction ----------------------------------------------
    def write_snapshot(self, rev: int, objects: dict) -> None:
        """Atomic snapshot + WAL truncation (the never-lose-state order:
        new snapshot durable FIRST, then drop the log it subsumes)."""
        with self._mu:
            tmp = f"{self._snap_path}.tmp"
            blob = wire.encode({"rev": rev, "objects": objects})
            if self.transformer is not None:
                blob = self.transformer.encrypt(blob)
            with open(tmp, "wb") as f:
                f.write(blob)
                f.flush()
                # blocking-ok — snapshot durable before the rename that retires the WAL
                os.fsync(f.fileno())
            os.replace(tmp, self._snap_path)
            if self._f is not None:
                self._f.close()
            self._f = open(self._wal_path, "wb")  # truncate
            self._f.write(_MAGIC)  # compaction upgrades a v1 log to v2
            self._f.flush()
            self._crc_format = True
            self._records_since_snapshot = 0

    def close(self) -> None:
        with self._mu:
            if self._f is not None:
                self._f.close()
                self._f = None
