"""Durable persistence for the store: write-ahead log + snapshot.

The reference's L0 is etcd: every write lands in a raft-replicated WAL
before it is acknowledged, and periodic snapshots bound replay time
(``vendor/github.com/coreos/etcd``; forked WAL code under
``third_party/forked/etcd221``).  This module gives the in-process store
the same durability contract on one node:

- every committed event appends a length-prefixed record to ``wal.bin``
  (binary wire codec — the same serialization the HTTP layer negotiates),
- ``snapshot.bin`` holds a full state image at a revision; opening a
  store replays snapshot + WAL tail,
- compaction rewrites the snapshot and truncates the WAL once it grows
  past ``compact_every`` records,
- a torn final record (crash mid-append) is detected by its length
  prefix and dropped — exactly the record that was never acknowledged.

Replication/HA remains by the reference's own split: the store process
is the etcd analogue; stateless apiservers above it restart freely, and
control-plane daemons fail over with leader election.
"""

from __future__ import annotations

import os
import struct
import threading
from typing import Optional

from ..api import wire

SNAPSHOT = "snapshot.bin"
WAL = "wal.bin"
_LEN = struct.Struct(">I")


class WriteAheadLog:
    def __init__(self, data_dir: str, compact_every: int = 100_000,
                 fsync: bool = False, transformer=None):
        os.makedirs(data_dir, exist_ok=True)
        self.dir = data_dir
        self.compact_every = compact_every
        self.fsync = fsync
        self._mu = threading.Lock()
        self._wal_path = os.path.join(data_dir, WAL)
        self._snap_path = os.path.join(data_dir, SNAPSHOT)
        self._f = None
        self._records_since_snapshot = 0
        # encryption at rest (store/encryption.py, the reference's
        # storage/value transformer seam): record/snapshot bytes pass
        # through here on the way to and from disk; None = plaintext
        self.transformer = transformer

    # -- recovery ----------------------------------------------------------
    def recover(self) -> tuple[int, dict, int]:
        """Returns (revision, {kind: {key: data}}, replayed_records)."""
        rev = 0
        objects: dict[str, dict[str, dict]] = {}
        if os.path.exists(self._snap_path):
            with open(self._snap_path, "rb") as f:
                raw = f.read()
            if self.transformer is not None:
                raw = self.transformer.decrypt(raw)
            snap = wire.decode(raw)
            rev = int(snap["rev"])
            objects = snap["objects"]
        replayed = 0
        valid_end = 0
        for rec, offset in self._read_wal():
            replayed += 1
            valid_end = offset
            rev = max(rev, int(rec["r"]))
            kind, key = rec["k"], rec["key"]
            bucket = objects.setdefault(kind, {})
            if rec["t"] == "DELETED":
                bucket.pop(key, None)
            else:
                bucket[key] = rec["o"]
        # drop the torn/corrupt tail NOW: future appends must follow the
        # last valid record, or they'd be unreachable behind the garbage
        if (os.path.exists(self._wal_path)
                and os.path.getsize(self._wal_path) > valid_end):
            with open(self._wal_path, "r+b") as f:
                f.truncate(valid_end)
        self._records_since_snapshot = replayed
        return rev, objects, replayed

    def _read_wal(self):
        """Yields (record, end_offset) for every intact record.

        Torn appends (a crash mid-write) are STRUCTURAL: the length
        prefix or payload comes up short and the tail is dropped — that
        record was never acknowledged.  A structurally complete record
        that fails decryption/decoding is a different animal entirely
        (wrong key, or real corruption of acknowledged history) and
        propagates loudly rather than silently truncating the log."""
        if not os.path.exists(self._wal_path):
            return
        with open(self._wal_path, "rb") as f:
            while True:
                head = f.read(_LEN.size)
                if len(head) < _LEN.size:
                    return  # clean EOF or torn length prefix
                (n,) = _LEN.unpack(head)
                payload = f.read(n)
                if len(payload) < n:
                    return  # torn record: crash mid-append, never acked
                if self.transformer is not None:
                    payload = self.transformer.decrypt(payload)
                yield wire.decode(payload), f.tell()

    # -- append ------------------------------------------------------------
    def open(self) -> None:
        self._f = open(self._wal_path, "ab")

    def append(self, ev_type: str, kind: str, key: str, rev: int,
               obj: dict) -> None:
        payload = wire.encode({"t": ev_type, "k": kind, "key": key,
                               "r": rev, "o": obj})
        if self.transformer is not None:
            payload = self.transformer.encrypt(payload)
        with self._mu:
            if self._f is None:
                self.open()
            self._f.write(_LEN.pack(len(payload)))
            self._f.write(payload)
            self._f.flush()
            if self.fsync:
                os.fsync(self._f.fileno())
            self._records_since_snapshot += 1

    def needs_compaction(self) -> bool:
        return self._records_since_snapshot >= self.compact_every

    # -- snapshot / compaction ----------------------------------------------
    def write_snapshot(self, rev: int, objects: dict) -> None:
        """Atomic snapshot + WAL truncation (the never-lose-state order:
        new snapshot durable FIRST, then drop the log it subsumes)."""
        with self._mu:
            tmp = f"{self._snap_path}.tmp"
            blob = wire.encode({"rev": rev, "objects": objects})
            if self.transformer is not None:
                blob = self.transformer.encrypt(blob)
            with open(tmp, "wb") as f:
                f.write(blob)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self._snap_path)
            if self._f is not None:
                self._f.close()
            self._f = open(self._wal_path, "wb")  # truncate
            self._records_since_snapshot = 0

    def close(self) -> None:
        with self._mu:
            if self._f is not None:
                self._f.close()
                self._f = None
