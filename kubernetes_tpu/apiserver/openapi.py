"""OpenAPI (swagger 2.0) document generation from the live type registry.

Capability of the reference's published schema
(``api/openapi-spec/swagger.json``; served by
``staging/src/k8s.io/apiserver/pkg/server/routes/openapi.go``): a
machine-readable description of every kind's wire shape and every
resource's REST surface, generated — not handwritten — from the same
registry the server decodes with, so CRD-registered kinds appear the
moment they establish.

Schemas are inferred by walking each kind's canonical wire form (the
``to_dict`` of a default instance): the era's codegen derived swagger
from Go struct tags; here the dataclass wire encoding IS the source of
truth, so inferring from it cannot drift from what the server actually
speaks.
"""

from __future__ import annotations

import logging
from typing import Optional

from ..api import types as api

_SWAGGER_VERSION = "2.0"


def _schema_for(value) -> dict:
    if isinstance(value, bool):
        return {"type": "boolean"}
    if isinstance(value, int):
        return {"type": "integer", "format": "int64"}
    if isinstance(value, float):
        return {"type": "number", "format": "double"}
    if isinstance(value, str):
        return {"type": "string"}
    if isinstance(value, list):
        items = _schema_for(value[0]) if value else {"type": "object"}
        return {"type": "array", "items": items}
    if isinstance(value, dict):
        if not value:
            return {"type": "object", "additionalProperties": True}
        return {
            "type": "object",
            "properties": {str(k): _schema_for(v) for k, v in value.items()},
        }
    return {"type": "string"}  # Quantity and friends serialize as strings


def _definition(kind: str, cls) -> Optional[dict]:
    try:
        wire = cls().to_dict()
    except Exception as e:  # noqa: BLE001 - kind omitted, doc still serves
        # a kind with no zero-arg construction silently vanishing from
        # /openapi would be a confusing hole — name the omission
        logging.getLogger("kubernetes_tpu.apiserver").debug(
            "openapi: kind %s has no zero-arg schema (%s); omitted", kind, e)
        return None
    schema = _schema_for(wire)
    if cls.__doc__:
        schema["description"] = cls.__doc__.strip().splitlines()[0]
    schema["x-kubernetes-group-version-kind"] = [
        {"group": "", "version": "v1", "kind": kind}]
    return schema


def build_openapi(version: str = "v1") -> dict:
    """The full document: one definition per registered kind, one path
    item per resource (list/create at collection, get/put/patch/delete
    at item scope — the verbs the server actually routes)."""
    from ..api.types import CLUSTER_SCOPED_KINDS, KIND_PLURALS, KINDS

    definitions = {}
    paths = {}
    for kind, cls in sorted(KINDS.items()):
        schema = _definition(kind, cls)
        if schema is None:
            continue
        name = f"io.k8s.api.core.v1.{kind}"
        definitions[name] = schema
        plural = KIND_PLURALS.get(kind)
        if plural is None:
            continue
        ref = {"$ref": f"#/definitions/{name}"}
        namespaced = kind not in CLUSTER_SCOPED_KINDS
        base = (f"/api/v1/namespaces/{{namespace}}/{plural}"
                if namespaced else f"/api/v1/{plural}")
        ns_param = ([{"name": "namespace", "in": "path", "required": True,
                      "type": "string"}] if namespaced else [])
        paths[base] = {
            "get": {"operationId": f"list{kind}",
                    "parameters": ns_param,
                    "responses": {"200": {"description": "OK"}}},
            "post": {"operationId": f"create{kind}",
                     "parameters": ns_param + [
                         {"name": "body", "in": "body", "schema": ref}],
                     "responses": {"201": {"description": "Created",
                                           "schema": ref}}},
        }
        item = f"{base}/{{name}}"
        item_params = ns_param + [{"name": "name", "in": "path",
                                   "required": True, "type": "string"}]
        paths[item] = {
            "get": {"operationId": f"read{kind}", "parameters": item_params,
                    "responses": {"200": {"description": "OK", "schema": ref}}},
            "put": {"operationId": f"replace{kind}",
                    "parameters": item_params + [
                        {"name": "body", "in": "body", "schema": ref}],
                    "responses": {"200": {"description": "OK", "schema": ref}}},
            "patch": {"operationId": f"patch{kind}", "parameters": item_params,
                      "responses": {"200": {"description": "OK",
                                            "schema": ref}}},
            "delete": {"operationId": f"delete{kind}",
                       "parameters": item_params,
                       "responses": {"200": {"description": "OK"}}},
        }
    from .. import __version__

    return {
        "swagger": _SWAGGER_VERSION,
        "info": {"title": "kubernetes-tpu", "version": __version__},
        "paths": paths,
        "definitions": definitions,
    }
