"""kube-apiserver daemon (reference ``cmd/kube-apiserver/app/server.go:112``).

    python -m kubernetes_tpu.apiserver --port 6443 \
        [--token-file tokens.csv] [--authorization-mode RBAC] \
        [--audit-log audit.jsonl] [--event-log-window 300000]

``--token-file`` rows are ``token,user[,group1|group2]`` (the reference's
static token file)."""

from __future__ import annotations

import argparse
import logging
import sys

from ..admission import default_chain
from ..daemon import install_signal_stop, wait_forever
from ..store.store import Store
from .server import APIServer


def parse_token_file(path: str) -> dict:
    from ..auth import UserInfo

    tokens: dict[str, object] = {}
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            parts = line.split(",")
            user = parts[1] if len(parts) > 1 else parts[0]
            groups = parts[2].split("|") if len(parts) > 2 and parts[2] else []
            tokens[parts[0]] = UserInfo(name=user, groups=groups)
    return tokens


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="kubernetes_tpu.apiserver")
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--port", type=int, default=6443)
    ap.add_argument("--token-file", default=None)
    ap.add_argument("--authorization-mode", default=None,
                    choices=[None, "AlwaysAllow", "RBAC"])
    ap.add_argument("--audit-log", default=None)
    ap.add_argument("--event-log-window", type=int, default=300_000)
    ap.add_argument("--disable-admission", action="store_true")
    ap.add_argument("--data-dir", default=None,
                    help="durable state directory (WAL + snapshots; "
                         "restart recovers the cluster — the etcd analogue)")
    ap.add_argument("--fsync", action="store_true",
                    help="fsync every WAL append (durability over latency)")
    ap.add_argument("--tls-cert-file", default=None)
    ap.add_argument("--tls-private-key-file", default=None)
    ap.add_argument("--client-ca-file", default=None,
                    help="verify client certificates against this CA; a "
                    "verified peer Subject becomes the request identity "
                    "(CN = user, O = groups)")
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s %(name)s %(levelname)s %(message)s")

    store_kw = dict(event_log_window=args.event_log_window,
                    data_dir=args.data_dir, fsync=args.fsync)
    if args.disable_admission:
        store = Store(**store_kw)
    else:
        from ..admission import AdmittedStore

        store = AdmittedStore(default_chain(), **store_kw)
    if args.data_dir:
        logging.info("durable store at %s (recovered to revision %d)",
                     args.data_dir, store.revision)

    tokens = parse_token_file(args.token_file) if args.token_file else None
    authorizer = None
    if args.authorization_mode == "RBAC":
        from ..auth import RBACAuthorizer

        authorizer = RBACAuthorizer(store)
    auditor = None
    if args.audit_log:
        from ..auth.audit import Auditor, LogBackend

        auditor = Auditor(backends=[LogBackend(args.audit_log)])

    if bool(args.tls_cert_file) != bool(args.tls_private_key_file):
        ap.error("--tls-cert-file and --tls-private-key-file go together")
    if args.client_ca_file and not args.tls_cert_file:
        ap.error("--client-ca-file requires --tls-cert-file "
                 "(client certificates ride the TLS handshake)")
    tls = None
    authenticator = None
    if args.tls_cert_file:
        from .server import TLSConfig

        tls = TLSConfig(args.tls_cert_file, args.tls_private_key_file,
                        client_ca=args.client_ca_file)
        if args.client_ca_file:
            # cert-authenticated control plane: peer certs carry identity,
            # static tokens (if any) and bootstrap tokens still work, and
            # anonymous stays ON so `join` can fetch the signed
            # cluster-info discovery document without credentials
            # (kubeadm's bootstrap contract) — but anonymous is then
            # AUTHORIZED only for that discovery surface unless an
            # explicit --authorization-mode overrides
            from ..auth import (
                AuthenticatedOrDiscovery,
                BootstrapTokenAuthenticator,
                TokenFileAuthenticator,
                UnionAuthenticator,
            )

            chain = []
            if tokens is not None:
                chain.append(TokenFileAuthenticator(tokens))
            chain.append(BootstrapTokenAuthenticator(store))
            authenticator = UnionAuthenticator(*chain, allow_anonymous=True)
            if authorizer is None and args.authorization_mode is None:
                authorizer = AuthenticatedOrDiscovery()

    server = APIServer(store, host=args.host, port=args.port, tokens=tokens,
                       authenticator=authenticator,
                       authorizer=authorizer, auditor=auditor, tls=tls)
    server.start()
    print(f"apiserver serving on {server.url}", flush=True)
    stop = install_signal_stop()
    wait_forever(stop)
    server.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
