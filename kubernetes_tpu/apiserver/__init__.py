"""HTTP API server: the store over REST + watch streams (SURVEY.md L3/L4)."""

from .server import APIServer, TLSConfig
