"""Master↔node secure channel: the SSH-tunnel capability.

Capability of the reference's ``pkg/master/tunneler`` (SSHTunnler /
SSHTunnelList): when nodes are not directly reachable from the master,
apiserver→kubelet traffic (stats scrapes, logs, exec) rides per-node
tunnels that the master dials, health-checks, and re-establishes.  Here
the channel is a REAL byte relay instead of sshd:

- :class:`NodeTunnelAgent` runs node-side next to the kubelet read API
  (which binds loopback): a TCP listener that authenticates one HMAC
  token line (minted under the cluster signing key, like the exec
  credential) and then splices bytes bidirectionally to the local
  kubelet port.  Without the token the agent closes without relaying —
  reaching the agent's port is not enough.
- :class:`Tunneler` runs master-side: per-node registry, lazy dialing,
  TTL-cached liveness (``SecondsSinceSync``'s role), and plain HTTP
  spoken OVER the tunnel socket, so the apiserver's node proxy can route
  through it without the kubelet being directly routable.

Both ends are tick-friendly and carry stats; the apiserver takes an
optional ``tunneler`` and prefers it for node-proxy traffic.
"""

from __future__ import annotations

import hashlib
import hmac
import http.client
import socket
import threading
import time
from typing import Callable, Optional

from ..auth.authn import CLUSTER_SIGNING_KEY


def tunnel_token(node_name: str, key: bytes = CLUSTER_SIGNING_KEY) -> str:
    """The master's credential for a node's tunnel agent (HMAC under the
    cluster signing key, like ``kubelet_exec_token``)."""
    return hmac.new(key, f"node-tunnel:{node_name}".encode(),
                    hashlib.sha256).hexdigest()


class NodeTunnelAgent:
    """Node-side relay: authenticated TCP in, loopback kubelet out."""

    def __init__(self, node_name: str, target_port: int,
                 host: str = "127.0.0.1", port: int = 0,
                 key: bytes = CLUSTER_SIGNING_KEY):
        self.node_name = node_name
        self.target_port = target_port
        self._token = tunnel_token(node_name, key)
        self._srv = socket.create_server((host, port))
        self.port = self._srv.getsockname()[1]
        self._thread: Optional[threading.Thread] = None
        self._stopped = False
        # counters bumped from the accept loop AND per-connection threads;
        # unguarded += loses updates under concurrent dials (RL303)
        self._stats_mu = threading.Lock()
        self.stats = {"accepted": 0, "relayed": 0, "rejected": 0}

    def _bump(self, key: str) -> None:
        with self._stats_mu:
            self.stats[key] += 1

    def start(self) -> None:
        self._thread = threading.Thread(target=self._accept_loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stopped = True
        # close() alone does NOT wake a thread parked in accept() — the
        # kernel keeps the listening socket alive under the blocked
        # syscall and the agent would keep serving; shutdown() forces
        # accept to return, then the join guarantees the port is
        # actually released before stop() returns
        try:
            self._srv.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._srv.close()
        except OSError:
            pass
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def _accept_loop(self) -> None:
        while not self._stopped:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return  # listener closed
            self._bump("accepted")
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True).start()

    def _read_line(self, conn: socket.socket, limit: int = 256) -> str:
        buf = b""
        while not buf.endswith(b"\n") and len(buf) < limit:
            chunk = conn.recv(1)
            if not chunk:
                break
            buf += chunk
        return buf.decode(errors="replace").strip()

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            conn.settimeout(5.0)
            line = self._read_line(conn)
            if not (line.startswith("TUNNEL ")
                    and hmac.compare_digest(line[len("TUNNEL "):], self._token)):
                self._bump("rejected")
                conn.close()
                return
            conn.sendall(b"OK\n")
            conn.settimeout(None)
            upstream = socket.create_connection(
                ("127.0.0.1", self.target_port), timeout=5.0)
        except OSError:
            conn.close()
            return
        self._bump("relayed")
        # real byte splicing, one thread per direction (the tunnel IS the
        # transport — HTTP, chunked streams, anything rides it verbatim)
        t = threading.Thread(target=self._pump, args=(conn, upstream),
                             daemon=True)
        t.start()
        self._pump(upstream, conn)
        t.join(timeout=5)

    @staticmethod
    def _pump(src: socket.socket, dst: socket.socket) -> None:
        try:
            while True:
                data = src.recv(65536)
                if not data:
                    break
                dst.sendall(data)
        except OSError:
            pass
        finally:
            for s in (src, dst):
                try:
                    s.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass


class _TunnelHTTPConnection(http.client.HTTPConnection):
    """HTTPConnection whose transport is an already-handshaken tunnel."""

    def __init__(self, sock: socket.socket):
        super().__init__("tunnel")
        self.sock = sock

    def connect(self) -> None:  # pragma: no cover - sock pre-set
        pass


class Tunneler:
    """Master-side tunnel registry + dialer + health cache.

    ``register(node, host, port)`` records where the node's agent
    listens; ``request(node, ...)`` speaks HTTP over a fresh tunnel;
    ``healthy(node)`` answers from a TTL cache, re-probing on expiry
    (the reference's SSHTunnelList healthcheck loop, tick-shaped)."""

    def __init__(self, key: bytes = CLUSTER_SIGNING_KEY,
                 health_ttl: float = 10.0,
                 clock: Callable[[], float] = time.monotonic):
        self._key = key
        self.health_ttl = health_ttl
        self._clock = clock
        self._agents: dict[str, tuple[str, int]] = {}
        self._health: dict[str, tuple[float, bool]] = {}  # node -> (t, ok)
        self._mu = threading.Lock()
        self.stats = {"dials": 0, "dial_failures": 0, "requests": 0}

    def register(self, node_name: str, host: str, port: int) -> None:
        with self._mu:
            self._agents[node_name] = (host, port)

    def unregister(self, node_name: str) -> None:
        with self._mu:
            self._agents.pop(node_name, None)
            self._health.pop(node_name, None)

    def nodes(self) -> list[str]:
        with self._mu:
            return sorted(self._agents)

    def has(self, node_name: str) -> bool:
        """O(1) membership — the proxy's per-request check must not copy
        and sort a 5k-node registry."""
        with self._mu:
            return node_name in self._agents

    def dial(self, node_name: str, timeout: float = 5.0) -> socket.socket:
        """Open + authenticate a tunnel; raises OSError on any failure."""
        with self._mu:
            addr = self._agents.get(node_name)
        if addr is None:
            raise OSError(f"no tunnel agent registered for node {node_name!r}")
        with self._mu:
            self.stats["dials"] += 1
        try:
            sock = socket.create_connection(addr, timeout=timeout)
            sock.sendall(f"TUNNEL {tunnel_token(node_name, self._key)}\n".encode())
            buf = b""
            while not buf.endswith(b"\n") and len(buf) < 8:
                chunk = sock.recv(1)
                if not chunk:
                    break
                buf += chunk
            if buf.strip() != b"OK":
                sock.close()
                raise OSError("tunnel handshake rejected")
            with self._mu:
                # a successful dial IS a health probe: request traffic
                # keeps the cache warm so healthy() rarely has to probe
                self._health[node_name] = (self._clock(), True)
            return sock
        except OSError:
            with self._mu:
                self.stats["dial_failures"] += 1
                self._health[node_name] = (self._clock(), False)
            raise

    def healthy(self, node_name: str) -> bool:
        """TTL-cached tunnel liveness; a probe IS a full handshake."""
        now = self._clock()
        with self._mu:
            cached = self._health.get(node_name)
        if cached is not None and now - cached[0] < self.health_ttl:
            return cached[1]
        try:
            self.dial(node_name).close()
            ok = True
        except OSError:
            ok = False
        with self._mu:
            self._health[node_name] = (now, ok)
        return ok

    def check_all(self) -> dict[str, bool]:
        """One health sweep (the reference's healthcheck loop body)."""
        return {n: self.healthy(n) for n in self.nodes()}

    def request(self, node_name: str, method: str, path: str,
                body: Optional[bytes] = None,
                headers: Optional[dict] = None,
                timeout: float = 10.0) -> tuple[int, bytes, str]:
        """HTTP over the tunnel: (status, body, content-type)."""
        sock = self.dial(node_name, timeout=timeout)
        sock.settimeout(timeout)
        with self._mu:
            self.stats["requests"] += 1
        conn = _TunnelHTTPConnection(sock)
        try:
            conn.request(method, path, body=body, headers=headers or {})
            resp = conn.getresponse()
            data = resp.read()
            return (resp.status, data,
                    resp.headers.get("Content-Type", "application/json"))
        finally:
            conn.close()
