"""HTTP API server: the store served over REST with watch streaming.

Capability of the reference's generic API server + kube-apiserver
(SURVEY.md L3/L4): resource routes installed per kind
(``apiserver/pkg/endpoints/installer.go``), per-verb handlers
(``handlers/rest.go:150 GetResource``, ``:276 ListResource`` incl. the
watch upgrade, ``:388 createHandler``), the Binding subresource
(``pkg/registry/core/pod/storage/storage.go:128``), and a filter chain
(``server/config.go:469``) reduced to its behavioral essentials:
panic recovery → request logging → authentication (optional static bearer
tokens) → dispatch.

Wire form: JSON.  Watches are chunked JSON-lines streams exactly like the
reference's ``?watch=true`` (one ``{"type": ..., "object": ...}`` per
line), resumable via ``resourceVersion``.

Routes:
  GET    /healthz  /metrics  /version
  GET    /api/v1/{resource}[?namespace=&watch=true&resourceVersion=N]
  POST   /api/v1/{resource}
  GET    /api/v1/namespaces/{ns}/{resource}/{name}
  PUT    /api/v1/namespaces/{ns}/{resource}/{name}[?cas=true]
  DELETE /api/v1/namespaces/{ns}/{resource}/{name}
  POST   /api/v1/namespaces/{ns}/pods/{name}/binding
  POST   /api/v1/bindings:batch          (the TPU batch-bind txn)
  POST   /api/v1/{resource}:batch        (batch create: one store txn)
Cluster-scoped objects use ns "-" in paths.
"""

from __future__ import annotations

import json
import logging
import math
import sys
import threading
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse

from .. import faults
from ..admission.framework import AdmissionDenied
from ..utils import tracing
from ..utils.health import handle_debug_path
from ..store.store import (
    AlreadyExistsError,
    ConflictError,
    ExpiredRevisionError,
    NotFoundError,
    Store,
)
from ..utils.metrics import Counter, Histogram, Registry

logger = logging.getLogger("kubernetes_tpu.apiserver")

# SelfSubjectAccessReview route (reference authorization.k8s.io group,
# served by the generic apiserver; evaluated against the live authorizer)
SSAR_PATH = "/apis/authorization.k8s.io/v1/selfsubjectaccessreviews"

# binary wire negotiation (reference application/vnd.kubernetes.protobuf)
from ..api.wire import CONTENT_TYPE as BINARY_CONTENT_TYPE  # noqa: E402


class TLSConfig:
    """Serving-side TLS for the wire server (reference
    ``--tls-cert-file``/``--tls-private-key-file``/``--client-ca-file``).
    With ``client_ca`` set, the handshake REQUESTS (not requires) a client
    certificate and verifies it against the CA; a verified peer cert
    becomes the request identity via
    ``X509CertificateAuthenticator.from_peercert`` — token-bearing clients
    still authenticate normally without one."""

    def __init__(self, certfile: str, keyfile: str,
                 client_ca: Optional[str] = None):
        self.certfile = certfile
        self.keyfile = keyfile
        self.client_ca = client_ca

    def context(self):
        import ssl

        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        ctx.load_cert_chain(self.certfile, self.keyfile)
        if self.client_ca:
            ctx.load_verify_locations(self.client_ca)
            ctx.verify_mode = ssl.CERT_OPTIONAL
        return ctx

# resource path segment -> kind, derived from the one type registry so
# every registered kind (incl. late-registered CRDs) is wire-addressable.
from ..api.types import CLUSTER_SCOPED_KINDS as CLUSTER_SCOPED  # noqa: E402
from ..api.types import kind_for_plural as _kind_for  # noqa: E402

# link the federation API group into the wire surface (the reference's
# federation-apiserver compiles its types in the same way) — importing
# registers the Cluster kind; federation/__init__ is import-light (lazy
# controller loading) so this does NOT pull in the controller tree
from ..federation import types as _federation_types  # noqa: E402,F401


class APIServer:
    """HTTP front end over the store.

    Filter order mirrors the reference's handler chain
    (``server/config.go:469 DefaultBuildHandlerChain``): panic recovery →
    request-info → max-in-flight → authentication → audit → impersonation
    → authorization → dispatch.
    ``tokens`` is the legacy static-token shorthand; pass ``authenticator``
    / ``authorizer`` / ``auditor`` for the full stack (admission runs in
    the store itself when constructed over an ``AdmittedStore``)."""

    def __init__(
        self,
        store: Store,
        host: str = "127.0.0.1",
        port: int = 0,
        tokens: Optional[dict[str, str]] = None,  # token -> username; None = authn off
        authenticator=None,
        authorizer=None,
        auditor=None,
        tls: Optional["TLSConfig"] = None,
        max_in_flight: int = 0,  # 0 = unlimited (reference default 400)
        tunneler=None,  # master↔node secure channel (tunneler.Tunneler)
    ):
        self.store = store
        self.tls = tls
        self.tunneler = tunneler
        # max-in-flight filter (server/filters/maxinflight.go): a
        # semaphore, never a queue — overload answers 429 immediately
        self._inflight = (threading.Semaphore(max_in_flight)
                          if max_in_flight > 0 else None)
        self.tokens = tokens
        self.authenticator = authenticator
        if authenticator is None and tokens is not None:
            from ..auth import TokenFileAuthenticator, UnionAuthenticator

            self.authenticator = UnionAuthenticator(
                TokenFileAuthenticator(tokens), allow_anonymous=False
            )
        self.authorizer = authorizer
        self.auditor = auditor
        self.registry = Registry()
        self.request_count = self.registry.register(
            Counter("apiserver_request_count", "total requests")
        )
        self.request_latency = self.registry.register(
            Histogram("apiserver_request_latencies_microseconds")
        )
        # /telemetry ingest (ISSUE 13): records shipped by daemons'
        # TelemetryShipper HTTP sinks.  Bounded — a chatty hollow fleet
        # must not grow the apiserver without bound; overflow evicts the
        # oldest and counts, mirroring the shipper's own drop posture.
        self.telemetry_records: deque = deque(maxlen=4096)
        self.telemetry_accepted = self.registry.register(Counter(
            "apiserver_telemetry_accepted_total",
            "telemetry records accepted at /telemetry"))
        # tolerated-failure visibility (ktpu-analyze CH702): best-effort
        # paths may fail, but never invisibly
        self.error_write_failures = self.registry.register(Counter(
            "apiserver_error_write_failures_total",
            "error responses that could not be written (client hung up)"))
        self.apiservice_status_failures = self.registry.register(Counter(
            "apiserver_apiservice_status_failures_total",
            "best-effort APIService availability updates that failed"))
        # overload control (ISSUE 17): an AdmissionThrottle (or anything
        # with .admit(resource, bodies) -> Optional[retry_after_s]) gates
        # the create paths at rung 3; None = always admit.  Distinct from
        # the validating admission chain (admission/framework.py): this
        # one answers 429 + Retry-After, not 400.
        self.admission_throttle = None
        self.admission_throttled = self.registry.register(Counter(
            "apiserver_admission_throttled_total",
            "create requests answered 429 + Retry-After by the overload "
            "admission gate (priority tier below the protected floor)"))
        self._telemetry_mu = threading.Lock()
        handler = _make_handler(self)
        if tls is not None:
            # The handshake must run in the per-connection worker thread,
            # never the accept loop: a client that connects and trickles
            # (or withholds) its ClientHello would otherwise block accept()
            # and deny service to everyone.
            ctx = tls.context()

            class _TLSServer(ThreadingHTTPServer):
                def get_request(self):
                    sock, addr = self.socket.accept()
                    return ctx.wrap_socket(
                        sock, server_side=True, do_handshake_on_connect=False
                    ), addr

                def finish_request(self, request, client_address):
                    request.settimeout(10.0)
                    request.do_handshake()
                    request.settimeout(None)
                    super().finish_request(request, client_address)

                def handle_error(self, request, client_address):
                    import ssl as _ssl

                    exc = sys.exc_info()[1]
                    if isinstance(exc, (_ssl.SSLError, TimeoutError,
                                        ConnectionError, OSError)):
                        return  # dropped/garbage handshakes are routine
                    super().handle_error(request, client_address)

            self.httpd = _TLSServer((host, port), handler)
        else:
            self.httpd = ThreadingHTTPServer((host, port), handler)
        self.port = self.httpd.server_port
        self._thread: Optional[threading.Thread] = None

    @property
    def url(self) -> str:
        scheme = "https" if self.tls is not None else "http"
        return f"{scheme}://{self.httpd.server_address[0]}:{self.port}"

    def start(self) -> None:
        self._thread = threading.Thread(target=self.httpd.serve_forever, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self.httpd.shutdown()
        if self._thread:
            self._thread.join(timeout=5)

    def ingest_telemetry(self, records: list) -> int:
        """Append shipped records to the bounded ring; returns accepted
        count (deque eviction handles overflow silently — the shipper
        side counts its own drops)."""
        with self._telemetry_mu:
            self.telemetry_records.extend(records)
        self.telemetry_accepted.inc(len(records))
        return len(records)

    def telemetry_snapshot(self) -> list:
        with self._telemetry_mu:
            return list(self.telemetry_records)


def _make_handler(server: APIServer):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        # -- plumbing ------------------------------------------------------
        def log_message(self, *args):
            pass

        def _send(self, code: int, obj) -> None:
            self._last_code = code
            # content negotiation (reference protobuf negotiation via
            # Accept: application/vnd.kubernetes.protobuf)
            if BINARY_CONTENT_TYPE in self.headers.get("Accept", ""):
                from ..api import wire as binwire

                data = binwire.encode(obj)
                ctype = BINARY_CONTENT_TYPE
            else:
                data = json.dumps(obj).encode()
                ctype = "application/json"
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(data)))
            for k, v in getattr(self, "_extra_headers", ()) or ():
                self.send_header(k, v)
            self._extra_headers = ()
            self.end_headers()
            self.wfile.write(data)

        def _error(self, code: int, reason: str, message: str,
                   retry_after: Optional[float] = None) -> None:
            if retry_after is not None:
                # RFC 7231 delta-seconds; ceil so a sub-second hint never
                # rounds down to an immediate retry
                self._extra_headers = (
                    ("Retry-After", str(max(1, math.ceil(retry_after)))),)
            self._send(code, {"kind": "Status", "code": code, "reason": reason, "message": message})

        def _body(self) -> dict:
            # cached: the auth filters peek at the body (namespace for
            # authorization) before dispatch consumes it
            if not hasattr(self, "_cached_body"):
                length = int(self.headers.get("Content-Length", 0))
                raw = self.rfile.read(length) if length else b""
                if raw and BINARY_CONTENT_TYPE in self.headers.get("Content-Type", ""):
                    from ..api import wire as binwire

                    self._cached_body = binwire.decode(raw)
                else:
                    self._cached_body = json.loads(raw) if raw else {}
            return self._cached_body

        def _admission_gate(self, resource: str, bodies: list) -> bool:
            """Overload admission (ISSUE 17): rung-3 throttling of create
            paths.  Returns False when the request was throttled — the
            429 + Retry-After response is already written (RemoteStore
            classifies it retryable and honors the hint).  The fault
            point ``apiserver.admit`` injects a throttle surge here (drop
            mode; the fault's value is the Retry-After seconds)."""
            retry_after: Optional[float] = None
            fault = faults.hit("apiserver.admit", resource=resource,
                               verb="create", n=len(bodies))
            if fault is not None and fault.mode == "drop":
                retry_after = float(fault.value or 1.0)
            else:
                gate = server.admission_throttle
                if gate is not None:
                    retry_after = gate.admit(resource, bodies)
            if retry_after is None:
                return True
            server.admission_throttled.inc()
            tr = tracing.current()
            if tr is not None:
                tr.instant("apiserver.admit.throttle", resource=resource,
                           n=len(bodies), retry_after=retry_after)
            self._error(429, "TooManyRequests",
                        f"admission throttled under overload "
                        f"({len(bodies)} {resource})",
                        retry_after=retry_after)
            return False

        def _serve_telemetry_ingest(self) -> None:
            # the shipper POSTs ndjson (one JSON record per line); plain
            # JSON documents ({"items": [...]}, a bare list, or a single
            # record) are accepted so curl debugging stays easy
            length = int(self.headers.get("Content-Length", 0))
            raw = self.rfile.read(length) if length else b""
            self._cached_body = {}  # raw body consumed here, not JSON
            ctype = self.headers.get("Content-Type", "")
            try:
                text = raw.decode()
                if "ndjson" in ctype:
                    records = [json.loads(line)
                               for line in text.splitlines() if line.strip()]
                else:
                    doc = json.loads(text) if text.strip() else []
                    if isinstance(doc, dict):
                        records = doc.get("items", [doc])
                    else:
                        records = list(doc)
            except (UnicodeDecodeError, ValueError) as e:
                return self._error(400, "BadRequest",
                                   f"undecodable telemetry payload: {e}")
            accepted = server.ingest_telemetry(records)
            self._send(200, {"kind": "Status", "code": 200,
                             "accepted": accepted})

        def _request_info(self, method: str):
            """(verb, resource, namespace, name) — the request-info filter
            (reference ``endpoints/filters/requestinfo``)."""
            url = urlparse(self.path)
            q = parse_qs(url.query)
            parts = [p for p in url.path.split("/") if p]
            verb = {"POST": "create", "PUT": "update", "DELETE": "delete",
                    "PATCH": "patch"}.get(method, "get")
            resource, ns, name = "", "", ""
            if parts and parts[0] == "apis" and len(parts) >= 2:
                # aggregated APIs: authorize/audit on "<group>/<resource>"
                # so RBAC rules can scope aggregated access per group
                group = parts[1]
                rest = parts[3:] if len(parts) >= 3 else []  # skip version
                if rest and rest[0] == "namespaces" and len(rest) >= 3:
                    ns = rest[1]
                    resource = f"{group}/{rest[2]}"
                    name = rest[3] if len(rest) >= 4 else ""
                else:
                    resource = f"{group}/{rest[0]}" if rest else group
                    name = rest[1] if len(rest) >= 2 else ""
                if method == "GET":
                    if q.get("watch", ["false"])[0] == "true":
                        verb = "watch"
                    elif not name:
                        verb = "list"
                return verb, resource, ns, name
            if len(parts) >= 3 and parts[0] == "api" and parts[1] == "v1":
                rest = parts[2:]
                if len(rest) == 1:
                    resource = rest[0]
                    if method == "GET":
                        verb = "watch" if q.get("watch", ["false"])[0] == "true" else "list"
                        ns = q.get("namespace", [""])[0] or ""
                    elif method == "POST":
                        # namespace rides in the body on collection creates
                        try:
                            ns = (self._body().get("metadata") or {}).get("namespace", "")
                        except Exception:
                            ns = ""
                elif rest[0] == "namespaces" and len(rest) >= 4:
                    ns = "" if rest[1] == "-" else rest[1]
                    resource = rest[2]
                    name = rest[3]
                    if len(rest) == 5 and rest[4] == "binding":
                        verb = "bind"
                    elif len(rest) == 5 and rest[4] in ("exec", "attach", "cp"):
                        # their own verb: create-pods rights must not imply
                        # command execution / container IO (pods/exec,
                        # pods/attach, pods/cp subresources — the reference
                        # gates attach and cp-over-exec the same way)
                        verb = "exec"
                    elif len(rest) == 5 and rest[4] == "eviction":
                        # distinct verb so create-pods rights do not imply
                        # eviction (reference treats pods/eviction as its
                        # own subresource)
                        verb = "evict"
                elif (rest[0] == "nodes" and len(rest) >= 3
                        and rest[2] == "proxy"):
                    # node proxy: RBAC scopes it as the "nodes/proxy"
                    # subresource (reference node proxy authz) — reading a
                    # node object must not imply reaching its kubelet
                    resource = "nodes/proxy"
                    name = rest[1]
            return verb, resource, ns, name

        def _auth_filters(self, method: str) -> bool:
            """authentication → audit(RequestReceived) → authorization.
            Returns False (response already sent) on 401/403."""
            self._user = None
            self._audit_user = None  # reset per request (keep-alive reuses
            # this handler instance across requests on one connection)
            if server.authenticator is not None:
                user = None
                if server.tls is not None and server.tls.client_ca:
                    # the reference's x509 path: the TLS handshake already
                    # verified the chain; map the peer subject to identity
                    from ..auth.authn import X509CertificateAuthenticator

                    peercert = getattr(self.connection, "getpeercert", lambda: None)()
                    user = X509CertificateAuthenticator.from_peercert(peercert)
                if user is None:
                    user = server.authenticator.authenticate(self.headers)
                if user is None:
                    self._error(401, "Unauthorized", "invalid or missing credentials")
                    return False
                # impersonation filter (endpoints/filters/impersonation.go):
                # Impersonate-User requires the "impersonate" verb on
                # "users" for the REAL identity; on success the request
                # proceeds AS the impersonated identity
                target = self.headers.get("Impersonate-User", "")
                if target:
                    from ..auth import ALLOW, AuthzAttributes, UserInfo

                    if server.authorizer is None:
                        self._error(403, "Forbidden",
                                    "impersonation requires an authorizer")
                        return False
                    # repeated headers (kubectl sends one per --as-group)
                    groups = [g.strip()
                              for raw in (self.headers.get_all("Impersonate-Group")
                                          or [])
                              for g in raw.split(",") if g.strip()]
                    # EVERY impersonated identity part is authorized for
                    # the REAL user: users AND each group — otherwise
                    # impersonate-users rights escalate to arbitrary
                    # group membership (impersonation.go checks each)
                    checks = [("users", target)] + [("groups", g) for g in groups]
                    for resource_name, name in checks:
                        decision, reason = server.authorizer.authorize(
                            AuthzAttributes(user=user, verb="impersonate",
                                            resource=resource_name, name=name))
                        if decision != ALLOW:
                            self._error(
                                403, "Forbidden",
                                f"cannot impersonate {resource_name[:-1]} "
                                f"{name!r}: {reason}")
                            return False
                    # the AUDIT trail must keep the real actor: the
                    # reference annotates impersonated requests with the
                    # original user (filters/impersonation.go + audit)
                    self._audit_user = f"{target} (impersonated-by {user.name})"
                    user = UserInfo(name=target, groups=groups)
                self._user = user
            verb, resource, ns, name = self._request_info(method)
            if server.auditor is not None:
                server.auditor.record(
                    "RequestReceived",
                    getattr(self, "_audit_user", None)
                    or (self._user.name if self._user else ""),
                    verb, resource, ns, name,
                )
            if urlparse(self.path).path in ("/api", "/api/v1", "/apis",
                                            "/openapi/v2", "/swagger.json",
                                            SSAR_PATH):
                # discovery and self-subject access review are granted to
                # every AUTHENTICATED identity (the reference's
                # system:discovery / system:basic-user bindings) — clients
                # must enumerate resources and ask "can I?" before any RBAC
                # rule can name them
                return True
            if server.authorizer is not None:
                from ..auth import ALLOW, ANONYMOUS, AuthzAttributes

                # no authenticator configured -> authorize as anonymous
                # (fail closed, never skip an explicit authorizer)
                user = self._user if self._user is not None else ANONYMOUS
                decision, reason = server.authorizer.authorize(AuthzAttributes(
                    user=user, verb=verb, resource=resource,
                    namespace=ns, name=name, path=urlparse(self.path).path,
                ))
                if decision != ALLOW:
                    self._error(403, "Forbidden", reason)
                    return False
            # per-request identity for admission plugins (thread-local on
            # AdmittedStore, so concurrent handler threads don't race)
            if self._user is not None and hasattr(server.store, "user"):
                server.store.user = self._user.name
            return True

        # -- dispatch ------------------------------------------------------
        def _route(self, method: str) -> None:
            import time

            start = time.perf_counter()
            server.request_count.inc()
            self._last_code = 0
            acquired = False
            # long-running requests (watches) are EXEMPT, as in
            # maxinflight.go's longRunningRequestCheck: N held watch
            # streams must never starve short requests into steady 429.
            # Parse the query PROPERLY — a substring match would let any
            # client opt out via ?foo=watch=true
            is_long_running = parse_qs(urlparse(self.path or "").query).get(
                "watch", ["false"])[0] == "true"
            if server._inflight is not None and not is_long_running:
                acquired = server._inflight.acquire(blocking=False)
                if not acquired:
                    # shed load NOW (maxinflight.go): queueing under
                    # overload just converts overload into latency
                    return self._error(429, "TooManyRequests",
                                       "server overloaded (max in flight)")
            try:
                if not self._auth_filters(method):
                    return
                self._dispatch(method)
            except AdmissionDenied as e:
                self._error(403, "Forbidden", str(e))
            except NotFoundError as e:
                self._error(404, "NotFound", str(e))
            except AlreadyExistsError as e:
                self._error(409, "AlreadyExists", str(e))
            except ConflictError as e:
                self._error(409, "Conflict", str(e))
            except ExpiredRevisionError as e:
                self._error(410, "Expired", str(e))
            except BrokenPipeError:
                pass
            except Exception as e:  # panic recovery filter
                logger.exception("handler panic")
                try:
                    self._error(500, "InternalError", str(e))
                except Exception:  # noqa: BLE001 - client gone mid-error
                    # the 500 is already logged above; the write failing
                    # means the peer hung up — count it, don't re-panic
                    server.error_write_failures.inc()
            finally:
                if acquired:
                    server._inflight.release()
                server.request_latency.observe((time.perf_counter() - start) * 1e6)
                if server.auditor is not None:
                    verb, resource, ns, name = self._request_info(method)
                    audit_user = getattr(self, "_audit_user", None) or (
                        self._user.name if getattr(self, "_user", None) else "")
                    server.auditor.record(
                        "ResponseComplete",
                        audit_user,
                        verb, resource, ns, name, code=self._last_code,
                    )

        def do_GET(self):
            self._route("GET")

        def do_POST(self):
            self._route("POST")

        def do_PUT(self):
            self._route("PUT")

        def do_PATCH(self):
            self._route("PATCH")

        def do_DELETE(self):
            self._route("DELETE")

        def _apply_list_selectors(self, items, q):
            """labelSelector / fieldSelector on LIST (reference
            ``ListOptions``; kubelets list pods with
            ``fieldSelector=spec.nodeName=X`` so a 5k-node fleet doesn't
            pull the whole cluster per node).  Returns filtered items, or
            None after writing a 400."""
            label_sel = q.get("labelSelector", [None])[0]
            field_sel = q.get("fieldSelector", [None])[0]
            if label_sel:
                from ..api.selectors import parse_selector_string

                try:
                    sel = parse_selector_string(label_sel)
                except ValueError as e:
                    self._error(400, "BadRequest", f"bad labelSelector: {e}")
                    return None
                items = [i for i in items
                         if sel.matches((i.get("metadata") or {}).get("labels") or {})]
            if field_sel:
                import re as _re

                # the fields the reference's own callers select on
                getters = {
                    "spec.nodeName": lambda i: (i.get("spec") or {}).get("nodeName") or "",
                    "metadata.name": lambda i: (i.get("metadata") or {}).get("name"),
                    "metadata.namespace": lambda i: (i.get("metadata") or {}).get("namespace"),
                    "status.phase": lambda i: (i.get("status") or {}).get("phase") or "",
                }
                for clause in field_sel.split(","):
                    m = _re.fullmatch(r"([^=!]+?)\s*(==|!=|=)\s*(.*)", clause.strip())
                    if m is None:
                        self._error(400, "BadRequest",
                                    f"bad fieldSelector clause {clause!r}")
                        return None
                    key, op, value = m.group(1), m.group(2), m.group(3)
                    get = getters.get(key)
                    if get is None:
                        self._error(400, "BadRequest",
                                    f"unsupported fieldSelector {key!r}")
                        return None
                    if op == "!=":
                        items = [i for i in items if get(i) != value]
                    else:  # '=' and '==' are the same operator
                        items = [i for i in items if get(i) == value]
            return items

        def _compile_selectors(self, q):
            """Parse label/field selectors ONCE into a per-object
            predicate for the watch stream (the LIST path keeps
            :meth:`_apply_list_selectors`, which filters a materialized
            list).  Returns (pred-or-None, error-or-None): pred=None
            with no error means no selectors; an error string means a
            malformed selector the caller must 400."""
            label_sel = q.get("labelSelector", [None])[0]
            field_sel = q.get("fieldSelector", [None])[0]
            tests = []
            if label_sel:
                from ..api.selectors import parse_selector_string

                try:
                    sel = parse_selector_string(label_sel)
                except ValueError as e:
                    return None, f"bad labelSelector: {e}"
                tests.append(lambda i, _s=sel: _s.matches(
                    (i.get("metadata") or {}).get("labels") or {}))
            if field_sel:
                import re as _re

                getters = {
                    "spec.nodeName": lambda i: (i.get("spec") or {}).get("nodeName") or "",
                    "metadata.name": lambda i: (i.get("metadata") or {}).get("name"),
                    "metadata.namespace": lambda i: (i.get("metadata") or {}).get("namespace"),
                    "status.phase": lambda i: (i.get("status") or {}).get("phase") or "",
                }
                for clause in field_sel.split(","):
                    m = _re.fullmatch(r"([^=!]+?)\s*(==|!=|=)\s*(.*)",
                                      clause.strip())
                    if m is None:
                        return None, f"bad fieldSelector clause {clause!r}"
                    key, op, value = m.group(1), m.group(2), m.group(3)
                    get = getters.get(key)
                    if get is None:
                        return None, f"unsupported fieldSelector {key!r}"
                    if op == "!=":
                        tests.append(lambda i, _g=get, _v=value: _g(i) != _v)
                    else:  # '=' and '==' are the same operator
                        tests.append(lambda i, _g=get, _v=value: _g(i) == _v)
            if not tests:
                return None, None
            if len(tests) == 1:
                return tests[0], None
            return (lambda i, _t=tuple(tests): all(t(i) for t in _t)), None

        def _serve_patch(self, kind: str, ns: str, name: str) -> None:
            """The PATCH verb (reference ``handlers/rest.go`` PatchResource):
            patch type negotiated via Content-Type, applied server-side
            under the CAS retry loop so concurrent writers never lose."""
            from ..api.patch import CONTENT_TYPES, apply_patch
            from ..api.scheme import convert_to_internal

            ctype = (self.headers.get("Content-Type") or "").split(";")[0].strip()
            patch_type = CONTENT_TYPES.get(ctype)
            if patch_type is None:
                # a mislabeled body must not be silently merge-patched
                return self._error(415, "UnsupportedMediaType",
                                   f"patch content type {ctype!r}; want one of "
                                   f"{sorted(CONTENT_TYPES)}")
            patch_doc = self._body()

            def _mutate(cur):
                gv = (patch_doc.get("apiVersion", "")
                      if isinstance(patch_doc, dict) else "")
                if gv:
                    # a VERSIONED patch applies in wire space: spoke-encode
                    # the stored hub object, merge, decode back — nested
                    # wire keys land where the conversion puts them, never
                    # as dead keys on the hub form (the reference patches
                    # the versioned object for the same reason)
                    from ..api.scheme import convert_from_internal

                    wire = convert_from_internal(cur, gv)
                    patched = apply_patch(wire, patch_doc, patch_type)
                    return convert_to_internal(patched)
                return apply_patch(cur, patch_doc, patch_type)

            try:
                out = server.store.guaranteed_update(kind, ns, name, _mutate)
            except NotFoundError:
                raise
            except (KeyError, IndexError, ValueError, TypeError) as e:
                return self._error(422, "Invalid", f"cannot apply patch: {e}")
            return self._send(200, out)

        def _serve_ssar(self) -> None:
            """SelfSubjectAccessReview: "can the CALLING user do X?"
            evaluated against the live authorizer (reference
            ``pkg/registry/authorization/selfsubjectaccessreview``).  The
            caller's authenticated identity is authoritative — the spec
            carries only the action, never the user."""
            attrs = (self._body().get("spec") or {}).get("resourceAttributes") or {}
            if server.authorizer is None:
                return self._send(201, {"status": {"allowed": True,
                                                   "reason": "no authorizer configured"}})
            from ..auth import ALLOW, ANONYMOUS, AuthzAttributes

            user = self._user if self._user is not None else ANONYMOUS
            decision, reason = server.authorizer.authorize(AuthzAttributes(
                user=user,
                verb=attrs.get("verb", ""),
                resource=attrs.get("resource", ""),
                namespace=attrs.get("namespace", ""),
                name=attrs.get("name", ""),
            ))
            return self._send(201, {"status": {"allowed": decision == ALLOW,
                                               "reason": reason}})

        def _serve_discovery(self, path: str) -> None:
            """Discovery endpoints (reference ``endpoints/discovery``):
            /api lists versions, /api/v1 the live resource list (built
            from the one type registry, so CRD kinds appear the moment
            they establish), /apis the aggregated groups."""
            from ..api.types import CLUSTER_SCOPED_KINDS, KIND_PLURALS

            if path == "/api":
                return self._send(200, {"kind": "APIVersions", "versions": ["v1"]})
            if path == "/api/v1":
                resources = [
                    {"name": plural, "kind": kind,
                     "namespaced": kind not in CLUSTER_SCOPED_KINDS}
                    for kind, plural in sorted(KIND_PLURALS.items())
                ]
                return self._send(200, {"kind": "APIResourceList",
                                        "groupVersion": "v1",
                                        "resources": resources})
            by_group: dict = {}
            for svc in server.store.list("APIService", "")[0]:
                spec = svc.get("spec") or {}
                g = spec.get("group", "")
                if not g:
                    continue
                avail = bool((svc.get("status") or {}).get("available"))
                by_group[g] = by_group.get(g, False) or avail
            groups = [{"name": g, "available": a} for g, a in sorted(by_group.items())]
            return self._send(200, {"kind": "APIGroupList", "groups": groups})

        def _resolve_pod_kubelet(self, ns: str, name: str, q):
            """Shared pod-subresource resolution: pod -> node -> kubelet
            endpoint + validated container, with CONNECT admission
            (reference exec/attach admission — DenyEscalatingExec runs
            here).  Returns (kubelet_url, container, node_name) or None
            after writing the error."""
            try:
                pod = server.store.get("Pod", ns, name)
            except NotFoundError:
                self._error(404, "NotFound", f"pod {ns}/{name}")
                return None
            chain = getattr(server.store, "chain", None)
            if chain is not None:
                from ..admission.framework import Attributes

                try:
                    chain.run(Attributes(operation="CONNECT", kind="Pod",
                                         namespace=ns, name=name,
                                         old_obj=pod,
                                         user=getattr(server.store, "user", "")))
                except AdmissionDenied as e:
                    self._error(403, "Forbidden", str(e))
                    return None
            node_name = (pod.get("spec") or {}).get("nodeName", "")
            if not node_name:
                self._error(400, "BadRequest", "pod is not scheduled yet")
                return None
            try:
                node = server.store.get("Node", "", node_name)
            except NotFoundError:
                self._error(502, "BadGateway", f"node {node_name} not found")
                return None
            kubelet_url = (node.get("status") or {}).get("kubeletURL", "")
            if not kubelet_url:
                self._error(502, "BadGateway",
                            f"node {node_name} exposes no kubelet endpoint")
                return None
            containers = (pod.get("spec") or {}).get("containers") or []
            known = [c.get("name", "") for c in containers]
            container = q.get("container", [None])[0] or (known[0] if known else "")
            if container not in known:
                # also blocks path traversal into other kubelet endpoints
                self._error(400, "BadRequest",
                            f"container {container!r} not in pod {ns}/{name}")
                return None
            return kubelet_url, container, node_name

        def _proxy_pod_log(self, ns: str, name: str, q) -> None:
            """pod/log subresource: resolve the pod's node, proxy to that
            node's kubelet read API (reference ``registry/core/pod/rest``
            LogREST -> kubelet :10250 /containerLogs)."""
            import urllib.request as _rq

            resolved = self._resolve_pod_kubelet(ns, name, q)
            if resolved is None:
                return
            kubelet_url, container, _ = resolved
            target = f"{kubelet_url}/containerLogs/{ns}/{name}/{container}"
            if "tailLines" in q:
                tail = q["tailLines"][0]
                if not tail.isdigit():
                    return self._error(400, "BadRequest", "tailLines must be an integer")
                target += f"?tailLines={tail}"
            try:
                with _rq.urlopen(target, timeout=10) as resp:
                    data = resp.read()
            except Exception as e:
                return self._error(502, "BadGateway", f"kubelet log fetch failed: {e}")
            self._last_code = 200
            self.send_response(200)
            self.send_header("Content-Type", "text/plain")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def _proxy_pod_simple(self, ns: str, name: str, q, endpoint: str,
                              what: str) -> None:
            """GET-style pod subresource proxied verbatim to the owning
            kubelet (attach — reference ``pod/rest`` AttachREST)."""
            import urllib.error
            import urllib.request as _rq

            resolved = self._resolve_pod_kubelet(ns, name, q)
            if resolved is None:
                return
            kubelet_url, container, _ = resolved
            try:
                with _rq.urlopen(f"{kubelet_url}/{endpoint}/{ns}/{name}/{container}",
                                 timeout=10) as resp:
                    data = resp.read()
            except urllib.error.HTTPError as e:
                return self._error(e.code, "KubeletError", e.read().decode()[:200])
            except Exception as e:
                return self._error(502, "BadGateway", f"kubelet {what} failed: {e}")
            self._last_code = 200
            self.send_response(200)
            self.send_header("Content-Type", "text/plain")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def _proxy_pod_cp(self, ns: str, name: str, q, method: str) -> None:
            """pods/cp subresource: file read (GET) / write (PUT) proxied
            to the kubelet's container file API, write-authenticated with
            the cluster exec token (the reference streams tar over exec —
            same capability, same credential class)."""
            import urllib.error
            import urllib.parse as _up
            import urllib.request as _rq

            from ..auth.authn import kubelet_exec_token

            resolved = self._resolve_pod_kubelet(ns, name, q)
            if resolved is None:
                return
            kubelet_url, container, node_name = resolved
            path = q.get("path", [""])[0]
            if not path:
                return self._error(400, "BadRequest", "path required")
            target = (f"{kubelet_url}/cp/{ns}/{name}/{container}"
                      f"?path={_up.quote(path)}")
            # both directions carry the exec credential: cp READ is an
            # exec-class capability (file exfiltration) on the kubelet too
            auth = {"Authorization": f"Bearer {kubelet_exec_token(node_name)}"}
            if method == "GET":
                req = _rq.Request(target, headers=auth)
            elif method == "PUT":
                length = int(self.headers.get("Content-Length", 0))
                raw = self.rfile.read(length) if length else b""
                self._cached_body = {}  # raw body consumed here, not JSON
                req = _rq.Request(target, data=raw, method="PUT", headers=auth)
            else:
                return self._error(405, "MethodNotAllowed", method)
            try:
                with _rq.urlopen(req, timeout=30) as resp:
                    data = resp.read()
            except urllib.error.HTTPError as e:
                return self._error(e.code, "KubeletError", e.read().decode()[:200])
            except Exception as e:
                return self._error(502, "BadGateway", f"kubelet cp failed: {e}")
            self._last_code = 200
            self.send_response(200)
            self.send_header("Content-Type", "application/octet-stream")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def _proxy_pod_exec(self, ns: str, name: str, q) -> None:
            """pods/exec subresource: resolve node, forward the command to
            the kubelet's exec endpoint (the SPDY exec path's capability
            over JSON), authenticated with the cluster-key exec token."""
            import urllib.error
            import urllib.request as _rq

            from ..auth.authn import kubelet_exec_token

            resolved = self._resolve_pod_kubelet(ns, name, q)
            if resolved is None:
                return
            kubelet_url, container, node_name = resolved
            command = self._body().get("command")
            if not isinstance(command, list) or not command:
                return self._error(400, "BadRequest", "command (list) required")
            body = json.dumps({"command": command}).encode()
            req = _rq.Request(
                f"{kubelet_url}/exec/{ns}/{name}/{container}", data=body,
                headers={"Content-Type": "application/json",
                         "Authorization": f"Bearer {kubelet_exec_token(node_name)}"},
                method="POST",
            )
            try:
                with _rq.urlopen(req, timeout=30) as resp:
                    data = resp.read()
            except urllib.error.HTTPError as e:
                # the kubelet's own verdict passes through (e.g. 400/404)
                return self._error(e.code, "KubeletError", e.read().decode()[:200])
            except Exception as e:
                return self._error(502, "BadGateway", f"kubelet exec failed: {e}")
            self._last_code = 200
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def _proxy_node(self, name: str, subpath: str, query: str = "") -> None:
            """GET proxied verbatim (path + query) to the node's kubelet
            read API — over the node's tunnel when a tunneler holds one
            (pkg/master/tunneler: nodes may not be directly routable)."""
            import urllib.error
            import urllib.request as _rq

            try:
                node = server.store.get("Node", "", name)
            except NotFoundError:
                return self._error(404, "NotFound", f'node "{name}" not found')
            if query:
                subpath = f"{subpath}?{query}"
            tun = server.tunneler
            if tun is not None and tun.has(name):
                if not tun.healthy(name):
                    return self._error(
                        502, "BadGateway", f'tunnel to node "{name}" is down')
                import http.client as _http_client

                try:
                    status, data, ctype = tun.request(name, "GET", f"/{subpath}")
                except (OSError, _http_client.HTTPException) as e:
                    # a kubelet dying mid-response (IncompleteRead /
                    # BadStatusLine) is a gateway failure, not a handler
                    # crash — same 502 contract as the direct-dial path
                    return self._error(502, "BadGateway",
                                       f"tunnel request failed: {e}")
                if status != 200:
                    return self._error(status, "KubeletError",
                                       data.decode(errors="replace")[:200])
                self._last_code = 200
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)
                return
            kubelet_url = (node.get("status") or {}).get("kubeletURL") or ""
            if not kubelet_url:
                return self._error(
                    502, "BadGateway", f'node "{name}" has no kubelet endpoint')
            try:
                with _rq.urlopen(f"{kubelet_url}/{subpath}", timeout=10) as resp:
                    data = resp.read()
                    ctype = resp.headers.get("Content-Type", "application/json")
            except urllib.error.HTTPError as e:
                return self._error(e.code, "KubeletError", e.read().decode()[:200])
            except Exception as e:  # noqa: BLE001
                return self._error(502, "BadGateway", f"kubelet proxy failed: {e}")
            self._last_code = 200
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        # -- chunked framing shared by watch serving and the proxy ---------
        def _write_chunk(self, data: bytes) -> None:
            self.wfile.write(f"{len(data):x}\r\n".encode() + data + b"\r\n")
            self.wfile.flush()

        def _end_chunks(self) -> None:
            self.wfile.write(b"0\r\n\r\n")

        def _lookup_apiservice(self, group: str):
            """By convention name==group, else fall back to spec.group (the
            reference names objects '<version>.<group>')."""
            from ..store.store import NotFoundError as _NF

            try:
                return server.store.get("APIService", "", group)
            except _NF:
                pass
            for svc in server.store.list("APIService", "")[0]:
                if (svc.get("spec") or {}).get("group") == group:
                    return svc
            return None

        def _mark_available(self, svc: dict, available: bool) -> None:
            """Best-effort availability condition (the reference's
            aggregator availability controller, folded into the proxy's
            own observations)."""
            name = (svc.get("metadata") or {}).get("name", "")
            if bool((svc.get("status") or {}).get("available")) == available:
                return
            try:
                def _set(d: dict) -> dict:
                    d.setdefault("status", {})["available"] = available
                    return d

                server.store.guaranteed_update("APIService", "", name, _set)
            except Exception as e:  # noqa: BLE001 - status is best-effort
                # availability is advisory (the next proxy attempt
                # re-observes it); a write that keeps failing should
                # still be visible somewhere
                logger.debug("APIService %s availability update failed: %s",
                             name, e)
                server.apiservice_status_failures.inc()

        def _proxy_aggregated(self, method: str, group: str, url) -> None:
            """The kube-aggregator seam (``staging/src/k8s.io/
            kube-aggregator`` proxy handler): ``/apis/<group>/...`` routes
            to the APIService-registered backend.

            Identity crosses as the front-proxy headers X-Remote-User /
            X-Remote-Group — the client's own Authorization credential is
            NEVER forwarded (forwarding it would hand bearer tokens to
            whoever registered the APIService; the reference's aggregator
            re-asserts identity the same way)."""
            import urllib.error
            import urllib.request as _rq

            svc = self._lookup_apiservice(group)
            if svc is None:
                return self._error(404, "NotFound", f"no APIService for group {group!r}")
            base = (svc.get("spec") or {}).get("url", "")
            if not base:
                return self._error(503, "ServiceUnavailable", f"APIService {group} has no backend")
            q = parse_qs(url.query)
            is_watch = q.get("watch", ["false"])[0] == "true"
            target = base.rstrip("/") + url.path + (f"?{url.query}" if url.query else "")
            body = None
            length = int(self.headers.get("Content-Length", 0))
            if length:
                body = self.rfile.read(length)
            req = _rq.Request(target, data=body, method=method)
            for h in ("Content-Type", "Accept"):
                if self.headers.get(h):
                    req.add_header(h, self.headers[h])
            user = getattr(self, "_user", None)
            if user is not None and getattr(user, "name", ""):
                req.add_header("X-Remote-User", user.name)
                if user.groups:
                    req.add_header("X-Remote-Group", ",".join(user.groups))
            try:
                # watches hold the socket open; plain requests fail fast
                resp = _rq.urlopen(req, timeout=300 if is_watch else 30)
            except urllib.error.HTTPError as e:
                data = e.read()
                self._last_code = e.code
                self.send_response(e.code)
                self.send_header("Content-Type", e.headers.get("Content-Type", "application/json"))
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)
                return
            except Exception as e:
                self._mark_available(svc, False)
                return self._error(502, "BadGateway", f"APIService {group} backend error: {e}")
            self._mark_available(svc, True)
            with resp:
                self._last_code = resp.status
                self.send_response(resp.status)
                chunked = resp.headers.get("Transfer-Encoding", "") == "chunked"
                ctype = resp.headers.get("Content-Type", "application/json")
                self.send_header("Content-Type", ctype)
                # once the response starts, failures may only close the
                # stream — a second status line would corrupt the body
                try:
                    if chunked:
                        self.send_header("Transfer-Encoding", "chunked")
                        self.end_headers()
                        while True:
                            chunk = resp.read1(65536) if hasattr(resp, "read1") else resp.read(65536)
                            if not chunk:
                                break
                            self._write_chunk(chunk)
                        self._end_chunks()
                    else:
                        data = resp.read()
                        self.send_header("Content-Length", str(len(data)))
                        self.end_headers()
                        self.wfile.write(data)
                except (BrokenPipeError, ConnectionResetError, OSError):
                    self.close_connection = True

        def _dispatch(self, method: str) -> None:
            url = urlparse(self.path)
            q = parse_qs(url.query)
            parts = [p for p in url.path.split("/") if p]

            if url.path == "/healthz":
                return self._send(200, {"status": "ok"})
            if url.path == "/telemetry":
                # off-box shipper ingest (ISSUE 13): POST accepts ndjson
                # (one record per line, the shipper's wire shape) or a
                # JSON {"items": [...]} document; GET snapshots the ring
                if method == "POST":
                    return self._serve_telemetry_ingest()
                if method == "GET":
                    records = server.telemetry_snapshot()
                    return self._send(200, {"kind": "TelemetryRecordList",
                                            "count": len(records),
                                            "items": records})
                return self._error(405, "MethodNotAllowed", method)
            # the shared daemon debug surface (utils/health.py): /metrics,
            # /debug/traces, /debug/flightrecorder, /debug/timeseries —
            # identical routes on every component, the apiserver included
            shared = handle_debug_path(url.path, server.registry)
            if shared is not None:
                if method != "GET":
                    return self._error(405, "MethodNotAllowed", method)
                code, payload = shared
                if not isinstance(payload, str):
                    return self._send(code, payload)
                text = payload.encode()
                self._last_code = code
                self.send_response(code)
                self.send_header("Content-Type", "text/plain")
                self.send_header("Content-Length", str(len(text)))
                self.end_headers()
                self.wfile.write(text)
                return
            if url.path in ("/api", "/api/v1", "/apis"):
                if method != "GET":
                    return self._error(405, "MethodNotAllowed", method)
                return self._serve_discovery(url.path)
            if url.path in ("/openapi/v2", "/swagger.json"):
                # the published schema (routes/openapi.go; the era also
                # served /swagger.json) — regenerated per request so CRD
                # kinds appear the moment they establish
                if method != "GET":
                    return self._error(405, "MethodNotAllowed", method)
                from .openapi import build_openapi

                return self._send(200, build_openapi())
            if url.path == "/version":
                from .. import __version__

                return self._send(200, {"version": __version__})
            if url.path == "/api/v1/bindings:batch" and method == "POST":
                items = self._body().get("bindings", [])
                errors = server.store.bind_many(
                    [(b.get("podNamespace", "default"), b["podName"], b["nodeName"]) for b in items]
                )
                return self._send(200, {"errors": errors})
            # batch create: POST /api/v1/{resource}:batch {"items": [...]}
            # — one store txn (Store.create_many: one lock/WAL/fanout
            # pass); per-item failures come back as null slots, the rest
            # commit (the wire twin of the typed client's create_many)
            if (url.path.startswith("/api/v1/") and url.path.endswith(":batch")
                    and method == "POST"):
                res = url.path[len("/api/v1/"):-len(":batch")]
                kind = _kind_for(res)
                if kind is None:
                    return self._error(404, "NotFound", f"unknown resource {res}")
                if not self._admission_gate(res, self._body().get("items", [])):
                    return
                from ..api.scheme import convert_to_internal

                items = [convert_to_internal(d)
                         for d in self._body().get("items", [])]
                if kind in CLUSTER_SCOPED:
                    for d in items:
                        d.setdefault("metadata", {})["namespace"] = ""
                created = server.store.create_many(kind, items)
                return self._send(201, {"items": created})

            if url.path == SSAR_PATH and method == "POST":
                return self._serve_ssar()
            if parts and parts[0] == "apis" and len(parts) >= 2:
                return self._proxy_aggregated(method, parts[1], url)
            if len(parts) < 3 or parts[0] != "api" or parts[1] != "v1":
                return self._error(404, "NotFound", f"no route for {url.path}")
            parts = parts[2:]

            # node proxy: /api/v1/nodes/{name}/proxy/<kubelet path> — the
            # metrics-scrape path (the reference's apiserver node proxy,
            # which heapster/the HPA metrics client ride to reach
            # kubelet /stats/summary without node-network access)
            if (len(parts) >= 4 and parts[0] == "nodes"
                    and parts[2] == "proxy" and method == "GET"):
                return self._proxy_node(parts[1], "/".join(parts[3:]),
                                        url.query)

            # collection routes: /api/v1/{resource}
            if len(parts) == 1:
                kind = _kind_for(parts[0])
                if kind is None:
                    return self._error(404, "NotFound", f"unknown resource {parts[0]}")
                if method == "GET":
                    if q.get("watch", ["false"])[0] == "true":
                        return self._serve_watch(kind, q)
                    ns = q.get("namespace", [None])[0]
                    # columnar wire fast-path (ISSUE 4): the packed batch
                    # LIST (pods only, no selector filtering — selector
                    # queries take the classic item path below)
                    if (q.get("columnar", ["0"])[0] in ("1", "true")
                            and "labelSelector" not in q
                            and "fieldSelector" not in q):
                        lc = getattr(server.store, "list_columns", None)
                        batch = lc(kind, ns) if lc is not None else None
                        if batch is not None:
                            return self._send(200, batch.to_wire())
                    items, rev = server.store.list(kind, ns)
                    items = self._apply_list_selectors(items, q)
                    if items is None:
                        return  # error already written
                    return self._send(200, {"items": items, "resourceVersion": rev})
                if method == "POST":
                    if not self._admission_gate(parts[0], [self._body()]):
                        return
                    from ..api.scheme import convert_to_internal

                    body = convert_to_internal(self._body())
                    if kind in CLUSTER_SCOPED:
                        body.setdefault("metadata", {})["namespace"] = ""
                    return self._send(201, server.store.create(kind, body))
                return self._error(405, "MethodNotAllowed", method)

            # namespaced collection: /api/v1/namespaces/{ns}/{resource}
            # (the canonical path the OpenAPI doc advertises; equivalent
            # to /api/v1/{resource}?namespace={ns})
            if parts[0] == "namespaces" and len(parts) == 3:
                ns = "" if parts[1] == "-" else parts[1]
                kind = _kind_for(parts[2])
                if kind is None:
                    return self._error(404, "NotFound", f"unknown resource {parts[2]}")
                if method == "GET":
                    if q.get("watch", ["false"])[0] == "true":
                        return self._serve_watch(kind, q)
                    items, rev = server.store.list(kind, ns)
                    items = self._apply_list_selectors(items, q)
                    if items is None:
                        return  # error already written
                    return self._send(200, {"items": items, "resourceVersion": rev})
                if method == "POST":
                    if not self._admission_gate(parts[2], [self._body()]):
                        return
                    from ..api.scheme import convert_to_internal

                    body = convert_to_internal(self._body())
                    meta = body.setdefault("metadata", {})
                    meta["namespace"] = "" if kind in CLUSTER_SCOPED else ns
                    return self._send(201, server.store.create(kind, body))
                return self._error(405, "MethodNotAllowed", method)

            # object routes: /api/v1/namespaces/{ns}/{resource}/{name}[/binding]
            if parts[0] == "namespaces" and len(parts) in (4, 5):
                ns = "" if parts[1] == "-" else parts[1]
                kind = _kind_for(parts[2])
                name = parts[3]
                if kind is None:
                    return self._error(404, "NotFound", f"unknown resource {parts[2]}")
                if len(parts) == 5:
                    if parts[4] == "binding" and kind == "Pod" and method == "POST":
                        body = self._body()
                        errors = server.store.bind_many([(ns, name, body["nodeName"])])
                        if errors[0] is not None:
                            return self._error(409, "Conflict", errors[0])
                        return self._send(201, {"status": "bound"})
                    if parts[4] == "log" and kind == "Pod" and method == "GET":
                        return self._proxy_pod_log(ns, name, q)
                    if parts[4] == "exec" and kind == "Pod" and method == "POST":
                        return self._proxy_pod_exec(ns, name, q)
                    if parts[4] == "attach" and kind == "Pod" and method == "GET":
                        return self._proxy_pod_simple(
                            ns, name, q, "attach", "attach stream")
                    if parts[4] == "cp" and kind == "Pod":
                        return self._proxy_pod_cp(ns, name, q, method)
                    if parts[4] == "eviction" and kind == "Pod" and method == "POST":
                        from ..client.clientset import Clientset, EvictionDisallowed

                        try:
                            Clientset(server.store).pods.evict(name, ns)
                        except EvictionDisallowed as e:
                            return self._error(429, "TooManyRequests", str(e))
                        return self._send(201, {"status": "evicted"})
                    return self._error(404, "NotFound", f"unknown subresource {parts[4]}")
                if method == "GET":
                    return self._send(200, server.store.get(kind, ns, name))
                if method == "PUT":
                    from ..api.scheme import convert_to_internal

                    obj = convert_to_internal(self._body())
                    cas = q.get("cas", ["true"])[0] == "true"
                    expect = None if cas else 0
                    out = server.store.update(kind, obj, expect_rev=expect or None)
                    return self._send(200, out)
                if method == "PATCH":
                    return self._serve_patch(kind, ns, name)
                if method == "DELETE":
                    return self._send(200, server.store.delete(kind, ns, name))
                return self._error(405, "MethodNotAllowed", method)

            return self._error(404, "NotFound", f"no route for {url.path}")

        # -- watch streaming (handlers/rest.go:276 watch upgrade) ----------
        def _serve_watch(self, kind: str, q) -> None:
            from ..store.frames import FRAME, event_wire_bytes

            from_rev = None
            if "resourceVersion" in q:
                from_rev = int(q["resourceVersion"][0])
            timeout = float(q.get("timeoutSeconds", ["30"])[0])
            # selectors compile ONCE per stream into a predicate (the
            # old shape reparsed them per event per client); a malformed
            # selector 400s BEFORE the stream starts
            pred, sel_err = self._compile_selectors(q)
            if sel_err is not None:
                return self._error(400, "BadRequest", sel_err)
            # column-packed frame delivery (?frames=1): one JSON line
            # per correlated batch txn instead of N.  Selector watches
            # get frames too (ISSUE 19): the predicate filters at the
            # COLUMN level and a matching sub-frame is re-packed before
            # encoding — per-event JSON lines only for clients that
            # never opted into frames
            want_frames = q.get("frames", ["0"])[0] in ("1", "true")
            watch = server.store.watch(kind, from_revision=from_rev,
                                       frames=want_frames)
            try:
                self._last_code = 200
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()
                import time as _t

                deadline = _t.monotonic() + timeout
                while _t.monotonic() < deadline:
                    ev = watch.get(timeout=min(0.5, max(0.0, deadline - _t.monotonic())))
                    if ev is None:
                        continue
                    if ev.type == FRAME:
                        frame = ev
                        if pred is not None:
                            # the LIST-then-WATCH contract at the column
                            # level: keep matching entries, re-pack, and
                            # stream the sub-frame (None = no entry
                            # matched; the client's fence advances on
                            # its next matching delivery)
                            frame = ev.select([
                                i for i, o in enumerate(ev.objects)
                                if o is not None and pred(o)])
                            if frame is None:
                                continue
                        # encoded ONCE per frame per revision and shared
                        # across every streaming client (frames are
                        # shared-immutable across watcher queues)
                        self._write_chunk(frame.wire_bytes())
                        continue
                    if pred is not None and not pred(ev.object):
                        # a selector silently ignored on watch would
                        # re-create the full-cluster fan-out the
                        # selector exists to avoid
                        continue
                    self._write_chunk(event_wire_bytes(ev))
                self._end_chunks()
            except (BrokenPipeError, ConnectionResetError):
                pass
            finally:
                watch.stop()

    return Handler
