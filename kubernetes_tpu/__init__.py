"""kubernetes_tpu — a TPU-native cluster-scheduling framework.

A brand-new framework with the capabilities of Kubernetes (reference: a
~v1.7/1.8-era tree), re-designed TPU-first.  The organizing idea: instead of
the reference's per-pod ``scheduleOne`` loop
(``plugin/pkg/scheduler/scheduler.go:253``), the scheduler drains the pending
queue, tensorizes cluster state into dense pods x nodes x resources arrays,
and evaluates filter feasibility masks, scoring, and batched assignment as
JAX kernels sharded over the node axis of a TPU mesh — while a faithful CPU
oracle guards binding-for-binding correctness.

Layer map (mirrors SURVEY.md section 1):

- ``api``        — types, Quantity arithmetic, label selectors (L1)
- ``store``      — revisioned in-memory KV with CAS + watch streams (L0/L2)
- ``client``     — reflector / informer / workqueue machinery (L5)
- ``scheduler``  — CPU oracle scheduler + batched TPU backend (L6')
- ``models``     — tensorized cluster-state snapshots (the NodeInfo analogue)
- ``ops``        — JAX/Pallas kernels: filters, scores, assignment
- ``parallel``   — device mesh / sharding utilities
- ``controllers``— reconciling control loops (L6)
- ``kubelet``    — hollow node agent for scale testing (L7 analogue)
- ``utils``      — workqueue-adjacent helpers, metrics, tracing
"""

__version__ = "0.1.0"
