"""Object builders for tests and benchmarks (reference ``test/utils``,
``plugin/pkg/scheduler/testing``)."""

from __future__ import annotations

from typing import Optional

from .api import (
    Container,
    ContainerPort,
    Node,
    NodeCondition,
    NodeSpec,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodSpec,
    Quantity,
    ResourceRequirements,
    Taint,
    Toleration,
)


def make_node(
    name: str,
    cpu: str = "4",
    memory: str = "8Gi",
    pods: int = 110,
    labels: Optional[dict] = None,
    taints: Optional[list[Taint]] = None,
    gpu: int = 0,
    storage: str = "0",
    annotations: Optional[dict] = None,
    unschedulable: bool = False,
    conditions: Optional[list[NodeCondition]] = None,
) -> Node:
    alloc = {
        "cpu": Quantity(cpu),
        "memory": Quantity(memory),
        "pods": Quantity(pods),
    }
    if gpu:
        alloc["nvidia.com/gpu"] = Quantity(gpu)
    if storage != "0":
        alloc["ephemeral-storage"] = Quantity(storage)
    return Node(
        meta=ObjectMeta(name=name, namespace="", labels=labels or {}, annotations=annotations or {}),
        spec=NodeSpec(taints=taints or [], unschedulable=unschedulable),
        status=NodeStatus(
            capacity=dict(alloc),
            allocatable=alloc,
            conditions=conditions or [NodeCondition(type="Ready", status="True")],
        ),
    )


def make_pod(
    name: str,
    cpu: str = "0",
    memory: str = "0",
    namespace: str = "default",
    labels: Optional[dict] = None,
    node_name: str = "",
    node_selector: Optional[dict] = None,
    tolerations: Optional[list[Toleration]] = None,
    host_ports: Optional[list[int]] = None,
    gpu: int = 0,
    affinity=None,
    volumes=None,
    owner_refs=None,
    containers: Optional[list[Container]] = None,
) -> Pod:
    if containers is None:
        requests = {}
        if cpu != "0":
            requests["cpu"] = Quantity(cpu)
        if memory != "0":
            requests["memory"] = Quantity(memory)
        if gpu:
            requests["nvidia.com/gpu"] = Quantity(gpu)
        ports = [ContainerPort(container_port=p, host_port=p) for p in host_ports or []]
        containers = [
            Container(
                name="c0",
                image="img",
                resources=ResourceRequirements(requests=requests),
                ports=ports,
            )
        ]
    return Pod(
        meta=ObjectMeta(
            name=name,
            namespace=namespace,
            labels=labels or {},
            owner_references=owner_refs or [],
        ),
        spec=PodSpec(
            containers=containers,
            node_name=node_name,
            node_selector=node_selector or {},
            tolerations=tolerations or [],
            affinity=affinity,
            volumes=volumes or [],
        ),
    )
