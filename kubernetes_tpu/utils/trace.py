"""Poor-man's spans: step-timestamped traces logged only when slow.

Capability of the reference's ``utiltrace.Trace``
(``apiserver/pkg/util/trace/trace.go``): the scheduler wraps every Schedule
call with a 100ms threshold (``generic_scheduler.go:89-90``).

Folded onto the structured span layer (``utils/tracing.py``, ISSUE 7):
the step bookkeeping lives in a :class:`~.tracing.Span` and the slow
rendering is :func:`~.tracing.format_slow` — the same code path the
tracer's slow-wave logging uses.  When tracing is enabled, the whole
Trace additionally lands in the active tracer as a span (steps become
instant marks in the Chrome export), so ``schedule_one`` shows up in
wave traces without a second instrumentation."""

from __future__ import annotations

import logging
import time
from typing import Callable

from . import tracing

logger = logging.getLogger("kubernetes_tpu.trace")


class Trace:
    # default clock is time.perf_counter — the SAME default the tracer
    # uses, so a Trace recorded into an active tracer lands in the same
    # timestamp domain by construction (time.monotonic and perf_counter
    # share an epoch on Linux but not on every platform)
    def __init__(self, name: str,
                 clock: Callable[[], float] = time.perf_counter):
        self.name = name
        self._clock = clock
        self._span = tracing.Span(name, cat="trace", t0=clock())

    @property
    def _start(self) -> float:  # kept for compatibility with older tests
        return self._span.t0

    def step(self, msg: str) -> None:
        self._span.step(self._clock(), msg)

    def total(self) -> float:
        return self._clock() - self._span.t0

    def log_if_long(self, threshold: float) -> None:
        now = self._clock()
        self._finish(now)
        if now - self._span.t0 < threshold:
            return
        logger.info(tracing.format_slow(self.name, self._span.t0,
                                        self._span.steps, now))

    def _finish(self, now: float) -> None:
        """Close the span and, when a tracer is active, record it there —
        Trace uses its OWN injected clock, so the span is recorded with
        explicit timestamps (meaningful only when both clocks share a
        domain; the defaults are both ``time.perf_counter``, so they do
        unless a caller injects a clock from another domain)."""
        if self._span.t1 is not None:
            return
        self._span.t1 = now
        tr = tracing.current()
        if tr is not None:
            recorded = tr.complete(self.name, self._span.t0, now, cat="trace")
            recorded.steps = list(self._span.steps)
