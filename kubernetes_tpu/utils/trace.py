"""Poor-man's spans: step-timestamped traces logged only when slow.

Capability of the reference's ``utiltrace.Trace``
(``apiserver/pkg/util/trace/trace.go``): the scheduler wraps every Schedule
call with a 100ms threshold (``generic_scheduler.go:89-90``)."""

from __future__ import annotations

import logging
import time
from typing import Callable

logger = logging.getLogger("kubernetes_tpu.trace")


class Trace:
    def __init__(self, name: str, clock: Callable[[], float] = time.monotonic):
        self.name = name
        self._clock = clock
        self._start = clock()
        self._steps: list[tuple[float, str]] = []

    def step(self, msg: str) -> None:
        self._steps.append((self._clock(), msg))

    def total(self) -> float:
        return self._clock() - self._start

    def log_if_long(self, threshold: float) -> None:
        total = self.total()
        if total < threshold:
            return
        lines = [f'Trace "{self.name}" (total {total * 1e3:.1f}ms):']
        prev = self._start
        for t, msg in self._steps:
            lines.append(f"  +{(t - prev) * 1e3:.1f}ms {msg}")
            prev = t
        logger.info("\n".join(lines))
