"""Per-CLIENT staleness attribution for the watch-fanout SLO.

PR 12 shipped the cluster-wide ``watch_fanout_staleness`` SLO and PR 18
added per-SHARD attribution for the mesh (``mesh_slos()``); this module
closes the remaining caveat — per-CLIENT attribution for the serving
tier.  The aggregate ratio tells you the fleet is stale; it cannot tell
you WHICH of 10k watchers is stale, and a single wedged dashboard client
hides behind 9,999 healthy ones in any mean.

:class:`WatchFanoutTracker` keeps one integer per registered client —
the last revision that client APPLIED — plus the store head, and derives:

- the **worst-client gauge** (``client_watch_worst_staleness_revisions``,
  registered by :class:`~.metrics.ClientMetrics`): the largest per-client
  revision lag at the last sample.  A gauge, so the serving SLO over it
  (``slo.serving_slos()``) keeps producing data — and can recover — when
  churn stops, exactly the property the mesh gauges rely on;
- the **top-K laggard dump**: on an SLO breach the flight recorder's
  snapshot carries ``[{client, lag, applied}...]`` for the K worst
  clients (wired through ``slo.register_breach_context``), so "fan-out
  is stale" auto-captures WHO is stale, not just that someone is.

Lock discipline: one flat lock around two dicts of ints; ``report()`` is
the hollow-watcher hot path and does one dict store.  All reads take a
snapshot under the lock and rank outside it.
"""

from __future__ import annotations

import threading
from typing import Optional

from .metrics import ClientMetrics, DEFAULT_CLIENT_METRICS


class WatchFanoutTracker:
    """Per-client applied-revision ledger → worst-client staleness."""

    def __init__(self, metrics: Optional[ClientMetrics] = None):
        self._mu = threading.Lock()
        # client id -> last revision that client applied to its cache.
        # bounded: one int per REGISTERED client; unregister() removes
        # the entry when a watcher leaves the fleet
        self._applied: dict[str, int] = {}
        self._head = 0  # the store head the lags are measured against
        self.metrics = metrics or DEFAULT_CLIENT_METRICS

    # -- the client side (hollow watchers, informers) ----------------------
    def register(self, client_id: str, revision: int = 0) -> None:
        with self._mu:
            self._applied[client_id] = int(revision)

    def unregister(self, client_id: str) -> None:
        with self._mu:
            self._applied.pop(client_id, None)

    def report(self, client_id: str, revision: int) -> None:
        """The hot path: one dict store per pump batch, no ranking."""
        with self._mu:
            self._applied[client_id] = revision

    # -- the sampling side (scrape loop / bench driver) --------------------
    def observe_head(self, revision: int) -> None:
        with self._mu:
            self._head = max(self._head, int(revision))

    def clients(self) -> int:
        with self._mu:
            return len(self._applied)

    def sample(self) -> int:
        """Recompute the worst-client lag, publish it to the gauge, and
        return it.  Called once per scrape (or bench sample tick) — the
        ranking walk is O(clients) and never runs on a client's path."""
        with self._mu:
            head = self._head
            worst = 0
            for rev in self._applied.values():
                lag = head - rev
                if lag > worst:
                    worst = lag
        self.metrics.watch_worst_staleness.set(float(worst))
        return worst

    def top_laggards(self, k: int = 10) -> list[dict]:
        """The K worst clients by revision lag — the flight recorder's
        breach attribution payload."""
        with self._mu:
            head = self._head
            snap = list(self._applied.items())
        snap.sort(key=lambda it: it[1])
        return [{"client": cid, "applied": rev, "lag": head - rev}
                for cid, rev in snap[:k] if head - rev > 0]

    # -- SLO wiring --------------------------------------------------------
    def attach_breach_context(self, slo_name: str = "watch_fanout_worst_client_staleness",
                              k: int = 10) -> None:
        """Register the top-K laggard dump as the breach context for the
        per-client serving SLO: when it burns, the flight-recorder
        snapshot names the laggards."""
        from . import slo as slo_mod

        slo_mod.register_breach_context(
            slo_name,
            lambda: {"clients": self.clients(),
                     "top_laggards": self.top_laggards(k)})
