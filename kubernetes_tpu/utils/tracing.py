"""Wave tracing + flight recorder: correlated structured spans from the
store txn to the device commit (ISSUE 7).

The steady-state pipeline overlaps ingest, tensorize, device scan, and
commit across threads (PRs 3-6); its only timing story so far was the ad
hoc ``Scheduler.last_batch_phases`` dict and unlabeled global counters.
This module is the structured replacement — production AI-cluster
schedulers live on exactly this kind of per-decision telemetry (Kant's
per-stage scheduling SLIs, Tesserae's per-job timeline attribution —
PAPERS.md):

- a **span tree per scheduling wave**: ``Scheduler.schedule_pending_batch``
  opens a ``wave`` root; everything the wave does on that thread
  (tensorize, per-segment dispatch/finalize, frontier chunks, commit,
  overlapped prep, ingest pumps) nests under it via a per-thread span
  stack.  Spans carry attributes (breaker rung, alive fraction, upload
  fraction, txn ids) and step marks;
- **correlation ids minted at the store txn**: ``Store.create_many`` /
  ``bind_many`` stamp a ``txn`` id onto the batch's
  :class:`~..store.frames.WatchFrame`; the informer's frame-apply span
  and the scheduler's bind-confirm span carry the same id, so one trace
  shows the full store → informer → confirm propagation latency;
- a **flight recorder**: a bounded ring of the last K completed wave
  traces plus instant events, which auto-dumps a JSON snapshot when a
  fault point fires (:func:`notify_fault`, wired in ``faults/core.py``),
  the kernel circuit breaker transitions (:func:`notify_breaker`), or a
  bind requeues (:func:`notify_requeue`);
- **Chrome trace-event export** (:meth:`Tracer.chrome_trace`): load the
  JSON from ``/debug/traces``, ``bench.py --trace``, or a flight dump
  into ``chrome://tracing`` / Perfetto.

Disabled (the default, and the only production state until enabled) the
instrumented sites cost one module-global load and a ``None`` check —
the same discipline as ``faults.hit``.  Enabled, every tracer operation
takes one lock; the enabled path is a debugging/benchmarking mode and is
priced by the ``--ab-trace`` bench leg, not assumed free.

``utils/trace.py``'s :class:`Trace` (the reference's ``utiltrace.Trace``)
is folded onto this layer: its slow-operation logging and the tracer's
slow-wave logging share :func:`format_slow`, so there is one code path
for "this took too long, show me the steps".
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from collections import deque
from typing import Callable, Optional

# -- the global switch (one load + None check at every instrumented site) --
_ACTIVE: Optional["Tracer"] = None


def current() -> Optional["Tracer"]:
    """The active tracer, or None (disabled).  Instrumented sites do
    ``tr = tracing.current()`` and branch on ``tr is None`` — nothing
    else happens on the disabled path."""
    return _ACTIVE


def enable(clock: Optional[Callable[[], float]] = None, ring_waves: int = 16,
           max_dumps: int = 32, dump_dir: Optional[str] = None,
           slow_wave_s: Optional[float] = None,
           verbose: bool = False) -> "Tracer":
    """Install a fresh process-wide tracer and return it.  ``clock`` is
    injectable for deterministic tests (defaults to ``time.perf_counter``
    — the same clock the backend's phase timers use, so trace-derived
    phase totals and the stats timers agree).  ``dump_dir`` additionally
    writes each flight-recorder dump as a JSON file."""
    global _ACTIVE
    tracer = Tracer(clock=clock, ring_waves=ring_waves, max_dumps=max_dumps,
                    dump_dir=dump_dir, slow_wave_s=slow_wave_s,
                    verbose=verbose)
    _ACTIVE = tracer
    return tracer


def disable() -> Optional["Tracer"]:
    """Uninstall the active tracer (its rings stay readable)."""
    global _ACTIVE
    tracer = _ACTIVE
    _ACTIVE = None
    return tracer


# -- spans -----------------------------------------------------------------


class Span:
    """One timed operation.  Opened/mutated by the thread that owns it;
    a span minted by a tracer carries the tracer's lock (``_mu``) so
    ``set``/``step`` synchronize with the cross-thread reads a flight
    dump or a ``/debug/traces`` export does on the LIVE tree.  Bare
    spans (``Trace``'s single-threaded bookkeeping) skip the lock.
    ``children`` form the tree, ``steps`` are the cheap ``Trace.step``
    marks, ``attrs`` is the structured payload."""

    __slots__ = ("name", "cat", "t0", "t1", "tid", "attrs", "steps",
                 "children", "_mu")

    def __init__(self, name: str, cat: str = "", t0: float = 0.0,
                 tid: int = 0, attrs: Optional[dict] = None, mu=None):
        self.name = name
        self.cat = cat
        self.t0 = t0
        self.t1: Optional[float] = None  # None while open
        self.tid = tid
        self.attrs = dict(attrs) if attrs else {}
        self.steps: list[tuple[float, str]] = []
        self.children: list[Span] = []
        self._mu = mu

    def set(self, **attrs) -> "Span":
        if self._mu is not None:
            with self._mu:
                self.attrs.update(attrs)
        else:
            self.attrs.update(attrs)
        return self

    def step(self, t: float, msg: str) -> None:
        if self._mu is not None:
            with self._mu:
                self.steps.append((t, msg))
        else:
            self.steps.append((t, msg))

    @property
    def duration(self) -> float:
        return (self.t1 if self.t1 is not None else self.t0) - self.t0

    def iter_spans(self):
        yield self
        for c in self.children:
            yield from c.iter_spans()

    def phase_totals(self) -> dict[str, float]:
        """Sum ``cat="phase"`` descendant durations by name, keyed
        ``<name>_s`` — the single source ``last_batch_phases`` derives
        from when tracing is enabled, so the dict and the trace can
        never disagree (they are the same measurements)."""
        out: dict[str, float] = {}
        for sp in self.iter_spans():
            if sp.cat == "phase" and sp.t1 is not None:
                key = f"{sp.name}_s"
                out[key] = out.get(key, 0.0) + sp.duration
        return out

    def to_dict(self) -> dict:
        d = {"name": self.name, "cat": self.cat, "t0": self.t0,
             "t1": self.t1, "tid": self.tid, "attrs": _jsonable(self.attrs)}
        if self.steps:
            d["steps"] = [[t, m] for t, m in self.steps]
        if self.children:
            d["children"] = [c.to_dict() for c in self.children]
        return d


def _jsonable(v):
    """Best-effort coercion to JSON-serializable values (attrs may carry
    tuples, numpy scalars, shape keys...)."""
    if isinstance(v, dict):
        return {str(k): _jsonable(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    try:  # numpy scalars expose item()
        return v.item()
    except Exception:
        return repr(v)


def format_slow(name: str, t0: float, steps: list[tuple[float, str]],
                t_end: float) -> str:
    """The shared slow-trace rendering: total + per-step deltas.  Both
    ``utils.trace.Trace.log_if_long`` and the tracer's slow-wave logging
    go through here — one code path for slow-operation logging."""
    lines = [f'Trace "{name}" (total {(t_end - t0) * 1e3:.1f}ms):']
    prev = t0
    for t, msg in steps:
        lines.append(f"  +{(t - prev) * 1e3:.1f}ms {msg}")
        prev = t
    return "\n".join(lines)


class _SpanCM:
    """Context manager for one span; also usable via explicit
    ``__enter__``/``__exit__`` when a ``with`` block can't wrap the
    scope (the scheduler's wave brackets a try/finally it must nest
    around)."""

    __slots__ = ("_tracer", "_span")

    def __init__(self, tracer: "Tracer", span: Span):
        self._tracer = tracer
        self._span = span

    def __enter__(self) -> Span:
        self._tracer._push(self._span)
        return self._span

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc_type is not None:
            with self._tracer._mu:
                self._span.attrs.setdefault(
                    "error", f"{exc_type.__name__}: {exc}")
        self._tracer._pop(self._span)


class _NullSpan:
    """The disabled-path span: ``set``/``step`` are no-ops, so an
    instrumented site can be one plain ``with`` block over either a real
    span or this singleton — no per-site ``if cm is not None`` forest."""

    __slots__ = ()

    def set(self, **attrs) -> "_NullSpan":
        return self

    def step(self, t: float, msg: str) -> None:
        pass


class _NullCM:
    __slots__ = ()

    def __enter__(self) -> _NullSpan:
        return _NULL_SPAN

    def __exit__(self, exc_type, exc, tb) -> None:
        return None


_NULL_SPAN = _NullSpan()

#: shared no-op context manager for instrumented sites:
#: ``with (tr.span(...) if tr is not None else tracing.NULL_SPAN) as sp:``
#: keeps the disabled path at one global load + None check + two no-op
#: calls, and lets the enabled path record error attrs via a real
#: ``with`` (the hand-rolled __enter__/__exit__(None, None, None)
#: pattern this replaces silently discarded exception info).
NULL_SPAN = _NullCM()


# txn-id mint: shared by every Store in the process (the ids only need
# to be unique, not dense); itertools.count is atomic under the GIL
_TXN_COUNTER = itertools.count(1)


def next_txn(op: str) -> str:
    """Mint a correlation id for one store batch txn.  Minted whether or
    not tracing is enabled — the id rides the watch frame and a consumer
    enabling tracing mid-stream must still see correlated ids."""
    return f"{op}-{next(_TXN_COUNTER)}"


class Tracer:
    """Process-wide span collector + flight recorder.

    Span trees are built through a per-thread stack: a span opened while
    another is open on the same thread becomes its child; a span opened
    on a bare stack is a root — ``cat="wave"`` roots complete into the
    wave ring, everything else into the background ring (store txns on
    the arrival thread, watch-thread applies).  All structural mutation
    happens under ``_mu`` so a flight dump from any thread sees
    consistent trees."""

    def __init__(self, clock: Optional[Callable[[], float]] = None,
                 ring_waves: int = 16, max_dumps: int = 32,
                 dump_dir: Optional[str] = None,
                 slow_wave_s: Optional[float] = None,
                 verbose: bool = False):
        self.clock = clock or time.perf_counter
        self._mu = threading.RLock()
        self._tls = threading.local()
        self.ring: deque[Span] = deque(maxlen=ring_waves)
        self.background: deque[Span] = deque(maxlen=max(4 * ring_waves, 64))
        self.instants: deque[dict] = deque(maxlen=512)
        self.dumps: deque[dict] = deque(maxlen=max_dumps)
        self.dump_dir = dump_dir
        self.slow_wave_s = slow_wave_s
        # verbose=True additionally opens a span per WATCH EVENT on the
        # per-event informer path (frames always get one span per frame)
        self.verbose = verbose
        self._t0 = self.clock()
        self._wave_seq = itertools.count(1)
        self._dump_seq = itertools.count(1)
        self._open_roots: dict[int, Span] = {}
        self._tid_map: dict[int, int] = {}
        self.dropped_dumps = 0
        # per-reason coalescing (bind.requeue can fire per POD in a
        # failed segment; one dump per window keeps the recorder from
        # amplifying the very stall it is recording)
        self._last_dump_t: dict[str, float] = {}
        self.coalesced_dumps = 0

    # -- span plumbing -----------------------------------------------------
    def _stack(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def _tid(self) -> int:
        ident = threading.get_ident()
        with self._mu:
            tid = self._tid_map.get(ident)
            if tid is None:
                tid = self._tid_map[ident] = len(self._tid_map) + 1
            return tid

    def span(self, name: str, cat: str = "", **attrs) -> _SpanCM:
        return _SpanCM(self, Span(name, cat=cat, t0=self.clock(),
                                  tid=self._tid(), attrs=attrs, mu=self._mu))

    def wave(self, **attrs) -> _SpanCM:
        wid = next(self._wave_seq)
        cm = self.span(f"wave-{wid}", cat="wave", **attrs)
        cm._span.attrs["wave"] = wid
        return cm

    def _push(self, span: Span) -> None:
        stack = self._stack()
        with self._mu:
            if stack:
                stack[-1].children.append(span)
            else:
                self._open_roots[id(span)] = span
            stack.append(span)

    def _pop(self, span: Span) -> None:
        stack = self._stack()
        with self._mu:
            span.t1 = self.clock()
            # unwind to this span even if a child leaked open (an
            # exception path that skipped a __exit__ must not corrupt
            # every later span's parentage)
            while stack and stack[-1] is not span:
                leaked = stack.pop()
                if leaked.t1 is None:
                    leaked.t1 = span.t1
            if stack:
                stack.pop()
            root = self._open_roots.pop(id(span), None)
            if root is not None:
                (self.ring if span.cat == "wave"
                 else self.background).append(span)
        if (span.cat == "wave" and self.slow_wave_s is not None
                and span.duration >= self.slow_wave_s):
            import logging

            logging.getLogger("kubernetes_tpu.tracing").info(
                format_slow(span.name, span.t0, span.steps, span.t1))

    def complete(self, name: str, t0: float, t1: float, cat: str = "",
                 **attrs) -> Span:
        """Record an already-timed span from explicit timestamps (the
        backend's phase timers measure once and feed BOTH their stats
        counters and the trace from the same two clock reads — that
        identity is what lets ``last_batch_phases`` derive from the
        trace without a second measurement that could disagree)."""
        span = Span(name, cat=cat, t0=t0, tid=self._tid(), attrs=attrs,
                    mu=self._mu)
        span.t1 = t1
        stack = self._stack()
        with self._mu:
            if stack:
                stack[-1].children.append(span)
            else:
                self.background.append(span)
        return span

    def instant(self, name: str, **attrs) -> dict:
        ev = {"name": name, "t": self.clock(), "tid": self._tid(),
              "attrs": _jsonable(attrs)}
        with self._mu:
            self.instants.append(ev)
        return ev

    # -- the flight recorder ----------------------------------------------
    def dump(self, reason: str, _coalesce_s: Optional[float] = None,
             **attrs) -> Optional[dict]:
        """Snapshot the recorder — last K wave traces, in-flight (live)
        roots, background spans, instant events — under one lock hold,
        as a JSON-serializable dict.  Appended to ``dumps`` (bounded;
        overflow counted) and optionally written to ``dump_dir``.

        ``_coalesce_s`` (underscored so a caller attr named
        ``coalesce_s`` can't collide): skip the dump — returning None,
        counting it in ``coalesced_dumps`` — when one with the same
        reason was taken inside the window.  Used by per-pod triggers
        (bind requeues): a 2000-pod failed segment must not serialize
        the recorder 2000 times on the commit path it is debugging."""
        with self._mu:
            now = self.clock()
            if _coalesce_s is not None:
                last = self._last_dump_t.get(reason)
                if last is not None and now - last < _coalesce_s:
                    self.coalesced_dumps += 1
                    return None
            self._last_dump_t[reason] = now
            n = next(self._dump_seq)
            snap = {
                "seq": n,
                "reason": reason,
                "at": now,
                "attrs": _jsonable(attrs),
                "waves": [s.to_dict() for s in self.ring],
                "live": [s.to_dict() for s in self._open_roots.values()],
                "background": [s.to_dict() for s in self.background],
                "instants": list(self.instants),
            }
            if len(self.dumps) == self.dumps.maxlen:
                self.dropped_dumps += 1
            self.dumps.append(snap)
        if self.dump_dir:
            try:
                os.makedirs(self.dump_dir, exist_ok=True)
                path = os.path.join(self.dump_dir, f"flight_{n:04d}.json")
                with open(path, "w", encoding="utf-8") as f:
                    json.dump(snap, f, indent=1)
            except Exception:  # noqa: BLE001 - recording must never crash
                import logging

                logging.getLogger("kubernetes_tpu.tracing").exception(
                    "flight-recorder dump write failed (in-memory copy kept)")
        # off-box shipping (outside _mu: offer() takes the shipper's own
        # lock, and a slow sink must never serialize the recorder).  Lazy
        # import — telemetry imports tracing, so the edge must point this
        # way only at call time.
        try:
            from . import telemetry

            shp = telemetry.current()
            if shp is not None:
                shp.offer({"kind": "flight_dump", "reason": reason,
                           "dump": snap})
        except Exception:  # noqa: BLE001 - recording must never crash
            import logging

            logging.getLogger("kubernetes_tpu.tracing").debug(
                "flight-dump telemetry offer failed (in-memory copy kept)",
                exc_info=True)
        return snap

    def flight_snapshot(self) -> dict:
        """The ``/debug/flightrecorder`` payload: every dump taken so
        far plus the current ring state (itself a fresh dump that is NOT
        appended — reading the recorder must not fill it)."""
        with self._mu:
            return {
                "enabled": True,
                "dropped_dumps": self.dropped_dumps,
                "coalesced_dumps": self.coalesced_dumps,
                "dumps": list(self.dumps),
                "current": {
                    "waves": [s.to_dict() for s in self.ring],
                    "live": [s.to_dict() for s in self._open_roots.values()],
                    "instants": list(self.instants),
                },
            }

    # -- export ------------------------------------------------------------
    def _chrome_events_for(self, span: Span, out: list) -> None:
        t1 = span.t1 if span.t1 is not None else self.clock()
        out.append({
            "name": span.name,
            "cat": span.cat or "span",
            "ph": "X",
            "ts": (span.t0 - self._t0) * 1e6,
            "dur": max((t1 - span.t0) * 1e6, 0.0),
            "pid": 1,
            "tid": span.tid,
            "args": _jsonable(span.attrs),
        })
        for t, msg in span.steps:
            out.append({"name": msg, "cat": "step", "ph": "i", "s": "t",
                        "ts": (t - self._t0) * 1e6, "pid": 1,
                        "tid": span.tid, "args": {}})
        for c in span.children:
            self._chrome_events_for(c, out)

    def chrome_trace(self) -> dict:
        """Chrome trace-event JSON (the ``chrome://tracing`` / Perfetto
        object form): every completed wave, background span, live span,
        and instant event as ``X``/``i`` phase events, timestamps in
        microseconds since the tracer was enabled."""
        events: list[dict] = []
        with self._mu:
            # the whole walk stays under the lock: live spans gain
            # children/attrs concurrently, and Span.set synchronizes on
            # this same lock — releasing it mid-walk would re-open the
            # torn-read race the lock exists to prevent
            roots = (list(self.ring) + list(self.background)
                     + list(self._open_roots.values()))
            instants = list(self.instants)
            for root in roots:
                self._chrome_events_for(root, events)
        for ev in instants:
            events.append({"name": ev["name"], "cat": "instant", "ph": "i",
                           "s": "g", "ts": (ev["t"] - self._t0) * 1e6,
                           "pid": 1, "tid": ev["tid"],
                           "args": ev["attrs"]})
        events.sort(key=lambda e: e["ts"])
        return {"traceEvents": events, "displayTimeUnit": "ms",
                "otherData": {"source": "kubernetes_tpu.utils.tracing"}}


# -- integration hooks (disabled path: one global load + None check) -------


def _never_crash(record: Callable[["Tracer"], None]) -> None:
    """Run one recording action against the active tracer, swallowing
    (and logging) ANY failure: the notify hooks sit on production paths
    (fault sites, the breaker, bind handling) and a recorder bug must
    never change the behavior it is observing."""
    tr = _ACTIVE
    if tr is None:
        return
    try:
        record(tr)
    except Exception:  # noqa: BLE001 - recording must never crash
        import logging

        logging.getLogger("kubernetes_tpu.tracing").exception(
            "flight-recorder notify hook failed (event lost)")


def notify_fault(point: str, ctx: dict, mode: str) -> None:
    """Called by ``faults.core`` the moment a fault policy fires —
    records an instant and dumps the flight recorder, so every injected
    failure carries the trace of the wave it fired into."""
    def record(tr: "Tracer") -> None:
        # ctx is the site's free-form kwargs: nest it rather than splat
        # it (a site key named "mode"/"name" must not crash the recorder)
        tr.instant(f"fault.{point}", mode=mode, ctx=_jsonable(ctx))
        tr.dump(f"fault:{point}", mode=mode, ctx=_jsonable(ctx))

    _never_crash(record)


def notify_breaker(kind: str, key, frm, to) -> None:
    """Called on every kernel circuit-breaker transition (degrade /
    probe_failed / restore)."""
    def record(tr: "Tracer") -> None:
        tr.instant(f"breaker.{kind}", shape=_jsonable(key), frm=frm, to=to)
        tr.dump(f"breaker:{kind}", shape=_jsonable(key), frm=frm, to=to)

    _never_crash(record)


#: minimum seconds between bind.requeue dumps: a transient bind_many
#: failure requeues every pod in the segment — each one still records an
#: instant (the timeline keeps per-pod visibility), but only the first
#: in a window pays for a full recorder serialization
REQUEUE_DUMP_COALESCE_S = 1.0


def notify_requeue(pod_key: str) -> None:
    """Called when a transient bind failure requeues a pod with
    backoff — the 'a placement we decided did not land' signal."""
    def record(tr: "Tracer") -> None:
        tr.instant("bind.requeue", pod=pod_key)
        tr.dump("bind.requeue", _coalesce_s=REQUEUE_DUMP_COALESCE_S,
                pod=pod_key)

    _never_crash(record)
