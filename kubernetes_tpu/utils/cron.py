"""Minimal 5-field cron schedule evaluation for the CronJob controller
(the reference vendors robfig/cron; ``pkg/controller/cronjob/utils.go``
getRecentUnmetScheduleTimes drives it the same way: step minute-by-minute
from the last schedule time)."""

from __future__ import annotations

import time
from dataclasses import dataclass


def _parse_field(field: str, lo: int, hi: int) -> frozenset[int]:
    out: set[int] = set()
    for part in field.split(","):
        step = 1
        if "/" in part:
            part, step_s = part.split("/", 1)
            step = int(step_s)
        if part in ("*", ""):
            rng = range(lo, hi + 1)
        elif "-" in part:
            a, b = part.split("-", 1)
            rng = range(int(a), int(b) + 1)
        else:
            rng = range(int(part), int(part) + 1)
        out.update(v for v in rng if (v - rng.start) % step == 0)
    bad = [v for v in out if v < lo or v > hi]
    if bad:
        raise ValueError(f"cron field value {bad} out of range [{lo},{hi}]")
    return frozenset(out)


@dataclass(frozen=True)
class CronSchedule:
    minutes: frozenset[int]
    hours: frozenset[int]
    days: frozenset[int]
    months: frozenset[int]
    weekdays: frozenset[int]  # 0=Sunday (cron convention)
    # standard cron: when BOTH day-of-month and day-of-week are restricted
    # (neither is "*"), a time matches if EITHER field matches
    dom_star: bool = True
    dow_star: bool = True

    @classmethod
    def parse(cls, expr: str) -> "CronSchedule":
        fields = expr.split()
        if len(fields) != 5:
            raise ValueError(f"cron expression needs 5 fields, got {expr!r}")
        m, h, dom, mon, dow = fields
        return cls(
            minutes=_parse_field(m, 0, 59),
            hours=_parse_field(h, 0, 23),
            days=_parse_field(dom, 1, 31),
            months=_parse_field(mon, 1, 12),
            weekdays=frozenset(v % 7 for v in _parse_field(dow, 0, 7)),
            dom_star=dom.split("/")[0] in ("*", ""),
            dow_star=dow.split("/")[0] in ("*", ""),
        )

    def matches(self, ts: float) -> bool:
        t = time.gmtime(ts)
        # cron weekday: 0=Sunday; struct_time: 0=Monday
        wd = (t.tm_wday + 1) % 7
        dom_ok = t.tm_mday in self.days
        dow_ok = wd in self.weekdays
        if not self.dom_star and not self.dow_star:
            day_ok = dom_ok or dow_ok  # standard cron OR rule
        else:
            day_ok = dom_ok and dow_ok
        return (
            t.tm_min in self.minutes
            and t.tm_hour in self.hours
            and day_ok
            and t.tm_mon in self.months
        )

    def next_after(self, ts: float, horizon_minutes: int = 366 * 24 * 60) -> float | None:
        """First matching minute strictly after ``ts`` (UTC)."""
        base = int(ts // 60 + 1) * 60
        for i in range(horizon_minutes):
            candidate = base + i * 60
            if self.matches(candidate):
                return float(candidate)
        return None

    def unmet_since(self, last: float, now: float, limit: int = 100) -> list[float]:
        """Schedule times in (last, now] — the controller's missed-run scan
        (``cronjob/utils.go getRecentUnmetScheduleTimes``)."""
        out: list[float] = []
        t = self.next_after(last)
        while t is not None and t <= now and len(out) < limit:
            out.append(t)
            t = self.next_after(t)
        return out
