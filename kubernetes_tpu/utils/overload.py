"""Overload control: the burn-rate SLO engine as actuator.

PR 12 turned the metrics rings into a pager (``utils/slo.py``); this
module turns the pager into an actuator, the way the kernel circuit
breaker (``ops/breaker.py``) already runs its own rung ladder for
compile-path health.  A :class:`DegradationLadder` consumes the
``BurnRateEvaluator``'s breach/recovery events — reusing its latch and
clean-streak hysteresis rather than re-deriving burn rates — and sheds
scheduling fidelity one rung at a time:

====  =============================================================
rung  what it sheds (each rung includes the ones below it)
====  =============================================================
0     nothing — full fidelity, bit-parity with the CPU oracle
1     latency for throughput: ``run_batch_loop``'s ``min_batch`` /
      ``max_wait`` widen by a scale factor and the tensorizer's sticky
      shape buckets coarsen (bigger waves, fewer recompiles; padding
      up is semantically inert).  Top-tier pods still cut the
      accumulation window short — they never wait the widened window.
2     interpod-affinity SCORE planes: preferred-affinity scoring is
      skipped on the kernel path.  Feasibility predicates (including
      REQUIRED affinity) are untouched, so occupancy invariants still
      hold vs the oracle — only preferred-placement quality degrades.
      Preemption is restricted to the critical tier (batched
      preemption protects the top tier; lower tiers take backoff).
3     admission: the apiserver throttles create paths below the
      protected tier floor with 429 + ``Retry-After`` (which
      ``RemoteStore`` already classifies retryable and now honors).
====  =============================================================

Transitions are hold-gated on an injectable clock: the ladder engages
on the first breach, steps UP one rung only after ``step_hold_s`` of
sustained breach, and steps DOWN one rung at a time only after
``recover_hold_s`` with the breached set empty — so a burn oscillating
around the threshold produces a bounded number of transitions, not a
re-fire storm (the evaluator's ``recovery_evals`` latch already gates
the events themselves).  Every transition lands in metrics (the
``scheduler_degradation_rung`` gauge + transitions counter, wired by
the scheduler), the flight recorder (a dump with the offending SLO
window attached, mirroring ``BurnRateEvaluator._fire_breach``), and —
via the scheduler's wave attrs — the wave-root spans.

Who degrades first is decided by :class:`PriorityTierClassifier`
(pod ``spec.priority`` → tiers batch/standard/critical), and the
apiserver-side rung-3 actuator is :class:`AdmissionThrottle`.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Optional

from . import tracing
from .slo import SLO, BurnRateEvaluator, GaugeSLI
from .timeseries import TimeSeriesStore

logger = logging.getLogger("kubernetes_tpu.overload")

#: rung index -> human name (metrics export the index; logs/dumps both)
RUNG_NAMES = ("full", "widened", "shed_planes", "throttled")
MAX_RUNG = len(RUNG_NAMES) - 1


def overload_slos(pending_threshold: float = 512.0,
                  fast_window_s: float = 2.0,
                  slow_window_s: float = 6.0,
                  recovery_evals: int = 3) -> list[SLO]:
    """Short-window overload SLOs over the scheduler's queue-depth gauge.

    Queue depth is the overload signal of choice because the gauge is
    sampled every scrape whether or not pods are flowing: the windowed
    mean rises while arrivals outpace drain and falls as the backlog
    clears, so the ladder can step back down after the surge without
    waiting for fresh traffic (a cumulative-histogram quantile would
    stay poisoned by the surge forever).  ``GaugeSLI`` grades the burn
    by how far the mean exceeds ``pending_threshold``; with objective
    0.9 and burn thresholds of 3.0 both windows must average >= 1.3x
    the threshold before the ladder engages.
    """
    return [
        SLO(name="overload_queue_depth",
            sli=GaugeSLI(metric="scheduler_pending_pods",
                         threshold=pending_threshold),
            objective=0.9,
            fast_window_s=fast_window_s,
            slow_window_s=slow_window_s,
            fast_burn=3.0,
            slow_burn=3.0,
            recovery_evals=recovery_evals),
    ]


class PriorityTierClassifier:
    """Pod ``spec.priority`` (plain int, 0 default) → service tier.

    Three tiers, after "Priority Matters" (PAPERS.md): tier 2
    (*critical*) keeps full service at every rung — never throttled,
    still preempts, still cuts accumulation windows short; tier 1
    (*standard*) degrades but is never admission-throttled; tier 0
    (*batch* / best-effort) degrades and throttles first.
    """

    CRITICAL = 2
    STANDARD = 1
    BATCH = 0

    def __init__(self, critical_at: int = 8, standard_at: int = 1):
        if critical_at < standard_at:
            raise ValueError("critical_at must be >= standard_at")
        self.critical_at = critical_at
        self.standard_at = standard_at

    def tier(self, priority: int) -> int:
        if priority >= self.critical_at:
            return self.CRITICAL
        if priority >= self.standard_at:
            return self.STANDARD
        return self.BATCH

    def tier_of(self, pod) -> int:
        return self.tier(getattr(pod.spec, "priority", 0) or 0)

    def tier_of_body(self, body: dict) -> int:
        """Tier from a wire-form pod dict — the apiserver's admission
        gate classifies JSON bodies before any decode."""
        spec = body.get("spec") or {}
        try:
            prio = int(spec.get("priority") or 0)
        except (TypeError, ValueError):
            prio = 0
        return self.tier(prio)


class DegradationLadder:
    """Hold-gated rung controller over burn-rate breach/recovery events.

    Owns (or is handed) a :class:`BurnRateEvaluator`; :meth:`poll` runs
    one evaluation and advances the ladder, :meth:`observe` advances on
    externally produced events (tests drive it directly on a fake
    clock).  ``attach(store)`` hooks :meth:`poll` to run after every
    scrape, same wiring shape as ``slo.monitor``.

    Thread-safe: the scraper thread (via the observer) and the batch
    loop (via per-wave polls) may race; one lock guards evaluator +
    ladder state, and transition side effects (gauge, counter, dump,
    user callback) fire after it is released.
    """

    def __init__(self,
                 evaluator: Optional[BurnRateEvaluator] = None,
                 slos: Optional[list[SLO]] = None,
                 store: Optional[TimeSeriesStore] = None,
                 clock: Optional[Callable[[], float]] = None,
                 step_hold_s: float = 4.0,
                 recover_hold_s: float = 6.0,
                 classifier: Optional[PriorityTierClassifier] = None,
                 min_batch_scale: int = 4,
                 max_wait_scale: float = 4.0,
                 bucket_coarsen: int = 2,
                 on_transition: Optional[Callable[[str, int, int], None]] = None):
        self.evaluator = (evaluator if evaluator is not None
                          else BurnRateEvaluator(slos=slos, store=store))
        self.clock = clock or time.monotonic
        self.step_hold_s = step_hold_s
        self.recover_hold_s = recover_hold_s
        self.classifier = classifier or PriorityTierClassifier()
        self.min_batch_scale = min_batch_scale
        self.max_wait_scale = max_wait_scale
        self.bucket_coarsen = bucket_coarsen
        self.on_transition = on_transition
        # wired by Scheduler.attach_overload (scheduler_degradation_rung
        # gauge + scheduler_degradation_transitions_total counter)
        self.gauge = None
        self.transition_counter = None
        self.rung = 0
        self.max_rung_seen = 0
        self.transitions = 0
        self._mu = threading.Lock()
        self._breached: set[str] = set()
        self._last_transition_at: Optional[float] = None
        # (t, rung) per transition — the bench's rung timeline.
        self._history: list[tuple[float, int]] = []

    # -- wiring ------------------------------------------------------------
    def attach(self, store: TimeSeriesStore) -> "DegradationLadder":
        """Hook this ladder to advance after every scrape."""
        self.evaluator.store = store
        store.add_observer(lambda _samples: self.poll())
        return self

    # -- advancing ---------------------------------------------------------
    def poll(self, now: Optional[float] = None) -> int:
        """Run one burn-rate evaluation and advance the ladder; returns
        the current rung.  The evaluator is single-threaded by contract,
        so it runs under the ladder lock (callers race: scraper observer
        vs the batch loop's per-wave poll)."""
        with self._mu:
            events = self.evaluator.evaluate()
            fired = self._advance(events, self.clock() if now is None else now)
        self._emit(fired)
        return self.rung

    def observe(self, events: list, now: Optional[float] = None) -> int:
        """Advance on externally produced evaluator events."""
        with self._mu:
            fired = self._advance(events, self.clock() if now is None else now)
        self._emit(fired)
        return self.rung

    def _advance(self, events: list, now: float) -> list:
        for ev in events:
            kind = ev.get("type")
            if kind == "breach":
                self._breached.add(ev["slo"])
            elif kind == "recovered":
                self._breached.discard(ev["slo"])
        fired = []
        if self._breached:
            if self.rung == 0:
                fired.append(self._shift(+1, "engage", now))
            elif (self.rung < MAX_RUNG
                  and now - self._last_transition_at >= self.step_hold_s):
                fired.append(self._shift(+1, "step", now))
        elif self.rung > 0:
            # recover_hold_s is measured from the LAST transition, so
            # each step-down re-arms the timer: recovery walks down one
            # rung per hold period instead of snapping to 0
            if now - self._last_transition_at >= self.recover_hold_s:
                fired.append(self._shift(-1, "recover", now))
        return fired

    def _shift(self, delta: int, kind: str, now: float) -> tuple:
        frm, to = self.rung, self.rung + delta
        self.rung = to
        self.max_rung_seen = max(self.max_rung_seen, to)
        self.transitions += 1
        self._last_transition_at = now
        # bounded: one entry per hold-gated transition (holds cap the rate)
        self._history.append((now, to))
        return (kind, frm, to, sorted(self._breached))

    def _emit(self, fired: list) -> None:
        for kind, frm, to, slos in fired:
            if self.gauge is not None:
                self.gauge.set(float(to))
            if self.transition_counter is not None:
                self.transition_counter.inc()
            logger.warning(
                "degradation ladder %s: rung %d (%s) -> %d (%s), breached=%s",
                kind, frm, RUNG_NAMES[frm], to, RUNG_NAMES[to], slos)
            self._record(kind, frm, to, slos)
            cb = self.on_transition
            if cb is not None:
                try:
                    cb(kind, frm, to)
                except Exception:  # noqa: BLE001 - callbacks never stall the ladder
                    logger.exception("overload on_transition callback failed")

    def _record(self, kind: str, frm: int, to: int, slos: list) -> None:
        """Flight-record the transition with the offending SLO window
        attached (the same window shape ``_fire_breach`` dumps), plus an
        instant marker on the live span tree."""
        tr = tracing.current()
        if tr is None:
            return
        try:
            tr.instant("overload.transition", kind=kind, frm=frm, to=to,
                       rung=RUNG_NAMES[to], breached=list(slos))
            window: dict = {}
            store = self.evaluator.store
            if store is not None:
                breached = set(slos)
                for slo in self.evaluator.slos:
                    if slo.name in breached:
                        for track in slo.sli.tracks():
                            window[track] = store.query(track, slo.slow_window_s)
            tr.dump(f"overload:{kind}:rung{to}", frm=frm, to=to,
                    breached=list(slos), window=window)
        except Exception:  # noqa: BLE001 - recording never crashes a transition
            logger.exception("overload transition dump failed (rung kept)")

    # -- actuator views ----------------------------------------------------
    def batch_knobs(self, min_batch: int, max_wait: float) -> tuple[int, float]:
        """Effective accumulation knobs for ``run_batch_loop``: rung >= 1
        widens both (bigger waves amortize fixed wave cost under load)."""
        if self.rung >= 1:
            return (max(1, int(min_batch * self.min_batch_scale)),
                    max_wait * self.max_wait_scale)
        return min_batch, max_wait

    @property
    def bucket_scale(self) -> int:
        """Tensorizer sticky-bucket multiplier: rung >= 1 coarsens shape
        buckets (fewer distinct compiled shapes under churny surges)."""
        return self.bucket_coarsen if self.rung >= 1 else 1

    @property
    def shed_score_planes(self) -> bool:
        """Rung >= 2: drop preferred interpod-affinity scoring planes
        (predicates untouched — feasibility and occupancy invariants hold)."""
        return self.rung >= 2

    @property
    def preempt_tier_floor(self) -> int:
        """Minimum tier still allowed to trigger preemption.  Rung >= 2
        restricts the batched PostFilter pass to the critical tier."""
        return self.classifier.CRITICAL if self.rung >= 2 else 0

    @property
    def admit_tier_floor(self) -> int:
        """Minimum tier admitted at the apiserver.  Rung 3 throttles the
        batch tier only — the floor never rises above STANDARD, so the
        top tier is *structurally* never throttled before lower tiers."""
        return self.classifier.STANDARD if self.rung >= MAX_RUNG else 0

    # -- introspection -----------------------------------------------------
    def history(self) -> list[tuple[float, int]]:
        with self._mu:
            return list(self._history)

    def state(self) -> dict:
        with self._mu:
            return {"rung": self.rung, "rung_name": RUNG_NAMES[self.rung],
                    "max_rung_seen": self.max_rung_seen,
                    "transitions": self.transitions,
                    "breached": sorted(self._breached)}


class AdmissionThrottle:
    """The rung-3 actuator, installed as ``APIServer.admission``.

    :meth:`admit` decides one create request: ``None`` admits, a float
    throttles (the handler answers 429 with that ``Retry-After``).  A
    batch request is judged by its highest-tier member — admitting on
    the max lets mixed batches ride with their most important pod
    rather than punishing it for its cohort.  Counters are guarded by a
    lock (apiserver handler threads race).

    The ``Retry-After`` hint is **load-adaptive**: a fixed hint invites
    every shed client back on the same schedule regardless of how deep
    the backlog actually is, so a 10x backlog gets the same retry storm
    as a 1.1x one.  Instead the hint scales with the live windowed mean
    of the queue-depth gauge (the same track the ladder's breach SLO
    watches, read from the evaluator's time-series store) relative to
    that SLO's threshold, clamped to [``retry_after_s``,
    ``retry_after_max_s``] — the configured value is preserved as the
    floor, and a dead store (no scraper, no samples) degrades to
    exactly the old fixed-hint behavior.
    """

    def __init__(self, ladder: DegradationLadder,
                 retry_after_s: float = 1.0,
                 resources: tuple = ("pods",),
                 retry_after_max_s: float = 30.0):
        self.ladder = ladder
        self.retry_after_s = retry_after_s
        self.retry_after_max_s = max(retry_after_max_s, retry_after_s)
        self.resources = frozenset(resources)
        self._mu = threading.Lock()
        self.admitted = 0
        self.throttled = 0
        self.throttled_by_tier: dict[int, int] = {}

    def _depth_slo(self) -> Optional[SLO]:
        """The ladder's queue-depth SLO (a GaugeSLI), if it has one —
        its metric name and threshold define 'how deep is deep'."""
        for slo in self.ladder.evaluator.slos:
            if isinstance(slo.sli, GaugeSLI):
                return slo
        return None

    def retry_after_hint(self) -> float:
        """Live Retry-After: base x (windowed mean queue depth /
        breach threshold), clamped to [base, max].  Reads the same ring
        the ladder breached on, so the hint and the rung agree about
        the backlog; any missing piece (no store, no samples, no gauge
        SLO) falls back to the configured base."""
        slo = self._depth_slo()
        store = self.ladder.evaluator.store
        if slo is None or store is None or slo.sli.threshold <= 0:
            return self.retry_after_s
        samples = store.query(slo.sli.metric, slo.fast_window_s)
        if not samples:
            return self.retry_after_s
        depth = sum(v for _, v in samples) / len(samples)
        scaled = self.retry_after_s * (depth / slo.sli.threshold)
        return min(max(scaled, self.retry_after_s), self.retry_after_max_s)

    def admit(self, resource: str, bodies: list) -> Optional[float]:
        if resource not in self.resources:
            return None
        floor = self.ladder.admit_tier_floor
        if floor <= 0:
            return None
        cls = self.ladder.classifier
        tier = max((cls.tier_of_body(b) for b in bodies if isinstance(b, dict)),
                   default=PriorityTierClassifier.BATCH)
        if tier >= floor:
            with self._mu:
                self.admitted += 1
            return None
        with self._mu:
            self.throttled += 1
            self.throttled_by_tier[tier] = self.throttled_by_tier.get(tier, 0) + 1
        return self.retry_after_hint()

    def stats(self) -> dict:
        with self._mu:
            return {"admitted": self.admitted, "throttled": self.throttled,
                    "throttled_by_tier": dict(self.throttled_by_tier)}
