"""Feature gates + component configuration.

Capability of the reference's ``pkg/features/kube_features.go:145`` +
``apimachinery feature.Gate``: named alpha/beta features with defaults,
flipped per component via ``--feature-gates=A=true,B=false``; and the
componentconfig pattern (``pkg/apis/componentconfig``): one declarative
config object per daemon, loadable from a YAML/JSON file, overridable by
flags.

The gate registry is process-global (as the reference's is); tests use
``FeatureGates(...)`` instances or ``override`` as a context manager."""

from __future__ import annotations

import copy
import json
import threading
from contextlib import contextmanager
from dataclasses import dataclass, field, fields
from typing import Optional

# -- the gate registry (kube_features.go) -----------------------------------

ALPHA = "ALPHA"
BETA = "BETA"
GA = "GA"

# feature -> (default, maturity); the era's gate set, mapped to what this
# framework actually implements
KNOWN_FEATURES: dict[str, tuple[bool, str]] = {
    "PodPriority": (True, BETA),  # priority admission + preemption
    "TaintBasedEvictions": (False, ALPHA),  # NoExecute taint manager path
    "PodPreset": (True, ALPHA),
    "TPUBatchScheduling": (True, BETA),  # the batch backend itself
    "PallasKernels": (True, BETA),  # fused kernel vs XLA scan
    # K sequential sub-steps per kernel loop iteration (SURVEY §7.4.1
    # "small sequential super-steps"): identical arithmetic order, fewer
    # loop iterations.  Default OFF: measured NEUTRAL-to-negative on
    # v5e (the step is bound by its dependent VPU chain, not loop
    # bookkeeping) while costing 10-45s extra compile per shape — see
    # BENCH_AB_supersteps.json for the recorded K sweep
    "PallasSuperSteps": (False, ALPHA),
    "DynamicKindRegistration": (True, BETA),  # CRDs
    "ExperimentalCriticalPodAnnotation": (False, ALPHA),
    "DynamicKubeletConfig": (False, ALPHA),  # kubelet config from the API
}


class FeatureGates:
    def __init__(self, overrides: Optional[dict[str, bool]] = None):
        self._mu = threading.Lock()
        self._enabled = {k: v for k, (v, _) in KNOWN_FEATURES.items()}
        if overrides:
            self.set_from_map(overrides)

    def enabled(self, feature: str) -> bool:
        with self._mu:
            if feature not in self._enabled:
                raise KeyError(f"unknown feature gate {feature!r}")
            return self._enabled[feature]

    def set_from_map(self, overrides: dict[str, bool]) -> None:
        with self._mu:
            for k, v in overrides.items():
                if k not in self._enabled:
                    raise KeyError(f"unknown feature gate {k!r}")
                self._enabled[k] = bool(v)

    def set_from_string(self, spec: str) -> None:
        """--feature-gates=A=true,B=false (flag wire format)."""
        overrides = {}
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            if "=" not in part:
                raise ValueError(f"bad feature gate {part!r} (want name=bool)")
            k, v = part.split("=", 1)
            if v.lower() not in ("true", "false"):
                raise ValueError(f"bad feature gate value {part!r}")
            overrides[k.strip()] = v.lower() == "true"
        self.set_from_map(overrides)

    @contextmanager
    def override(self, feature: str, value: bool):
        with self._mu:
            old = self._enabled[feature]
            self._enabled[feature] = value
        try:
            yield
        finally:
            with self._mu:
                self._enabled[feature] = old


DEFAULT_FEATURE_GATES = FeatureGates()  # the process-global gate


# -- componentconfig (pkg/apis/componentconfig) ------------------------------


@dataclass
class SchedulerConfiguration:
    """``KubeSchedulerConfiguration`` analogue."""

    scheduler_name: str = "default-scheduler"
    backend: str = "tpu"  # tpu | oracle
    batch_interval: float = 0.05
    policy_config_file: str = ""
    leader_elect: bool = False
    feature_gates: dict = field(default_factory=dict)


@dataclass
class ControllerManagerConfiguration:
    controllers: list = field(default_factory=lambda: ["*"])
    workers_per_controller: int = 2
    node_monitor_period: float = 5.0
    use_taint_based_evictions: bool = False
    leader_elect: bool = False
    feature_gates: dict = field(default_factory=dict)


@dataclass
class KubeletConfiguration:
    cpu: str = "8"
    memory: str = "16Gi"
    max_pods: int = 110
    tick: float = 1.0
    memory_pressure_fraction: float = 0.95
    feature_gates: dict = field(default_factory=dict)


def load_component_config(cls, path: str):
    """YAML/JSON file -> config dataclass; unknown keys are rejected (the
    reference's strict decoding), flag layering is the caller's job."""
    import yaml

    with open(path) as f:
        raw = yaml.safe_load(f) or {}
    known = {f.name for f in fields(cls)}
    unknown = set(raw) - known
    if unknown:
        raise ValueError(f"unknown config keys for {cls.__name__}: {sorted(unknown)}")
    return cls(**{k: copy.deepcopy(v) for k, v in raw.items()})
