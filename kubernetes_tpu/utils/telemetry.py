"""Off-box telemetry shipper: flight dumps and time-series deltas leave
the process.

Closes the standing ROADMAP caveat that flight dumps are process-local:
a bounded-queue background thread ships JSON records — flight-recorder
dumps (offered by ``Tracer.dump``) and per-scrape time-series deltas
(offered by the scraper's telemetry observer) — as JSON-lines to a
:class:`FileSink` or an HTTP collector (:class:`HTTPSink`; the apiserver
grows a ``/telemetry`` ingest endpoint so a hollow fleet can aggregate).

Failure posture, in order of importance:

1. **A dead collector must never stall a wave.**  Producers only ever
   :meth:`TelemetryShipper.offer` — append to a bounded queue under a
   queue lock, drop-and-count on overflow.  No producer ever blocks on
   the network.
2. Ship attempts retry with exponential backoff using the same
   classification the remote client uses: transport errors and 5xx/429
   are retryable, other 4xx are fatal (a collector rejecting the payload
   will reject the retry too).
3. A batch that exhausts its retries (or classifies fatal) degrades to
   the local ``dead`` ring — bounded, inspectable, counted.  The
   in-process flight recorder still holds every dump regardless; losing
   the *shipment* loses a copy, never the data.

``telemetry.ship`` is a registered fault point armed in the fault matrix
(tests/test_faults.py): collector down mid-churn → local ring intact,
drop counters visible, convergence unaffected.

Deliberate non-goals (recorded in ROADMAP): no OTLP/Jaeger wire format —
the payload is the recorder's own JSON, one object per line — and no
sampling; the queue bound plus the scrape cadence are the backpressure.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request
from collections import deque
from typing import Optional

from .. import faults
from . import tracing
from .metrics import Counter, Registry

# -- the global switch (one load + None check at every producer site) ------
_ACTIVE: Optional["TelemetryShipper"] = None


def current() -> Optional["TelemetryShipper"]:
    """The active shipper, or None (disabled)."""
    return _ACTIVE


def enable(sink, registry: Optional[Registry] = None,
           start_thread: bool = True, **kwargs) -> "TelemetryShipper":
    """Install a process-wide shipper over ``sink`` and return it."""
    global _ACTIVE
    disable()
    shipper = TelemetryShipper(sink, registry=registry, **kwargs)
    if start_thread:
        shipper.start()
    _ACTIVE = shipper
    return shipper


def disable() -> Optional["TelemetryShipper"]:
    """Uninstall the active shipper; drains what it can, then stops."""
    global _ACTIVE
    shipper = _ACTIVE
    _ACTIVE = None
    if shipper is not None:
        shipper.stop()
    return shipper


class FileSink:
    """JSON-lines append to a local file — the zero-dependency collector
    (bench artifacts, air-gapped runs).  Called only from the shipper's
    worker thread, so no lock."""

    def __init__(self, path: str):
        self.path = path

    def ship(self, records: list[dict]) -> None:
        with open(self.path, "a", encoding="utf-8") as f:
            for rec in records:
                f.write(json.dumps(rec, default=str) + "\n")


class HTTPSink:
    """POST JSON-lines to a collector URL (the apiserver's ``/telemetry``
    ingest, or anything that accepts ndjson).  Raises on non-2xx — the
    shipper owns retry/backoff and classification."""

    def __init__(self, url: str, timeout: float = 5.0):
        self.url = url
        self.timeout = timeout

    def ship(self, records: list[dict]) -> None:
        body = "".join(json.dumps(r, default=str) + "\n"
                       for r in records).encode()
        req = urllib.request.Request(
            self.url, data=body, method="POST",
            headers={"Content-Type": "application/x-ndjson"})
        with urllib.request.urlopen(req, timeout=self.timeout):
            pass


def _retryable(exc: BaseException) -> bool:
    """The remote client's classification, applied to shipping: HTTP 4xx
    (except 429) is fatal — the collector will reject the retry too;
    transport errors, 5xx, and 429 are worth the backoff.  An injected
    ``FaultInjected`` models a transport failure (retryable)."""
    if isinstance(exc, urllib.error.HTTPError):
        return exc.code >= 500 or exc.code == 429
    return True


class TelemetryShipper:
    """Bounded-queue background shipper.

    Producers call :meth:`offer` (never blocks, never raises); the
    worker thread drains batches through the sink with retry + backoff.
    ``start_thread=False`` mode (tests, synchronous benches) drains via
    explicit :meth:`drain_all` calls."""

    def __init__(self, sink, queue_max: int = 1024, batch_max: int = 64,
                 dead_max: int = 256, retries: int = 3,
                 backoff_s: float = 0.05, backoff_max_s: float = 2.0,
                 flush_interval_s: float = 0.5,
                 sleep=time.sleep, registry: Optional[Registry] = None):
        self.sink = sink
        self.queue_max = queue_max
        self.batch_max = batch_max
        self.retries = retries
        self.backoff_s = backoff_s
        self.backoff_max_s = backoff_max_s
        self.flush_interval_s = flush_interval_s
        self.sleep = sleep
        self._mu = threading.Lock()
        self._queue: deque = deque()
        #: the local degrade ring: batches that exhausted their retries
        self.dead: deque = deque(maxlen=dead_max)
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # counters are real metrics so a daemon's own scrape loop sees
        # its shipper's health (register into the daemon registry when
        # given; standalone Counter objects otherwise)
        self.shipped = Counter(
            "telemetry_shipped_total", "records delivered to the sink")
        self.overflow = Counter(
            "telemetry_overflow_total",
            "records dropped at offer() because the queue was full")
        self.dead_lettered = Counter(
            "telemetry_dead_lettered_total",
            "records that exhausted ship retries and degraded to the "
            "local dead ring")
        self.ship_retries = Counter(
            "telemetry_ship_retries_total",
            "ship attempts re-issued after a retryable failure")
        self.feedback_dropped = Counter(
            "telemetry_feedback_dropped_total",
            "records refused because they were produced from inside a "
            "ship attempt (instrumentation of the shipper itself — "
            "accepting them would feed the queue it is draining)")
        # per-thread re-entrancy guard: a ship failure fires the fault/
        # trace instrumentation, which may take a flight dump, whose
        # ship hook would offer a NEW record — an unbounded feedback
        # loop keeping drain_all spinning forever.  Anything offered
        # while the same thread is inside _ship_batch is that loop.
        self._shipping = threading.local()
        if registry is not None:
            for c in (self.shipped, self.overflow, self.dead_lettered,
                      self.ship_retries, self.feedback_dropped):
                registry.register(c)

    # -- producer side (hot-adjacent: must never block or raise) -----------
    def offer(self, record: dict) -> bool:
        """Enqueue one record; drop-and-count when the queue is full.
        The overflow counter increments outside the queue lock (Counter
        carries its own) — no nested lock orders here."""
        if getattr(self._shipping, "active", False):
            self.feedback_dropped.inc()
            return False
        with self._mu:
            if len(self._queue) < self.queue_max:
                self._queue.append(record)
                self._wake.set()
                return True
        self.overflow.inc()
        return False

    def pending(self) -> int:
        with self._mu:
            return len(self._queue)

    def stats(self) -> dict:
        """The drop/overflow visibility contract of the fault matrix."""
        with self._mu:
            queued = len(self._queue)
            dead = len(self.dead)
        return {
            "queued": queued,
            "dead": dead,
            "shipped": self.shipped.value,
            "overflow": self.overflow.value,
            "dead_lettered": self.dead_lettered.value,
            "ship_retries": self.ship_retries.value,
            "feedback_dropped": self.feedback_dropped.value,
        }

    # -- consumer side (worker thread, or explicit drains in tests) --------
    def _pop_batch(self) -> list[dict]:
        with self._mu:
            batch = []
            while self._queue and len(batch) < self.batch_max:
                batch.append(self._queue.popleft())
            return batch

    def _ship_batch(self, batch: list[dict]) -> bool:
        """One batch through the sink with retry + backoff.  Returns
        False when the batch degraded to the dead ring.  Runs with NO
        shipper lock held — a slow sink must not block offer()."""
        attempt = 0
        backoff = self.backoff_s
        self._shipping.active = True
        try:
            while True:
                try:
                    faults.hit("telemetry.ship", records=len(batch),
                               attempt=attempt)
                    self.sink.ship(batch)
                    self.shipped.inc(len(batch))
                    return True
                except Exception as e:  # noqa: BLE001 - classified below
                    if not _retryable(e) or attempt >= self.retries:
                        with self._mu:  # stats() reads len(dead) under _mu
                            self.dead.extend(batch)
                        self.dead_lettered.inc(len(batch))
                        tr = tracing.current()
                        if tr is not None:
                            tr.instant("telemetry.ship_failed",
                                       records=len(batch), error=str(e),
                                       attempts=attempt + 1)
                        return False
                    attempt += 1
                    self.ship_retries.inc()
                    self.sleep(backoff)
                    backoff = min(backoff * 2, self.backoff_max_s)
        finally:
            self._shipping.active = False

    def drain_all(self) -> int:
        """Ship until the queue is empty; returns records delivered."""
        delivered = 0
        while True:
            batch = self._pop_batch()
            if not batch:
                return delivered
            if self._ship_batch(batch):
                delivered += len(batch)

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="ktpu-telemetry-shipper", daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.is_set():
            self._wake.wait(self.flush_interval_s)
            self._wake.clear()
            try:
                self.drain_all()
            except Exception:  # noqa: BLE001 - shipping must never crash
                import logging

                logging.getLogger("kubernetes_tpu.telemetry").exception(
                    "telemetry drain failed (worker keeps running)")
        self.drain_all()  # final drain on stop

    def stop(self) -> None:
        self._stop.set()
        self._wake.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5)
            self._thread = None
        else:
            self.drain_all()  # threadless mode still flushes on stop


def timeseries_observer(shipper: "TelemetryShipper"):
    """A scrape observer that offers each scrape's delta batch to the
    shipper — wire with ``store.add_observer(timeseries_observer(shp))``
    (``utils/health.py`` does this for daemons)."""

    def _observe(samples: list) -> None:
        if samples:
            shipper.offer({"kind": "timeseries",
                           "samples": [list(s) for s in samples]})

    return _observe
