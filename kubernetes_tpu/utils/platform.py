"""Virtual-CPU JAX platform provisioning.

The scheduling kernels are tested multi-chip on a virtual N-device CPU
platform (``--xla_force_host_platform_device_count``), because real
multi-chip hardware is not available in CI.  The ambient environment may
point ``JAX_PLATFORMS`` at a live TPU tunnel — and a pre-baked
``jax_platforms`` config value outranks the env var — so forcing must
happen before jax initializes AND override the config.  Shared by
``tests/conftest.py`` and ``__graft_entry__.dryrun_multichip``.
"""

from __future__ import annotations

import os
import re

_COUNT_FLAG = "--xla_force_host_platform_device_count"


def force_virtual_cpu(n_devices: int) -> None:
    """Force jax onto a virtual ``n_devices``-device CPU platform.

    Must be called before jax first initializes a backend.  Raises if jax
    already initialized on a different platform or with too few devices
    (the env/config knobs are silently inert once a backend exists).
    """
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if _COUNT_FLAG in flags:
        # Replace an ambient count (which may be smaller) rather than
        # trusting it.
        flags = re.sub(rf"{_COUNT_FLAG}=\d+", f"{_COUNT_FLAG}={n_devices}", flags)
        os.environ["XLA_FLAGS"] = flags
    else:
        os.environ["XLA_FLAGS"] = (flags + f" {_COUNT_FLAG}={n_devices}").strip()

    import jax

    jax.config.update("jax_platforms", "cpu")
    devices = jax.devices()
    if devices[0].platform != "cpu":
        raise RuntimeError(
            f"jax already initialized on platform {devices[0].platform!r}; "
            "force_virtual_cpu must run before any jax backend use"
        )
    if len(devices) < n_devices:
        raise RuntimeError(
            f"virtual CPU platform has {len(devices)} devices, need "
            f"{n_devices}: jax initialized before force_virtual_cpu could "
            f"set {_COUNT_FLAG}"
        )
