"""Declarative SLOs with multi-window burn-rate evaluation.

An SLO here is an *objective* (allowed good fraction, e.g. 0.99) over an
SLI derived from the time-series rings (``utils/timeseries.py``):

- :class:`RatioSLI` — bad/total counter deltas over a window (the
  bind-requeue rate, watch-gap rate);
- :class:`QuantileSLI` — the fraction of a histogram quantile track's
  samples above a threshold over a window (wave e2e latency p99);
- :class:`GaugeSLI` — the windowed mean of a gauge track graded against
  a threshold (queue depth for overload control).

Evaluation is the SRE multi-window burn-rate recipe: the *burn rate* is
``bad_fraction / error_budget`` and a breach fires only when BOTH the
fast window (pages fast on a cliff) and the slow window (arms only on a
sustained burn, so a single slow wave cannot page) exceed their
thresholds.  Recovery has hysteresis — ``recovery_evals`` consecutive
clean evaluations re-arm the breach — so a burn oscillating around the
threshold fires one dump, not one per scrape.

A breach fires the existing flight recorder (``tracing.current().dump``)
with the breach reason and the offending metric window attached: the
dump carries the last K wave traces with their txn-correlated spans, so
"throughput sagged" auto-captures the waves that sagged.  With the
off-box shipper enabled (``utils/telemetry.py``) that dump leaves the
process — the recorder's dump hook offers every snapshot to the shipper.

Everything takes an injectable clock through the store; no wall time is
read here.  Metric names in SLO specs are linted statically (MN405): a
referenced name that no registry registers fails ``ktpu-analyze``.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from . import tracing
from .timeseries import TimeSeriesStore


@dataclass(frozen=True)
class RatioSLI:
    """bad/total counter-delta ratio over a window.  ``bad_metric`` and
    ``total_metric`` are registered counter names (keyword-only and
    literal in every spec — the MN405 lint resolves them statically)."""

    bad_metric: str
    total_metric: str

    def bad_fraction(self, store: TimeSeriesStore,
                     window_s: float) -> Optional[float]:
        total = store.delta(self.total_metric, window_s)
        if total <= 0:
            return None  # no traffic in the window: no data, never a breach
        bad = store.delta(self.bad_metric, window_s)
        return max(0.0, min(1.0, bad / total))

    def tracks(self) -> list[str]:
        return [self.bad_metric, self.total_metric]


@dataclass(frozen=True)
class QuantileSLI:
    """Fraction of a histogram quantile track's samples above a
    threshold.  ``metric`` is the registered histogram name; the track
    read is ``<metric>:<quantile>`` as the scraper derives it."""

    metric: str
    threshold: float
    quantile: str = "p99"

    def bad_fraction(self, store: TimeSeriesStore,
                     window_s: float) -> Optional[float]:
        samples = store.query(f"{self.metric}:{self.quantile}", window_s)
        if not samples:
            return None
        bad = sum(1 for _, v in samples if v > self.threshold)
        return bad / len(samples)

    def tracks(self) -> list[str]:
        return [f"{self.metric}:{self.quantile}"]


@dataclass(frozen=True)
class GaugeSLI:
    """Windowed mean of a gauge track against a threshold, graded: the
    bad fraction is how far the mean exceeds the threshold (clamped to
    [0, 1]), so the burn rate scales with severity instead of stepping.
    Gauges are sampled every scrape regardless of traffic, so this SLI
    keeps producing data — and can therefore *recover* — even when the
    pipeline goes quiet, unlike counter-delta ratios (the property the
    degradation ladder in ``utils/overload.py`` needs to step back down
    after a surge drains)."""

    metric: str
    threshold: float

    def bad_fraction(self, store: TimeSeriesStore,
                     window_s: float) -> Optional[float]:
        samples = store.query(self.metric, window_s)
        if not samples:
            return None
        mean = sum(v for _, v in samples) / len(samples)
        if self.threshold <= 0:
            return 1.0 if mean > 0 else 0.0
        return max(0.0, min(1.0, mean / self.threshold - 1.0))

    def tracks(self) -> list[str]:
        return [self.metric]


@dataclass(frozen=True)
class SLO:
    """One objective over one SLI, with its burn-rate policy.  The
    default thresholds are the classic SRE pairing: 14.4x on a short
    window catches a cliff inside the hour, 6x on the long window
    catches a slow leak — both must agree before anyone is paged."""

    name: str
    sli: object  # RatioSLI | QuantileSLI | GaugeSLI
    objective: float = 0.99
    fast_window_s: float = 60.0
    slow_window_s: float = 300.0
    fast_burn: float = 14.4
    slow_burn: float = 6.0
    recovery_evals: int = 3

    @property
    def error_budget(self) -> float:
        return max(1.0 - self.objective, 1e-9)


#: the pipeline's standing SLOs, over metrics ``SchedulerMetrics`` /
#: ``ClientMetrics`` register (names resolved statically by MN405).
#: The latency threshold matches the bench churn gate (5 s e2e p99).
DEFAULT_SLOS = [
    SLO(name="wave_e2e_latency_p99",
        sli=QuantileSLI(
            metric="scheduler_e2e_scheduling_latency_microseconds",
            threshold=5_000_000.0)),
    SLO(name="bind_requeue_rate",
        sli=RatioSLI(
            bad_metric="scheduler_bind_requeues_total",
            total_metric="scheduler_schedule_attempts_total")),
    SLO(name="watch_fanout_staleness",
        sli=RatioSLI(
            bad_metric="client_watch_gaps_total",
            total_metric="scheduler_watch_frames_total")),
]


def serving_slos(worst_lag_revisions: float = 500.0) -> list[SLO]:
    """SLOs over the serving tier's per-CLIENT attribution gauge — the
    caveat the mesh PR left open ("per-CLIENT attribution still waits
    for the serving-tier tentpole") closes here: the fleet's WORST
    watcher gets a first-class signal instead of hiding in the
    cluster-wide gap ratio.  A GaugeSLI for the same reason as
    ``mesh_slos()``: it keeps producing samples (and can recover) while
    churn idles; ``worst_lag_revisions`` is the lag the budget is graded
    against (the bench compresses it along with the windows)."""
    return [
        SLO(name="watch_fanout_worst_client_staleness",
            sli=GaugeSLI(
                metric="client_watch_worst_staleness_revisions",
                threshold=worst_lag_revisions)),
    ]


#: breach-context providers by SLO name (``register_breach_context``):
#: a provider's dict rides the flight-recorder dump when that SLO
#: breaches — the serving tier attaches its top-K laggard attribution
#: here.  Module-level and lock-free by the evaluator's single-threaded
#: contract (providers are registered at wiring time, read on the
#: scraper thread).
_BREACH_CONTEXT: dict = {}


def register_breach_context(slo_name: str, provider) -> None:
    """Attach ``provider`` (a zero-arg callable returning a JSON-shaped
    dict) to ``slo_name``: its output is included in the flight-recorder
    dump fired when that SLO breaches.  Last registration wins."""
    _BREACH_CONTEXT[slo_name] = provider


def mesh_slos() -> list[SLO]:
    """SLOs over the per-shard attribution gauges the sharded wave loop
    exports — this lands the per-shard SLO caveat left open when the
    telemetry pipeline first shipped: a single hot shard (skewed upload
    traffic or a lopsided alive distribution after compaction) now burns
    its own budget instead of hiding in the cluster-wide mean.  Gauge
    SLIs so both keep producing data (and can recover) while the mesh
    idles between waves."""
    return [
        SLO(name="mesh_shard_upload_skew",
            sli=GaugeSLI(
                metric="scheduler_mesh_worst_shard_upload_fraction",
                threshold=0.5)),
        SLO(name="mesh_shard_alive_skew",
            sli=GaugeSLI(
                metric="scheduler_mesh_shard_alive_skew",
                threshold=0.25)),
    ]


class BurnRateEvaluator:
    """Evaluates a set of SLOs against a time-series store.

    Single-threaded by contract: hooked as a scrape observer it runs on
    the scraper thread only (tests drive :meth:`evaluate` directly on a
    fake clock).  Each evaluation returns the events it fired —
    ``{"type": "breach"|"recovered", ...}`` — and a breach additionally
    takes a flight-recorder dump with the offending window attached."""

    def __init__(self, slos: Optional[list[SLO]] = None,
                 store: Optional[TimeSeriesStore] = None):
        self.slos = list(DEFAULT_SLOS if slos is None else slos)
        self.store = store
        self._state = {slo.name: {"breached": False, "clean": 0}
                       for slo in self.slos}
        self.breaches_fired = 0

    def attach(self, store: TimeSeriesStore) -> "BurnRateEvaluator":
        """Hook this evaluator to run after every scrape."""
        self.store = store
        store.add_observer(lambda _samples: self.evaluate())
        return self

    def state(self, name: str) -> dict:
        return dict(self._state[name])

    def evaluate(self) -> list[dict]:
        store = self.store
        if store is None:
            return []
        events: list[dict] = []
        for slo in self.slos:
            fast = slo.sli.bad_fraction(store, slo.fast_window_s)
            slow = slo.sli.bad_fraction(store, slo.slow_window_s)
            if fast is None or slow is None:
                continue  # no data on either window: never a breach
            fast_burn = fast / slo.error_budget
            slow_burn = slow / slo.error_budget
            burning = (fast_burn >= slo.fast_burn
                       and slow_burn >= slo.slow_burn)
            st = self._state[slo.name]
            if not st["breached"]:
                if burning:
                    st["breached"] = True
                    st["clean"] = 0
                    self.breaches_fired += 1
                    ev = {"type": "breach", "slo": slo.name,
                          "fast_burn": fast_burn, "slow_burn": slow_burn,
                          "objective": slo.objective}
                    events.append(ev)
                    self._fire_breach(slo, ev)
            elif burning:
                st["clean"] = 0
            else:
                st["clean"] += 1
                if st["clean"] >= slo.recovery_evals:
                    st["breached"] = False
                    st["clean"] = 0
                    events.append({"type": "recovered", "slo": slo.name})
        return events

    def _fire_breach(self, slo: SLO, ev: dict) -> None:
        """Dump the flight recorder with the offending metric window —
        the dump's waves carry the txn-correlated spans that burned the
        budget.  Recording must never crash the scrape loop."""
        tr = tracing.current()
        if tr is None:
            return
        try:
            window = {track: self.store.query(track, slo.slow_window_s)
                      for track in slo.sli.tracks()}
            extra = {}
            provider = _BREACH_CONTEXT.get(slo.name)
            if provider is not None:
                # per-SLO attribution (the serving tier's top-K laggard
                # dump): a provider failure must not lose the dump — the
                # outer except already guards, but keep the window even
                # when only the context breaks
                try:
                    extra["context"] = provider()
                except Exception:  # noqa: BLE001
                    import logging

                    logging.getLogger("kubernetes_tpu.slo").exception(
                        "SLO breach context provider failed (dump kept)")
            tr.dump(f"slo:{slo.name}", fast_burn=ev["fast_burn"],
                    slow_burn=ev["slow_burn"], objective=slo.objective,
                    window=window, **extra)
        except Exception:  # noqa: BLE001
            import logging

            logging.getLogger("kubernetes_tpu.slo").exception(
                "SLO breach dump failed (breach state kept)")


def monitor(slos: Optional[list[SLO]] = None,
            store: Optional[TimeSeriesStore] = None
            ) -> Optional[BurnRateEvaluator]:
    """Attach a burn-rate evaluator to the active (or given) time-series
    store — the one-call wiring daemons use after ``timeseries.enable``.
    Returns None when no store is active (monitoring needs rings)."""
    from . import timeseries

    target = store if store is not None else timeseries.current()
    if target is None:
        return None
    return BurnRateEvaluator(slos=slos, store=target).attach(target)
