"""Cross-cutting helpers: metrics, tracing (SURVEY.md §5)."""

from .metrics import Counter, Gauge, Histogram, Registry, SchedulerMetrics
from .trace import Trace
