"""In-process TSDB-lite: a background scraper over the metrics Registry.

``Registry.expose()`` is a point-in-time snapshot with no history — a
throughput sag between two scrapes is invisible, and the SLO layer
(``utils/slo.py``) needs windows, not points.  This module samples a
:class:`~kubernetes_tpu.utils.metrics.Registry` on a fixed cadence into
bounded per-track rings:

- **counters** → one track per counter holding the *cumulative* value
  (deltas/rates are computed at query time from two ring points, so a
  scrape is one read, not a diff);
- **gauges** → last-value track;
- **histograms** → quantile tracks (``name:p50`` / ``name:p90`` /
  ``name:p99``) derived from the existing 80-bucket exponential layout
  via one consistent ``state()`` snapshot, plus ``name:count`` and
  ``name:sum`` cumulative tracks (windowed averages need both).

The rings are served as JSON at ``/debug/timeseries`` on every daemon's
health server (see ``utils/health.py``) and feed the off-box shipper
(``utils/telemetry.py``) with per-scrape deltas.

Like the tracer, the module-global switch keeps the disabled path at one
global load + a None check: nothing in the wave hot path ever touches
this module — the scraper runs on its own thread and the only producers
it reads are the metric objects the pipeline already updates.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Optional

from .metrics import Counter, Gauge, Histogram, Registry

# -- the global switch (one load + None check at every consumer site) ------
_ACTIVE: Optional["TimeSeriesStore"] = None

#: quantile tracks derived per histogram per scrape
QUANTILE_TRACKS = (("p50", 0.50), ("p90", 0.90), ("p99", 0.99))


def current() -> Optional["TimeSeriesStore"]:
    """The active store, or None (disabled)."""
    return _ACTIVE


def enable(registry: Registry, interval_s: float = 1.0, capacity: int = 600,
           clock: Optional[Callable[[], float]] = None,
           start_thread: bool = True) -> "TimeSeriesStore":
    """Install a process-wide store scraping ``registry`` and return it.

    ``clock`` is injectable for deterministic tests; ``start_thread=False``
    leaves sampling to explicit :meth:`TimeSeriesStore.sample_once` calls
    (tests, and the bench's synchronous mode)."""
    global _ACTIVE
    disable()
    store = TimeSeriesStore(registry, interval_s=interval_s,
                            capacity=capacity, clock=clock)
    if start_thread:
        store.start()
    _ACTIVE = store
    return store


def disable() -> Optional["TimeSeriesStore"]:
    """Uninstall the active store (its rings stay readable) and stop its
    scraper thread."""
    global _ACTIVE
    store = _ACTIVE
    _ACTIVE = None
    if store is not None:
        store.stop()
    return store


def _quantile_from_state(buckets: list[float], counts: list[int],
                         total: int, q: float) -> float:
    """Bucket-boundary quantile (upper bound) from a ``Histogram.state()``
    snapshot — the same arithmetic as ``Histogram.quantile`` but over ONE
    consistent population for all three tracks of a scrape."""
    if total == 0:
        return 0.0
    target = q * total
    acc = 0
    for i, c in enumerate(counts):
        acc += c
        if acc >= target:
            return buckets[i] if i < len(buckets) else float("inf")
    return float("inf")


class TimeSeriesStore:
    """Bounded per-track rings of ``(t, value)`` samples.

    ``sample_once`` walks the registry's locked snapshot; the rings are
    guarded by one store lock (scraper thread vs. the health server's
    per-connection query threads).  Observers registered with
    :meth:`add_observer` run after every scrape on the scraper thread —
    the SLO evaluator and the telemetry shipper hook in there, each
    wrapped so a crashing observer can never kill the scrape loop."""

    def __init__(self, registry: Registry, interval_s: float = 1.0,
                 capacity: int = 600,
                 clock: Optional[Callable[[], float]] = None):
        self.registry = registry
        self.interval_s = interval_s
        self.capacity = capacity
        self.clock = clock or time.monotonic
        self._mu = threading.Lock()
        self._tracks: dict[str, deque] = {}
        self._observers: list[Callable[[list], None]] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.scrapes = 0
        self.observer_errors = 0

    # -- sampling ----------------------------------------------------------
    def _append(self, out: list, t: float, track: str, value: float) -> None:
        ring = self._tracks.get(track)
        if ring is None:
            # bounded: one ring per registered metric name; rings evict via maxlen
            ring = self._tracks[track] = deque(maxlen=self.capacity)
        ring.append((t, value))
        out.append((track, t, value))

    def sample_once(self) -> list[tuple[str, float, float]]:
        """Scrape every registered metric into the rings; returns the
        samples this scrape appended (the telemetry shipper's delta
        batch).  Safe to call concurrently with queries and with metric
        writers — each metric read is its own consistent snapshot."""
        t = self.clock()
        metrics = self.registry.snapshot()
        # read the metrics OUTSIDE the store lock (each takes its own),
        # then append under one short hold
        readings: list[tuple[str, float]] = []
        for m in metrics:
            if isinstance(m, Histogram):
                counts, total, hsum = m.state()
                for label, q in QUANTILE_TRACKS:
                    readings.append((
                        f"{m.name}:{label}",
                        _quantile_from_state(m.buckets, counts, total, q)))
                readings.append((f"{m.name}:count", float(total)))
                readings.append((f"{m.name}:sum", hsum))
            elif isinstance(m, (Counter, Gauge)):
                readings.append((m.name, m.value))
        out: list[tuple[str, float, float]] = []
        with self._mu:
            self.scrapes += 1
            for track, value in readings:
                self._append(out, t, track, value)
        for obs in list(self._observers):
            try:
                obs(out)
            except Exception:  # noqa: BLE001 - observers never kill scrapes
                with self._mu:
                    self.observer_errors += 1
        return out

    def add_observer(self, fn: Callable[[list], None]) -> None:
        """``fn(samples)`` runs after every scrape on the scraper thread
        (outside the store lock, so observers may query the rings)."""
        with self._mu:
            self._observers.append(fn)

    # -- queries -----------------------------------------------------------
    def tracks(self) -> list[str]:
        with self._mu:
            return sorted(self._tracks)

    def query(self, track: str,
              window_s: Optional[float] = None) -> list[tuple[float, float]]:
        """Samples of ``track`` newer than ``now - window_s`` (all of the
        ring when ``window_s`` is None), oldest first."""
        with self._mu:
            ring = self._tracks.get(track)
            samples = list(ring) if ring is not None else []
        if window_s is None:
            return samples
        cutoff = self.clock() - window_s
        return [s for s in samples if s[0] >= cutoff]

    def delta(self, track: str, window_s: float) -> float:
        """last - first over the window — the counter-delta primitive the
        burn-rate math is built on.  0.0 when the window holds fewer than
        two samples (no data is never a breach)."""
        samples = self.query(track, window_s)
        if len(samples) < 2:
            return 0.0
        return samples[-1][1] - samples[0][1]

    def rate(self, track: str, window_s: float) -> float:
        """delta / observed span (per second); 0.0 without two samples."""
        samples = self.query(track, window_s)
        if len(samples) < 2:
            return 0.0
        dt = samples[-1][0] - samples[0][0]
        if dt <= 0:
            return 0.0
        return (samples[-1][1] - samples[0][1]) / dt

    def last(self, track: str) -> Optional[float]:
        with self._mu:
            ring = self._tracks.get(track)
            return ring[-1][1] if ring else None

    def to_dict(self, window_s: Optional[float] = None) -> dict:
        """The ``/debug/timeseries`` payload.  Non-finite quantile values
        (beyond the last bucket) serialize as None — strict-JSON clients
        choke on ``Infinity``."""
        with self._mu:
            tracks = {name: list(ring) for name, ring in self._tracks.items()}
        if window_s is not None:
            cutoff = self.clock() - window_s
            tracks = {n: [s for s in ss if s[0] >= cutoff]
                      for n, ss in tracks.items()}
        import math

        return {
            "enabled": True,
            "interval_s": self.interval_s,
            "capacity": self.capacity,
            "scrapes": self.scrapes,
            "tracks": {
                n: [[t, v if math.isfinite(v) else None] for t, v in ss]
                for n, ss in sorted(tracks.items())
            },
        }

    # -- the scraper thread ------------------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="ktpu-timeseries-scraper", daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.sample_once()
            except Exception:  # noqa: BLE001 - scraping must never crash
                import logging

                logging.getLogger("kubernetes_tpu.timeseries").exception(
                    "metrics scrape failed (scraper keeps running)")

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5)
            self._thread = None
