"""Shared daemon debug/health routes (ISSUE 13 satellite).

Before this module each daemon hand-rolled its health routes: the
scheduler was the only one with debug endpoints, the apiserver served
``/metrics`` inline, kubelet and federation served nothing.  Now one
handler implements the contract everywhere:

- ``/healthz``                 — liveness (200 ``{"status": "ok"}``)
- ``/metrics``                 — Prometheus text from the daemon registry
- ``/debug/traces``            — Chrome trace-event JSON (Perfetto)
- ``/debug/flightrecorder``    — every dump + the current wave ring
- ``/debug/timeseries``        — the TSDB-lite rings as JSON

:func:`handle_debug_path` is the pure routing core — usable from any
server shape (the apiserver's request handler calls it directly);
:class:`DebugRoutesMixin` binds it to the ``_HealthHTTPServer``
``handle(path) -> (code, body) | None`` contract for the standalone
health servers (``daemon.serve_health``).

Probing any endpoint must never perturb the production path: tracing or
time-series disabled answer ``{"enabled": false}``, and every handler is
wrapped so an export bug returns a 500 body instead of killing the
connection thread.
"""

from __future__ import annotations

from typing import Optional


def handle_debug_path(path: str, registry=None) -> Optional[tuple]:
    """Route one GET path; ``None`` means "not one of ours" (404 or the
    caller's own routes).  String bodies are raw text (Prometheus
    exposition); dicts are JSON."""
    if path == "/healthz":
        return 200, {"status": "ok"}
    if path == "/metrics":
        if registry is None:
            return None
        try:
            return 200, registry.expose()  # raw exposition text
        except Exception as e:  # noqa: BLE001 - never crash health
            return 500, {"error": str(e)}
    if path in ("/debug/traces", "/debug/flightrecorder"):
        from . import tracing

        tr = tracing.current()
        if tr is None:
            return 200, {"enabled": False}
        try:
            return 200, (tr.chrome_trace() if path == "/debug/traces"
                         else tr.flight_snapshot())
        except Exception as e:  # noqa: BLE001 - never crash health
            return 500, {"error": str(e)}
    if path == "/debug/timeseries":
        from . import timeseries

        ts = timeseries.current()
        if ts is None:
            return 200, {"enabled": False}
        try:
            return 200, ts.to_dict()
        except Exception as e:  # noqa: BLE001 - never crash health
            return 500, {"error": str(e)}
    return None


class DebugRoutesMixin:
    """Binds :func:`handle_debug_path` to the ``_HealthHTTPServer``
    contract.  Subclasses set ``registry`` (or leave it None to serve no
    ``/metrics``) and may override :meth:`handle` to layer extra routes
    before delegating up."""

    registry = None

    def handle(self, path: str):
        return handle_debug_path(path, self.registry)
