"""Prometheus-style metrics primitives.

Capability of the vendored Prometheus client as the reference uses it:
counters and histograms with labels, a process-global registry, and a text
exposition dump.  The scheduler's three SLIs
(``plugin/pkg/scheduler/metrics/metrics.go:26-50``) are predefined below;
the e2e SLO checks read exactly these (SURVEY.md §5.4).
"""

from __future__ import annotations

import bisect
import threading
from typing import Optional

# reference metrics.go shape: 1ms .. ~1000s exponential (in microseconds),
# at 2^(1/4) steps — 80 buckets instead of the reference's 20, so a
# reported quantile's upper bound is within ~19% of the true value (the
# bench's SLI block reads these).  At sqrt(2) steps the >8s buckets were
# ~3.4s wide and adjacent segment commits of a north drain could land in
# ONE bucket, collapsing p50 and p99 to the same boundary.
_DEFAULT_BUCKETS = [1e3 * (2 ** (i / 4)) for i in range(80)]


class Histogram:
    def __init__(self, name: str, help: str = "", buckets: Optional[list[float]] = None):
        self.name = name
        self.help = help
        self.buckets = sorted(buckets or _DEFAULT_BUCKETS)
        self._counts = [0] * (len(self.buckets) + 1)
        self._sum = 0.0
        self._total = 0
        self._mu = threading.Lock()

    def observe(self, value: float) -> None:
        with self._mu:
            i = bisect.bisect_left(self.buckets, value)
            self._counts[i] += 1
            self._sum += value
            self._total += 1

    def observe_many(self, value: float, n: int) -> None:
        """n observations of the same value under one lock/bisect — the
        batch scheduler records one shared e2e latency for every pod in a
        committed batch; per-pod observe() would cost 150k lock rounds."""
        with self._mu:
            i = bisect.bisect_left(self.buckets, value)
            self._counts[i] += n
            self._sum += value * n
            self._total += n

    @property
    def count(self) -> int:
        return self._total

    @property
    def sum(self) -> float:
        return self._sum

    def quantile(self, q: float) -> float:
        """Approximate quantile from bucket boundaries (upper bound)."""
        with self._mu:
            if self._total == 0:
                return 0.0
            target = q * self._total
            acc = 0
            for i, c in enumerate(self._counts):
                acc += c
                if acc >= target:
                    return self.buckets[i] if i < len(self.buckets) else float("inf")
            return float("inf")

    def expose(self) -> str:
        lines = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} histogram"]
        acc = 0
        for b, c in zip(self.buckets, self._counts):
            acc += c
            lines.append(f'{self.name}_bucket{{le="{b}"}} {acc}')
        lines.append(f'{self.name}_bucket{{le="+Inf"}} {self._total}')
        lines.append(f"{self.name}_sum {self._sum}")
        lines.append(f"{self.name}_count {self._total}")
        return "\n".join(lines)


class Counter:
    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._value = 0.0
        self._mu = threading.Lock()

    def inc(self, amount: float = 1.0) -> None:
        with self._mu:
            self._value += amount

    @property
    def value(self) -> float:
        return self._value

    def expose(self) -> str:
        return (
            f"# HELP {self.name} {self.help}\n# TYPE {self.name} counter\n"
            f"{self.name} {self._value}"
        )


class Gauge:
    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self.value = 0.0

    def set(self, v: float) -> None:
        self.value = v

    def expose(self) -> str:
        return (
            f"# HELP {self.name} {self.help}\n# TYPE {self.name} gauge\n"
            f"{self.name} {self.value}"
        )


class Registry:
    def __init__(self):
        self._metrics: dict[str, object] = {}
        self._mu = threading.Lock()

    def register(self, metric):
        with self._mu:
            self._metrics[metric.name] = metric
        return metric

    def get(self, name: str):
        return self._metrics.get(name)

    def expose(self) -> str:
        with self._mu:
            return "\n".join(m.expose() for m in self._metrics.values()) + "\n"


class SchedulerMetrics:
    """The reference's three scheduling SLIs, in microseconds
    (``metrics/metrics.go:26-50``), plus batch-backend extras."""

    def __init__(self, registry: Optional[Registry] = None):
        r = registry or Registry()
        self.registry = r
        self.e2e_scheduling_latency = r.register(
            Histogram("scheduler_e2e_scheduling_latency_microseconds")
        )
        self.scheduling_algorithm_latency = r.register(
            Histogram("scheduler_scheduling_algorithm_latency_microseconds")
        )
        self.binding_latency = r.register(
            Histogram("scheduler_binding_latency_microseconds")
        )
        self.schedule_attempts = r.register(Counter("scheduler_schedule_attempts_total"))
        self.schedule_failures = r.register(Counter("scheduler_schedule_failures_total"))
        # batch-backend extras
        self.batch_size = r.register(Histogram("scheduler_batch_size", buckets=[2**i for i in range(20)]))
        self.batch_device_latency = r.register(
            Histogram("scheduler_batch_device_latency_microseconds")
        )
        self.pallas_fallback_total = r.register(Counter(
            "scheduler_pallas_fallback_total",
            "pallas dispatch/finalize failures that fell back to the XLA scan",
        ))
        # preemption (the PostFilter phase)
        self.preemption_attempts = r.register(Counter(
            "scheduler_preemption_attempts_total"))
        self.preemption_victims = r.register(Counter(
            "scheduler_preemption_victims_total"))
        self.preemption_latency = r.register(Histogram(
            "scheduler_preemption_latency_microseconds"))
