"""Prometheus-style metrics primitives.

Capability of the vendored Prometheus client as the reference uses it:
counters and histograms with labels, a process-global registry, and a text
exposition dump.  The scheduler's three SLIs
(``plugin/pkg/scheduler/metrics/metrics.go:26-50``) are predefined below;
the e2e SLO checks read exactly these (SURVEY.md §5.4).
"""

from __future__ import annotations

import bisect
import threading
from typing import Optional

# reference metrics.go shape: 1ms .. ~1000s exponential (in microseconds),
# at 2^(1/4) steps — 80 buckets instead of the reference's 20, so a
# reported quantile's upper bound is within ~19% of the true value (the
# bench's SLI block reads these).  At sqrt(2) steps the >8s buckets were
# ~3.4s wide and adjacent segment commits of a north drain could land in
# ONE bucket, collapsing p50 and p99 to the same boundary.
_DEFAULT_BUCKETS = [1e3 * (2 ** (i / 4)) for i in range(80)]


class Histogram:
    def __init__(self, name: str, help: str = "", buckets: Optional[list[float]] = None):
        self.name = name
        self.help = help
        self.buckets = sorted(buckets or _DEFAULT_BUCKETS)
        self._counts = [0] * (len(self.buckets) + 1)
        self._sum = 0.0
        self._total = 0
        self._mu = threading.Lock()

    def observe(self, value: float) -> None:
        with self._mu:
            i = bisect.bisect_left(self.buckets, value)
            self._counts[i] += 1
            self._sum += value
            self._total += 1

    def observe_many(self, value: float, n: int) -> None:
        """n observations of the same value under one lock/bisect — the
        batch scheduler records one shared e2e latency for every pod in a
        committed batch; per-pod observe() would cost 150k lock rounds."""
        with self._mu:
            i = bisect.bisect_left(self.buckets, value)
            self._counts[i] += n
            self._sum += value * n
            self._total += n

    @property
    def count(self) -> int:
        return self._total

    @property
    def sum(self) -> float:
        return self._sum

    def quantile(self, q: float) -> float:
        """Approximate quantile from bucket boundaries (upper bound)."""
        with self._mu:
            if self._total == 0:
                return 0.0
            target = q * self._total
            acc = 0
            for i, c in enumerate(self._counts):
                acc += c
                if acc >= target:
                    return self.buckets[i] if i < len(self.buckets) else float("inf")
            return float("inf")

    def state(self) -> tuple[list[int], int, float]:
        """One consistent ``(bucket_counts, total, sum)`` snapshot under a
        single lock round — the time-series scraper derives several
        quantile tracks per scrape, and three ``quantile()`` calls could
        each see a different population."""
        with self._mu:
            return list(self._counts), self._total, self._sum

    def expose(self) -> str:
        # one consistent snapshot: without the lock a concurrent
        # observe() can land between the bucket walk and the _total
        # read, exposing cumulative bucket counts that exceed (or trail)
        # the reported _count — scrapers and the SLO checks both assume
        # the exposition is internally consistent
        with self._mu:
            counts = list(self._counts)
            total = self._total
            total_sum = self._sum
        lines = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} histogram"]
        acc = 0
        for b, c in zip(self.buckets, counts):
            acc += c
            lines.append(f'{self.name}_bucket{{le="{b}"}} {acc}')
        lines.append(f'{self.name}_bucket{{le="+Inf"}} {total}')
        lines.append(f"{self.name}_sum {total_sum}")
        lines.append(f"{self.name}_count {total}")
        return "\n".join(lines)


class Counter:
    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._value = 0.0
        self._mu = threading.Lock()

    def inc(self, amount: float = 1.0) -> None:
        with self._mu:
            self._value += amount

    @property
    def value(self) -> float:
        return self._value

    def expose(self) -> str:
        return (
            f"# HELP {self.name} {self.help}\n# TYPE {self.name} counter\n"
            f"{self.name} {self._value}"
        )


class Gauge:
    """Last-write-wins gauge.  ``set`` takes a lock like the other
    primitives — gauges are written from resync/compaction threads and
    scraped from the health server's connection threads, so the
    single-writer assumption the pre-lock version leaned on does not
    hold for every instance (ktpu-analyze race-lint hygiene)."""

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._value = 0.0
        self._mu = threading.Lock()

    def set(self, v: float) -> None:
        with self._mu:
            self._value = v

    def inc(self, amount: float = 1.0) -> None:
        with self._mu:
            self._value += amount

    @property
    def value(self) -> float:
        return self._value

    def expose(self) -> str:
        return (
            f"# HELP {self.name} {self.help}\n# TYPE {self.name} gauge\n"
            f"{self.name} {self._value}"
        )


class Registry:
    def __init__(self):
        self._metrics: dict[str, object] = {}
        self._mu = threading.Lock()

    def register(self, metric):
        with self._mu:
            self._metrics[metric.name] = metric
        return metric

    def get(self, name: str):
        with self._mu:
            return self._metrics.get(name)

    def snapshot(self) -> list:
        """The registered metrics as a list, captured under the registry
        lock.  Daemons register metrics lazily (first use), so a scrape
        racing a registration must not iterate the mutating dict — both
        ``expose()`` and the time-series scraper walk this snapshot
        instead, outside the lock."""
        with self._mu:
            return list(self._metrics.values())

    def expose(self) -> str:
        # per-metric expose() takes each metric's own lock; holding the
        # registry lock across that walk would nest registry-lock →
        # metric-lock against every observe() in flight — snapshot the
        # dict under the lock, render outside it
        return "\n".join(m.expose() for m in self.snapshot()) + "\n"


class ClientMetrics:
    """Client-transport observability: retry/reconnect/relist counters.

    The fault-injection matrix (tests/test_faults.py) asserts recovery
    through exactly these — a retry that happens but is invisible here
    fails the test.  One instance per RemoteStore (watches inherit it);
    informers default to the process-wide :data:`DEFAULT_CLIENT_METRICS`
    unless handed their own."""

    def __init__(self, registry: Optional[Registry] = None):
        r = registry or Registry()
        self.registry = r
        self.remote_retries = r.register(Counter(
            "client_remote_retries_total",
            "request attempts re-issued after a retryable failure"))
        self.remote_fatal = r.register(Counter(
            "client_remote_fatal_total",
            "requests abandoned on a non-retryable (4xx) classification"))
        self.remote_retry_exhausted = r.register(Counter(
            "client_remote_retry_exhausted_total",
            "requests abandoned after the retry budget ran out"))
        self.watch_reconnects = r.register(Counter(
            "client_watch_reconnects_total",
            "watch streams re-established after an error or EOF"))
        self.watch_gaps = r.register(Counter(
            "client_watch_gaps_total",
            "watch resumes refused with 410 Gone — informer must relist"))
        self.watch_errors = r.register(Counter(
            "client_watch_errors_total",
            "classified watch-stream errors (transport + HTTP)"))
        # best-effort cleanup visibility (ktpu-analyze CH702): a close or
        # drain that fails is tolerated by design, but never invisibly
        self.watch_close_errors = r.register(Counter(
            "client_watch_close_errors_total",
            "watch response closes that raised (half-open stream torn "
            "down anyway)"))
        self.remote_drain_errors = r.register(Counter(
            "client_remote_drain_errors_total",
            "keep-alive body drains that raised before a retry (socket "
            "abandoned to the pool's cleanup)"))
        self.informer_relists = r.register(Counter(
            "client_informer_relists_total",
            "full LIST + watch restarts (gap escalation or resync)"))
        self.informer_dropped_events = r.register(Counter(
            "client_informer_dropped_events_total",
            "deltas dropped before application (fault injection)"))
        self.informer_handler_errors = r.register(Counter(
            "client_informer_handler_errors_total",
            "handler callbacks that raised (isolated, loop continues)"))
        # zero-copy ingest observability (ISSUE 4): decode failures heal
        # via relist; bytes counts the wire payload the watch delivered
        # (remote transport only — the in-process store never serializes)
        self.informer_decode_errors = r.register(Counter(
            "client_informer_decode_errors_total",
            "event payloads that failed to decode (delta lost, gap marked "
            "for relist)"))
        self.informer_frame_errors = r.register(Counter(
            "client_informer_frame_errors_total",
            "column-packed watch frames lost whole before application "
            "(apply fault / broken columns) — gap marked for relist"))
        self.ingest_bytes = r.register(Counter(
            "scheduler_ingest_decode_bytes_total",
            "wire bytes of watch payloads delivered to informers"))
        # cache compaction (ISSUE 7 satellite: compact_cache wired to the
        # resync loop): objects whose pinned wire payload was released,
        # and the approximate bytes the LAST sweep freed
        self.informer_compactions = r.register(Counter(
            "client_informer_compactions_total",
            "lazy cache objects promoted-and-raw-dropped by the "
            "resync-time compaction sweep"))
        self.informer_compaction_freed_bytes = r.register(Gauge(
            "client_informer_compaction_freed_bytes",
            "approximate wire-payload bytes released by the most recent "
            "compaction sweep"))
        # overload control (ISSUE 17): retries whose backoff came from a
        # server Retry-After hint (429/503) instead of the client-side
        # exponential schedule
        self.retry_after_honored = r.register(Counter(
            "client_retry_after_honored_total",
            "retry sleeps that honored a server Retry-After header "
            "(clamped to the client's max backoff, jitter preserved)"))
        # serving tier (ISSUE 19): per-CLIENT staleness attribution of
        # the watch-fanout SLO — the WORST client's revision lag behind
        # the store head, sampled every scrape by WatchFanoutTracker
        # (gauge, not counter: it keeps producing data — and can
        # recover — while the fleet idles, the GaugeSLI property)
        self.watch_worst_staleness = r.register(Gauge(
            "client_watch_worst_staleness_revisions",
            "largest per-client revision lag behind the store head at "
            "the last fan-out staleness sample (0 = every watcher "
            "caught up)"))


# informers without an explicit metrics object aggregate here: one place
# to ask "did anything relist / drop / leak handler errors this process"
DEFAULT_CLIENT_METRICS = ClientMetrics()


class StoreMetrics:
    """Broadcaster-side observability (the serving tier): the
    time-window coalescer's flushes, folds, and flush-path fallbacks.
    The fault matrix asserts recovery through
    ``store_coalesce_fallbacks_total`` — a degraded window that is
    invisible here fails the test."""

    def __init__(self, registry: Optional[Registry] = None):
        r = registry or Registry()
        self.registry = r
        self.coalesce_flushes = r.register(Counter(
            "store_coalesce_flushes_total",
            "coalescing windows flushed to the watcher queues (deadline, "
            "ordering barrier, key cap, or shutdown)"))
        self.coalesced_events = r.register(Counter(
            "store_coalesced_events_total",
            "per-key deliveries superseded inside a coalescing window "
            "(latest-wins folds — fan-out work that never happened)"))
        self.coalesce_fallbacks = r.register(Counter(
            "store_coalesce_fallbacks_total",
            "coalescing windows degraded to per-event delivery after a "
            "flush-path failure (state preserved, packing lost)"))


# stores aggregate here (one broadcaster seam per process in practice);
# the fleet bench scrapes this registry alongside the client one
DEFAULT_STORE_METRICS = StoreMetrics()


class SchedulerMetrics:
    """The reference's three scheduling SLIs, in microseconds
    (``metrics/metrics.go:26-50``), plus batch-backend extras."""

    def __init__(self, registry: Optional[Registry] = None):
        r = registry or Registry()
        self.registry = r
        self.e2e_scheduling_latency = r.register(
            Histogram("scheduler_e2e_scheduling_latency_microseconds")
        )
        self.scheduling_algorithm_latency = r.register(
            Histogram("scheduler_scheduling_algorithm_latency_microseconds")
        )
        self.binding_latency = r.register(
            Histogram("scheduler_binding_latency_microseconds")
        )
        self.schedule_attempts = r.register(Counter("scheduler_schedule_attempts_total"))
        self.schedule_failures = r.register(Counter("scheduler_schedule_failures_total"))
        # batch-backend extras
        self.batch_size = r.register(Histogram("scheduler_batch_size", buckets=[2**i for i in range(20)]))
        self.batch_device_latency = r.register(
            Histogram("scheduler_batch_device_latency_microseconds")
        )
        self.pallas_fallback_total = r.register(Counter(
            "scheduler_pallas_fallback_total",
            "pallas dispatch/finalize failures that fell back to the XLA scan",
        ))
        self.kernel_breaker_transitions = r.register(Counter(
            "scheduler_kernel_breaker_transitions_total",
            "circuit-breaker level changes (degrade, probe, restore) on "
            "the pallas→interpret→oracle ladder",
        ))
        self.bind_failures = r.register(Counter(
            "scheduler_bind_failures_total",
            "bind attempts that failed (conflict, not-found, transport)",
        ))
        self.bind_requeues = r.register(Counter(
            "scheduler_bind_requeues_total",
            "pods requeued with backoff after a transient bind failure",
        ))
        # steady-state pipeline (run_batch_loop / overlapped ingest)
        self.batch_queue_wait = r.register(Histogram(
            "scheduler_batch_queue_wait_microseconds",
            "time from the first ready pod to the wave's drain (the "
            "min-batch/max-wait accumulation window)",
        ))
        self.pipeline_prep_latency = r.register(Histogram(
            "scheduler_pipeline_prep_microseconds",
            "host prep (pump + signature warming) run inside the device's "
            "shadow between the final dispatch and its finalize",
        ))
        self.pipeline_device_wait = r.register(Histogram(
            "scheduler_pipeline_device_wait_microseconds",
            "device time left after the overlapped prep returned — the "
            "unfilled overlap headroom of the wave",
        ))
        self.pipeline_prep_failures = r.register(Counter(
            "scheduler_pipeline_prep_failures_total",
            "overlapped-prep runs that raised; the work is deferred to the "
            "next wave's synchronous path (no decisions are affected)",
        ))
        # zero-copy ingest (ISSUE 4): per-wave informer decode time in
        # SECONDS (lazy wrap ~0; the eager compatibility path shows the
        # true from_dict cost), plus lazy-promotion volume — how much
        # typed decode the wave's consumers actually pulled
        self.ingest_decode_seconds = r.register(Histogram(
            "scheduler_ingest_decode_seconds",
            "informer event-decode time per scheduling wave (seconds; "
            "near-zero on the lazy path)",
            buckets=[1e-5 * (2 ** (i / 2)) for i in range(44)],
        ))
        self.ingest_promotions = r.register(Counter(
            "scheduler_ingest_promotions_total",
            "lazy-object sections/objects promoted to typed form by "
            "consumers (decode work that was actually needed)",
        ))
        # batched watch frames (ISSUE 6): per-wave pump APPLICATION time
        # in SECONDS (informer cache apply + handler fan-out + the
        # scheduler's bind confirm), plus frame/event volume and how often
        # the columnar confirm had to fall back to the per-pod compare
        self.pump_apply_seconds = r.register(Histogram(
            "scheduler_pump_apply_seconds",
            "informer event/frame application time per scheduling wave "
            "(cache apply + handler fan-out + bind confirm; seconds)",
            buckets=[1e-5 * (2 ** (i / 2)) for i in range(44)],
        ))
        self.watch_frames = r.register(Counter(
            "scheduler_watch_frames_total",
            "column-packed watch frames applied by this scheduler's "
            "informers (one per correlated store batch txn)",
        ))
        self.watch_frame_events = r.register(Counter(
            "scheduler_watch_frame_events_total",
            "events delivered inside watch frames (the per-event path "
            "they replaced)",
        ))
        self.confirm_fallbacks = r.register(Counter(
            "scheduler_confirm_fallbacks_total",
            "frame bind-confirm entries the columnar revision fence "
            "rejected — routed through the per-pod compare instead",
        ))
        self.tensorize_upload_fraction = r.register(Histogram(
            "scheduler_tensorize_upload_fraction",
            "fraction of node-axis columns re-uploaded to device per wave "
            "(0 = fully cache-resident, 1 = full upload)",
            buckets=[i / 20 for i in range(21)],
        ))
        # frontier scan (ISSUE 5): monotone node pruning + mid-segment
        # node-axis compaction on the XLA scan path
        self.frontier_compactions = r.register(Counter(
            "scheduler_frontier_compactions_total",
            "mid-segment device node-axis compactions (the alive-union "
            "fraction fell below the threshold and the scan resumed at a "
            "smaller power-of-two width)",
        ))
        self.frontier_alive_fraction = r.register(Histogram(
            "scheduler_frontier_alive_fraction",
            "lowest alive-union fraction observed per frontier segment "
            "(1.0 = no column ever died; small = heavy pruning)",
            buckets=[i / 20 for i in range(21)],
        ))
        # device-resident wave loop (ISSUE 11): blocking device→host
        # round-trips on the finalize path — O(compactions + 1) per wave
        # with the while_loop form, O(chunks) with the chunked host loop
        self.host_syncs = r.register(Counter(
            "scheduler_host_syncs_total",
            "blocking device→host round-trips performed by batch "
            "finalize (control reads + result copies)",
        ))
        # preemption (the PostFilter phase)
        self.preemption_attempts = r.register(Counter(
            "scheduler_preemption_attempts_total"))
        self.preemption_victims = r.register(Counter(
            "scheduler_preemption_victims_total"))
        self.preemption_latency = r.register(Histogram(
            "scheduler_preemption_latency_microseconds"))
        # overload control (ISSUE 17): the degradation ladder's state and
        # its shed actions.  pending_pods is the ladder's input signal
        # (GaugeSLI windowed mean — sampled every scrape, so the ladder
        # can recover even with zero traffic); the rest are its outputs.
        self.pending_pods = r.register(Gauge(
            "scheduler_pending_pods",
            "ready pods in the scheduling queue at the last batch-loop "
            "iteration (the overload ladder's queue-depth signal)"))
        self.degradation_rung = r.register(Gauge(
            "scheduler_degradation_rung",
            "current overload degradation rung (0=full fidelity, "
            "1=widened batching, 2=score planes shed, 3=admission "
            "throttled)"))
        self.degradation_transitions = r.register(Counter(
            "scheduler_degradation_transitions_total",
            "degradation-ladder rung changes (engage, step, recover)"))
        self.score_plane_sheds = r.register(Counter(
            "scheduler_score_plane_sheds_total",
            "batches scheduled with preferred interpod-affinity score "
            "planes shed (rung >= 2; feasibility untouched)"))
        self.preemption_sheds = r.register(Counter(
            "scheduler_preemption_sheds_total",
            "preemption-eligible pods denied the PostFilter pass because "
            "their tier is below the ladder's floor (rung >= 2)"))
        # sharded wave loop (ISSUE 18): per-shard SLO attribution of the
        # node-axis mesh — aggregate fractions hid one cold shard behind
        # the warm ones (the PR-12 caveat), so the WORST shard is what
        # gets a first-class signal.  Gauges, not histograms: the SLO
        # layer consumes them as windowed means (GaugeSLI).
        self.mesh_shards = r.register(Gauge(
            "scheduler_mesh_shards",
            "shard count of the node-axis mesh the last sharded wave "
            "loop ran on (0 = single-device path)"))
        self.mesh_worst_shard_upload_fraction = r.register(Gauge(
            "scheduler_mesh_worst_shard_upload_fraction",
            "highest per-shard dirty-column upload fraction of the last "
            "wave (1 = some shard re-uploaded its whole node slice)"))
        self.mesh_shard_alive_skew = r.register(Gauge(
            "scheduler_mesh_shard_alive_skew",
            "max spread between per-shard alive fractions at the last "
            "sharded loop exit (large = the frontier died unevenly and "
            "some shards carry dead columns)"))
