"""Tensorized cluster-state models (the NodeInfo → device-array bridge)."""

from .snapshot import BatchStatic, InitialState, Tensorizer, pod_signature_key
