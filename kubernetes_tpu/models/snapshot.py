"""Tensorization: cluster snapshot + pod batch → dense device arrays.

This is the bridge between the object world (``SchedulerCache`` /
``NodeInfo``, SURVEY.md §2.4) and the TPU kernels (``kubernetes_tpu/ops``).

Design (TPU-first, not a port):

- **Node axis**: nodes sorted by name form the canonical axis shared with
  the oracle; padded to a lane/shard-friendly multiple with an ``exists``
  mask so shapes stay static under churn (SURVEY.md §7.4 hard part 2).

- **Pod equivalence signatures**: pods created from the same template
  (labels, namespace, requests, selectors, tolerations, affinity, ports,
  owner) are *identical* to every predicate and priority.  The batch is
  deduped into G signatures, and every per-pod×node static quantity
  (selector/taint/pressure masks, preferred-node-affinity raw counts,
  image scores, …) becomes a [G, N] array — the tensor-native
  generalization of the reference's equivalence cache
  (``core/equivalence_cache.go``), and the reason 150k pods don't need
  150k×5k precomputed bytes.

- **Strings die on the host**: selectors, labels, taints, topology keys are
  evaluated once here; the device sees only int32/bool arrays.

The produced ``BatchStatic`` (numpy, host) feeds ``ops.batch_kernel``;
``initial_state`` extracts the dynamic scan state from the NodeInfo map.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from ..api import lazy as lazy_mod
from ..api import types as api
from ..native import MatchEngine
from ..scheduler.nodeinfo import NodeInfo
from ..scheduler.predicates import (
    VOLUME_COUNT_LIMITS,
    _READONLY_SHARED_KINDS,
    _pod_matches_term,
)
from ..scheduler.priorities import (
    PREFER_AVOID_PODS_ANNOTATION,
    PriorityContext,
    SelectorSpreadPriority,
    _zone_key,
)
from ..scheduler.units import (
    CPU_MILLI,
    MEM_MIB,
    NUM_RESOURCES,
    pod_nonzero_request_vec,
    pod_request_vec,
)

_MIN_IMG_MIB = 23
_MAX_IMG_MIB = 1000


def _freeze(x):
    """Recursively convert dict/list structures into hashable tuples
    (dicts as sorted item tuples)."""
    if isinstance(x, dict):
        return tuple(sorted((k, _freeze(v)) for k, v in x.items()))
    if isinstance(x, (list, tuple)):
        return tuple(_freeze(v) for v in x)
    return x


def _raw_sig_spec_parts(spec: dict, ns: str, labels_t: tuple, ref) -> tuple:
    """Assemble the signature key from a RAW spec dict plus resolved meta
    components — field-for-field the same key `pod_signature_key` builds
    from a decoded pod (store payloads are ``to_dict`` images, so the
    frozen subtrees come out identical; test_lazy pins it)."""
    aff = spec.get("affinity")
    return (
        ns,
        labels_t,
        tuple(sorted((spec.get("nodeSelector") or {}).items())),
        spec.get("nodeName", ""),
        _freeze(aff) if aff else None,
        tuple(_freeze(t) for t in spec.get("tolerations") or ()),
        tuple(_freeze(v) for v in spec.get("volumes") or ()
              if not v.get("diskID")),
        ref,
        tuple(
            (
                c.get("image", ""),
                tuple(sorted(
                    (k, str(v)) for k, v in
                    (((c.get("resources") or {}).get("requests")) or {}).items())),
                tuple(sorted(
                    (p.get("protocol", "TCP"), p.get("hostPort", 0))
                    for p in c.get("ports") or () if p.get("hostPort", 0) > 0)),
            )
            for c in spec.get("containers") or ()
        ),
    )


def raw_pod_signature_key(d: dict) -> tuple:
    """``pod_signature_key`` straight from a wire dict — the column-batch
    emit path computes grouping without constructing a single typed
    object."""
    meta = d.get("metadata") or {}
    spec = d.get("spec") or {}
    return _raw_sig_spec_parts(
        spec,
        meta.get("namespace", "default"),
        tuple(sorted((meta.get("labels") or {}).items())),
        lazy_mod.raw_controller_ref(meta),
    )


def pod_signature_key(pod: api.Pod) -> tuple:
    """Canonical scheduling-equivalence key (the ecache hash analogue:
    reference ``equivalence_cache.go:98 getEquivalenceHash`` uses the
    controller ref; this key is exact over everything predicates and
    priorities read, so it is strictly safer).  An opaque hashable — a
    nested tuple, NOT a string: serializing to json cost more than every
    consumer's dict lookups combined at 150k-pod scale.

    Memoized on the pod object: the backend's segmenter and build_static
    both key every pod of every segment.  Safe because batch pods are
    immutable while in flight (informer objects; mutation is a bug the
    cache mutation detector exists to catch) — a spec patch produces a new
    object and therefore a fresh key.

    Lazy pods whose spec is still undecoded key straight off the wire
    dict (``_raw_sig_spec_parts``): identical tuples for store
    round-tripped payloads, so grouping is unchanged and no Container/
    Affinity objects are ever built for non-representative pods.
    Payloads that entered via the HTTP POST path may keep the client's
    UNnormalized JSON (omitted defaulted keys) — their raw key then
    differs from the eager key, which only splits equivalence groups
    more finely (same-raw pods are still truly identical), never merges
    distinct pods: correctness and parity are unaffected, G grows a
    little for unnormalized clients."""
    cached = getattr(pod, "_sig_key", None)
    if cached is not None:
        return cached
    spec_raw = lazy_mod.undecoded_spec(pod)
    if spec_raw is not None:
        meta_raw = lazy_mod.undecoded_meta(pod)
        if meta_raw is not None:
            key = _raw_sig_spec_parts(
                spec_raw,
                meta_raw.get("namespace", "default"),
                tuple(sorted((meta_raw.get("labels") or {}).items())),
                lazy_mod.raw_controller_ref(meta_raw))
        else:
            # meta already decoded (e.g. the queue touched .key): read it
            # typed — promotion makes the decoded section authoritative
            ref = pod.meta.controller_ref()
            key = _raw_sig_spec_parts(
                spec_raw,
                pod.meta.namespace,
                tuple(sorted(pod.meta.labels.items())),
                (ref.kind, ref.uid) if ref else None)
    else:
        ref = pod.meta.controller_ref()
        key = (
            pod.meta.namespace,
            tuple(sorted(pod.meta.labels.items())),
            tuple(sorted(pod.spec.node_selector.items())),
            pod.spec.node_name,
            _freeze(pod.spec.affinity.to_dict()) if pod.spec.affinity else None,
            tuple(_freeze(t.to_dict()) for t in pod.spec.tolerations),
            # direct-disk volumes are deliberately EXCLUDED: their identity
            # lives on the per-pod volume-slot axis (pod_vol_ids), not the
            # signature axis — otherwise every distinct disk id would mint a
            # new signature and G would grow with the batch.  PVC-backed and
            # other volumes stay in the key (their constraints fold into the
            # static [G, N] masks).
            tuple(_freeze(v.to_dict()) for v in pod.spec.volumes if not v.disk_id),
            (ref.kind, ref.uid) if ref else None,
            tuple(
                (
                    c.image,
                    tuple(sorted((k, str(v)) for k, v in c.resources.requests.items())),
                    tuple(sorted((p.protocol, p.host_port) for p in c.ports if p.host_port > 0)),
                )
                for c in pod.spec.containers
            ),
        )
    try:
        object.__setattr__(pod, "_sig_key", key)
    except AttributeError:
        pass  # slotted/frozen pod stand-ins: just skip the memo
    return key


def count_affinity_terms(pod: api.Pod) -> int:
    """Number of (anti)affinity term rows this pod contributes to the [T, G]
    tables (empty-topology-key terms never become rows).  Shared by the
    build_static budget probe and the backend's segmenter so both always
    agree on what fits.  The raw branch mirrors the ``from_dict``
    topology-key default (absent key → hostname → counts)."""
    spec_raw = lazy_mod.undecoded_spec(pod)
    if spec_raw is not None:
        a = spec_raw.get("affinity")
        if not a:
            return 0
        n = 0
        for fld in ("podAffinityRequired", "podAntiAffinityRequired"):
            for t in a.get(fld) or ():
                if t.get("topologyKey", api.HOSTNAME_LABEL):
                    n += 1
        for fld in ("podAffinityPreferred", "podAntiAffinityPreferred"):
            for wt in a.get(fld) or ():
                if (wt.get("podAffinityTerm") or {}).get(
                        "topologyKey", api.HOSTNAME_LABEL):
                    n += 1
        return n
    a = pod.spec.affinity
    if a is None:
        return 0
    return (
        sum(1 for t in a.pod_affinity_required if t.topology_key)
        + sum(1 for t in a.pod_anti_affinity_required if t.topology_key)
        + sum(1 for wt in a.pod_affinity_preferred if wt.term.topology_key)
        + sum(1 for wt in a.pod_anti_affinity_preferred if wt.term.topology_key)
    )


def _disk_refs(pod: api.Pod) -> list:
    """(disk_kind, disk_id, read_only) per direct-disk volume reference,
    raw-first: the [P] loops (build_static slot fill, host-state ingest)
    must never decode a spec just to learn it has no volumes."""
    spec_raw = lazy_mod.undecoded_spec(pod)
    if spec_raw is not None:
        return [(v.get("diskKind", ""), v.get("diskID", ""),
                 bool(v.get("readOnly", False)))
                for v in spec_raw.get("volumes") or () if v.get("diskID")]
    if not pod.spec.volumes:
        return []
    return [(v.disk_kind, v.disk_id, v.read_only)
            for v in pod.spec.volumes if v.disk_id]


def pod_disk_vols(pod: api.Pod) -> set:
    """Distinct (disk_kind, disk_id) identities the pod references — the
    per-pod volume-slot budget unit (same sharing contract as above)."""
    return {(kind, disk_id) for kind, disk_id, _ in _disk_refs(pod)}


@dataclass
class _AffinityTerm:
    """One flattened (anti)affinity term carried by a batch signature.

    Phase B puts the batch pods' own terms on device: each term becomes a
    row of the [T, G] match matrix, a row of the [T, N] topology-domain map,
    and entries in the symmetry/own weight tables the scan step contracts
    against (reference semantics: ``predicates.go:982,1065,1146``,
    ``interpod_affinity.go:119``)."""

    owner: int  # signature index
    kind: str  # RA | RAA | PA | PAA
    weight: int  # symmetry scoring weight (RA: hard weight, PA: +w, PAA: -w)
    term: api.PodAffinityTerm


_VOL_KINDS = list(VOLUME_COUNT_LIMITS)  # fixed kind axis for [K, N] counts

# benchmark seam: True forces build_static to recompute every signature's
# per-node rows (the pre-dedup behavior) so the interaction-key cache can
# be A/B-measured honestly; never set in production code
_DISABLE_ROW_CACHE = False

_NS_KEY = "\x00ns"  # namespace rides the label space as a reserved key


def _node_static_cols(rep, infos, js, is_best_effort, ref, images,
                      prefer_avoid_weight, image_weight,
                      out_ok, out_aff, out_taint, out_score) -> None:
    """Fill node columns ``js`` of one signature's static rows.

    ``ref`` is the interaction-key's controller-ref component: the actual
    ref when some node's prefer-avoid annotation names its uid, ``None``
    otherwise — so a cached row recomputed for a dirty column keeps the
    semantics of its interaction CLASS, not of the particular pod that
    first populated it."""
    # kernel: implements CheckNodeSchedulable, CheckNodeCondition,
    # kernel: implements PodToleratesNodeTaints, CheckNodeMemoryPressure
    # kernel: implements CheckNodeDiskPressure
    # (node-static predicate verdicts folded into the [G, N] mask the
    # device step ANDs in — the host/selector half of GeneralPredicates
    # lands here too; ktpu-analyze parity pass reads these markers)
    for j in js:
        info = infos[j]
        node = info.node
        labels = node.meta.labels
        ok = not node.spec.unschedulable
        # Ready-condition gate (CheckNodeCondition)
        if ok:
            ready = node.status.condition(api.NODE_READY)
            ok = ready is None or ready.status == "True"
        # host match
        if ok and rep.spec.node_name:
            ok = rep.spec.node_name == node.meta.name
        # selector + required node affinity
        if ok and rep.spec.node_selector:
            ok = all(labels.get(k) == v for k, v in rep.spec.node_selector.items())
        if ok and rep.spec.affinity is not None and rep.spec.affinity.node_affinity_required is not None:
            ok = rep.spec.affinity.node_affinity_required.matches(labels)
        # taints (NoSchedule/NoExecute)
        if ok:
            for taint in node.spec.taints:
                if taint.effect not in (api.NO_SCHEDULE, api.NO_EXECUTE):
                    continue
                if not any(t.tolerates(taint) for t in rep.spec.tolerations):
                    ok = False
                    break
        # pressure conditions
        if ok and is_best_effort and info.memory_pressure:
            ok = False
        if ok and info.disk_pressure:
            ok = False
        out_ok[j] = ok

        # preferred node affinity raw weight
        if rep.spec.affinity is not None:
            cnt = 0
            for pt in rep.spec.affinity.node_affinity_preferred:
                if pt.weight > 0 and pt.preference.matches(labels):
                    cnt += pt.weight
            out_aff[j] = cnt
        # intolerable PreferNoSchedule taints
        cnt = 0
        for taint in node.spec.taints:
            if taint.effect != api.PREFER_NO_SCHEDULE:
                continue
            if not any(t.tolerates(taint) for t in rep.spec.tolerations):
                cnt += 1
        out_taint[j] = cnt

        # absolute (non-normalized) priorities folded into one array
        score = 0
        if prefer_avoid_weight:
            avoided = False
            if ref is not None and ref.kind in ("ReplicaSet", "ReplicationController"):
                ann = node.meta.annotations.get(PREFER_AVOID_PODS_ANNOTATION, "")
                avoided = ref.uid in [u.strip() for u in ann.split(",") if u.strip()]
            score += prefer_avoid_weight * (0 if avoided else 10)
        if image_weight:
            total_mib = 0
            for img in node.status.images:
                if any(nm in images for nm in img.get("names", [])):
                    total_mib += int(img.get("sizeBytes", 0)) // (2**20)
            if total_mib < _MIN_IMG_MIB:
                iscore = 0
            elif total_mib > _MAX_IMG_MIB:
                iscore = 10
            else:
                iscore = ((total_mib - _MIN_IMG_MIB) * 10) // (_MAX_IMG_MIB - _MIN_IMG_MIB)
            score += image_weight * iscore
        out_score[j] = score


def _pod_content_key(pod: api.Pod) -> tuple:
    """Content identity of a pod AS THE HOST STATE SEES IT (labels +
    namespace + disk refs) — what decides whether a same-key pod must be
    re-ingested on reconcile.  Memoized on the pod object under the same
    immutability contract as ``pod_signature_key``; lazy pods read the
    wire dict directly (identical tuples by the round-trip argument)."""
    cached = getattr(pod, "_hbs_key", None)
    if cached is not None:
        return cached
    spec_raw = lazy_mod.undecoded_spec(pod)
    if spec_raw is not None:
        disks = None
        vols = spec_raw.get("volumes")
        if vols:
            disks = tuple(sorted(
                (v.get("diskKind", ""), v.get("diskID", ""),
                 bool(v.get("readOnly", False)))
                for v in vols if v.get("diskID")))
        labels, ns = lazy_mod.labels_ns_of(pod)
        key = (ns, tuple(sorted(labels.items())), disks)
    else:
        disks = None
        if pod.spec.volumes:
            disks = tuple(sorted(
                (v.disk_kind, v.disk_id, v.read_only)
                for v in pod.spec.volumes if v.disk_id))
        key = (pod.meta.namespace, tuple(sorted(pod.meta.labels.items())), disks)
    try:
        object.__setattr__(pod, "_hbs_key", key)
    except AttributeError:
        pass
    return key


class HostBatchState:
    """Incremental host-side cluster state shared by every kernel segment
    of one batch — and, via ``reconcile``, ACROSS batches.

    Without it, ``initial_state`` rebuilds its selector-match corpus and
    volume occupancy by scanning EVERY pod on EVERY node once per
    segment — O(existing-pods × segments), the dominant host cost at
    150k-pod scale.  Within a batch it is updated per placed pod;
    between batches ``reconcile`` diffs only the nodes whose NodeInfo
    generation moved (the copy-on-write counters of ``cache.go:79``
    carried through the snapshot clones), so a steady-state churn wave
    pays O(pods on touched nodes), not O(cluster).

    Pod label content and spread/term selectors are content-interned:
    wave after wave of template-stamped pods reuses the same native
    labelmap/selector ids, which both bounds engine growth and removes
    the per-pod ctypes marshalling that dominated ingest at scale.

    The node order is the same sorted order ``build_static`` uses, so
    node indices agree across the batch."""

    # engine compaction threshold: rebuild the native corpus when more
    # than this many interned labelmaps have no live pod AND the dead
    # outnumber the live (churn with per-rollout-unique labels would
    # otherwise grow the engine for the process lifetime)
    MAX_DEAD_CONTENT = 4096

    def __init__(self, node_info_map: dict[str, "NodeInfo"]):
        self.eng = MatchEngine()
        self._lid_memo: dict[tuple, int] = {}
        self._sel_memo: dict[tuple, int] = {}
        self._content_rc: dict[tuple, int] = {}  # live pods per labelmap
        self._kind_pos = {k: i for i, k in enumerate(_VOL_KINDS)}
        self.last_dirty: list[int] = []  # node_j's touched by the last reconcile
        self._rebuild(node_info_map)

    def _rebuild(self, node_info_map: dict[str, "NodeInfo"]) -> None:
        # live-content refcounts restart with the pod arrays (interned
        # labelmaps persist in the engine; rc==0 entries are the garbage
        # the compaction threshold watches)
        self._content_rc = {}
        self.node_names = sorted(
            n for n, i in node_info_map.items() if i.node is not None
        )
        self.node_index = {n: j for j, n in enumerate(self.node_names)}
        self.node_gen: dict[str, int] = {}
        self.pod_lids: list[int] = []
        self.pod_node_j: list[int] = []
        self.pod_keys: list[str] = []
        self.pod_content: list[tuple] = []
        self.pod_disks: list[Optional[list]] = []
        # per node_j: pod key -> index into the parallel arrays
        self.node_pods: list[dict[str, int]] = [
            {} for _ in self.node_names
        ]
        self._node_j_cache: Optional[np.ndarray] = None
        # (kind, id) -> {node_j: [refcount, non-sharable refcount]}
        self.disk_locations: dict[tuple, dict[int, list]] = {}
        # distinct limited-kind disks per node: [K, N_real]
        self.nk_counts = np.zeros(
            (len(_VOL_KINDS), len(self.node_names)), dtype=np.int32)
        for name in self.node_names:
            j = self.node_index[name]
            info = node_info_map[name]
            for q in info.pods:
                self._ingest(q, j)
            self.node_gen[name] = info.generation

    def reconcile(self, node_info_map: dict[str, "NodeInfo"]) -> None:
        """Bring the state up to date with a fresh snapshot: nodes whose
        generation is unchanged are skipped wholesale; changed nodes are
        diffed by pod key + content.  A changed node SET falls back to a
        full rebuild (node add/remove is rare and re-indexes the axis).

        ``last_dirty`` records the node positions whose generation moved
        (cache assume/forget and informer deliveries both bump it via the
        CoW counters) — the backend accumulates it into
        ``stats["host_state_dirty_nodes"]``, the per-wave reconcile-width
        companion to the device cache's upload stats."""
        names = sorted(
            n for n, i in node_info_map.items() if i.node is not None
        )
        dead = sum(1 for rc in self._content_rc.values() if rc <= 0)
        if dead > self.MAX_DEAD_CONTENT and dead > len(self._content_rc) - dead:
            # compact: the native engine has no labelmap removal, so a
            # corpus dominated by dead content is rebuilt from scratch
            self.eng.close()
            self.eng = MatchEngine()
            self._lid_memo.clear()
            self._sel_memo.clear()
            self._content_rc.clear()
            self._rebuild(node_info_map)
            self.last_dirty = list(range(len(self.node_names)))
            return
        if names != self.node_names:
            self._rebuild(node_info_map)
            self.last_dirty = list(range(len(self.node_names)))
            return
        self.last_dirty = []
        for name in names:
            info = node_info_map[name]
            if self.node_gen.get(name) == info.generation:
                continue
            j = self.node_index[name]
            self.last_dirty.append(j)
            mine = self.node_pods[j]
            current: dict[str, api.Pod] = {q.meta.key: q for q in info.pods}
            for key in [k for k in mine if k not in current]:
                self._remove(mine[key])
            for key, q in current.items():
                idx = mine.get(key)
                if idx is None:
                    self._ingest(q, j)
                elif self.pod_content[idx] != _pod_content_key(q):
                    self._remove(idx)
                    self._ingest(q, j)
            self.node_gen[name] = info.generation

    @property
    def mounted_disks(self):
        """Membership view over every (kind, id) mounted anywhere."""
        return self.disk_locations

    def add_pod(self, pod: api.Pod, node_name: str) -> None:
        j = self.node_index.get(node_name)
        if j is None:
            return
        key = pod.meta.key
        if key not in self.node_pods[j]:
            self._ingest(pod, j, key)

    def selector_id(self, reqs: list[tuple]) -> int:
        """Content-interned ``eng.add_selector``: per-segment spread and
        term selectors repeat across segments and batches (same services/
        controllers), so the native selector corpus stays bounded."""
        key = tuple((k, op, tuple(vs)) for k, op, vs in reqs)
        sid = self._sel_memo.get(key)
        if sid is None:
            sid = self.eng.add_selector(reqs)
            self._sel_memo[key] = sid
        return sid

    def _ingest(self, pod: api.Pod, j: int, key: "str | None" = None) -> None:
        if key is None:
            key = pod.meta.key
        content = _pod_content_key(pod)
        lid = self._lid_memo.get(content[:2])
        if lid is None:
            labels, ns = lazy_mod.labels_ns_of(pod)
            lid = self.eng.add_labelmap({**labels, _NS_KEY: ns})
            self._lid_memo[content[:2]] = lid
        self._content_rc[content[:2]] = self._content_rc.get(content[:2], 0) + 1
        idx = len(self.pod_lids)
        self.pod_lids.append(lid)
        self.pod_node_j.append(j)
        self.pod_keys.append(key)
        self.pod_content.append(content)
        self.node_pods[j][key] = idx
        self._node_j_cache = None
        disks = None
        vol_refs = _disk_refs(pod)
        if vol_refs:
            per_pod: dict[tuple, bool] = {}  # all-refs-read-only per disk
            for kind, disk_id, read_only in vol_refs:
                key = (kind, disk_id)
                per_pod[key] = per_pod.get(key, True) and read_only
            if per_pod:
                disks = []
                for key, all_ro in per_pod.items():
                    ns = not (key[0] in _READONLY_SHARED_KINDS and all_ro)
                    disks.append((key, ns))
                    self._disk_add(key, j, ns)
        self.pod_disks.append(disks)

    def _disk_add(self, key: tuple, j: int, ns: bool) -> None:
        locs = self.disk_locations.setdefault(key, {})
        rc = locs.get(j)
        if rc is None:
            locs[j] = [1, 1 if ns else 0]
            pos = self._kind_pos.get(key[0])
            if pos is not None:
                self.nk_counts[pos, j] += 1
        else:
            rc[0] += 1
            if ns:
                rc[1] += 1

    def _disk_sub(self, key: tuple, j: int, ns: bool) -> None:
        locs = self.disk_locations.get(key)
        if locs is None:
            return
        rc = locs.get(j)
        if rc is None:
            return
        rc[0] -= 1
        if ns:
            rc[1] -= 1
        if rc[0] <= 0:
            del locs[j]
            pos = self._kind_pos.get(key[0])
            if pos is not None:
                self.nk_counts[pos, j] -= 1
            if not locs:
                del self.disk_locations[key]

    def _remove(self, idx: int) -> None:
        """Swap-remove entry ``idx`` so the parallel arrays stay dense
        (matching never needs an alive mask)."""
        j = self.pod_node_j[idx]
        del self.node_pods[j][self.pod_keys[idx]]
        content2 = self.pod_content[idx][:2]
        rc = self._content_rc.get(content2, 0) - 1
        self._content_rc[content2] = rc  # rc==0 marks engine garbage
        disks = self.pod_disks[idx]
        if disks:
            for key, ns in disks:
                self._disk_sub(key, j, ns)
        last = len(self.pod_lids) - 1
        if idx != last:
            self.pod_lids[idx] = self.pod_lids[last]
            self.pod_node_j[idx] = self.pod_node_j[last]
            self.pod_keys[idx] = self.pod_keys[last]
            self.pod_content[idx] = self.pod_content[last]
            self.pod_disks[idx] = self.pod_disks[last]
            self.node_pods[self.pod_node_j[idx]][self.pod_keys[idx]] = idx
        self.pod_lids.pop()
        self.pod_node_j.pop()
        self.pod_keys.pop()
        self.pod_content.pop()
        self.pod_disks.pop()
        self._node_j_cache = None

    def node_j_array(self) -> np.ndarray:
        if self._node_j_cache is None:
            self._node_j_cache = np.asarray(self.pod_node_j, dtype=np.int64)
        return self._node_j_cache

    def close(self) -> None:
        self.eng.close()


class NodeStaticRows:
    """Cross-wave cache of the per-signature node-static rows
    (``static_ok`` / ``node_aff_raw`` / ``taint_intol_raw`` /
    ``static_score``) keyed by the signature's node-interaction identity.

    The rows depend only on NODE OBJECTS (labels, taints, conditions,
    annotations, images) — never on pod placements — so in steady-state
    churn, where waves of template-stamped pods repeat the same
    interaction keys against an unchanged fleet, every wave after the
    first reuses the rows outright instead of paying the [G, N] Python
    sweep (the dominant host cost of ``build_static`` at 5k nodes).

    Invalidation is per NODE COLUMN: ``sync`` diffs the node-object
    identity per axis position (``set_node`` always installs a fresh
    object, so identity diffing is exact) and eagerly recomputes exactly
    the dirty columns of every cached row.  A changed node SET or a
    changed weight configuration flushes the cache (new axis epoch).
    The (epoch, version) token and the dirty column list ride the
    produced ``BatchStatic`` so the device-side cache
    (``ops.batch_kernel.DeviceNodeCache``) can mirror the same
    only-upload-dirty-columns discipline for the node-axis tensors."""

    _NONCE = itertools.count(1)

    def __init__(self, max_entries: int = 4096):
        self.max_entries = max_entries
        self._axis: Optional[tuple] = None
        self._node_refs: list = []
        self._weights_key = None
        # instance nonce: tokens from DIFFERENT NodeStaticRows instances
        # must never compare equal (a swapped-in tensorizer restarts at
        # epoch 1 / version 0, which would alias a stale device cache)
        self._nonce = next(NodeStaticRows._NONCE)
        self.epoch = 0
        self.version = 0
        self.last_dirty: list[int] = []
        # interaction_key -> (rep, is_best_effort, ref, images, rows)
        self._rows: dict = {}
        self.stats = {"hits": 0, "misses": 0, "flushes": 0,
                      "dirty_nodes": 0, "dirty_recomputes": 0}

    def sync(self, node_names: list[str], infos: list, weights_key: tuple,
             row_fn) -> None:
        """Bring the cache in line with the current node axis.  ``row_fn``
        recomputes one cached entry's columns: called as
        ``row_fn(entry, js)`` for each cached row when columns are dirty."""
        axis = tuple(node_names)
        if axis != self._axis or weights_key != self._weights_key:
            self._rows.clear()
            self.epoch += 1
            self.version = 0
            self._axis = axis
            self._weights_key = weights_key
            self._node_refs = [info.node for info in infos]
            self.last_dirty = []
            self.stats["flushes"] += 1
            return
        dirty = [j for j, info in enumerate(infos)
                 if info.node is not self._node_refs[j]]
        if not dirty:
            self.last_dirty = []
            return
        self._node_refs = [info.node for info in infos]
        self.version += 1
        self.last_dirty = dirty
        self.stats["dirty_nodes"] += len(dirty)
        if len(dirty) > max(8, len(infos) // 4):
            # a mostly-dirty axis: recomputing every cached row column by
            # column costs more than letting the rows rebuild on miss
            self._rows.clear()
            return
        for entry in self._rows.values():
            row_fn(entry, dirty)
            self.stats["dirty_recomputes"] += 1

    def get(self, key: tuple):
        entry = self._rows.get(key)
        if entry is None:
            self.stats["misses"] += 1
            return None
        self.stats["hits"] += 1
        return entry[4]

    def put(self, key: tuple, rep, is_best_effort: bool, ref, images,
            rows: tuple) -> None:
        if len(self._rows) >= self.max_entries:
            self._rows.clear()  # wholesale: keys churn together (rollouts)
            self.stats["flushes"] += 1
        self._rows[key] = (rep, is_best_effort, ref, images, rows)

    def token(self) -> tuple:
        return (self._nonce, self.epoch, self.version)


@dataclass
class BatchStatic:
    """Host-computed static arrays for one kernel segment (numpy)."""

    # node axis
    node_names: list[str]  # length N_real (pre-padding)
    n_pad: int  # padded N
    node_exists: np.ndarray  # [N] bool
    node_alloc: np.ndarray  # [N, R] int32
    node_alloc_pods: np.ndarray  # [N] int32
    node_zone: np.ndarray  # [N] int32, -1 = no zone
    num_zones: int

    # signatures
    group_of_pod: np.ndarray  # [P] int32
    pod_names: list[str]
    # per-signature static masks / scores [G, N]
    static_ok: np.ndarray  # bool
    node_aff_raw: np.ndarray  # int32 (preferred node affinity weights)
    taint_intol_raw: np.ndarray  # int32 (PreferNoSchedule intolerable count)
    static_score: np.ndarray  # int32 (weight-scaled absolute priorities)
    # per-signature resources
    g_request: np.ndarray  # [G, R] int32
    g_nonzero: np.ndarray  # [G, 2] int32
    # ports
    g_ports: np.ndarray  # [G, Pv] bool
    port_vocab: list[tuple[str, int]]
    # spreading
    g_has_spread: np.ndarray  # [G] bool (has matching selectors)
    spread_inc: np.ndarray  # [G, G] int32: landing of sig h bumps counts of sig g
    # inter-pod affinity contributions from EXISTING pods (static: existing
    # pods do not move during the batch):
    interpod_raw: np.ndarray  # [G, N] int32 (scoring symmetry, may be negative)

    # -- phase B: the batch pods' own (anti)affinity terms on device --------
    # T >= 1 (padded with an inert term when the batch carries none)
    terms: "list[_AffinityTerm]" = field(default_factory=list)
    term_matches_sig: np.ndarray = None  # [T, G] bool: sig-g pod in term t's scope
    sym_w: np.ndarray = None  # [T] int32 symmetry scoring weight
    own_w: np.ndarray = None  # [G, T] int32 own soft-term weight (PA +w / PAA -w)
    own_ra: np.ndarray = None  # [G, T] bool own required-affinity terms
    own_raa: np.ndarray = None  # [G, T] bool own required-anti terms
    own_all: np.ndarray = None  # [G, T] bool any term owned by sig
    is_raa: np.ndarray = None  # [T] bool required anti (symmetry forbids)
    self_match: np.ndarray = None  # [T] bool owner matches own term (first-pod rule)
    node_domain: np.ndarray = None  # [T, N] int32 domain id (trash where key absent)
    dom_valid: np.ndarray = None  # [T, N] bool node carries the topology key

    # -- phase B: volumes on device ----------------------------------------
    # Per-POD slot lists: each pod references <= W distinct (kind, id) disks;
    # slot s holds an index into the [V, N] dynamic occupancy arrays
    # (sentinel = v_state-1, an always-empty row for unused slots).  Keeping
    # volume identity off the signature axis keeps G independent of how many
    # distinct disks the batch carries, and makes the per-step device cost
    # O(W·N) instead of O(V·N).
    vol_vocab: list = field(default_factory=list)
    v_state: int = 1  # padded row count of the dynamic [V, N] arrays
    pod_vol_ids: np.ndarray = None  # [P, W] int32 (sentinel for unused slots)
    pod_vol_valid: np.ndarray = None  # [P, W] bool
    pod_vol_ro_ok: np.ndarray = None  # [P, W] bool (all refs ro AND kind sharable)
    pod_vol_kind: np.ndarray = None  # [P, W] int32 (K = kind without a count limit)
    # conflict-free disks: valid for MaxVolumeCount, no occupancy identity
    # (they read the sentinel row and are masked out of the state write)
    pod_vol_count_only: np.ndarray = None  # [P, W] bool
    use_vols: bool = False  # compile-time flag: any volume slot in segment
    vol_limits: np.ndarray = None  # [K] int32

    # scoring mode flags
    weights: dict = field(default_factory=dict)

    # node-axis identity for the device-resident node-state cache
    # (ops.batch_kernel.DeviceNodeCache): (epoch, version) from
    # NodeStaticRows plus the columns dirtied since version-1.  None when
    # the tensorizer runs without persistent rows (cache bypassed).
    node_token: Optional[tuple] = None
    node_dirty: Optional[list] = None

    # compile-time flag: any host port in the segment (no ports → the
    # kernel skips the [N, Pv] port logic and carry write entirely)
    use_ports: bool = True
    # resource-axis selection: the NUM_RESOURCES slots some signature in
    # the segment actually requests (always including CPU_MILLI/MEM_MIB
    # at positions 0/1 — scoring indexes them positionally).  None = all.
    # Host arrays stay full-width (oracle/commit paths); only the device
    # upload is sliced.  Sticky-unioned across waves so the compiled
    # kernel's [.., R'] shapes never wobble mid-run.
    r_sel: Optional[np.ndarray] = None
    # (compacted frontier views carry node_token=None — see
    # compact_segment — so they can never alias a full-width
    # DeviceNodeCache entry; chosen-index mapping flows through the
    # compacted node_names subset, no extra provenance field needed)


@dataclass
class InitialState:
    """Dynamic scan state extracted from the NodeInfo map (numpy)."""

    requested: np.ndarray  # [N, R] int32
    nonzero_requested: np.ndarray  # [N, 2] int32
    pod_count: np.ndarray  # [N] int32
    ports_used: np.ndarray  # [N, Pv] bool
    spread_counts: np.ndarray  # [G, N] int32
    round_robin: int
    # phase B dynamic state
    # Affinity-domain state is kept EXPANDED over the node axis — dm[t, j] is
    # the count of pods matching term t in node j's topology domain (0 where
    # the node lacks the key).  The expansion trades a little memory for
    # scatter/gather-free steps: reads are plain rows and the placement
    # update is an elementwise same-domain mask — TPU-friendly on both the
    # XLA and Pallas paths.
    dm: np.ndarray = None  # [T, N] int32: pods matching term t, per node's domain
    downer: np.ndarray = None  # [T, N] int32: placed owners of term t, per node's domain
    total_match: np.ndarray = None  # [T] int32: pods matching term t anywhere
    vol_any: np.ndarray = None  # [V, N] bool volume instance present
    vol_ns: np.ndarray = None  # [V, N] bool non-sharable instance present
    nk: np.ndarray = None  # [K, N] int32 distinct limited-kind disks on node
    # frontier mode: step-0 monotone feasibility per signature (seeded by
    # ``frontier_seed``); becomes the kernel's still_ok carry plane
    still_ok: np.ndarray = None  # [G, N] bool


def _pad_to(n: int, multiple: int) -> int:
    if multiple <= 1:
        return max(n, 1)
    return max(((n + multiple - 1) // multiple) * multiple, multiple)


# -- frontier scan: tensorize-time prefilter + host-side compaction ---------

# BatchStatic / InitialState fields carrying a node axis → axis position
# (shared by compact_segment; the device-side twin lives in
# ops.batch_kernel._STATIC_NODE_AXES / _STATE_NODE_AXES)
_STATIC_NODE_FIELDS = {
    "node_exists": 0, "node_alloc": 0, "node_alloc_pods": 0, "node_zone": 0,
    "static_ok": 1, "node_aff_raw": 1, "taint_intol_raw": 1,
    "static_score": 1, "interpod_raw": 1, "node_domain": 1, "dom_valid": 1,
}
_INIT_NODE_FIELDS = {
    "requested": 0, "nonzero_requested": 0, "pod_count": 0, "ports_used": 0,
    "spread_counts": 1, "dm": 1, "downer": 1, "vol_any": 1, "vol_ns": 1,
    "nk": 1, "still_ok": 1,
}


def monotone_plane(static: BatchStatic, requested: np.ndarray,
                   pod_count: np.ndarray, ports_used: np.ndarray,
                   dm: "np.ndarray | None" = None,
                   downer: "np.ndarray | None" = None) -> np.ndarray:
    """The MONOTONE feasibility plane [G, N] at an arbitrary dynamic
    state — the refresh-plane builder shared by :func:`frontier_seed`
    (step-0 state) and the device-resident loop's periodic all-G
    ``still_ok`` refresh (whose jnp twin is
    ``ops.batch_kernel.monotone_plane_device``; tests cross-check the
    two against each other on materialized mid-segment states).

    Only components that can never improve as the carry grows belong
    here: resource fit, pod-count, ports, placed-owner symmetric
    required-anti hits (``downer > 0``), and own required-anti hits
    (``dm > 0``).  The own required-AFFINITY terms and the first-pod
    rule are non-monotone (a landing pod can turn them ON) and are
    deliberately excluded — the plane must over-approximate every
    FUTURE pod's feasibility, never under."""
    # kernel: implements GeneralPredicates
    # (the plane evaluates the same resource/pod-count/port masks the
    # step computes, vectorized over [G, N] at the given state)
    g_request = static.g_request  # full-width: r_sel only trims the device
    fit = np.all(
        (requested[None, :, :] + g_request[:, None, :]
         <= static.node_alloc[None, :, :]) | (g_request[:, None, :] <= 0),
        axis=2)  # [G, N]
    pods_ok = pod_count + 1 <= static.node_alloc_pods  # [N]
    mono = static.static_ok & static.node_exists[None, :] & fit & pods_ok[None, :]
    if static.use_ports:
        ports_bad = (ports_used[None, :, :]
                     & static.g_ports[:, None, :]).any(axis=2)  # [G, N]
        mono &= ~ports_bad
    if static.terms and dm is not None:
        # own required-anti terms violated by matching pods already in
        # the node's domain
        raa_bad = static.own_raa.astype(np.int32) @ (dm > 0).astype(np.int32) > 0
        mono &= ~raa_bad
    if static.terms and downer is not None:
        # placed owners' symmetric required-anti terms forbid their
        # domains for every matching signature (predicates.go:1146)
        sym = (static.term_matches_sig & static.is_raa[:, None]).astype(np.int32)
        mono &= ~(sym.T @ (downer > 0).astype(np.int32) > 0)
    return mono


def frontier_seed(static: BatchStatic, init: InitialState) -> np.ndarray:
    """Compute the step-0 MONOTONE feasibility plane [G, N] and seed
    ``init.still_ok`` with it; returns the G-union alive mask [N].

    A column False here for signature g can never become feasible for g
    within the segment: static_ok never changes, requested/pod_count/
    ports_used only grow (fit/pods/ports only get worse), and the
    required-anti-affinity hit (``dm > 0`` on an own-RAA term) is
    monotone because placements only add matching pods.  A column
    False for EVERY signature is therefore provably inert: every
    normalization, tie set, and n_feasible in the kernel ranges over
    feasible columns only, so dropping it is bit-exact."""
    # downer is omitted: it starts at zero (placed-owner symmetry cannot
    # have fired before the segment's first step)
    mono = monotone_plane(
        static, init.requested, init.pod_count, init.ports_used,
        dm=init.dm if static.terms and init.dm is not None else None)
    init.still_ok = mono
    return mono.any(axis=0)


def compact_segment(static: BatchStatic, init: InitialState,
                    js: np.ndarray, width: int
                    ) -> tuple[BatchStatic, InitialState]:
    """Host-side node-axis compaction (the tensorize-time prefilter's
    second half): keep columns ``js`` (full-axis order preserved — the
    round-robin tie-break walks the axis in order) padded to ``width``.
    ``node_names`` becomes the kept subset, so chosen indices map back
    through it and the backend's commit path needs no change.
    ``node_token`` is cleared: a compacted view must never alias a
    full-width DeviceNodeCache entry."""
    import dataclasses

    k = len(js)
    assert width >= k

    def take(arr, axis):
        pad = [(0, 0)] * arr.ndim
        pad[axis] = (0, width - k)
        return np.pad(np.take(arr, js, axis=axis), pad)

    s_fields = {f: take(getattr(static, f), ax)
                for f, ax in _STATIC_NODE_FIELDS.items()}
    s_fields["node_exists"][k:] = False
    cstatic = dataclasses.replace(
        static,
        # js past the named range are pre-existing pad columns (the name
        # list covers real nodes only); they keep node_exists False and
        # can never be chosen, so dropping their (nonexistent) names is
        # safe — chosen indices always land inside the named prefix
        node_names=[static.node_names[j] for j in js
                    if j < len(static.node_names)],
        n_pad=width,
        node_token=None,
        node_dirty=None,
        **s_fields,
    )
    i_fields = {f: take(getattr(init, f), ax)
                for f, ax in _INIT_NODE_FIELDS.items()
                if getattr(init, f) is not None}
    cinit = dataclasses.replace(init, **i_fields)
    return cstatic, cinit


def pad_segment_to_multiple(static: BatchStatic, init: InitialState,
                            multiple: int
                            ) -> tuple[BatchStatic, InitialState]:
    """Pad the node axis up to the next multiple of ``multiple`` (the
    sharded loop needs every shard to own an equal slice).  Identity when
    it already divides.  Padding rides ``compact_segment`` with the full
    identity column set, so the padded columns get ``node_exists`` /
    ``still_ok`` forced False — they are infeasible for every signature
    and can never surface as phantom feasible columns in any reduce."""
    n = int(static.n_pad)
    m = max(int(multiple), 1)
    if n % m == 0:
        return static, init
    width = -(-n // m) * m
    return compact_segment(static, init, np.arange(n), width)


class Tensorizer:
    def __init__(
        self,
        pad_multiple: int = 128,
        max_groups: int = 512,
        max_terms: int = 128,
        max_vols: int = 1024,
        vols_per_pod: int = 8,
        group_multiple: int = 32,
        term_multiple: int = 4,
        vol_multiple: int = 32,
        port_multiple: int = 8,
        sticky_buckets: bool = True,
        persistent_rows: bool = True,
    ):
        # Every shape-determining axis is padded to a bucket multiple so XLA
        # compiles ONE kernel per bucket combination instead of one per
        # batch (SURVEY.md §7.4 hard part 2: dynamic shapes vs static XLA).
        # The term/vol multiples are deliberately TIGHT (padded [T, N] /
        # [V, N] rows cost real per-step device time — ~25us/pod per padded
        # term row at N=5120); sticky_buckets below keeps the tight pads
        # from turning into per-wave recompiles.
        self.pad_multiple = pad_multiple
        self.max_groups = max_groups
        self.max_terms = max_terms
        self.max_vols = max_vols
        self.vols_per_pod = vols_per_pod
        self.group_multiple = group_multiple
        self.term_multiple = term_multiple
        self.vol_multiple = vol_multiple
        self.port_multiple = port_multiple
        # Sticky shape buckets: each padded axis remembers its high-water
        # bucket and never shrinks, so successive steady-state waves reuse
        # the compiled kernel for their shape instead of recompiling when a
        # wave's natural bucket wobbles (e.g. the volume vocab crossing a
        # pad boundary mid-run cost a multi-second XLA recompile on the
        # timed path).  Padding UP is always semantically inert.
        self.sticky_buckets = sticky_buckets
        self._sticky: dict[str, int] = {}
        # resource slots seen requested so far (sticky union: the device
        # [.., R'] shapes must never shrink mid-run); cpu/mem always in
        self._r_sticky: set[int] = {CPU_MILLI, MEM_MIB}
        # Cross-wave node-static row cache (see NodeStaticRows).
        self.persistent_rows = persistent_rows
        self._node_rows: Optional[NodeStaticRows] = None
        # Overload ladder rung 1 (ISSUE 17): a live multiplier on every
        # bucket multiple.  Coarser buckets mean fewer distinct compiled
        # shapes while a surge churns the axis sizes; padding UP is
        # semantically inert, and the sticky high-water discipline means
        # scaling back to 1 never shrinks a shape mid-run.
        self.bucket_scale = 1

    def _bucket(self, axis: str, n: int, multiple: int) -> int:
        return self._sticky_pad(axis, _pad_to(n, multiple * max(1, int(self.bucket_scale))))

    def _sticky_pad(self, axis: str, pad: int) -> int:
        """One high-water discipline for every axis — including the vols
        axis, whose natural pad has its own empty-vocab floor."""
        if not self.sticky_buckets:
            return pad
        pad = max(pad, self._sticky.get(axis, 0))
        self._sticky[axis] = pad
        return pad

    @property
    def node_rows_stats(self) -> Optional[dict]:
        return self._node_rows.stats if self._node_rows is not None else None

    # -- static ------------------------------------------------------------
    def build_static(
        self,
        pods: list[api.Pod],
        node_info_map: dict[str, NodeInfo],
        pctx: PriorityContext,
        least_requested_weight: int = 0,
        most_requested_weight: int = 0,
        balanced_weight: int = 1,
        spread_weight: int = 1,
        node_affinity_weight: int = 1,
        taint_weight: int = 1,
        prefer_avoid_weight: int = 10000,
        image_weight: int = 0,
        interpod_weight: int = 1,
        mounted_disks: Optional[set] = None,
    ) -> Optional[BatchStatic]:
        node_names = sorted(n for n, i in node_info_map.items() if i.node is not None)
        n_real = len(node_names)
        if n_real == 0 or not pods:
            return None
        n_pad = _pad_to(n_real, self.pad_multiple)  # device: static — pad_multiple buckets the node axis at build time
        infos = [node_info_map[n] for n in node_names]

        # signatures
        sig_to_gid: dict[str, int] = {}
        group_of_pod = np.empty(len(pods), dtype=np.int32)
        reps: list[api.Pod] = []  # representative pod per group
        for i, pod in enumerate(pods):
            key = pod_signature_key(pod)
            gid = sig_to_gid.get(key)
            if gid is None:
                gid = len(reps)
                if gid >= self.max_groups:
                    return None  # caller falls back to oracle for this segment
                sig_to_gid[key] = gid
                reps.append(pod)
            group_of_pod[i] = gid
        G = len(reps)

        # cheap tensor-budget probes BEFORE the expensive [G, N] loops: the
        # backend's split fallback re-tensorizes each piece, so an
        # over-budget segment must be rejected for near-free.
        #
        # Only CONFLICT-CAPABLE disks need identity rows in the [V, N]
        # occupancy state: a disk referenced by exactly one pod in the
        # segment and mounted nowhere can never trip NoDiskConflict — by
        # the time a later segment references it again it is mounted and
        # re-enters the vocab there.  Everything else becomes a
        # "count-only" slot (MaxVolumeCount still sees it; see phase B).
        n_terms = sum(count_affinity_terms(rep) for rep in reps)
        if mounted_disks is None:
            mounted_disks = set()
            for info in infos:
                for q in info.pods:
                    mounted_disks |= pod_disk_vols(q)
        seen_once: set[tuple[str, str]] = set()
        conflict_vols: set[tuple[str, str]] = set()
        w_used = 0  # max distinct disks any ONE pod carries (slot axis)
        for pod in pods:
            per_pod = pod_disk_vols(pod)
            if len(per_pod) > self.vols_per_pod:
                return None  # caller falls back to oracle for this pod
            if len(per_pod) > w_used:
                w_used = len(per_pod)
            for d in per_pod:
                if d in mounted_disks or d in seen_once:
                    conflict_vols.add(d)
                else:
                    seen_once.add(d)
        if n_terms > self.max_terms or len(conflict_vols) > self.max_vols:
            return None

        # node-side basics
        node_exists = np.zeros(n_pad, dtype=bool)
        node_exists[:n_real] = True
        node_alloc = np.zeros((n_pad, NUM_RESOURCES), dtype=np.int32)
        node_alloc_pods = np.zeros(n_pad, dtype=np.int32)
        zone_vocab: dict[str, int] = {}
        node_zone = np.full(n_pad, -1, dtype=np.int32)
        for j, info in enumerate(infos):
            node_alloc[j] = info.allocatable.units
            node_alloc_pods[j] = info.allocatable_pods
            zk = _zone_key(info.node)
            if zk:
                if zk not in zone_vocab:
                    zone_vocab[zk] = len(zone_vocab)
                node_zone[j] = zone_vocab[zk]
        num_zones = max(len(zone_vocab), 1)

        # port vocab over the batch
        port_vocab: dict[tuple[str, int], int] = {}
        for rep in reps:
            for port in rep.host_ports():
                if port not in port_vocab:
                    port_vocab[port] = len(port_vocab)
        pv = self._bucket("ports", len(port_vocab), self.port_multiple)
        g_ports = np.zeros((G, pv), dtype=bool)
        for g, rep in enumerate(reps):
            for port in rep.host_ports():
                g_ports[g, port_vocab[port]] = True

        # per-signature resources
        g_request = np.zeros((G, NUM_RESOURCES), dtype=np.int32)
        g_nonzero = np.zeros((G, 2), dtype=np.int32)
        for g, rep in enumerate(reps):
            g_request[g] = pod_request_vec(rep).units
            nz = pod_nonzero_request_vec(rep)
            g_nonzero[g, 0] = nz[CPU_MILLI]
            g_nonzero[g, 1] = nz[MEM_MIB]
        # resource-axis selection: slots no signature requests are inert
        # in the kernel step (masked True in fit, zero in the commit) —
        # the device upload carries only the used ones.  cpu/mem stay at
        # positions 0/1 (sorted; both always present) for the scoring
        # formulas' positional reads.
        r_used = {CPU_MILLI, MEM_MIB}
        for r in range(NUM_RESOURCES):
            if g_request[:, r].any():
                r_used.add(r)
        if self.sticky_buckets:
            self._r_sticky |= r_used
            r_used = set(self._r_sticky)
        r_sel = (None if len(r_used) == NUM_RESOURCES
                 else np.array(sorted(r_used), dtype=np.int64))

        # static per-(signature, node) masks & raw scores.  Signatures that
        # differ only in resources/ports/pod-labels interact with every
        # node IDENTICALLY, so the expensive per-node sweep is deduped by
        # the signature's node-interaction identity (node_name, selector,
        # node affinity, tolerations, QoS, controller ref, images): at
        # north scale ~512 signatures × 5k nodes collapses from 2.5M
        # Python iterations per segment to a handful of [N] sweeps —
        # the dominant host cost of build_static (r4 profile).  The sweep
        # itself lives in _node_static_cols; with persistent_rows the rows
        # additionally survive ACROSS segments and waves in NodeStaticRows,
        # invalidated per dirty node column.
        static_ok = np.zeros((G, n_pad), dtype=bool)
        node_aff_raw = np.zeros((G, n_pad), dtype=np.int32)
        taint_intol_raw = np.zeros((G, n_pad), dtype=np.int32)
        static_score = np.zeros((G, n_pad), dtype=np.int32)
        row_cache: dict[tuple, tuple] = {}
        # the controller ref only influences the sweep when some node's
        # prefer-avoid annotation NAMES its uid — precompute that uid set
        # once so unannotated clusters dedupe across controllers (keying
        # on every distinct ReplicaSet uid would fragment the cache)
        avoided_uids: set[str] = set()
        if prefer_avoid_weight:
            for info in infos:
                ann = info.node.meta.annotations.get(PREFER_AVOID_PODS_ANNOTATION, "")
                avoided_uids.update(u.strip() for u in ann.split(",") if u.strip())

        # cross-wave persistent rows: validate the cache against the node
        # axis and eagerly refresh dirty columns of every cached entry
        # (each entry recomputes with its interaction CLASS's keyed ref)
        rows_cache: Optional[NodeStaticRows] = None
        node_token = node_dirty = None
        if not _DISABLE_ROW_CACHE and self.persistent_rows:
            if self._node_rows is None:
                self._node_rows = NodeStaticRows()
            rows_cache = self._node_rows

            def _refresh(entry, js):
                e_rep, e_be, e_ref, e_images, e_rows = entry
                _node_static_cols(e_rep, infos, js, e_be, e_ref, e_images,
                                  prefer_avoid_weight, image_weight, *e_rows)

            rows_cache.sync(node_names, infos,
                            (prefer_avoid_weight, image_weight), _refresh)
            node_token = rows_cache.token()
            node_dirty = list(rows_cache.last_dirty)

        all_js = range(n_real)
        for g, rep in enumerate(reps):
            is_best_effort = rep.qos_class() == api.BEST_EFFORT
            ref = rep.meta.controller_ref()
            images = {c.image for c in rep.spec.containers if c.image}
            aff = rep.spec.affinity
            # the keyed ref: None unless some node's prefer-avoid
            # annotation names this controller (see _node_static_cols)
            keyed_ref = (ref if ref is not None and ref.uid in avoided_uids
                         else None)
            interaction_key = None
            if not _DISABLE_ROW_CACHE:
                interaction_key = (
                    rep.spec.node_name,
                    tuple(sorted(rep.spec.node_selector.items()))
                    if rep.spec.node_selector else (),
                    repr(aff.node_affinity_required) if aff is not None else "",
                    repr(aff.node_affinity_preferred) if aff is not None else "",
                    tuple(sorted(repr(t) for t in rep.spec.tolerations)),
                    is_best_effort,
                    (keyed_ref.kind, keyed_ref.uid) if keyed_ref is not None else None,
                    tuple(sorted(images)) if image_weight else (),
                )
                cached = (rows_cache.get(interaction_key)
                          if rows_cache is not None
                          else row_cache.get(interaction_key))
                if cached is not None:
                    static_ok[g] = cached[0]
                    node_aff_raw[g] = cached[1]
                    taint_intol_raw[g] = cached[2]
                    static_score[g] = cached[3]
                    continue
            rows = (np.zeros(n_pad, dtype=bool), np.zeros(n_pad, dtype=np.int32),
                    np.zeros(n_pad, dtype=np.int32), np.zeros(n_pad, dtype=np.int32))
            _node_static_cols(rep, infos, all_js, is_best_effort, keyed_ref,
                              images, prefer_avoid_weight, image_weight, *rows)
            static_ok[g] = rows[0]
            node_aff_raw[g] = rows[1]
            taint_intol_raw[g] = rows[2]
            static_score[g] = rows[3]
            if interaction_key is not None:
                if rows_cache is not None:
                    # the cache owns the row arrays: dirty-column syncs
                    # update them in place, later gets return them directly
                    rows_cache.put(interaction_key, rep, is_best_effort,
                                   keyed_ref, images, rows)
                else:
                    row_cache[interaction_key] = rows

        # inter-pod affinity interactions with EXISTING pods.  Phase-A batch
        # pods have no (anti)affinity terms of their own, but existing pods'
        # terms still act on them (the symmetry rules):
        #  - required anti-affinity of an existing pod matching the incoming
        #    pod FORBIDS its topology domain (predicates.go:1146) -> static_ok;
        #  - required/preferred affinity (+ preferred anti) of existing pods
        #    matching the incoming pod contribute interpod priority weight
        #    (interpod_affinity.go:160-186) -> interpod_raw.
        interpod_raw = np.zeros((G, n_pad), dtype=np.int32)
        # Existing pods' (anti)affinity terms, grouped by scheduling
        # signature: _pod_matches_term depends only on (candidate,
        # owner namespace, term) — identical for every pod of a
        # signature — so a template-stamped fleet collapses thousands of
        # per-pod matcher calls per segment into one per (rep, group,
        # term), with per-node instance COUNTS scaling the weights.
        # Contributions are bitwise identical (weights are additive).
        aff_groups: dict = {}  # sig -> [q_rep, {node_name|None: [qinfo, count]}]
        for qinfo in node_info_map.values():
            for q in qinfo.pods_with_affinity:
                sig = pod_signature_key(q)
                entry = aff_groups.get(sig)
                if entry is None:
                    entry = aff_groups[sig] = [q, {}]
                nkey = qinfo.node.meta.name if qinfo.node is not None else None
                loc = entry[1].get(nkey)
                if loc is None:
                    entry[1][nkey] = [qinfo, 1]
                else:
                    loc[1] += 1
        if aff_groups:
            # (topology key, value) -> weight accumulations per signature
            for g, rep in enumerate(reps):
                topo_weights: dict[tuple[str, str], int] = {}
                forbidden: list[tuple[str, str]] = []  # (key, value) domains

                def _add(node: Optional[api.Node], key: str, weight: int) -> None:
                    if node is None or not key:
                        return
                    value = node.meta.labels.get(key)
                    if value is None:
                        return
                    topo_weights[(key, value)] = topo_weights.get((key, value), 0) + weight

                for q_rep, locs in aff_groups.values():
                    qaff = q_rep.spec.affinity
                    for term in qaff.pod_anti_affinity_required:
                        if _pod_matches_term(rep, q_rep, term):
                            for qinfo, _cnt in locs.values():
                                qnode = qinfo.node
                                if qnode is not None and term.topology_key:
                                    value = qnode.meta.labels.get(term.topology_key)
                                    if value is not None:
                                        forbidden.append((term.topology_key, value))
                                else:
                                    forbidden.append(("", ""))  # malformed term: always blocks
                    if pctx.hard_pod_affinity_weight > 0:
                        for term in qaff.pod_affinity_required:
                            if _pod_matches_term(rep, q_rep, term):
                                for qinfo, cnt in locs.values():
                                    _add(qinfo.node, term.topology_key,
                                         pctx.hard_pod_affinity_weight * cnt)
                    for wt in qaff.pod_affinity_preferred:
                        if _pod_matches_term(rep, q_rep, wt.term):
                            for qinfo, cnt in locs.values():
                                _add(qinfo.node, wt.term.topology_key,
                                     wt.weight * cnt)
                    for wt in qaff.pod_anti_affinity_preferred:
                        if _pod_matches_term(rep, q_rep, wt.term):
                            for qinfo, cnt in locs.values():
                                _add(qinfo.node, wt.term.topology_key,
                                     -wt.weight * cnt)

                if topo_weights or forbidden:
                    # group by topology KEY before the node sweep: a node
                    # matches at most one value per key, so the sweep is
                    # one label get per key — the pairwise loop was
                    # O(placed-owners x N) under required-anti-affinity
                    # fan-out (one forbidden entry per placed owner) and
                    # dominated steady-state build_static
                    w_by_key: dict[str, dict[str, int]] = {}
                    for (key, value), w in topo_weights.items():
                        w_by_key.setdefault(key, {})[value] = w
                    forb_by_key: dict[str, set] = {}
                    always_block = False
                    for key, value in forbidden:
                        if not key:
                            always_block = True  # malformed term: blocks all
                        else:
                            forb_by_key.setdefault(key, set()).add(value)
                    if always_block:
                        static_ok[g, :] = False
                    for j, info in enumerate(infos):
                        labels = info.node.meta.labels
                        total = 0
                        for key, vmap in w_by_key.items():
                            w = vmap.get(labels.get(key))
                            if w:
                                total += w
                        interpod_raw[g, j] = total
                        if static_ok[g, j]:
                            for key, vals in forb_by_key.items():
                                if labels.get(key) in vals:
                                    static_ok[g, j] = False
                                    break

        # -- phase B: the batch's own (anti)affinity terms ------------------
        # Flatten every term carried by a signature into one table; empty
        # topology keys on REQUIRED terms make the owner statically
        # infeasible everywhere (predicates.go:1181 "empty topologyKey is
        # not allowed"), and soft terms with empty keys never contribute
        # (interpod_affinity.go add() skips them) so both drop from the
        # table after marking.
        terms: list[_AffinityTerm] = []
        hard_w = pctx.hard_pod_affinity_weight
        for g, rep in enumerate(reps):
            a = rep.spec.affinity
            if a is None:
                continue
            for t in a.pod_affinity_required:
                if not t.topology_key:
                    static_ok[g, :] = False
                    continue
                terms.append(_AffinityTerm(g, "RA", hard_w, t))
            for t in a.pod_anti_affinity_required:
                if not t.topology_key:
                    static_ok[g, :] = False
                    continue
                terms.append(_AffinityTerm(g, "RAA", 0, t))
            for wt in a.pod_affinity_preferred:
                if wt.term.topology_key:
                    terms.append(_AffinityTerm(g, "PA", wt.weight, wt.term))
            for wt in a.pod_anti_affinity_preferred:
                if wt.term.topology_key:
                    terms.append(_AffinityTerm(g, "PAA", -wt.weight, wt.term))
        T = self._bucket("terms", len(terms), self.term_multiple)  # padded rows stay inert

        term_matches_sig = np.zeros((T, G), dtype=bool)
        sym_w = np.zeros(T, dtype=np.int32)
        own_w = np.zeros((G, T), dtype=np.int32)
        own_ra = np.zeros((G, T), dtype=bool)
        own_raa = np.zeros((G, T), dtype=bool)
        own_all = np.zeros((G, T), dtype=bool)
        is_raa = np.zeros(T, dtype=bool)
        self_match = np.zeros(T, dtype=bool)
        for t, at in enumerate(terms):
            owner_rep = reps[at.owner]
            own_all[at.owner, t] = True
            for g, rep in enumerate(reps):
                term_matches_sig[t, g] = _pod_matches_term(rep, owner_rep, at.term)
            self_match[t] = term_matches_sig[t, at.owner]
            if at.kind == "RA":
                own_ra[at.owner, t] = True
                sym_w[t] = at.weight
            elif at.kind == "RAA":
                own_raa[at.owner, t] = True
                is_raa[t] = True
            else:  # PA / PAA soft terms
                own_w[at.owner, t] = at.weight
                sym_w[t] = at.weight

        # topology domains: per distinct key, enumerate label values over the
        # node axis once; each term gets its own global domain-id range so
        # the flat [D+1] count arrays stay per-term (last slot = trash for
        # nodes missing the key — never read unmasked)
        key_vals: dict[str, tuple[np.ndarray, int]] = {}
        for at in terms:
            key = at.term.topology_key
            if key in key_vals:
                continue
            vocab: dict[str, int] = {}
            arr = np.full(n_pad, -1, dtype=np.int32)
            for j, info in enumerate(infos):
                v = info.node.meta.labels.get(key)
                if v is not None:
                    arr[j] = vocab.setdefault(v, len(vocab))
            key_vals[key] = (arr, len(vocab))
        node_domain = np.zeros((T, n_pad), dtype=np.int32)
        dom_valid = np.zeros((T, n_pad), dtype=bool)
        offset = 0
        for t, at in enumerate(terms):
            arr, count = key_vals[at.term.topology_key]
            dom_valid[t] = arr >= 0
            node_domain[t] = np.where(arr >= 0, offset + arr, 0)  # trash fixed below
            offset += count
        trash = offset
        node_domain[~dom_valid] = trash
        if not terms:
            dom_valid[:] = False
            node_domain[:] = trash

        # -- phase B: volumes (per-pod slot lists) --------------------------
        # Volume identity lives on the pod axis, not the signature axis:
        # each pod gets <= W slots pointing into the [V, N] occupancy arrays.
        K = len(_VOL_KINDS)
        # volume-SLOT axis tightening: size the per-pod slot axis to the
        # segment's real maximum (power-of-two, sticky so the compiled
        # [W, N] shapes never shrink mid-run) instead of the worst-case
        # vols_per_pod.  Slots past a pod's real disks are invalid on
        # every pod, so the kernel's per-step [W, N] gathers and the
        # commit scatter shrink with zero semantic change (vols_per_pod
        # stays the segmentation budget bound).
        w_nat = 1
        while w_nat < max(w_used, 1):
            w_nat *= 2
        W = max(min(self._sticky_pad("volslots", w_nat), self.vols_per_pod),
                w_used)
        P = len(pods)
        vol_vocab: dict[tuple[str, str], int] = {}
        pod_vol_ids = np.zeros((P, W), dtype=np.int32)
        pod_vol_valid = np.zeros((P, W), dtype=bool)
        pod_vol_ro_ok = np.zeros((P, W), dtype=bool)
        pod_vol_kind = np.zeros((P, W), dtype=np.int32)
        any_count_only = False
        for i, pod in enumerate(pods):
            vol_refs = _disk_refs(pod)  # raw-first: no [P]-wide spec decode
            if not vol_refs:
                continue
            per_pod: dict[tuple[str, str], bool] = {}  # all-refs-read-only
            for kind, disk_id, read_only in vol_refs:
                key = (kind, disk_id)
                per_pod[key] = per_pod.get(key, True) and read_only
            for s, (key, all_ro) in enumerate(per_pod.items()):
                if key in conflict_vols:
                    v = vol_vocab.setdefault(key, len(vol_vocab))
                    pod_vol_ids[i, s] = v
                else:
                    # count-only: no conflict identity — reads the
                    # always-empty sentinel row (never blocked, always
                    # "new" for MaxVolumeCount) and is excluded from the
                    # occupancy write (kernel masks it out)
                    pod_vol_ids[i, s] = -1  # fixed up to sentinel below
                    any_count_only = True
                pod_vol_valid[i, s] = True
                pod_vol_ro_ok[i, s] = all_ro and key[0] in _READONLY_SHARED_KINDS
                pod_vol_kind[i, s] = (
                    _VOL_KINDS.index(key[0]) if key[0] in VOLUME_COUNT_LIMITS else K
                )
        # volume-less segments keep a tiny (never-touched) state footprint;
        # the kernel's use_vols flag skips the volume logic entirely.
        # The vocab holds conflict-capable disks only, so its bucketed pad
        # is small and stable across random workload mixes — shape-bucket
        # stability is what lets one warm-up compile cover every segment.
        use_vols = bool(vol_vocab) or any_count_only
        v_state = self._sticky_pad(
            "vols",
            8 if not vol_vocab else _pad_to(len(vol_vocab) + 1, self.vol_multiple))
        pod_vol_count_only = pod_vol_valid & (pod_vol_ids < 0)
        pod_vol_ids[~pod_vol_valid | pod_vol_count_only] = v_state - 1  # sentinel row
        vol_limits = np.array([VOLUME_COUNT_LIMITS[k] for k in _VOL_KINDS], dtype=np.int32)

        # PVC-backed volumes: zone / PV-node-affinity constraints are static
        # per (signature, node) — PVC↔PV bindings do not change mid-batch —
        # so they fold into static_ok (oracle: no_volume_zone_conflict /
        # no_volume_node_conflict, predicates.go:402,1323)
        # kernel: implements NoVolumeZoneConflict, NoVolumeNodeConflict
        for g, rep in enumerate(reps):
            pvc_vols = [v for v in rep.spec.volumes if v.pvc_name]
            if not pvc_vols:
                continue
            pv_zones: list[str] = []
            pv_sels: list = []
            unresolved = False
            for vol in pvc_vols:
                pvc = pctx.pvcs.get(f"{rep.meta.namespace}/{vol.pvc_name}")
                pv = pctx.pvs.get(pvc.volume_name) if pvc is not None and pvc.volume_name else None
                if pv is None:
                    unresolved = True
                    break
                if pv.zone:
                    pv_zones.append(pv.zone)
                if pv.node_affinity is not None:
                    pv_sels.append(pv.node_affinity)
            if unresolved:
                static_ok[g, :] = False
                continue
            if pv_zones or pv_sels:
                for j, info in enumerate(infos):
                    if not static_ok[g, j]:
                        continue
                    labels = info.node.meta.labels
                    node_zone_label = labels.get(api.ZONE_LABEL, "")
                    if any(z != node_zone_label for z in pv_zones):
                        static_ok[g, j] = False
                        continue
                    if any(not sel.matches(labels) for sel in pv_sels):
                        static_ok[g, j] = False

        # spreading: selectors per signature; inc matrix between signatures
        ssp = SelectorSpreadPriority()
        g_selectors = [ssp._selectors_for_pod(rep, pctx) for rep in reps]
        g_has_spread = np.array([len(s) > 0 for s in g_selectors], dtype=bool)
        spread_inc = np.zeros((G, G), dtype=np.int32)
        for g in range(G):
            if not g_has_spread[g]:
                continue
            for h in range(G):
                if reps[h].meta.namespace != reps[g].meta.namespace:
                    continue
                if ssp._matches_any(g_selectors[g], reps[h]):
                    spread_inc[g, h] = 1

        # -- bucket-pad the signature axis ----------------------------------
        # Padded rows are never referenced (group_of_pod < G) but keep the
        # compiled kernel's shapes stable across batches.
        Gp = self._bucket("groups", G, self.group_multiple)
        if Gp != G:
            pad_g = Gp - G
            static_ok = np.pad(static_ok, ((0, pad_g), (0, 0)))
            node_aff_raw = np.pad(node_aff_raw, ((0, pad_g), (0, 0)))
            taint_intol_raw = np.pad(taint_intol_raw, ((0, pad_g), (0, 0)))
            static_score = np.pad(static_score, ((0, pad_g), (0, 0)))
            interpod_raw = np.pad(interpod_raw, ((0, pad_g), (0, 0)))
            g_request = np.pad(g_request, ((0, pad_g), (0, 0)))
            g_nonzero = np.pad(g_nonzero, ((0, pad_g), (0, 0)))
            g_ports = np.pad(g_ports, ((0, pad_g), (0, 0)))
            g_has_spread = np.pad(g_has_spread, (0, pad_g))
            spread_inc = np.pad(spread_inc, ((0, pad_g), (0, pad_g)))
            term_matches_sig = np.pad(term_matches_sig, ((0, 0), (0, pad_g)))
            own_w = np.pad(own_w, ((0, pad_g), (0, 0)))
            own_ra = np.pad(own_ra, ((0, pad_g), (0, 0)))
            own_raa = np.pad(own_raa, ((0, pad_g), (0, 0)))
            own_all = np.pad(own_all, ((0, pad_g), (0, 0)))

        return BatchStatic(
            node_names=node_names,
            n_pad=n_pad,
            node_exists=node_exists,
            node_alloc=node_alloc,
            node_alloc_pods=node_alloc_pods,
            node_zone=node_zone,
            num_zones=num_zones,
            group_of_pod=group_of_pod,
            pod_names=[p.meta.key for p in pods],
            static_ok=static_ok,
            node_aff_raw=node_aff_raw,
            taint_intol_raw=taint_intol_raw,
            static_score=static_score,
            g_request=g_request,
            g_nonzero=g_nonzero,
            g_ports=g_ports,
            port_vocab=list(port_vocab),
            g_has_spread=g_has_spread,
            spread_inc=spread_inc,
            interpod_raw=interpod_raw,
            terms=terms,
            term_matches_sig=term_matches_sig,
            sym_w=sym_w,
            own_w=own_w,
            own_ra=own_ra,
            own_raa=own_raa,
            own_all=own_all,
            is_raa=is_raa,
            self_match=self_match,
            node_domain=node_domain,
            dom_valid=dom_valid,
            vol_vocab=list(vol_vocab),
            v_state=v_state,
            pod_vol_ids=pod_vol_ids,
            pod_vol_valid=pod_vol_valid,
            pod_vol_ro_ok=pod_vol_ro_ok,
            pod_vol_kind=pod_vol_kind,
            pod_vol_count_only=pod_vol_count_only,
            use_vols=use_vols,
            vol_limits=vol_limits,
            node_token=node_token,
            node_dirty=node_dirty,
            use_ports=bool(port_vocab),
            r_sel=r_sel,
            weights={
                "least": least_requested_weight,
                "most": most_requested_weight,
                "balanced": balanced_weight,
                "spread": spread_weight,
                "node_affinity": node_affinity_weight,
                "taint": taint_weight,
                "interpod": interpod_weight,
            },
        )

    # -- dynamic state -----------------------------------------------------
    def initial_state(
        self,
        static: BatchStatic,
        node_info_map: dict[str, NodeInfo],
        pctx: PriorityContext,
        pods: list[api.Pod],
        round_robin: int = 0,
        host_state: Optional[HostBatchState] = None,
    ) -> InitialState:
        n_pad = static.n_pad
        G = static.static_ok.shape[0]
        requested = np.zeros((n_pad, NUM_RESOURCES), dtype=np.int32)
        nonzero = np.zeros((n_pad, 2), dtype=np.int32)
        pod_count = np.zeros(n_pad, dtype=np.int32)
        ports_used = np.zeros((n_pad, static.g_ports.shape[1]), dtype=bool)
        port_idx = {p: i for i, p in enumerate(static.port_vocab)}
        spread_counts = np.zeros((G, n_pad), dtype=np.int32)

        ssp = SelectorSpreadPriority()
        # representative pod per group for selector extraction
        reps: dict[int, api.Pod] = {}
        for i, gid in enumerate(static.group_of_pod):
            reps.setdefault(int(gid), pods[i])
        g_selectors = {g: ssp._selectors_for_pod(rep, pctx) for g, rep in reps.items()}

        for j, name in enumerate(static.node_names):
            info = node_info_map[name]
            requested[j] = info.requested.units
            nonzero[j, 0] = info.nonzero_requested[CPU_MILLI]
            nonzero[j, 1] = info.nonzero_requested[MEM_MIB]
            pod_count[j] = len(info.pods)
            for port in info.used_ports:
                if port in port_idx:
                    ports_used[j, port_idx[port]] = True

        # existing matching-pod counts per spread group and per affinity
        # term (zone sums are recomputed in-step from these, over the
        # feasible mask).  This is (groups + terms) x existing-pods selector
        # matching — tens of millions of probes on a loaded 150k-pod cluster
        # — so it runs in the native engine (csrc/labelmatch.cpp); namespace
        # scoping rides along as a reserved pseudo-label.
        groups_with_sels = {g: sels for g, sels in g_selectors.items() if sels}
        T = static.term_matches_sig.shape[0]
        # per-term flat domain counts, expanded to [T, N] after the fill
        # (trash id = node_domain.max() where the key is absent — its counts
        # vanish in the expansion because dom_valid masks them)
        n_dom = int(static.node_domain.max()) + 1 if static.terms else 1
        dom_match = np.zeros(n_dom, dtype=np.int32)
        total_match = np.zeros(T, dtype=np.int32)
        matchable_terms = [
            (t, at) for t, at in enumerate(static.terms) if at.term.selector is not None
        ]
        if groups_with_sels or matchable_terms:
            # the engine + labelmap corpus: batch-persistent when a
            # HostBatchState is supplied (selectors are per-segment either
            # way); scratch-built and torn down otherwise
            if host_state is not None:
                eng = host_state.eng
                add_selector = host_state.selector_id
            else:
                eng = MatchEngine()
                add_selector = eng.add_selector
            NS_KEY = _NS_KEY
            sel_ids: dict[int, list[int]] = {}
            for g, sels in groups_with_sels.items():
                ns_req = (NS_KEY, "Eq", [reps[g].meta.namespace])
                ids = []
                for kind, sel in sels:
                    if kind == "simple":
                        reqs = [ns_req] + [(k, "Eq", [str(v)]) for k, v in sel.items()]
                    else:
                        reqs = (
                            [ns_req]
                            + [(k, "Eq", [str(v)]) for k, v in sel.match_labels.items()]
                            + [(r.key, r.operator, list(r.values)) for r in sel.match_expressions]
                        )
                    ids.append(add_selector(reqs))
                sel_ids[g] = ids
            # one selector per affinity term: namespace-scope ∈ term
            # namespaces (empty → owner's namespace) AND the term selector
            term_sids: list[int] = []
            for t, at in matchable_terms:
                namespaces = at.term.namespaces or [reps[at.owner].meta.namespace]
                sel = at.term.selector
                reqs = (
                    [(NS_KEY, "In", [str(n) for n in namespaces])]
                    + [(k, "Eq", [str(v)]) for k, v in sel.match_labels.items()]
                    + [(r.key, r.operator, list(r.values)) for r in sel.match_expressions]
                )
                term_sids.append(add_selector(reqs))
            if host_state is not None:
                pod_lids = host_state.pod_lids
                node_j = host_state.node_j_array()
            else:
                pod_lids = []
                pod_node_j: list[int] = []
                for j, name in enumerate(static.node_names):
                    for q in node_info_map[name].pods:
                        pod_lids.append(
                            eng.add_labelmap({**q.meta.labels, NS_KEY: q.meta.namespace})
                        )
                        pod_node_j.append(j)
                node_j = np.asarray(pod_node_j, dtype=np.int64)
            if pod_lids:
                # content-interned lids repeat heavily (template-stamped
                # pods share one labelmap), so match each DISTINCT lid
                # once and broadcast: native probes go from O(L × sels)
                # to O(distinct × sels) + numpy O(L)
                lids_arr = np.asarray(pod_lids, dtype=np.int64)
                uniq, inverse = np.unique(lids_arr, return_inverse=True)
                for g, ids in sel_ids.items():
                    hits = eng.match_any(ids, uniq)[inverse]
                    np.add.at(spread_counts[g], node_j[hits], 1)
                if matchable_terms:
                    tm = eng.match_matrix(term_sids, uniq)  # [T_real, U]
                    for row, (t, _at) in enumerate(matchable_terms):
                        hits = tm[row][inverse]
                        total_match[t] = int(hits.sum())
                        np.add.at(dom_match, static.node_domain[t, node_j[hits]], 1)
            if host_state is None:
                eng.close()
        dm = (dom_match[static.node_domain] * static.dom_valid).astype(np.int32)

        # volume occupancy from existing pods: instance presence and
        # non-sharable presence per batch-vocab volume, plus distinct
        # limited-kind disk counts per node (NoDiskConflict /
        # MaxVolumeCount dynamic state)
        V = static.v_state
        K = len(_VOL_KINDS)
        vol_any = np.zeros((V, n_pad), dtype=bool)
        vol_ns = np.zeros((V, n_pad), dtype=bool)
        nk = np.zeros((K, n_pad), dtype=np.int32)
        if host_state is not None:
            # O(vocab): the disk-location dicts already aggregate the world
            for v, key in enumerate(static.vol_vocab):
                for j, rc in host_state.disk_locations.get(key, {}).items():
                    vol_any[v, j] = True
                    if rc[1] > 0:
                        vol_ns[v, j] = True
            nk[:, : host_state.nk_counts.shape[1]] = host_state.nk_counts
        else:
            vol_idx = {key: v for v, key in enumerate(static.vol_vocab)}
            kind_pos = {k: i for i, k in enumerate(_VOL_KINDS)}
            for j, name in enumerate(static.node_names):
                seen: dict[str, set] = {}
                for q in node_info_map[name].pods:
                    if not q.spec.volumes:
                        continue
                    for vol in q.spec.volumes:
                        if not vol.disk_id:
                            continue
                        if vol.disk_kind in kind_pos:
                            seen.setdefault(vol.disk_kind, set()).add(vol.disk_id)
                        v = vol_idx.get((vol.disk_kind, vol.disk_id))
                        if v is not None:
                            vol_any[v, j] = True
                            if not (vol.disk_kind in _READONLY_SHARED_KINDS and vol.read_only):
                                vol_ns[v, j] = True
                for kind, ids in seen.items():
                    nk[kind_pos[kind], j] = len(ids)

        return InitialState(
            requested=requested,
            nonzero_requested=nonzero,
            pod_count=pod_count,
            ports_used=ports_used,
            spread_counts=spread_counts,
            round_robin=round_robin,
            dm=dm,
            downer=np.zeros((T, n_pad), dtype=np.int32),
            total_match=total_match,
            vol_any=vol_any,
            vol_ns=vol_ns,
            nk=nk,
        )
