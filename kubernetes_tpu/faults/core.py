"""Deterministic fault injection: named fault points + seeded plans.

Production AI-cluster schedulers treat failure handling as a first-class
scheduling concern (Kant, arXiv:2510.01256; Tesserae, arXiv:2508.04953):
a control plane that has never *seen* a bind conflict, a torn WAL tail,
or a watch-stream gap will mishandle the first real one.  This module
makes every such failure a named, seeded, repeatable event:

- a process-wide **registry** of :class:`FaultPoint` names — the
  catalogue of places the codebase has agreed a failure can be injected
  (``store.wal.append``, ``remote.request``, ``scheduler.bind``, …);
- instrumented sites call :func:`hit` with the point name.  Disarmed
  (the default, and the only production state) this is one module-global
  load and a ``None`` check — no allocation, no locking, no branching on
  policy;
- a :class:`FaultPlan` (seeded RNG + per-point :class:`FaultSpec`
  policies) armed via ``with plan.armed():`` makes selected hits
  misbehave: raise an error, sleep, tear a write, or drop an item —
  deterministically, so a failing chaos run replays exactly.

The reference's e2e suite injects failures from the *outside* (kill a
node, restart a component — ``test/e2e/chaosmonkey``); fault points
inject them at the exact internal seam where the real failure would
surface, which is what makes single-fault recovery a checkable parity
property (tests/test_faults.py fault matrix).
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

from ..utils import tracing


class FaultInjected(Exception):
    """Default error raised by an ``error``-mode fault point."""


class FaultConfigError(Exception):
    """A plan referenced an unregistered point, or a spec is malformed."""


class FaultPoint:
    """One named injection seam.  Instances live in the process-wide
    registry; ``hits``/``fired`` count across every armed plan (the
    coverage gate in tests/test_faults.py reads these)."""

    def __init__(self, name: str, description: str = ""):
        self.name = name
        self.description = description
        self.hits = 0  # times an ARMED plan saw this site execute
        self.fired = 0  # times a policy actually misbehaved here

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"FaultPoint({self.name!r}, hits={self.hits}, fired={self.fired})"


_REGISTRY: dict[str, FaultPoint] = {}
_ARM_MU = threading.Lock()
_ACTIVE: Optional["FaultPlan"] = None


def register(name: str, description: str = "") -> FaultPoint:
    """Idempotent registration; the canonical catalogue lives in
    ``faults/__init__.py`` so importing the package yields the complete
    registry (the coverage gate depends on that)."""
    point = _REGISTRY.get(name)
    if point is None:
        point = _REGISTRY[name] = FaultPoint(name, description)
    return point


def registry() -> dict[str, FaultPoint]:
    """The live registry (read-only by convention)."""
    return _REGISTRY


def active_plan() -> Optional["FaultPlan"]:
    return _ACTIVE


@dataclass
class Fault:
    """What :func:`hit` returns when a non-raising policy fires.  The
    site interprets ``mode``: ``torn`` → write a partial record, ``drop``
    → discard the item, ``delay`` → already slept."""

    mode: str
    value: float = 0.0
    spec: Optional["FaultSpec"] = None


@dataclass
class FaultSpec:
    """Policy for one fault point inside one plan.

    mode:
      - ``error``: :func:`hit` raises (``error_factory()`` if given, else
        :class:`FaultInjected`) — models the operation failing outright;
      - ``delay``: :func:`hit` sleeps ``value`` seconds, site proceeds;
      - ``torn``: returned to the site, which writes ``value`` fraction
        of the payload then simulates the crash (WAL append);
      - ``drop``: returned to the site, which discards the item (watch
        event, informer delivery, one binding of a batch).

    Triggers (combined with AND; default = every matching hit fires):
      - ``match``: ctx filter — every key must be present and equal in
        the site's ``hit(name, **ctx)`` keywords;
      - ``nth``: fire only on the nth *matching* hit (1-based);
      - ``first_n``: fire on the first n matching hits;
      - ``probability``: fire with probability p from the plan's seeded
        RNG (deterministic per seed);
      - ``max_fires``: stop firing after this many fires.
    """

    mode: str = "error"
    error_factory: Optional[Callable[[], BaseException]] = None
    value: float = 0.5
    match: Optional[dict] = None
    nth: Optional[int] = None
    first_n: Optional[int] = None
    probability: Optional[float] = None
    max_fires: Optional[int] = None
    # runtime counters (per plan arming)
    seen: int = field(default=0, compare=False)
    fires: int = field(default=0, compare=False)

    _MODES = ("error", "delay", "torn", "drop")

    def __post_init__(self):
        if self.mode not in self._MODES:
            raise FaultConfigError(f"unknown fault mode {self.mode!r}")

    def _matches(self, ctx: dict) -> bool:
        if not self.match:
            return True
        return all(k in ctx and ctx[k] == v for k, v in self.match.items())

    def _should_fire(self, rng: random.Random) -> bool:
        # `seen` was already incremented for this matching hit
        if self.max_fires is not None and self.fires >= self.max_fires:
            return False
        if self.nth is not None and self.seen != self.nth:
            return False
        if self.first_n is not None and self.seen > self.first_n:
            return False
        if self.probability is not None and rng.random() >= self.probability:
            return False
        return True


class FaultPlan:
    """Seeded set of per-point policies, armed process-wide for a scope.

    One plan may be armed at a time (nesting two plans would make the
    "which policy fired" question ambiguous); arming is test-scoped by
    construction — ``with plan.armed(): ...``."""

    def __init__(self, seed: int = 0):
        self.seed = seed
        self.rng = random.Random(seed)
        self._specs: dict[str, list[FaultSpec]] = {}
        self.hits: dict[str, int] = {}
        self.fired: dict[str, int] = {}
        # Counter lock: watch/informer threads and the main thread hit
        # armed points concurrently; per-point fire counts must be EXACT
        # (nth/first_n/max_fires triggers and the coverage gate read
        # them — ROADMAP "Fault-point thread counters").  The policy
        # decision (seen/fires/rng) happens under the lock; the ACTION
        # (raise / sleep / return) happens outside it so a delay-mode
        # fault never stalls other threads' fault points.
        self._mu = threading.Lock()

    def on(self, point: str, spec: Optional[FaultSpec] = None, **kwargs) -> "FaultPlan":
        """Attach a policy to a registered point.  Chainable."""
        if point not in _REGISTRY:
            raise FaultConfigError(
                f"unknown fault point {point!r} — register it in the "
                f"faults/__init__.py catalogue first (known: {sorted(_REGISTRY)})"
            )
        if spec is None:
            spec = FaultSpec(**kwargs)
        elif kwargs:
            raise FaultConfigError("pass a FaultSpec or kwargs, not both")
        self._specs.setdefault(point, []).append(spec)
        return self

    # -- arming ------------------------------------------------------------
    def armed(self):
        return _Armed(self)

    # -- the hot path (only reached while armed) ---------------------------
    def _fire(self, name: str, ctx: dict) -> Optional[Fault]:
        point = _REGISTRY.get(name)
        if point is None:
            raise FaultConfigError(
                f"hit() on unregistered fault point {name!r} — add it to "
                "the faults/__init__.py catalogue"
            )
        fired_spec: Optional[FaultSpec] = None
        with self._mu:
            point.hits += 1
            self.hits[name] = self.hits.get(name, 0) + 1
            for spec in self._specs.get(name, ()):
                if not spec._matches(ctx):
                    continue
                spec.seen += 1
                if not spec._should_fire(self.rng):
                    continue
                spec.fires += 1
                point.fired += 1
                self.fired[name] = self.fired.get(name, 0) + 1
                fired_spec = spec
                break
        if fired_spec is None:
            return None
        # flight-recorder trigger (ISSUE 7): every fired fault dumps the
        # trace of the wave it fired into, BEFORE the site misbehaves —
        # a raise below must not lose the recording.  Disarmed runs never
        # reach here, so the production path is untouched.
        tracing.notify_fault(name, ctx, fired_spec.mode)
        if fired_spec.mode == "error":
            raise (fired_spec.error_factory() if fired_spec.error_factory is not None
                   else FaultInjected(f"injected fault at {name}"))
        if fired_spec.mode == "delay":
            time.sleep(fired_spec.value)
            return None  # the site proceeds, just later
        return Fault(fired_spec.mode, fired_spec.value, fired_spec)


class _Armed:
    def __init__(self, plan: FaultPlan):
        self._plan = plan

    def __enter__(self) -> FaultPlan:
        global _ACTIVE
        with _ARM_MU:
            if _ACTIVE is not None:
                raise FaultConfigError("another FaultPlan is already armed")
            _ACTIVE = self._plan
        return self._plan

    def __exit__(self, *exc) -> None:
        global _ACTIVE
        with _ARM_MU:
            _ACTIVE = None


def hit(name: str, **ctx) -> Optional[Fault]:
    """The instrumented-site entry point.  Disarmed: one global load and
    a None check — safe on every hot path.  Armed: consult the plan
    (may raise, sleep, or return a :class:`Fault` for the site to
    interpret)."""
    plan = _ACTIVE
    if plan is None:
        return None
    return plan._fire(name, ctx)
