"""Deterministic fault-injection framework (see ``faults/core.py``).

This module is the **catalogue**: every fault point the codebase
instruments is registered here, so ``import kubernetes_tpu.faults``
yields the complete registry.  The tier-1 gate in
``tests/test_faults.py`` asserts that every point below is exercised by
at least one seeded test — adding a point without a matrix scenario
fails CI, exactly like an unmarked kernel in the parity pass.

Catalogue (point → instrumented site → recovery path under test):

======================== ================================== ===========================
point                    site                               recovery
======================== ================================== ===========================
store.wal.append         WriteAheadLog.append               torn-tail truncate on replay
store.commit             Store.create/update/delete/        caller retry (remote 5xx) or
                         bind_many entry                    scheduler requeue-with-backoff
remote.request           RemoteStore request loop           retry + exponential backoff
remote.watch.stream      RemoteWatch connect/read loop      reconnect from resourceVersion;
                                                            410 → GAP → informer relist
informer.deliver         SharedInformer._apply              relist/resync reconverges cache
informer.decode          SharedInformer._apply decode       delta lost, gap marked; next
                         (lazy wrap / eager from_dict)      pump relists and reconverges
informer.apply_batch     SharedInformer._apply_batch        frame lost as a unit, gap
                         (column-packed watch frames)       marked; next pump relists
scheduler.bind           Scheduler._bind /                  forget + requeue with backoff;
                         Store.bind_many per item           retry lands on freed capacity
backend.pallas.segment   TPUBatchBackend kernel dispatch/   circuit breaker: pallas →
                         finalize                           interpret → oracle, re-probe
scheduler.pipeline.prep  Scheduler._pipeline_idle (the      contained: prep failure counted,
                         overlapped cross-wave host prep)   work re-runs synchronously at
                                                            the next wave (decisions and
                                                            parity unaffected)
backend.compact          frontier-scan prefilter seed /     segment retries on the
                         mid-segment node-axis gather       full-width scan from the same
                         (TPUBatchBackend / FrontierRun)    state — identical bindings,
                                                            only the pruning win is lost
telemetry.ship           TelemetryShipper._ship_batch       retry + backoff; exhausted
                         (one batch through the sink)       batches degrade to the local
                                                            dead ring — a dead collector
                                                            never stalls a wave
apiserver.admit          APIServer create-path admission    client retries honoring
                         gate (429 + Retry-After)           Retry-After; delayed pods
                                                            re-decide — occupancy
                                                            invariants converge
======================== ================================== ===========================
"""

from .core import (
    Fault,
    FaultConfigError,
    FaultInjected,
    FaultPlan,
    FaultPoint,
    FaultSpec,
    active_plan,
    hit,
    register,
    registry,
)

# -- the canonical fault-point catalogue ---------------------------------
register("store.wal.append",
         "WAL record append — error: append fails before any byte lands; "
         "torn: a partial record hits disk and the process 'crashes'")
register("store.commit",
         "store write commit (create/update/delete/bind_many) — error: "
         "the write fails before any state mutates (apiserver overload)")
register("store.coalesce",
         "coalescing-window flush at the broadcaster seam — error: the "
         "framed flush path fails and THAT window degrades to per-event "
         "delivery of the same folded events (state preserved, packing "
         "lost, store_coalesce_fallbacks_total increments)")
register("remote.request",
         "one HTTP request attempt in RemoteStore — error: transport "
         "failure; delay: slow apiserver")
register("remote.watch.stream",
         "RemoteWatch connect/read — error: stream breaks mid-flight "
         "(connection reset, 410 Gone on resume); phase=frame: a "
         "column-packed frame fails to decode — the watch emits a GAP "
         "and ends (the informer relists), never a partial apply")
register("informer.deliver",
         "SharedInformer delta application — drop: the event never "
         "reaches cache or handlers (lossy delivery)")
register("informer.decode",
         "watch-event payload decode (lazy wrap or eager from_dict) — "
         "error: the payload cannot be decoded; the delta is lost and "
         "the informer marks a gap so the next pump relists")
register("informer.apply_batch",
         "column-packed watch-frame application (SharedInformer."
         "_apply_batch) — error: the whole frame is lost as a unit "
         "before any event applied; the informer marks a gap and the "
         "existing relist path reconverges the cache")
register("scheduler.bind",
         "placement commit — error/drop: one pod's bind CAS fails "
         "(per-pod path raises, bind_many reports a per-item error)")
register("backend.pallas.segment",
         "kernel segment dispatch/finalize — error: the device program "
         "fails for this segment (Mosaic compile/runtime failure)")
register("scheduler.pipeline.prep",
         "overlapped host prep (informer pump + signature warming) run in "
         "the device's shadow between waves — error: the prep step dies "
         "mid-wave; the wave still completes and prep re-runs synchronously")
register("telemetry.ship",
         "one telemetry batch through the sink (file append or collector "
         "POST) — error: the collector is down; retry + backoff, then the "
         "batch degrades to the shipper's local dead ring (never blocks "
         "the pipeline)")
register("apiserver.admit",
         "the apiserver's overload admission gate on create paths — "
         "drop: the request is throttled with 429 + Retry-After (the "
         "fault's value is the hint in seconds); clients classify it "
         "retryable, honor the hint, and the delayed pods re-decide")
register("backend.compact",
         "frontier-scan node-axis compaction (phase=seed: the tensorize-"
         "time monotone prefilter; phase=gather: the mid-segment device "
         "gather) — error: the frontier step dies; the segment retries on "
         "the full-width scan from the same state, so bindings are "
         "unchanged and only time is lost")

__all__ = [
    "Fault",
    "FaultConfigError",
    "FaultInjected",
    "FaultPlan",
    "FaultPoint",
    "FaultSpec",
    "active_plan",
    "hit",
    "register",
    "registry",
]
