"""Cluster PKI: the kubeadm certs + kubeconfig phases.

Capability of ``cmd/kubeadm/app/phases/certs`` and ``phases/kubeconfig``:
one self-signed cluster CA, a serving certificate for the apiserver
(SANs for loopback + the cluster DNS names), and per-component CLIENT
certificates whose Subject carries the component identity the way the
reference encodes it (CN = user, O = group — ``system:kube-scheduler``,
``system:kube-controller-manager``, ``system:node:<name>``/
``system:nodes``, ``kubernetes-admin``/``system:masters``).  The
kubeconfig phase writes one JSON connection document per component
(server URL + CA + client cert/key paths) consumed by
``daemon.remote_clientset(kubeconfig=...)``.

Everything is generated with the ``cryptography`` library — no openssl
shell-outs — and written with 0600 keys like the reference.
"""

from __future__ import annotations

import datetime
import ipaddress
import json
import os
from typing import Optional

CERT_DAYS = 365


def _write(path: str, data: bytes, private: bool = False) -> str:
    if private:
        # 0600 from birth — chmod-after-write leaves the key world-readable
        # for a window (and forever, if interrupted between the two calls)
        fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
        with os.fdopen(fd, "wb") as f:
            f.write(data)
        return path
    with open(path, "wb") as f:
        f.write(data)
    return path


def _key_pem(key) -> bytes:
    from cryptography.hazmat.primitives import serialization

    return key.private_bytes(
        serialization.Encoding.PEM,
        serialization.PrivateFormat.TraditionalOpenSSL,
        serialization.NoEncryption(),
    )


def _new_key():
    from cryptography.hazmat.primitives.asymmetric import ec

    # ECDSA P-256: small certs, fast handshakes; the reference default is
    # RSA-2048 but the contract is "X.509 chain", not the key algorithm
    return ec.generate_private_key(ec.SECP256R1())


def _name(cn: str, org: Optional[str] = None):
    from cryptography import x509
    from cryptography.x509.oid import NameOID

    attrs = [x509.NameAttribute(NameOID.COMMON_NAME, cn)]
    if org:
        attrs.insert(0, x509.NameAttribute(NameOID.ORGANIZATION_NAME, org))
    return x509.Name(attrs)


def create_ca(pki_dir: str, cn: str = "kubernetes") -> tuple[str, str]:
    """Self-signed cluster CA -> (ca.crt, ca.key) paths."""
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization

    os.makedirs(pki_dir, exist_ok=True)
    key = _new_key()
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (
        x509.CertificateBuilder()
        .subject_name(_name(cn))
        .issuer_name(_name(cn))
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=5))
        .not_valid_after(now + datetime.timedelta(days=CERT_DAYS * 10))
        .add_extension(x509.BasicConstraints(ca=True, path_length=None),
                       critical=True)
        .sign(key, hashes.SHA256())
    )
    crt = _write(os.path.join(pki_dir, "ca.crt"),
                 cert.public_bytes(serialization.Encoding.PEM))
    keyf = _write(os.path.join(pki_dir, "ca.key"), _key_pem(key), private=True)
    return crt, keyf


def issue_cert(pki_dir: str, name: str, cn: str, org: Optional[str] = None,
               dns_sans: tuple = (), ip_sans: tuple = (),
               server: bool = False) -> tuple[str, str]:
    """CA-signed leaf -> (<name>.crt, <name>.key).  ``server=True`` adds
    serverAuth EKU + the SANs; client certs get clientAuth EKU."""
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.x509.oid import ExtendedKeyUsageOID

    with open(os.path.join(pki_dir, "ca.crt"), "rb") as f:
        ca_cert = x509.load_pem_x509_certificate(f.read())
    with open(os.path.join(pki_dir, "ca.key"), "rb") as f:
        ca_key = serialization.load_pem_private_key(f.read(), password=None)

    key = _new_key()
    now = datetime.datetime.now(datetime.timezone.utc)
    eku = [ExtendedKeyUsageOID.SERVER_AUTH if server
           else ExtendedKeyUsageOID.CLIENT_AUTH]
    builder = (
        x509.CertificateBuilder()
        .subject_name(_name(cn, org))
        .issuer_name(ca_cert.subject)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=5))
        .not_valid_after(now + datetime.timedelta(days=CERT_DAYS))
        .add_extension(x509.BasicConstraints(ca=False, path_length=None),
                       critical=True)
        .add_extension(x509.ExtendedKeyUsage(eku), critical=False)
    )
    sans = [x509.DNSName(d) for d in dns_sans]
    sans += [x509.IPAddress(ipaddress.ip_address(i)) for i in ip_sans]
    if sans:
        builder = builder.add_extension(
            x509.SubjectAlternativeName(sans), critical=False)
    cert = builder.sign(ca_key, hashes.SHA256())
    crt = _write(os.path.join(pki_dir, f"{name}.crt"),
                 cert.public_bytes(serialization.Encoding.PEM))
    keyf = _write(os.path.join(pki_dir, f"{name}.key"), _key_pem(key),
                  private=True)
    return crt, keyf


# the reference's component identities (kubeadm phases/certs/certs.go)
COMPONENTS = {
    "admin": ("kubernetes-admin", "system:masters"),
    "kube-scheduler": ("system:kube-scheduler", None),
    "kube-controller-manager": ("system:kube-controller-manager", None),
}


def create_cluster_pki(cluster_dir: str, node_name: str = "control-plane",
                       advertise_ip: str = "127.0.0.1") -> dict:
    """The full certs phase: CA + apiserver serving cert + component
    client certs + the kubelet's node client cert.  Returns a path map."""
    pki_dir = os.path.join(cluster_dir, "pki")
    ca_crt, ca_key = create_ca(pki_dir)
    paths = {"ca": ca_crt, "ca_key": ca_key, "dir": pki_dir}
    paths["apiserver"], paths["apiserver_key"] = issue_cert(
        pki_dir, "apiserver", "kube-apiserver", server=True,
        dns_sans=("localhost", "kubernetes", "kubernetes.default",
                  "kubernetes.default.svc", "kubernetes.default.svc.cluster.local"),
        ip_sans=(advertise_ip,),
    )
    for name, (cn, org) in COMPONENTS.items():
        paths[name], paths[f"{name}_key"] = issue_cert(pki_dir, name, cn, org)
    kubelet_name = f"kubelet-{node_name}"
    paths["kubelet"], paths["kubelet_key"] = issue_cert(
        pki_dir, kubelet_name, f"system:node:{node_name}", "system:nodes")
    return paths


def write_kubeconfig(cluster_dir: str, component: str, server: str,
                     ca: str, client_cert: Optional[str] = None,
                     client_key: Optional[str] = None,
                     token: Optional[str] = None) -> str:
    """The kubeconfig phase: one connection document per component
    (kubeadm ``phases/kubeconfig``).  JSON, not YAML-kubeconfig — the
    fields carry the same facts: server, CA pin, client identity."""
    path = os.path.join(cluster_dir, f"{component}.kubeconfig")
    doc = {"server": server, "certificate-authority": os.path.abspath(ca)}
    if client_cert:
        doc["client-certificate"] = os.path.abspath(client_cert)
        doc["client-key"] = os.path.abspath(client_key)
    if token:
        doc["token"] = token
    fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
    with os.fdopen(fd, "w") as f:
        json.dump(doc, f, indent=2)
    return path


def load_kubeconfig(path: str) -> dict:
    """Parse a connection kubeconfig document.  Raises ValueError (not a
    raw json/KeyError traceback) on files that are not this format —
    e.g. the YAML clusters/contexts file ``kubectl config`` maintains."""
    with open(path) as f:
        text = f.read()
    try:
        doc = json.loads(text)
    except json.JSONDecodeError as e:
        raise ValueError(
            f"{path} is not a connection kubeconfig (JSON): {e}. "
            "Files written by 'kubectl config set-*' are a different "
            "format; pass a kubeconfig generated by 'cluster up' / "
            "kubeadm-style init instead.") from e
    if not isinstance(doc, dict) or "server" not in doc:
        raise ValueError(
            f"{path}: connection kubeconfig must be a JSON object with "
            "a 'server' field")
    return doc
