"""The TPU batch scheduling backend.

Plugs into ``Scheduler.schedule_pending_batch`` (the seam the reference
exposes as the HTTP extender, ``core/extender.go`` — here it is in-process
and batch-shaped).  Guarantees **binding parity with the oracle**: the
drained FIFO batch executes on device via the scan kernel — including
inter-pod (anti)affinity and volume predicates (phase B) — reproducing
the sequential-greedy decision sequence a pure-oracle run produces.

Fallback ladder (every rung preserves parity):
1. unsupported predicate/priority/extender config → all-oracle;
2. one ordered greedy pass cuts the batch into segments that respect the
   tensor budgets (max_groups signatures / max_terms affinity terms /
   max_vols distinct disks / max_segment_pods scan length), each segment
   re-tensorized against the evolving state;
3. pods no kernel can express (> vols_per_pod distinct disks) run as
   singleton oracle segments; a binary split inside run_kernel_segment
   remains as a safety net should build_static still reject a segment.
"""

from __future__ import annotations

import logging
import time
from typing import Optional

from .. import faults
from ..api import types as api
from ..utils import tracing
from ..scheduler.generic_scheduler import FitError, GenericScheduler
from ..scheduler.nodeinfo import NodeInfo
from ..scheduler.predicates import DEFAULT_PREDICATES
from ..scheduler.priorities import (
    BalancedResourceAllocation,
    EqualPriority,
    ImageLocalityPriority,
    InterPodAffinityPriority,
    LeastRequestedPriority,
    MostRequestedPriority,
    NodeAffinityPriority,
    NodePreferAvoidPodsPriority,
    PriorityContext,
    SelectorSpreadPriority,
    TaintTolerationPriority,
)
from ..models.snapshot import (
    HostBatchState,
    Tensorizer,
    count_affinity_terms,
    pod_disk_vols,
    pod_signature_key,
)
from .batch_kernel import schedule_batch_arrays
from .breaker import LEVELS, KernelCircuitBreaker

logger = logging.getLogger("kubernetes_tpu.backend")


def _device_platform() -> str:
    import jax

    try:
        return jax.devices()[0].platform
    except Exception:
        return "unknown"

# The oracle priorities the kernel scoring path reproduces bit-for-bit —
# a configured priority outside this table forces the all-oracle path
# (_config_supported), so this dict IS the kernel-coverage claim.  The
# parity-pass `kernel: implements` markers for these live in
# _kernel_weights, the function that consumes this table — the analyzer
# only counts markers inside functions the kernel call graph reaches.
_PRIORITY_WEIGHT_KEY = {
    LeastRequestedPriority: "least",
    MostRequestedPriority: "most",
    BalancedResourceAllocation: "balanced",
    SelectorSpreadPriority: "spread",
    NodeAffinityPriority: "node_affinity",
    TaintTolerationPriority: "taint",
    InterPodAffinityPriority: "interpod",
    NodePreferAvoidPodsPriority: "prefer_avoid",
    ImageLocalityPriority: "image",
}


def _segment_vecs(static):
    """Per-signature ResourceVecs for the commit path (once per segment,
    G <= max_groups): the full request vector, and the nonzero variant
    (cpu/mem replaced by the per-container-defaulted values; other slots
    are identical by construction — see units.pod_nonzero_request_vec)."""
    from ..scheduler.units import CPU_MILLI, MEM_MIB, ResourceVec

    req_vecs, nz_vecs = [], []
    for g in range(len(static.g_request)):
        units = [int(x) for x in static.g_request[g]]
        req_vecs.append(ResourceVec(units))
        nz_units = list(units)
        nz_units[CPU_MILLI] = int(static.g_nonzero[g][0])
        nz_units[MEM_MIB] = int(static.g_nonzero[g][1])
        nz_vecs.append(ResourceVec(nz_units))
    return req_vecs, nz_vecs


class _PrefilteredScan:
    """Dispatch wrapper for a prefilter-compacted segment served by the
    PLAIN (unchunked) scan: holds the compacted static (whose node_names
    the chosen indices refer to) next to the in-flight arrays."""

    def __init__(self, static, fut):
        self.static = static
        self.fut = fut

    @property
    def device_probe(self):
        cand = self.fut[0] if isinstance(self.fut, (tuple, list)) else self.fut
        return cand if hasattr(cand, "is_ready") else None


class TPUBatchBackend:
    def __init__(
        self,
        algorithm: Optional[GenericScheduler] = None,
        tensorizer: Optional[Tensorizer] = None,
        # Segment cap: a power of two so every full segment lands in one
        # scan-length bucket.  Large segments amortize the per-segment host
        # work (tensorize, corpus matching, dispatch) across more pods —
        # 4096 -> 65536 took the north preset from 44x to 125x; the other
        # budgets (signatures/terms/conflict-vols) still cut when exceeded,
        # and the Pallas scan runs to the REAL pod count, not the pad.
        max_segment_pods: int = 65536,
        kernel_impl: str = "auto",  # auto | pallas | xla
        # Per-SHAPE failure tolerance: a shape (≡ one compilation unit,
        # pallas_kernel.shape_key) that fails this many CONSECUTIVE times
        # trips the circuit breaker one rung down the pallas → interpret
        # (XLA scan) → oracle ladder; below the threshold, later segments
        # of the same shape retry — a transient Mosaic failure must not
        # permanently downgrade the whole process (r3 VERDICT Weak #5)
        pallas_max_failures: int = 2,
        # Tripped shapes re-probe the better rung after this cool-down
        # (doubling on failed probes) — degradation is stated AND
        # reversible, never a silent permanent blacklist
        breaker_cooldown: float = 30.0,
        clock=time.monotonic,
        # Frontier scan (XLA path only): tensorize-time prefilter drops
        # node columns monotonically infeasible for every signature, the
        # scan runs in chunks carrying the still_ok plane, and when the
        # alive-union fraction falls below frontier_compact_frac the node
        # axis is compacted on device to a power-of-two width (≥
        # frontier_min_width).  Parity is exact by construction (see
        # models.snapshot.frontier_seed); any frontier failure falls back
        # to the full-width scan of the SAME segment state.
        frontier: bool = True,
        frontier_chunk: int = 512,
        frontier_compact_frac: float = 0.5,
        frontier_min_width: int = 128,
        # Device-resident wave loop: drive the chunked frontier scan as
        # ONE lax.while_loop dispatch with donated carries and a
        # device-computed compaction flag — host syncs per segment drop
        # from O(chunks) to O(compactions + 1).  Any loop failure falls
        # back to the chunked host loop (same carry plane), then to the
        # full-width scan; the breaker is never involved.
        frontier_device_loop: bool = True,
        # chunked still_ok mode engages when the prefilter's alive
        # fraction is at or below this.  Default 1.0 = always chunk when
        # the segment is big enough: measured on the north churn preset
        # the chunked scan is FASTER than the single monolithic scan even
        # with zero compactions (3/3 interleaved runs), so the knob
        # exists for experiments, not as a cost gate.
        frontier_engage_frac: float = 1.0,
        # Node-axis mesh (the shard_map wave loop): "auto" engages only
        # on a real multi-device accelerator platform — forced host
        # devices (tests/bench) opt in with True; False disables.  When
        # on, the device loop runs under shard_map over a 1-D mesh
        # partitioning the node axis; the in-loop reductions become
        # cross-shard collectives and the host-sync budget stays
        # O(compactions + 1) per wave.  Mesh-construction or dispatch
        # failure falls back breaker-style to the single-device loop
        # (frontier_loop_fallbacks, mode "mesh").
        frontier_mesh="auto",
        # cap on the shard count; the largest power of two <= min(cap,
        # device count) is used (None = all devices)
        mesh_devices: Optional[int] = None,
    ):
        self.algorithm = algorithm or GenericScheduler()
        self.tensorizer = tensorizer or Tensorizer()
        self.max_segment_pods = max_segment_pods
        self.kernel_impl = kernel_impl
        self.pallas_max_failures = pallas_max_failures
        self.breaker = KernelCircuitBreaker(
            failure_threshold=pallas_max_failures, cooldown=breaker_cooldown,
            clock=clock, on_transition=self._on_breaker_transition)
        # wired to scheduler_pallas_fallback_total by Scheduler.__init__
        self.fallback_counter = None
        # wired to scheduler_kernel_breaker_transitions_total
        self.breaker_counter = None
        # batch-to-batch host state (SURVEY §7.4.5): reconciled against
        # each batch's snapshot via per-node generation diffs instead of
        # rebuilt from every existing pod — the steady-state churn cost
        # drops from O(cluster) to O(touched nodes) per wave
        self._host_state = None
        self.reuse_host_state = True
        # device-resident node-axis tensors, reused across segments and
        # waves via the tensorizer's (epoch, version) node tokens
        from .batch_kernel import DeviceNodeCache

        self.device_node_cache = DeviceNodeCache()
        self.frontier = frontier
        self.frontier_chunk = frontier_chunk
        self.frontier_compact_frac = frontier_compact_frac
        self.frontier_min_width = frontier_min_width
        self.frontier_engage_frac = frontier_engage_frac
        self.frontier_device_loop = frontier_device_loop
        self.frontier_mesh = frontier_mesh
        self.mesh_devices = mesh_devices
        self._mesh = None
        self._mesh_failed = False
        # wired to scheduler_frontier_compactions_total
        self.frontier_counter = None
        # overload ladder rung 2 (ISSUE 17): when set, _kernel_weights
        # zeroes the preferred interpod-affinity SCORE plane — feasibility
        # predicates (incl. required affinity) are untouched, so occupancy
        # invariants vs the oracle still hold; only preferred-placement
        # quality degrades.  Wired by the scheduler per wave.
        self.shed_score_planes = False
        # wired to scheduler_score_plane_sheds_total
        self.shed_counter = None
        # per-batch frontier trajectory: one entry per frontier segment
        # ({"widths": [...], "alive_frac": [...], ...}); bench snapshots it
        self.last_frontier: list = []
        self.stats = {"kernel_pods": 0, "oracle_pods": 0, "segments": 0,
                      "pallas_segments": 0, "pallas_fallbacks": 0,
                      "interpret_fallbacks": 0, "oracle_segments": 0,
                      "breaker_transitions": 0,
                      "host_state_rebuilds": 0, "host_state_reconciles": 0,
                      "host_state_dirty_nodes": 0,
                      # frontier scan: segments served by it, device
                      # compactions, columns dropped at tensorize time,
                      # and full-width retries after a frontier failure
                      "frontier_segments": 0, "frontier_compactions": 0,
                      "frontier_prefilter_cols": 0, "frontier_fallbacks": 0,
                      # device-resident loop: segments that degraded from
                      # the while_loop form to the chunked host loop, and
                      # the degradation modes by name ("mesh" = sharded
                      # dispatch -> single-device loop, "loop" =
                      # while_loop form -> chunked host loop)
                      "frontier_loop_fallbacks": 0,
                      "frontier_fallback_modes": {},
                      # blocking device→host round-trips on the finalize
                      # path (cumulative) — the scheduler deltas this per
                      # wave next to the phase timers below
                      "host_syncs": 0,
                      # steady-state phase timers (seconds, cumulative):
                      # host tensorize, device dispatch, device wait
                      # (finalize block) — bench deltas these per wave
                      "tensorize_s": 0.0, "dispatch_s": 0.0,
                      "device_wait_s": 0.0}
        self._clock_wall = time.perf_counter

    def _on_breaker_transition(self, kind: str, key: tuple, frm: int,
                               to: int) -> None:
        """Breaker state changes are stated, not incidental: counted in
        stats + the scheduler's metrics registry, and logged with the
        ladder rungs spelled out."""
        self.stats["breaker_transitions"] += 1
        if self.breaker_counter is not None:
            self.breaker_counter.inc()
        # every transition is a flight-recorder trigger (ISSUE 7): the
        # dump carries the wave the rung change fired into
        tracing.notify_breaker(kind, key, LEVELS[frm], LEVELS[to])
        logger.warning("kernel breaker %s for shape %s: %s -> %s",
                       kind, key, LEVELS[frm], LEVELS[to])

    def _pallas_floor(self, static) -> int:
        """Best ladder rung the environment supports for this shape: 0
        (pallas) on real TPU / forced pallas for supported shapes with
        the gate on; 1 (interpret — the XLA scan) otherwise."""
        if self.kernel_impl == "xla":
            return 1
        from ..utils.features import DEFAULT_FEATURE_GATES

        if not DEFAULT_FEATURE_GATES.enabled("PallasKernels"):
            return 1
        from .pallas_kernel import supports_pallas

        if not supports_pallas(static):
            return 1
        if self.kernel_impl == "pallas" or _device_platform() == "tpu":
            return 0
        return 1

    def _use_pallas(self, static) -> bool:
        """Would the next segment of this shape attempt the fused Pallas
        rung?  Read-only probe over eligibility + breaker state (kept
        from the pre-breaker API; dispatch itself asks the breaker)."""
        if self._pallas_floor(static) != 0:
            return False
        from .pallas_kernel import shape_key

        return self.breaker.plan_level(shape_key(static), floor=0) == 0

    def _note_pallas_failure(self, static) -> None:
        """Record one pallas dispatch/finalize failure with the breaker
        and bump the fallback counter; degradation (and the later
        re-probe) is the breaker's call."""
        from .pallas_kernel import shape_key

        self.breaker.record_failure(shape_key(static), 0)
        self.stats["pallas_fallbacks"] += 1
        if self.fallback_counter is not None:
            self.fallback_counter.inc()

    def _note_interpret_failure(self, static) -> None:
        from .pallas_kernel import shape_key

        self.breaker.record_failure(shape_key(static), 1)
        self.stats["interpret_fallbacks"] += 1
        if self.fallback_counter is not None:
            self.fallback_counter.inc()

    # -- frontier scan (XLA path only) --------------------------------------
    def _on_frontier_compact(self, width: int, width_new: int,
                             n_alive: int) -> None:
        # fault seam BEFORE the gather: an injected compaction failure
        # aborts the frontier run and the segment retries full-width
        faults.hit("backend.compact", phase="gather", width=width,
                   new_width=width_new)
        self.stats["frontier_compactions"] += 1
        if self.frontier_counter is not None:
            self.frontier_counter.inc()
        tr = tracing.current()
        if tr is not None:
            tr.instant("frontier.compact", width=width, new_width=width_new,
                       alive=n_alive)

    def _on_frontier_loop(self, run_index: int, width: int,
                          start_chunk: int) -> None:
        # fault seam BEFORE every device-loop dispatch (initial AND each
        # re-entry after a compaction): an injected failure at run 0
        # degrades the segment to the chunked host loop; at a re-entry it
        # aborts finalize and the segment retries full-width — either
        # way parity holds, only time is lost
        faults.hit("backend.compact", phase="loop", run=run_index,
                   width=width, start_chunk=start_chunk)
        tr = tracing.current()
        if tr is not None:
            tr.instant("frontier.loop_enter", run=run_index, width=width,
                       start_chunk=start_chunk)

    def _note_frontier_fallback(self, mode: str) -> None:
        """One loop-form degradation, by mode: ``"mesh"`` = sharded
        dispatch → single-device loop, ``"loop"`` = while_loop form →
        chunked host loop.  Both ride the existing
        ``frontier_loop_fallbacks`` counter (the mode split is additive
        bookkeeping, not a second ladder)."""
        self.stats["frontier_loop_fallbacks"] += 1
        modes = self.stats.setdefault("frontier_fallback_modes", {})
        modes[mode] = modes.get(mode, 0) + 1

    def _mesh_enabled(self) -> bool:
        if self.frontier_mesh == "auto":
            # auto: only a real accelerator mesh is worth the collectives
            # (forced host devices are a test/bench construct — those
            # callers pass frontier_mesh=True explicitly)
            import jax

            return _device_platform() == "tpu" and len(jax.devices()) > 1
        return bool(self.frontier_mesh)

    def _frontier_mesh(self):
        """The node-axis mesh, built once per backend: the largest
        power-of-two shard count <= min(mesh_devices, device count), >= 2
        required.  None when disabled or after a failure — mesh
        construction trips ``_mesh_failed`` breaker-style (the
        single-device loop is always correct, so there is no probe-back:
        a broken device topology does not heal mid-process)."""
        if self._mesh is not None:
            return self._mesh
        if self._mesh_failed or not self._mesh_enabled():
            return None
        try:
            import jax

            from ..parallel.mesh import make_mesh

            n = len(jax.devices())
            if self.mesh_devices is not None:
                n = min(n, int(self.mesh_devices))
            p = 1
            while p * 2 <= n:
                p *= 2
            if p < 2:
                raise ValueError(
                    f"sharded loop needs >= 2 devices, have {n}")
            self._mesh = make_mesh(p)
            self.device_node_cache.set_mesh(self._mesh)
            return self._mesh
        except Exception:
            logger.exception(
                "mesh construction failed; the sharded loop is disabled "
                "for this backend (single-device loop serves all segments)")
            self._mesh_failed = True
            self._note_frontier_fallback("mesh")
            self.device_node_cache.set_mesh(None)
            return None

    def _dispatch_frontier(self, static, init):
        """Try to serve this segment through the frontier scan: seed the
        monotone step-0 plane, compact the node axis at tensorize time
        when enough columns are already dead, and hand the chunked run
        (``FrontierRun``) back as the dispatch future.  Returns None when
        the frontier adds nothing for this segment (no prefilter drop and
        too few pods to chunk) or when any frontier step fails — the
        caller then dispatches the plain full-width scan, so a frontier
        bug can cost time, never parity."""
        import numpy as np

        from ..models.snapshot import compact_segment, frontier_seed
        from .batch_kernel import FrontierRun, _pow2_width

        try:
            faults.hit("backend.compact", phase="seed")
            alive = frontier_seed(static, init)
            n_alive = int(alive.sum())
            width = _pow2_width(n_alive, self.frontier_min_width)  # device: static — pow2 buckets bound compiles to log2(N)
            cstatic, cinit = static, init
            if (width < static.n_pad
                    and n_alive <= self.frontier_compact_frac * static.n_pad):
                js = np.nonzero(alive)[0]
                cstatic, cinit = compact_segment(static, init, js, width)
                self.stats["frontier_prefilter_cols"] += static.n_pad - width
            # chunked still_ok mode only when the axis is actually dying
            # (otherwise the carry plane + per-chunk syncs cost scan time
            # and no compaction can ever trigger); a mostly-alive fleet
            # takes the prefilter (if it cut anything) + the plain scan
            chunked = (len(cstatic.group_of_pod) > self.frontier_chunk
                       and cstatic.n_pad > self.frontier_min_width
                       and n_alive <= self.frontier_engage_frac * static.n_pad)
            if not chunked:
                if cstatic is static:
                    return None  # nothing to prune, nothing to watch
                from .batch_kernel import dispatch_batch_arrays

                fut = dispatch_batch_arrays(
                    cstatic, cinit, node_cache=self.device_node_cache)
                self.stats["frontier_segments"] += 1
                return _PrefilteredScan(cstatic, fut)
            run = None
            use_loop = (self.frontier_device_loop and self.frontier_chunk > 0
                        and self.frontier_chunk & (self.frontier_chunk - 1) == 0)
            if use_loop:
                mesh = self._frontier_mesh()
                if mesh is not None:
                    try:
                        from ..models.snapshot import pad_segment_to_multiple
                        from ..parallel.mesh import mesh_dispatch_span

                        mstatic, minit = pad_segment_to_multiple(
                            cstatic, cinit, int(mesh.size))
                        with mesh_dispatch_span(mesh, int(mstatic.n_pad)):
                            run = FrontierRun(
                                mstatic, minit,
                                node_cache=self.device_node_cache,
                                chunk_len=self.frontier_chunk,
                                compact_frac=self.frontier_compact_frac,
                                min_width=self.frontier_min_width,
                                on_compact=self._on_frontier_compact,
                                device_loop=True,
                                on_loop=self._on_frontier_loop, mesh=mesh)
                        cstatic = mstatic
                    except Exception:
                        logger.exception(
                            "sharded loop dispatch failed; the segment "
                            "degrades to the single-device loop and the "
                            "mesh path is disabled")
                        self._note_frontier_fallback("mesh")
                        self._mesh = None
                        self._mesh_failed = True
                        self.device_node_cache.set_mesh(None)
                        run = None
            if run is None and use_loop:
                try:
                    run = FrontierRun(
                        cstatic, cinit, node_cache=self.device_node_cache,
                        chunk_len=self.frontier_chunk,
                        compact_frac=self.frontier_compact_frac,
                        min_width=self.frontier_min_width,
                        on_compact=self._on_frontier_compact,
                        device_loop=True, on_loop=self._on_frontier_loop)
                except Exception:
                    logger.exception(
                        "device-resident loop dispatch failed; the segment "
                        "degrades to the chunked host loop")
                    self._note_frontier_fallback("loop")
            if run is None:
                run = FrontierRun(
                    cstatic, cinit, node_cache=self.device_node_cache,
                    chunk_len=self.frontier_chunk,
                    compact_frac=self.frontier_compact_frac,
                    min_width=self.frontier_min_width,
                    on_compact=self._on_frontier_compact)
            run.prefilter_width = (static.n_pad, cstatic.n_pad)
            self.stats["frontier_segments"] += 1
            return run
        except Exception:
            logger.exception(
                "frontier dispatch failed; the segment runs full-width")
            self.stats["frontier_fallbacks"] += 1
            return None

    # -- greedy segmentation ------------------------------------------------
    def _segments(
        self, pods: list[api.Pod], mounted_disks: Optional[set] = None
    ) -> list[tuple[str, list[tuple[int, api.Pod]]]]:
        """Split the (ordered) batch into kernel segments that respect the
        tensor budgets, walking pod order once — every cut point preserves
        sequential-greedy parity because each segment re-tensorizes against
        the state left by its predecessors.  Pods no kernel can express
        (> vols_per_pod distinct disks) become singleton oracle segments.

        The volume budget counts CONFLICT-CAPABLE disks only (shared
        within the segment or already mounted) — build_static gives
        singleton unmounted disks no identity row, so they cost nothing."""
        tz = self.tensorizer
        mounted = mounted_disks if mounted_disks is not None else set()
        out: list[tuple[str, list[tuple[int, api.Pod]]]] = []
        cur: list[tuple[int, api.Pod]] = []
        sigs: set[str] = set()
        vols_once: set = set()
        vols_conflict: set = set()
        n_terms = 0

        def flush() -> None:
            nonlocal cur, sigs, vols_once, vols_conflict, n_terms
            if cur:
                out.append(("kernel", cur))
            cur, sigs, vols_once, vols_conflict, n_terms = [], set(), set(), set(), 0

        for i, pod in enumerate(pods):
            pv = pod_disk_vols(pod)
            if len(pv) > tz.vols_per_pod:
                flush()
                out.append(("oracle", [(i, pod)]))
                continue
            pv_conflict = {d for d in pv if d in mounted or d in vols_once}
            key = pod_signature_key(pod)
            t_new = count_affinity_terms(pod) if key not in sigs else 0
            if cur and (
                len(cur) >= self.max_segment_pods
                or (key not in sigs and len(sigs) >= tz.max_groups)
                or n_terms + t_new > tz.max_terms
                or len(vols_conflict | pv_conflict) > tz.max_vols
            ):
                flush()
                t_new = count_affinity_terms(pod)
                pv_conflict = {d for d in pv if d in mounted}
            sigs.add(key)
            n_terms += t_new
            vols_conflict |= pv_conflict
            vols_once |= pv
            cur.append((i, pod))
        flush()
        return out

    # -- config support check ---------------------------------------------
    def _kernel_weights(self) -> Optional[dict]:
        """Map the oracle's priority config onto kernel weights; None if any
        configured plugin has no kernel implementation."""
        # kernel: implements LeastRequestedPriority, MostRequestedPriority
        # kernel: implements BalancedResourceAllocation, SelectorSpreadPriority
        # kernel: implements NodeAffinityPriority, TaintTolerationPriority
        # kernel: implements InterPodAffinityPriority, NodePreferAvoidPodsPriority
        # kernel: implements ImageLocalityPriority
        weights = {
            "least": 0,
            "most": 0,
            "balanced": 0,
            "spread": 0,
            "node_affinity": 0,
            "taint": 0,
            "interpod": 0,
            "prefer_avoid": 0,
            "image": 0,
        }
        for prio, weight in self.algorithm.priorities:
            if isinstance(prio, EqualPriority):
                # kernel: implements EqualPriority
                continue  # constant shift; never changes argmax or ties
            key = _PRIORITY_WEIGHT_KEY.get(type(prio))
            if key is None:
                return None
            weights[key] += weight
        if self.shed_score_planes and weights["interpod"]:
            # overload rung 2: the interpod score plane is the kernel's
            # most expensive priority (pairwise term matching); shedding
            # it changes WHICH feasible node wins, never whether a pod
            # fits — counted so the degradation is stated, not silent
            weights["interpod"] = 0
            self.stats["score_plane_sheds"] = (
                self.stats.get("score_plane_sheds", 0) + 1)
            if self.shed_counter is not None:
                self.shed_counter.inc()
        return weights

    def _config_supported(self) -> Optional[dict]:
        if self.algorithm.extenders:
            return None
        if set(self.algorithm.predicates.keys()) != set(DEFAULT_PREDICATES.keys()):
            return None
        return self._kernel_weights()

    # -- the batch entry point ---------------------------------------------
    def schedule_batch(
        self,
        pods: list[api.Pod],
        node_info_map: dict[str, NodeInfo],
        pctx: PriorityContext,
        on_segment=None,
        on_idle=None,
    ) -> list[Optional[str]]:
        """``on_segment`` (optional): called with ``[(pod, node_name|None,
        req_vec|None, nz_vec|None), ...]`` per completed segment, AFTER the
        NEXT segment's device scan has been dispatched — the caller's
        commit work (cache assume, bind txn, events) runs on host while
        the TPU executes, hiding most of the commit cost behind device
        time.  Kernel-path entries carry the segment's per-signature
        request vectors (the ``add_pod_counted`` contract) so the caller's
        cache assume can skip its per-pod quantity parse; oracle-path
        entries carry ``None``.  Entry order across calls equals pod
        order, so sequential semantics are unchanged; with
        ``on_segment=None`` behavior is exactly the unpipelined batch.

        ``on_idle`` (optional): called ONCE as ``on_idle(device_busy=fn)``
        after the batch's final kernel segment has been dispatched and
        every earlier segment committed — the point where the host would
        otherwise sit blocked in finalize while the device still
        executes.  ``device_busy`` (or None when the dispatch exposes no
        readiness probe) polls the in-flight result, so the callback can
        fill the WHOLE device window with the next wave's ingest
        (informer pump, signature warming), extending the per-segment
        commit overlap across wave boundaries.  Must not mutate the
        snapshot this batch was tensorized from."""
        weights = self._config_supported()
        self.last_frontier = []  # this batch's frontier trajectory
        # Clone-on-write working state: speculative assumptions must never
        # leak into the scheduler's CoW snapshot, but nothing here READS
        # differently through a clone — so a NodeInfo is cloned only when
        # the first pod actually lands on it.  At steady state a wave
        # touches a fraction of the fleet; cloning all N up front was
        # ~50ms/wave at 5k nodes.  Every mutation in this method flows
        # through ``mutable_info`` (apply() is the only writer); the
        # oracle, tensorizer, and host-state reconcile only read.
        work_map = dict(node_info_map)
        _cloned: set[str] = set()

        def mutable_info(node_name: str):
            info = work_map.get(node_name)
            if info is None or node_name in _cloned:
                return info
            info = info.clone()
            work_map[node_name] = info
            _cloned.add(node_name)
            return info
        work_pctx = PriorityContext(
            work_map,
            services=pctx.services,
            replicasets=pctx.replicasets,
            hard_pod_affinity_weight=pctx.hard_pod_affinity_weight,
            pvcs=pctx.pvcs,
            pvs=pctx.pvs,
        )

        assignments: list[Optional[str]] = [None] * len(pods)

        # batch-persistent host state: selector-match corpus + disk
        # locations, kept ACROSS batches and reconciled against this
        # batch's snapshot by per-node generation diff (otherwise
        # initial_state re-scans every existing pod per segment and every
        # batch re-ingests the whole cluster).  Its disk-location keys
        # double as the mounted-disk membership that keeps singleton
        # disks out of the occupancy vocab.  Only the kernel path needs
        # it — the oracle-only fallback must not pay the corpus build.
        host_state = None
        if weights is not None:
            if not self.reuse_host_state:
                # benchmark seam: the pre-incremental behavior (fresh
                # O(cluster) build per batch) for honest A/B runs
                if self._host_state is not None:
                    self._host_state.close()
                self._host_state = None
            if self._host_state is None:
                self._host_state = HostBatchState(work_map)
                self.stats["host_state_rebuilds"] += 1
            else:
                self._host_state.reconcile(work_map)
                self.stats["host_state_reconciles"] += 1
                self.stats["host_state_dirty_nodes"] += len(
                    self._host_state.last_dirty)
            host_state = self._host_state
        mounted_disks = host_state.mounted_disks if host_state is not None else set()

        def apply(pod: api.Pod, node_name: Optional[str], i: int,
                  req_vec=None, nz_vec=None) -> None:
            assignments[i] = node_name
            if node_name is not None:
                info = mutable_info(node_name)
                if info is not None:
                    if req_vec is not None:
                        # kernel path: the segment's per-signature vectors
                        # spare a quantity re-parse per placed pod
                        info.add_pod_counted(pod, req_vec, nz_vec)
                    else:
                        info.add_pod(pod)
                if host_state is not None:
                    host_state.add_pod(pod, node_name)

        def run_oracle(pod: api.Pod, i: int) -> None:
            try:
                res = self.algorithm.schedule(pod, work_map, work_pctx)
                apply(pod, res.node_name, i)
            except FitError:
                apply(pod, None, i)
            self.stats["oracle_pods"] += 1

        def run_kernel_segment(segment: list[tuple[int, api.Pod]]) -> None:
            """Sync path: dispatch + finish immediately.  On a budget
            reject (signatures / affinity terms / volumes), halve the
            segment — each half re-tensorizes against the updated working
            state, so sequential parity is preserved."""
            finish = dispatch_kernel_segment(segment)
            if finish is None:
                if len(segment) == 1:
                    run_oracle(segment[0][1], segment[0][0])
                    return
                mid = len(segment) // 2
                run_kernel_segment(segment[:mid])
                run_kernel_segment(segment[mid:])
                return
            finish()

        def dispatch_kernel_segment(segment: list[tuple[int, api.Pod]]):
            """Async half of run_kernel_segment: tensorize + dispatch and
            return a finisher closure that materializes, applies, and
            returns the segment's commit entries.  Returns None when the
            segment needs the sync split path (budget reject)."""
            seg_pods = [p for _, p in segment]
            tr = tracing.current()
            t_tensorize = self._clock_wall()
            static = self.tensorizer.build_static(
                seg_pods,
                work_map,
                work_pctx,
                least_requested_weight=weights["least"],
                most_requested_weight=weights["most"],
                balanced_weight=weights["balanced"],
                spread_weight=weights["spread"],
                node_affinity_weight=weights["node_affinity"],
                taint_weight=weights["taint"],
                prefer_avoid_weight=weights["prefer_avoid"],
                image_weight=weights["image"],
                interpod_weight=weights["interpod"],
                mounted_disks=mounted_disks,
            )
            if static is None:
                t_end = self._clock_wall()
                self.stats["tensorize_s"] += t_end - t_tensorize
                if tr is not None:
                    tr.complete("tensorize", t_tensorize, t_end, cat="phase",
                                pods=len(seg_pods), rejected=True)
                return None
            init = self.tensorizer.initial_state(
                static, work_map, work_pctx, seg_pods,
                round_robin=self.algorithm._round_robin, host_state=host_state,
            )
            t_end = self._clock_wall()
            self.stats["tensorize_s"] += t_end - t_tensorize
            if tr is not None:
                # same clock reads as the stats timer: the trace-derived
                # tensorize_s IS this measurement
                tr.complete("tensorize", t_tensorize, t_end, cat="phase",
                            pods=len(seg_pods), groups=len(static.g_request),
                            n_pad=int(static.n_pad))
            from .pallas_kernel import shape_key

            key = shape_key(static)
            floor = self._pallas_floor(static)
            # the breaker picks the ladder rung (pallas → interpret →
            # oracle) for this shape — including the half-open re-probe of
            # a better rung once a tripped shape's cool-down elapses
            level = self.breaker.plan_level(key, floor=floor)
            fut = None
            t_dispatch = self._clock_wall()
            if level == 0:
                from .pallas_kernel import dispatch_batch_pallas

                try:
                    # trace/compile-time failures surface AT dispatch —
                    # same fallback contract as the run-time path
                    faults.hit("backend.pallas.segment", impl="pallas")
                    fut = dispatch_batch_pallas(static, init)
                except Exception:
                    logger.exception(
                        "pallas dispatch failed; degrading segment to the "
                        "XLA scan")
                    self._note_pallas_failure(static)
                    level = 1
            if level == 1:
                from .batch_kernel import dispatch_batch_arrays

                if self.frontier:
                    # frontier scan first; any frontier failure already
                    # degraded to None inside (full-width retry below)
                    fut = self._dispatch_frontier(static, init)
                if fut is None:
                    try:
                        faults.hit("backend.pallas.segment", impl="interpret")
                        fut = dispatch_batch_arrays(
                            static, init, node_cache=self.device_node_cache)
                    except Exception:
                        logger.exception(
                            "XLA scan dispatch failed; the oracle serves "
                            "this segment")
                        self._note_interpret_failure(static)
                        level = 2
            t_end = self._clock_wall()
            self.stats["dispatch_s"] += t_end - t_dispatch
            if tr is not None:
                # the breaker's chosen ladder rung rides on the span —
                # "this wave quietly ran on the slow path" is trace-visible
                tr.complete("dispatch", t_dispatch, t_end, cat="phase",
                            rung=LEVELS[level], shape=str(key),
                            frontier=bool(self.frontier and level == 1))

            device_probe = None
            if fut is not None:
                cand = fut[0] if isinstance(fut, (tuple, list)) and fut else fut
                if hasattr(cand, "device_probe"):
                    cand = cand.device_probe
                if hasattr(cand, "is_ready"):
                    device_probe = cand

            def run_segment_oracle() -> list:
                # the ladder's floor: sequential per-pod oracle — slow,
                # but bindings are identical by definition
                t0 = self._clock_wall()
                for i, pod in segment:
                    run_oracle(pod, i)
                self.stats["oracle_segments"] += 1
                tr2 = tracing.current()
                if tr2 is not None:
                    tr2.complete("oracle", t0, self._clock_wall(),
                                 cat="phase", pods=len(segment))
                return [(pod, assignments[i], None, None) for i, pod in segment]

            if level == 2:
                return run_segment_oracle

            def finish() -> list:
                nonlocal level
                t_wait = self._clock_wall()
                # which static's node axis the chosen indices refer to
                # (a FrontierRun's compacted view, or the original)
                names_static = static
                if level == 0:
                    from .pallas_kernel import finalize_batch_pallas

                    try:
                        chosen, final_rr = finalize_batch_pallas(static, *fut)
                        self.stats["host_syncs"] += 1
                        self.stats["pallas_segments"] += 1
                        self.breaker.record_success(key, 0)
                    except Exception:
                        logger.exception(
                            "pallas kernel failed; falling back to XLA scan")
                        self._note_pallas_failure(static)
                        level = 1
                        try:
                            chosen, final_rr = schedule_batch_arrays(static, init)
                            self.stats["host_syncs"] += 1
                            self.breaker.record_success(key, 1)
                        except Exception:
                            logger.exception(
                                "XLA scan failed after pallas; the oracle "
                                "serves this segment")
                            self._note_interpret_failure(static)
                            return run_segment_oracle()
                else:
                    from .batch_kernel import (FrontierRun,
                                               finalize_batch_arrays)

                    # one finalize ladder for all three XLA shapes: the
                    # frontier forms may additionally retry the SAME
                    # segment state full-width on failure (a frontier bug
                    # is not a SHAPE failure — the breaker stays out of
                    # it); the last rung is always the per-pod oracle
                    if isinstance(fut, _PrefilteredScan):
                        def finalize_primary():
                            chosen, rr = finalize_batch_arrays(
                                fut.static, *fut.fut)
                            self.stats["host_syncs"] += 1
                            self.last_frontier.append({
                                "prefilter": [static.n_pad,
                                              fut.static.n_pad],
                                "widths": [fut.static.n_pad],
                                "alive_frac": [],
                                "chunks": 1,
                                "compactions": 0,
                                "mode": "plain",
                                "host_syncs": 1,
                            })
                            return chosen, rr, fut.static
                        frontier_retry = True
                    elif isinstance(fut, FrontierRun):
                        def finalize_primary():
                            chosen, rr = fut.finalize()
                            self.stats["host_syncs"] += fut.stats["host_syncs"]
                            entry = {
                                "prefilter": list(
                                    getattr(fut, "prefilter_width",
                                            (static.n_pad, static.n_pad))),
                                "widths": fut.stats["widths"],
                                "alive_frac": fut.stats["alive_frac"],
                                "chunks": fut.stats["chunks"],
                                "compactions": fut.stats["compactions"],
                                "mode": ("mesh" if fut.mesh is not None
                                         else "loop" if fut.device_loop
                                         else "chunked"),
                                "host_syncs": fut.stats["host_syncs"],
                            }
                            if fut.mesh is not None:
                                # per-shard attribution rides the SAME
                                # per-segment entry (no second format)
                                entry["n_shards"] = fut.stats["n_shards"]
                                entry["shard_alive_frac"] = (
                                    fut.stats["shard_alive_frac"])
                            self.last_frontier.append(entry)
                            return chosen, rr, fut.static
                        frontier_retry = True
                    else:
                        def finalize_primary():
                            chosen, rr = finalize_batch_arrays(static, *fut)
                            self.stats["host_syncs"] += 1
                            return chosen, rr, static
                        frontier_retry = False

                    try:
                        chosen, final_rr, names_static = finalize_primary()
                        self.breaker.record_success(key, 1)
                    except Exception:
                        if frontier_retry:
                            logger.exception(
                                "frontier scan failed; retrying the "
                                "segment full-width")
                            self.stats["frontier_fallbacks"] += 1
                        else:
                            logger.exception(
                                "XLA scan failed; the oracle serves this "
                                "segment")
                            self._note_interpret_failure(static)
                            return run_segment_oracle()
                        try:
                            chosen, final_rr = schedule_batch_arrays(
                                static, init)
                            self.stats["host_syncs"] += 1
                            names_static = static
                            self.breaker.record_success(key, 1)
                        except Exception:
                            logger.exception(
                                "XLA scan failed; the oracle serves this "
                                "segment")
                            self._note_interpret_failure(static)
                            return run_segment_oracle()
                t_wait_end = self._clock_wall()
                self.stats["device_wait_s"] += t_wait_end - t_wait
                if tr is not None:
                    tr.complete("device_wait", t_wait, t_wait_end,
                                cat="phase", rung=LEVELS[level],
                                pods=len(segment))
                self.algorithm._round_robin = final_rr
                req_vecs, nz_vecs = _segment_vecs(static)
                group_of_pod = static.group_of_pod
                entries = []
                for k, ((i, pod), idx) in enumerate(zip(segment, chosen)):
                    node_name = names_static.node_names[int(idx)] if int(idx) >= 0 else None
                    g = int(group_of_pod[k])
                    apply(pod, node_name, i, req_vecs[g], nz_vecs[g])
                    # the segment's per-signature vectors ride along so the
                    # caller's cache assume can skip its own quantity parse
                    entries.append((pod, node_name, req_vecs[g], nz_vecs[g]))
                self.stats["kernel_pods"] += len(segment)
                self.stats["segments"] += 1
                return entries

            finish.device_probe = device_probe
            return finish

        # Phase B: every pod is kernel-expressible (inter-pod affinity and
        # volumes run on device).  One ordered pass cuts the batch into
        # budget-respecting segments up front (no trial-and-error splits);
        # the binary split inside run_kernel_segment remains only as a
        # safety net should build_static still reject a segment.
        if weights is None:
            for i, pod in enumerate(pods):
                run_oracle(pod, i)
            if on_segment is not None and pods:
                on_segment([(pod, assignments[i], None, None)
                            for i, pod in enumerate(pods)])
            return assignments
        pending: list = []  # prior segments' entries awaiting commit

        def flush_pending() -> None:
            nonlocal pending
            if on_segment is not None and pending:
                on_segment(pending)
            pending = []

        try:
            segments = self._segments(pods, mounted_disks=mounted_disks)
            for si, (kind, segment) in enumerate(segments):
                if kind == "oracle":
                    for i, pod in segment:
                        run_oracle(pod, i)
                    pending.extend((pod, assignments[i], None, None) for i, pod in segment)
                    continue
                finish = dispatch_kernel_segment(segment)
                if finish is None:
                    # budget reject (rare): sync safety-net split path
                    flush_pending()
                    run_kernel_segment(segment)
                    pending.extend((pod, assignments[i], None, None) for i, pod in segment)
                    continue
                # the device is executing THIS segment: commit everything
                # earlier on host in the shadow of the scan
                flush_pending()
                if on_idle is not None and si == len(segments) - 1:
                    # final segment in flight, nothing left to commit:
                    # hand the device's shadow to the caller's cross-wave
                    # prep instead of blocking straight into finalize
                    probe = getattr(finish, "device_probe", None)
                    on_idle(device_busy=(
                        (lambda p=probe: not p.is_ready())
                        if probe is not None else None))
                pending = finish()
            flush_pending()
        except BaseException:
            # an aborted batch leaves speculatively-applied pods in the
            # persistent host state that no cache generation will ever
            # account for — drop the state so the next batch rebuilds
            # from the snapshot instead of scheduling against phantoms
            if self._host_state is not None:
                self._host_state.close()
                self._host_state = None
            raise
        return assignments
