"""Fused Pallas TPU kernel for the batched scheduling scan.

Why this exists: the XLA `lax.scan` step (``batch_kernel.py``) is
semantically right but latency-bound — each pod's step is a chain of
dependent reduce→broadcast stages (feasibility → scores → normalize →
argmax-with-tie-break → commit), and under XLA every stage round-trips
HBM, costing ~25μs per serialized stage and ~160μs per pod.  This kernel
runs the WHOLE scan as one Pallas program: all dynamic state lives in
VMEM scratch for the duration of the batch, each pod's step is a handful
of VPU passes over [.., N] rows, and the only HBM traffic is the initial
state load and the chosen-index writeback.

Parity contract: every arithmetic op mirrors ``batch_kernel.make_step``
in int32 (fixed-point ``scheduler/units.py``) — same masks, same
normalizations, same round-robin tie-break — so bindings are
bit-identical to the sequential oracle.  Signature-table "gathers" use
f32 one-hot matmuls on the MXU; the gathered values are small ints
(exact in f32) and are cast straight back to int32, so no float rounding
can reach a score.

Layouts (host-prepped in ``_pack``): the node axis is the lane axis
everywhere; per-signature tables are stored [*, G] so a one-hot e_gid
[G, 1] matmul yields sublane-major columns; volume occupancy packs
(any, non-sharable) into two bits of an int8 [V, N] map whose rows are
dynamically sliced per volume slot.

Reference capability: the scheduling algorithm of
``plugin/pkg/scheduler/core/generic_scheduler.go:88`` (filter → score →
selectHost) batched over the pod queue.
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np

from ..models.snapshot import BatchStatic, InitialState
from ..scheduler.predicates import VOLUME_COUNT_LIMITS
from ..scheduler.units import FIXED_POINT_ONE, MAX_PRIORITY
from .batch_kernel import WEIGHT_KEYS

INT32_MIN = -(2**31)

_VOL_LIMITS = list(VOLUME_COUNT_LIMITS.values())  # static: baked into the kernel

# VMEM budget guard: leave headroom under the ~16 MB/core budget for
# Mosaic's own temporaries and spills.
VMEM_BUDGET_BYTES = 14 * 2**20


def _f32(x):
    return np.ascontiguousarray(x, dtype=np.float32)


def _i32(x):
    return np.ascontiguousarray(x, dtype=np.int32)


def pallas_vmem_bytes(static: BatchStatic) -> int:
    """VMEM footprint of the kernel for this segment's shapes.  Static maps
    and tables are VMEM inputs; the dynamic state lives ONCE in scratch
    (its initial values arrive via HBM + DMA, so they are not
    double-counted); the int8 volume map is the only non-int32 piece."""
    n = static.n_pad
    g = static.static_ok.shape[0]
    t = static.term_matches_sig.shape[0]
    pv = static.g_ports.shape[1]
    v = static.v_state
    r = static.node_alloc.shape[1]
    k = len(_VOL_LIMITS)
    p = len(static.group_of_pod)
    ints = (
        # static [.., N] maps + node vectors (VMEM inputs)
        (5 * g + 2 * t + r + 4) * n
        # state scratch: requested/nonzero/count/ports/spread/dm/downer/nk
        + (r + 2 + 1 + pv + g + 2 * t + k) * n
        # signature tables (f32) + per-pod xs + chosen output
        + g * (g + t * 5 + r + 2 + pv + 1)
        + p * 9
    )
    return ints * 4 + v * n  # + int8 volume map (scratch)


def supports_pallas(static: BatchStatic) -> bool:
    return (
        static.num_zones <= 8
        and pallas_vmem_bytes(static) <= VMEM_BUDGET_BYTES
    )


def _pod_pad(p_real: int) -> int:
    """Power-of-two pod-count buckets (same policy as batch_xs): tails of
    different runs land in the same bucket, so the warm-up compile covers
    them.  Shared by ``_pack`` and ``shape_key`` — the fallback blacklist
    must bucket exactly as the compile cache does."""
    p_pad = 128
    while p_pad < p_real:
        p_pad *= 2
    return p_pad


def _pack(static: BatchStatic, init: InitialState):
    """numpy host prep: transposes, one-hot-matmul layouts, bit packing."""
    n = static.n_pad
    g = static.static_ok.shape[0]
    t = static.term_matches_sig.shape[0]
    p_real = len(static.group_of_pod)
    p_pad = _pod_pad(p_real)
    w = static.pod_vol_ids.shape[1]

    gids = np.zeros(p_pad, dtype=np.int32)
    gids[:p_real] = static.group_of_pod
    # packed per-pod volume slots: vid*64 | kind*8 | ro*4 | count_only*2 | valid
    pod_vol = np.full((p_pad, w), (static.v_state - 1) * 64, dtype=np.int32)
    pod_vol[:p_real] = (
        static.pod_vol_ids * 64
        + static.pod_vol_kind * 8
        + static.pod_vol_ro_ok.astype(np.int32) * 4
        + static.pod_vol_count_only.astype(np.int32) * 2
        + static.pod_vol_valid.astype(np.int32)
    )

    vol_flags = (init.vol_any.astype(np.int8) | (init.vol_ns.astype(np.int8) << 1))

    ins = (
        # -- static node-axis maps (int32) --
        _i32(static.node_alloc.T),  # alloc_t [R, N]
        _i32(static.node_alloc_pods)[None, :],  # [1, N]
        _i32(static.node_exists)[None, :],  # [1, N]
        _i32(static.node_zone)[None, :],  # [1, N]
        _i32(static.static_ok),  # [G, N]
        _i32(static.node_aff_raw),
        _i32(static.taint_intol_raw),
        _i32(static.static_score),
        _i32(static.interpod_raw),
        _i32(static.node_domain),  # [T, N]
        _i32(static.dom_valid),  # [T, N]
        # -- signature tables, [*, G] f32 for one-hot matmul gathers --
        _f32(static.g_request.T),  # [R, G]
        _f32(static.g_nonzero.T),  # [2, G]
        _f32(static.g_ports.T),  # [Pv, G]
        _f32(static.g_has_spread)[None, :],  # [1, G]
        _f32(static.spread_inc),  # [G, G] (col gid = increments)
        _f32(static.term_matches_sig),  # [T, G]
        _f32(static.own_w.T),  # [T, G]
        _f32(static.own_ra.T),  # [T, G]
        _f32(static.own_raa.T),  # [T, G]
        _f32(static.own_all.T),  # [T, G]
        _i32(static.sym_w)[:, None],  # [T, 1]
        _i32(static.is_raa)[:, None],  # [T, 1]
        _i32(static.self_match)[:, None],  # [T, 1]
        # -- xs --
        _i32(pod_vol),  # [P, W]
        # -- initial state --
        _i32(init.requested.T),  # [R, N]
        _i32(init.nonzero_requested.T),  # [2, N]
        _i32(init.pod_count)[None, :],  # [1, N]
        _i32(init.ports_used.T),  # [Pv, N]
        _i32(init.spread_counts),  # [G, N]
        _i32(init.dm),  # [T, N]
        _i32(init.downer),  # [T, N]
        _i32(init.total_match)[:, None],  # [T, 1]
        vol_flags,  # [V, N] int8
        _i32(init.nk),  # [K, N]
    )
    scalars = (
        np.array([p_real], dtype=np.int32),
        np.array([init.round_robin], dtype=np.int32),
        gids,
    )
    return scalars, tuple(ins), p_pad


@lru_cache(maxsize=64)
def _pallas_runner(
    n: int,
    g: int,
    t: int,
    pv: int,
    v: int,
    r: int,
    w: int,
    p_pad: int,
    num_zones: int,
    weights: tuple,
    use_terms: bool,
    use_vols: bool,
    k_unroll: int = 1,
):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    wd = dict(zip(WEIGHT_KEYS, weights))
    k = len(_VOL_LIMITS)
    pc = p_pad // 128

    def kernel(
        p_real_ref,
        rr0_ref,
        gids_ref,
        # static
        alloc_t,
        alloc_pods,
        exists,
        zone,
        static_ok,
        aff_raw,
        taint_raw,
        score_raw,
        interpod_raw,
        node_domain,
        dom_valid,
        g_request_f,
        g_nonzero_f,
        g_ports_f,
        g_has_spread_f,
        spread_inc_f,
        tm_f,
        own_w_f,
        own_ra_f,
        own_raa_f,
        own_all_f,
        sym_w_c,
        is_raa_c,
        self_match_c,
        pod_vol,
        # initial state
        req0,
        nz0,
        cnt0,
        ports0,
        spread0,
        dm0,
        downer0,
        total0,
        volf0,
        nk0,
        # outputs
        chosen_out,
        rr_out,
        # scratch (state)
        req_s,
        nz_s,
        cnt_s,
        ports_s,
        spread_s,
        dm_s,
        downer_s,
        total_s,
        volf_s,
        nk_s,
        state_sem,
    ):
        # ---- DMA initial state (HBM inputs) into VMEM scratch ----
        # State inputs stay in HBM so VMEM holds exactly ONE copy of the
        # mutable state; without this the v_state*N volume map alone would
        # blow the budget at 5k-node scale.
        copies = [(req0, req_s), (nz0, nz_s), (cnt0, cnt_s), (ports0, ports_s),
                  (spread0, spread_s)]
        if use_terms:
            copies += [(dm0, dm_s), (downer0, downer_s), (total0, total_s)]
        if use_vols:
            copies += [(volf0, volf_s), (nk0, nk_s)]
        for src, dst in copies:
            dma = pltpu.make_async_copy(src, dst, state_sem)
            dma.start()
            dma.wait()
        chosen_out[:] = jnp.full((pc, 128), -1, dtype=jnp.int32)

        lane = jax.lax.broadcasted_iota(jnp.int32, (1, n), 1)
        lane128 = jax.lax.broadcasted_iota(jnp.int32, (1, 128), 1)
        giota = jax.lax.broadcasted_iota(jnp.int32, (g, 1), 0)
        exists_b = exists[:] > 0

        def cumsum_lanes(x):
            """Inclusive prefix sum along lanes (Mosaic has no cumsum):
            log2(N) rounds of roll-and-add, masking the wrapped lanes."""
            off = 1
            while off < n:
                shifted = pltpu.roll(x, off, axis=1)
                x = x + jnp.where(lane >= off, shifted, 0)
                off *= 2
            return x

        def body(i, rr, step_valid=None):
            # ``step_valid`` (trace-time None = unconditionally valid):
            # the super-step loop (k_unroll > 1) runs fixed K sub-steps
            # per iteration, so tail sub-steps past p_real execute with
            # step_valid=False — they commit nothing and never bump rr,
            # keeping the arithmetic stream identical to the K=1 program.
            # (NB the name: the volume-slot loop below binds a local
            # ``valid`` — the per-slot validity bit — which must not
            # shadow this parameter.)
            gid = gids_ref[i]
            e_gid = (giota == gid).astype(jnp.float32)  # [G, 1]

            def gather_col(tab_f):  # [X, G] f32 @ [G, 1] -> [X, 1] int32
                col = jax.lax.dot_general(
                    tab_f[:], e_gid,
                    dimension_numbers=(((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                return col.astype(jnp.int32)

            g_req_c = gather_col(g_request_f)  # [R, 1]
            g_nz_c = gather_col(g_nonzero_f)  # [2, 1]
            g_ports_c = gather_col(g_ports_f)  # [Pv, 1]

            # ---- feasibility ----
            # NOTE: sublane reductions run in int32 — Mosaic cannot lower
            # bool (i8->i1) reductions
            fit_rn = jnp.where(
                g_req_c > 0,
                (req_s[:] + g_req_c <= alloc_t[:]).astype(jnp.int32),
                1,
            )  # [R, N]
            fit = jnp.min(fit_rn, axis=0, keepdims=True) > 0  # [1, N]
            pods_ok = cnt_s[:] + 1 <= alloc_pods[:]
            ports_bad = (
                jnp.max(
                    ((g_ports_c > 0) & (ports_s[:] > 0)).astype(jnp.int32),
                    axis=0, keepdims=True,
                )
                > 0
            )
            ok_row = static_ok[pl.ds(gid, 1), :] > 0
            feasible = ok_row & fit & pods_ok & ~ports_bad & exists_b

            if use_terms:
                m_g_c = gather_col(tm_f)  # [T, 1]
                own_ra_c = gather_col(own_ra_f)
                own_raa_c = gather_col(own_raa_f)
                own_all_c = gather_col(own_all_f)
                own_w_c = gather_col(own_w_f)
                dm = dm_s[:]  # [T, N]
                downer = downer_s[:]
                sym_anti_bad = (
                    jnp.max(
                        (((m_g_c > 0) & (is_raa_c[:] > 0)) & (downer > 0)).astype(jnp.int32),
                        axis=0, keepdims=True,
                    )
                    > 0
                )
                first_ok = (total_s[:] == 0) & (self_match_c[:] > 0)  # [T, 1]
                ra_ok = (dm > 0) | first_ok
                own_ra_bad = (
                    jnp.max(((own_ra_c > 0) & ~ra_ok).astype(jnp.int32), axis=0, keepdims=True)
                    > 0
                )
                own_raa_bad = (
                    jnp.max(((own_raa_c > 0) & (dm > 0)).astype(jnp.int32), axis=0, keepdims=True)
                    > 0
                )
                feasible = feasible & ~sym_anti_bad & ~own_ra_bad & ~own_raa_bad

            if use_vols:
                sub8 = jax.lax.broadcasted_iota(jnp.int32, (8, 1), 0)

                def vol_row(vid):
                    # int8 dynamic sublane slices must be 8-aligned: fetch
                    # the aligned 8-row block and mask-select the row
                    base = pl.multiple_of((vid // 8) * 8, 8)
                    blk = volf_s[pl.ds(base, 8), :].astype(jnp.int32)  # [8, N]
                    sel = sub8 == vid % 8
                    return jnp.max(jnp.where(sel, blk, 0), axis=0, keepdims=True)

                disk_bad = jnp.zeros((1, n), dtype=jnp.bool_)
                slot_rows = []  # (vid, valid, ro, kind, any_row, new_row)
                count_new = [jnp.zeros((1, n), dtype=jnp.int32) for _ in range(k)]
                has_kind = [jnp.int32(0) for _ in range(k)]
                for s in range(w):
                    packed = pod_vol[i, s]
                    vid = packed // 64
                    kind = (packed // 8) % 8
                    ro = (packed // 4) % 2
                    co = (packed // 2) % 2  # count-only: sentinel row, no write
                    valid = packed % 2
                    row = vol_row(vid)  # [1, N]
                    any_row = row % 2
                    ns_row = row // 2
                    blocked = jnp.where(ro > 0, ns_row, any_row)
                    disk_bad = disk_bad | ((valid > 0) & (blocked > 0))
                    new_row = jnp.where(valid > 0, 1 - any_row, 0)  # [1, N]
                    slot_rows.append((vid, valid, ro, co, kind, any_row, new_row))
                    for kk in range(k):
                        kin = (kind == kk) & (valid > 0)
                        count_new[kk] = count_new[kk] + jnp.where(kin, new_row, 0)
                        has_kind[kk] = has_kind[kk] | kin.astype(jnp.int32)
                vol_bad = disk_bad
                for kk in range(k):
                    over = (has_kind[kk] > 0) & (
                        nk_s[pl.ds(kk, 1), :] + count_new[kk] > _VOL_LIMITS[kk]
                    )
                    vol_bad = vol_bad | over
                feasible = feasible & ~vol_bad

            n_feasible = jnp.sum(feasible.astype(jnp.int32))

            # ---- scores (int32 fixed point; mirrors batch_kernel) ----
            cpu_req = nz_s[pl.ds(0, 1), :] + g_nz_c[0, 0]
            mem_req = nz_s[pl.ds(1, 1), :] + g_nz_c[1, 0]
            cpu_cap = alloc_t[pl.ds(0, 1), :]
            mem_cap = alloc_t[pl.ds(1, 1), :]
            total = score_raw[pl.ds(gid, 1), :]

            def usage(requested, capacity, most: bool):
                safe_cap = jnp.maximum(capacity, 1)
                if most:
                    raw = (requested * MAX_PRIORITY) // safe_cap
                else:
                    raw = ((capacity - requested) * MAX_PRIORITY) // safe_cap
                return jnp.where((capacity == 0) | (requested > capacity), 0, raw)

            if wd["least"]:
                s_ = (usage(cpu_req, cpu_cap, False) + usage(mem_req, mem_cap, False)) // 2
                total = total + wd["least"] * s_
            if wd["most"]:
                s_ = (usage(cpu_req, cpu_cap, True) + usage(mem_req, mem_cap, True)) // 2
                total = total + wd["most"] * s_
            if wd["balanced"]:
                f_cpu = (cpu_req * FIXED_POINT_ONE) // jnp.maximum(cpu_cap, 1)
                f_mem = (mem_req * FIXED_POINT_ONE) // jnp.maximum(mem_cap, 1)
                diff = jnp.abs(f_cpu - f_mem)
                sc = (MAX_PRIORITY * FIXED_POINT_ONE - diff * MAX_PRIORITY) // FIXED_POINT_ONE
                bad = (
                    (cpu_cap == 0) | (mem_cap == 0)
                    | (cpu_req >= cpu_cap) | (mem_req >= mem_cap)
                )
                total = total + wd["balanced"] * jnp.where(bad, 0, sc)
            if wd["spread"]:
                cnt = spread_s[pl.ds(gid, 1), :]  # [1, N]
                max_n = jnp.max(jnp.where(feasible, cnt, 0))
                node_fp = jnp.where(
                    max_n > 0,
                    ((max_n - cnt) * (MAX_PRIORITY * FIXED_POINT_ONE))
                    // jnp.maximum(max_n, 1),
                    MAX_PRIORITY * FIXED_POINT_ONE,
                )
                has_zone = zone[:] >= 0
                zcnt = jnp.zeros((1, n), dtype=jnp.int32)
                max_z = jnp.int32(0)
                for z in range(num_zones):
                    zs = jnp.sum(
                        jnp.where(feasible & (zone[:] == z), cnt, 0)
                    )
                    max_z = jnp.maximum(max_z, zs)
                    zcnt = jnp.where(zone[:] == z, zs, zcnt)
                zone_fp = jnp.where(
                    max_z > 0,
                    ((max_z - zcnt) * (MAX_PRIORITY * FIXED_POINT_ONE))
                    // jnp.maximum(max_z, 1),
                    MAX_PRIORITY * FIXED_POINT_ONE,
                )
                g_sp = gather_col(g_has_spread_f)  # [1, 1]
                have_zones = (g_sp[0, 0] > 0) & (
                    jnp.max((feasible & has_zone).astype(jnp.int32)) > 0
                )
                total_fp = jnp.where(
                    have_zones & has_zone, (node_fp + 2 * zone_fp) // 3, node_fp
                )
                total = total + wd["spread"] * (total_fp // FIXED_POINT_ONE)
            if wd["node_affinity"]:
                raw = aff_raw[pl.ds(gid, 1), :]
                max_c = jnp.max(jnp.where(feasible, raw, 0))
                total = total + wd["node_affinity"] * jnp.where(
                    max_c > 0, (MAX_PRIORITY * raw) // jnp.maximum(max_c, 1), 0
                )
            if wd["taint"]:
                raw = taint_raw[pl.ds(gid, 1), :]
                max_c = jnp.max(jnp.where(feasible, raw, 0))
                total = total + wd["taint"] * jnp.where(
                    max_c > 0,
                    (MAX_PRIORITY * (max_c - raw)) // jnp.maximum(max_c, 1),
                    MAX_PRIORITY,
                )
            if wd["interpod"]:
                raw = interpod_raw[pl.ds(gid, 1), :]
                if use_terms:
                    raw = raw + jnp.sum(own_w_c * dm, axis=0, keepdims=True)
                    raw = raw + jnp.sum(
                        (m_g_c * sym_w_c[:]) * downer, axis=0, keepdims=True
                    )
                max_c = jnp.maximum(0, jnp.max(jnp.where(feasible, raw, INT32_MIN)))
                min_c = jnp.minimum(0, jnp.min(jnp.where(feasible, raw, 2**31 - 1)))
                rng_ = max_c - min_c
                s_ = jnp.where(
                    rng_ > 0, (MAX_PRIORITY * (raw - min_c)) // jnp.maximum(rng_, 1), 0
                )
                total = total + wd["interpod"] * s_

            # ---- selection (selectHost + lastNodeIndex round-robin) ----
            masked = jnp.where(feasible, total, INT32_MIN)
            max_score = jnp.max(masked)
            ties = feasible & (total == max_score)
            t_count = jnp.sum(ties.astype(jnp.int32))
            idx = rr % jnp.maximum(t_count, 1)
            cum = cumsum_lanes(ties.astype(jnp.int32))
            pick_among = jnp.min(jnp.where(ties & (cum == idx + 1), lane, n))
            only = jnp.min(jnp.where(feasible, lane, n))
            chosen = jnp.where(
                n_feasible == 0,
                jnp.int32(-1),
                jnp.where(n_feasible == 1, only, pick_among).astype(jnp.int32),
            )
            if step_valid is None:
                rr_new = rr + (n_feasible >= 2).astype(jnp.int32)
            else:
                rr_new = rr + ((n_feasible >= 2) & step_valid).astype(jnp.int32)

            # ---- commit ----
            landed = (chosen >= 0) if step_valid is None \
                else (chosen >= 0) & step_valid
            safe = jnp.maximum(chosen, 0)
            oh = ((lane == safe) & landed).astype(jnp.int32)  # [1, N]
            req_s[:] = req_s[:] + g_req_c * oh
            nz_s[:] = nz_s[:] + g_nz_c * oh
            cnt_s[:] = cnt_s[:] + oh
            ports_s[:] = ports_s[:] | ((g_ports_c > 0) & (oh > 0)).astype(jnp.int32)
            spread_col = jax.lax.dot_general(
                spread_inc_f[:], e_gid,
                dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            ).astype(jnp.int32)  # [G, 1]
            spread_s[:] = spread_s[:] + spread_col * oh

            if use_terms:
                d_at_safe = jnp.sum(node_domain[:] * oh, axis=1, keepdims=True)  # [T,1]
                valid_at_safe = jnp.sum(dom_valid[:] * oh, axis=1, keepdims=True)
                same_dom = (
                    (node_domain[:] == d_at_safe)
                    & (dom_valid[:] > 0)
                    & (valid_at_safe > 0)
                )
                m_i = ((m_g_c > 0) & landed).astype(jnp.int32)  # [T, 1]
                own_i = ((own_all_c > 0) & landed).astype(jnp.int32)
                dm_s[:] = dm_s[:] + same_dom * m_i
                downer_s[:] = downer_s[:] + same_dom * own_i
                total_s[:] = total_s[:] + m_i

            if use_vols:
                for (vid, valid, ro, co, kind, any_row, new_row) in slot_rows:
                    # count-only slots aim at the sentinel row, which must
                    # stay empty: they never write occupancy
                    upd = ((valid > 0) & (co == 0) & landed & (oh > 0)).astype(jnp.int32)  # [1,N]
                    bits = upd * (1 + 2 * (1 - ro))
                    base = pl.multiple_of((vid // 8) * 8, 8)
                    blk = volf_s[pl.ds(base, 8), :].astype(jnp.int32)  # [8, N]
                    sel = sub8 == vid % 8
                    volf_s[pl.ds(base, 8), :] = jnp.where(
                        sel, blk | bits, blk
                    ).astype(jnp.int8)
                    new_at = jnp.sum(new_row * oh)  # scalar 0/1
                    for kk in range(k):
                        inc = (
                            ((kind == kk) & (valid > 0)).astype(jnp.int32)
                            * new_at
                        )
                        nk_s[pl.ds(kk, 1), :] = nk_s[pl.ds(kk, 1), :] + inc * oh

            # ---- writeback chosen ----
            row_i = i // 128
            col_i = i % 128
            crow = chosen_out[pl.ds(row_i, 1), :]
            chosen_out[pl.ds(row_i, 1), :] = jnp.where(lane128 == col_i, chosen, crow)
            return rr_new

        if k_unroll <= 1:
            rr_final = jax.lax.fori_loop(0, p_real_ref[0], body, rr0_ref[0])
        else:
            # super-steps (SURVEY §7.4.1): K sequential sub-steps per loop
            # iteration.  Same dependent chain per pod, but Mosaic gets a
            # K×-larger straightline window to overlap pod i+1's gathers
            # and static reads with pod i's commit, and pays the loop
            # bookkeeping once per K pods.  k_unroll divides p_pad (both
            # powers of two), so sub-step indices never exceed the arrays;
            # tail sub-steps carry valid=False and are inert.
            p_real = p_real_ref[0]
            n_iters = (p_real + (k_unroll - 1)) // k_unroll

            def super_body(io, rr):
                base = io * k_unroll
                for kk in range(k_unroll):
                    i = base + kk
                    rr = body(i, rr, step_valid=i < p_real)
                return rr

            rr_final = jax.lax.fori_loop(0, n_iters, super_body, rr0_ref[0])
        rr_out[0, 0] = rr_final

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(1,),
        # 25 static/table/xs inputs in VMEM; the 10 initial-state inputs in
        # HBM (DMA'd into scratch — one VMEM copy of the mutable state)
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)] * 25
        + [pl.BlockSpec(memory_space=pltpu.ANY)] * 10,
        out_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        scratch_shapes=[
            pltpu.VMEM((r, n), jnp.int32),
            pltpu.VMEM((2, n), jnp.int32),
            pltpu.VMEM((1, n), jnp.int32),
            pltpu.VMEM((pv, n), jnp.int32),
            pltpu.VMEM((g, n), jnp.int32),
            pltpu.VMEM((t, n), jnp.int32),
            pltpu.VMEM((t, n), jnp.int32),
            pltpu.VMEM((t, 1), jnp.int32),
            pltpu.VMEM((v, n), jnp.int8),
            pltpu.VMEM((k, n), jnp.int32),
            pltpu.SemaphoreType.DMA,
        ],
    )

    fn = pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct((pc, 128), jnp.int32),
            jax.ShapeDtypeStruct((1, 1), jnp.int32),
        ),
        grid_spec=grid_spec,
        compiler_params=pltpu.CompilerParams(has_side_effects=True),
    )
    return jax.jit(fn)


def _superstep_k() -> int:
    """Sub-steps per kernel loop iteration: the PallasSuperSteps gate
    picks the default (8); ``KTPU_SUPERSTEP_K`` overrides for tuning.
    Must divide 128 (the p_pad granule) — enforced by rounding down to a
    power of two."""
    import os

    from ..utils.features import DEFAULT_FEATURE_GATES

    if not DEFAULT_FEATURE_GATES.enabled("PallasSuperSteps"):
        return 1
    k = int(os.environ.get("KTPU_SUPERSTEP_K", "8"))
    k = max(1, min(128, k))
    while k & (k - 1):
        k -= 1
    return k


def schedule_batch_pallas(static: BatchStatic, init: InitialState):
    """Drop-in replacement for ``schedule_batch_arrays`` on TPU."""
    chosen2d, rr = dispatch_batch_pallas(static, init)
    return finalize_batch_pallas(static, chosen2d, rr)


def shape_key(static: BatchStatic) -> tuple:
    """The compiled-program identity for ``static`` — the same key
    ``_pallas_runner`` caches compiles on (dims + weights + structure
    flags), so a fallback-blacklist entry maps 1:1 to one compilation
    unit (backend.py's per-shape fallback: one bad shape must not take
    every other shape off the Pallas path)."""
    return (
        static.n_pad,
        static.static_ok.shape[0],
        static.term_matches_sig.shape[0],
        static.g_ports.shape[1],
        static.v_state,
        static.node_alloc.shape[1],
        static.pod_vol_ids.shape[1],
        _pod_pad(len(static.group_of_pod)),
        int(static.num_zones),
        tuple(int(static.weights.get(kk, 0)) for kk in WEIGHT_KEYS),
        bool(static.terms),
        bool(static.use_vols),
        _superstep_k(),
    )


def dispatch_batch_pallas(static: BatchStatic, init: InitialState):
    """Async half of ``schedule_batch_pallas``: dispatch and return the
    unmaterialized device arrays (see dispatch_batch_arrays)."""
    scalars, ins, p_pad = _pack(static, init)
    weights = tuple(int(static.weights.get(kk, 0)) for kk in WEIGHT_KEYS)
    # device: static — grid/shape keys are BatchStatic fields, frozen per segment build
    run = _pallas_runner(
        static.n_pad,
        static.static_ok.shape[0],
        static.term_matches_sig.shape[0],
        static.g_ports.shape[1],
        static.v_state,
        static.node_alloc.shape[1],
        static.pod_vol_ids.shape[1],
        p_pad,
        int(static.num_zones),
        weights,
        bool(static.terms),
        bool(static.use_vols),
        _superstep_k(),
    )
    out = run(*scalars, *ins)
    # enqueue the D2H transfer behind the kernel NOW: by finalize time the
    # chosen indices are already host-side (the copy rides the device's
    # shadow with the commit work instead of serializing after it — the
    # transfer is latency-bound through the device tunnel, not size-bound)
    for a in out:
        a.copy_to_host_async()
    return out


def finalize_batch_pallas(static: BatchStatic, chosen2d, rr):
    chosen = np.asarray(chosen2d).reshape(-1)[: len(static.group_of_pod)]
    return chosen, int(np.asarray(rr)[0, 0])
