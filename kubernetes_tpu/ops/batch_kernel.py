"""The batched scheduling kernel: a `lax.scan` over the pod batch where
every step is fully vectorized over the node axis.

This replaces the reference's per-pod ``scheduleOne`` loop
(``scheduler.go:253``) + 16-goroutine node parallel-for
(``generic_scheduler.go:204``, SURVEY.md P1): the node axis becomes the
TPU's vector axis (and the sharded mesh axis for multi-chip), and the
sequential-greedy cache feedback the oracle gets from ``assume`` becomes
the scan carry.  Bit-parity with the oracle holds because every operation
is int32 fixed-point (see ``scheduler/units.py``) and the selection rule
(feasibility mask → integer weighted score → argmax with round-robin
tie-break in node-axis order, counter bumped only when ≥2 nodes are
feasible — the reference's ``selectHost``/``lastNodeIndex`` semantics) is
identical on both paths.

Memory shape: dynamic state is O(N·R + G·N); per-pod static data is
O(G·N) via equivalence signatures — nothing is ever [P, N].
"""

from __future__ import annotations

from functools import lru_cache
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..models.snapshot import BatchStatic, InitialState
from ..scheduler.units import FIXED_POINT_ONE, MAX_PRIORITY
from ..utils import tracing

INT32_MIN = jnp.int32(-(2**31))
INT32_MAX = jnp.int32(2**31 - 1)

WEIGHT_KEYS = ("least", "most", "balanced", "spread", "node_affinity", "taint", "interpod")


class ScanState(NamedTuple):
    requested: jnp.ndarray  # [N, R] int32
    nonzero_requested: jnp.ndarray  # [N, 2] int32
    pod_count: jnp.ndarray  # [N] int32
    ports_used: jnp.ndarray  # [N, Pv] bool
    spread_counts: jnp.ndarray  # [G, N] int32
    round_robin: jnp.ndarray  # [] int32
    # phase B: affinity-term domain counters + volume occupancy
    dm: jnp.ndarray  # [T, N] int32 pods matching term t in node n's domain
    downer: jnp.ndarray  # [T, N] int32 placed term owners in node n's domain
    total_match: jnp.ndarray  # [T] int32 pods matching term t anywhere
    vol_any: jnp.ndarray  # [V, N] bool
    vol_ns: jnp.ndarray  # [V, N] bool non-sharable instance present
    nk: jnp.ndarray  # [K, N] int32 distinct limited-kind disks
    # frontier mode: per-signature monotone-feasibility plane.  Row g is
    # ANDed each step a sig-g pod is processed with the MONOTONE filter
    # components (resource fit, pod-count, ports, required-anti-affinity
    # hits) — once a column goes infeasible for g it can never come back
    # within the segment, so still_ok over-approximates every FUTURE
    # pod's feasibility and its G-union is a safe compaction mask.  The
    # non-monotone terms (own required-affinity / first-pod rule, which
    # dm growth can turn BACK on) deliberately stay out.  None outside
    # frontier mode (an empty pytree leaf: zero carry cost).
    still_ok: "jnp.ndarray | None" = None  # [G, N] bool


class StaticArrays(NamedTuple):
    """Device-resident static arrays (a pytree of arrays only — scalars that
    change compilation live in the cached-runner key instead)."""

    node_exists: jnp.ndarray  # [N] bool
    node_alloc: jnp.ndarray  # [N, R] int32
    node_alloc_pods: jnp.ndarray  # [N] int32
    node_zone: jnp.ndarray  # [N] int32
    static_ok: jnp.ndarray  # [G, N] bool
    node_aff_raw: jnp.ndarray  # [G, N] int32
    taint_intol_raw: jnp.ndarray  # [G, N] int32
    static_score: jnp.ndarray  # [G, N] int32
    interpod_raw: jnp.ndarray  # [G, N] int32
    g_request: jnp.ndarray  # [G, R] int32
    g_nonzero: jnp.ndarray  # [G, 2] int32
    g_ports: jnp.ndarray  # [G, Pv] bool
    g_has_spread: jnp.ndarray  # [G] bool
    spread_inc: jnp.ndarray  # [G, G] int32
    # phase B: the batch's own (anti)affinity terms
    term_matches_sig: jnp.ndarray  # [T, G] bool
    sym_w: jnp.ndarray  # [T] int32
    own_w: jnp.ndarray  # [G, T] int32
    own_ra: jnp.ndarray  # [G, T] bool
    own_raa: jnp.ndarray  # [G, T] bool
    own_all: jnp.ndarray  # [G, T] bool
    is_raa: jnp.ndarray  # [T] bool
    self_match: jnp.ndarray  # [T] bool
    node_domain: jnp.ndarray  # [T, N] int32 (trash slot id where key absent)
    dom_valid: jnp.ndarray  # [T, N] bool
    # phase B: volumes (identity rides the per-pod xs slots, not here)
    vol_limits: jnp.ndarray  # [K] int32


class DeviceNodeCache:
    """Device-resident node-axis static tensors, kept across segments and
    waves.

    ``BatchStatic.node_token`` — (instance nonce, epoch, version) stamped
    by the tensorizer's ``NodeStaticRows`` — names the node-axis state
    the host arrays were built from; the nonce keeps tokens from a
    swapped-in tensorizer (fresh epoch counter) from aliasing a stale
    cache.  Same token → the previous device
    buffers are reused with NO host→device transfer (every segment of a
    wave, and every wave against an unchanged fleet: the arrays are pure
    functions of the node objects, which the token versions).  On a new
    token the incremental path diffs each HOST array against the cached
    host copy and writes only the changed columns (``.at[js].set``) —
    diffing the arrays themselves, not trusting the dirty-node list,
    because a single node change can move OTHER columns' values (e.g. a
    zone relabel shifts the first-occurrence zone_vocab ids of every
    node).  Bulk changes fall back to a full upload — always correct,
    just not incremental."""

    FIELDS = ("node_exists", "node_alloc", "node_alloc_pods", "node_zone")

    def __init__(self):
        self._token = None
        self._arrays = None
        self._host = None  # host-side copies backing the device arrays
        self._mesh = None
        self._mesh_key = None
        self.stats = {"reuses": 0, "col_updates": 0, "uploads": 0,
                      "dirty_cols": 0, "cols_total": 0,
                      "shard_dirty_cols": [], "shard_cols_total": []}

    def set_mesh(self, mesh) -> None:
        """Bind (or clear, ``mesh=None``) the node-axis mesh uploads are
        committed to.  The mesh identity joins the cache token, so
        sharded and single-device entries never alias; binding a
        different mesh simply misses on the next lookup and re-uploads.
        Also (re)sets the per-shard dirty/total column counters the
        scheduler's per-shard upload-fraction attribution reads."""
        if mesh is None:
            key, n_shards = None, 0
        else:
            key = (tuple(mesh.shape.items()),
                   tuple(int(d.id) for d in mesh.devices.flat))
            n_shards = int(mesh.size)
        if key != self._mesh_key:
            self._mesh = mesh
            self._mesh_key = key
            self.stats["shard_dirty_cols"] = [0] * n_shards
            self.stats["shard_cols_total"] = [0] * n_shards

    def _note_shard_dirty(self, js, n: int) -> None:
        """Attribute dirty columns to the shard that will receive the
        upload bytes (``js=None`` = full-plane rewrite)."""
        ns = len(self.stats["shard_dirty_cols"])
        if not ns or n % ns:
            return
        n_loc = n // ns
        if js is None:
            for s in range(ns):
                self.stats["shard_dirty_cols"][s] += n_loc
        else:
            counts = np.bincount(
                np.asarray(js, dtype=np.int64) // n_loc, minlength=ns)
            for s in range(ns):
                self.stats["shard_dirty_cols"][s] += int(counts[s])

    def _note_shard_total(self, n: int) -> None:
        ns = len(self.stats["shard_cols_total"])
        if not ns or n % ns:
            return
        n_loc = n // ns
        for s in range(ns):
            self.stats["shard_cols_total"][s] += n_loc

    def _shard_put(self, arr):
        """Host→device with the node axis partitioned over the bound
        mesh.  Widths that don't divide the shard count fall back to a
        plain transfer (the sharded dispatch path pads segment widths to
        the shard count, so this only triggers for cache users outside
        the sharded loop — correct either way, GSPMD follows whatever
        sharding the inputs carry)."""
        if self._mesh is None or int(arr.shape[0]) % max(int(self._mesh.size), 1):
            return jnp.asarray(arr)
        from jax.sharding import NamedSharding, PartitionSpec
        axis = tuple(self._mesh.shape.keys())[0]
        spec = PartitionSpec(*([axis] + [None] * (arr.ndim - 1)))
        return jax.device_put(np.asarray(arr), NamedSharding(self._mesh, spec))

    @staticmethod
    def _host_val(static: BatchStatic, f: str):
        """The field as the DEVICE wants it: node_alloc is resource-axis
        sliced here (not after the cache) so the cached buffer IS the
        buffer the kernel consumes — repeated same-token calls return
        identical device arrays with no per-segment gather."""
        arr = getattr(static, f)
        r_sel = getattr(static, "r_sel", None)
        if f == "node_alloc" and r_sel is not None:
            arr = arr[:, r_sel]
        return arr

    def _token_for(self, static: BatchStatic):
        tok = static.node_token
        r_sel = getattr(static, "r_sel", None)
        if tok is not None and r_sel is not None:
            # a changed resource selection changes the cached node_alloc
            # SHAPE — it must never alias a same-(epoch, version) entry
            tok = tok + (tuple(int(r) for r in r_sel),)
        if tok is not None and self._mesh_key is not None:
            # sharded placements must never alias single-device entries
            tok = tok + (self._mesh_key,)
        return tok

    def _upload(self, static: BatchStatic) -> tuple:
        return tuple(self._shard_put(self._host_val(static, f))
                     for f in self.FIELDS)

    @staticmethod
    def _changed_cols(new: np.ndarray, old: np.ndarray):
        diff = new != old
        if diff.ndim > 1:
            diff = diff.any(axis=tuple(range(1, diff.ndim)))
        return np.nonzero(diff)[0]

    def node_arrays(self, static: BatchStatic) -> tuple:
        tok = self._token_for(static)
        n = len(static.node_exists)
        if tok is None:
            # cache bypassed (no persistent rows): a full upload every
            # call — counted as all-dirty so the upload-fraction metric
            # reads 1.0, not a spurious "fully resident"
            self.stats["uploads"] += 1
            self.stats["dirty_cols"] += n
            self.stats["cols_total"] += n
            self._note_shard_dirty(None, n)
            self._note_shard_total(n)
            return self._upload(static)
        self.stats["cols_total"] += n
        self._note_shard_total(n)
        if self._token == tok and self._arrays is not None:
            self.stats["reuses"] += 1
            return self._arrays
        host = tuple(np.array(self._host_val(static, f)) for f in self.FIELDS)
        incremental = (
            self._arrays is not None and self._host is not None
            and self._token is not None and self._token[0] == tok[0]
            and all(h.shape == o.shape for h, o in zip(host, self._host)))
        if incremental:
            arrays = []
            dirty_total = 0
            for new_h, old_h, arr in zip(host, self._host, self._arrays):
                js = self._changed_cols(new_h, old_h)
                dirty_total += len(js)
                self._note_shard_dirty(js, n)
                if len(js) == 0:
                    arrays.append(arr)
                elif len(js) <= max(1, n // 8):
                    # in-place column scatter: GSPMD keeps the result on
                    # the input's (possibly node-sharded) placement, so
                    # only the owning shards receive update bytes
                    jdev = jnp.asarray(js.astype(np.int32))
                    arrays.append(arr.at[jdev].set(jnp.asarray(new_h[js])))
                else:
                    arrays.append(self._shard_put(new_h))
            arrays = tuple(arrays)
            self.stats["col_updates"] += 1
            self.stats["dirty_cols"] += dirty_total
        else:
            arrays = self._upload(static)
            self.stats["uploads"] += 1
            self.stats["dirty_cols"] += n
            self._note_shard_dirty(None, n)
        self._token = tok
        self._arrays = arrays
        self._host = host
        return arrays


def to_device(static: BatchStatic,
              node_cache: "DeviceNodeCache | None" = None) -> StaticArrays:
    # resource-axis tightening: slots no signature in the segment requests
    # are inert in the step (`g_req > 0` masks them to True in fit, and
    # the commit adds zero), so the device arrays carry only the selected
    # slots.  r_sel always keeps CPU_MILLI/MEM_MIB at positions 0/1 — the
    # scoring formulas index them positionally.  Host arrays stay
    # full-width for the oracle/commit paths; the slice happens at upload
    # (DeviceNodeCache._host_val on the cached path, here otherwise).
    r_sel = getattr(static, "r_sel", None)
    if node_cache is not None:
        node_exists, node_alloc, node_alloc_pods, node_zone = (
            node_cache.node_arrays(static))
    else:
        node_exists = jnp.asarray(static.node_exists)
        node_alloc = jnp.asarray(
            static.node_alloc if r_sel is None else static.node_alloc[:, r_sel])
        node_alloc_pods = jnp.asarray(static.node_alloc_pods)
        node_zone = jnp.asarray(static.node_zone)
    g_request = static.g_request
    if r_sel is not None:
        g_request = g_request[:, r_sel]
    return StaticArrays(
        node_exists=node_exists,
        node_alloc=node_alloc,
        node_alloc_pods=node_alloc_pods,
        node_zone=node_zone,
        static_ok=jnp.asarray(static.static_ok),
        node_aff_raw=jnp.asarray(static.node_aff_raw),
        taint_intol_raw=jnp.asarray(static.taint_intol_raw),
        static_score=jnp.asarray(static.static_score),
        interpod_raw=jnp.asarray(static.interpod_raw),
        g_request=jnp.asarray(g_request),
        g_nonzero=jnp.asarray(static.g_nonzero),
        g_ports=jnp.asarray(static.g_ports),
        g_has_spread=jnp.asarray(static.g_has_spread),
        spread_inc=jnp.asarray(static.spread_inc),
        term_matches_sig=jnp.asarray(static.term_matches_sig),
        sym_w=jnp.asarray(static.sym_w),
        own_w=jnp.asarray(static.own_w),
        own_ra=jnp.asarray(static.own_ra),
        own_raa=jnp.asarray(static.own_raa),
        own_all=jnp.asarray(static.own_all),
        is_raa=jnp.asarray(static.is_raa),
        self_match=jnp.asarray(static.self_match),
        node_domain=jnp.asarray(static.node_domain),
        dom_valid=jnp.asarray(static.dom_valid),
        vol_limits=jnp.asarray(static.vol_limits),
    )


def batch_xs(static: BatchStatic, min_length: int = 512):
    """Per-pod scan inputs, padded to a power-of-two bucket length so the
    scan's trip count (and therefore the compiled executable) is stable
    across batches: with the backend's max_segment_pods also a power of
    two, every full segment and every tail lands in the same bucket.
    Padded entries carry valid=False and are inert in the step."""
    p_real = len(static.group_of_pod)
    p_pad = max(min_length, 1)
    while p_pad < p_real:
        p_pad *= 2
    w = static.pod_vol_ids.shape[1]
    gids = np.zeros(p_pad, dtype=np.int32)
    gids[:p_real] = static.group_of_pod
    pvalid = np.zeros(p_pad, dtype=bool)
    pvalid[:p_real] = True
    vids = np.full((p_pad, w), static.v_state - 1, dtype=np.int32)
    vids[:p_real] = static.pod_vol_ids
    vval = np.zeros((p_pad, w), dtype=bool)
    vval[:p_real] = static.pod_vol_valid
    vro = np.zeros((p_pad, w), dtype=bool)
    vro[:p_real] = static.pod_vol_ro_ok
    vkind = np.zeros((p_pad, w), dtype=np.int32)
    vkind[:p_real] = static.pod_vol_kind
    vco = np.zeros((p_pad, w), dtype=bool)
    if static.pod_vol_count_only is not None:
        vco[:p_real] = static.pod_vol_count_only
    return (
        jnp.asarray(gids),
        jnp.asarray(pvalid),
        jnp.asarray(vids),
        jnp.asarray(vval),
        jnp.asarray(vro),
        jnp.asarray(vkind),
        jnp.asarray(vco),
    )


def state_to_device(init: InitialState, r_sel=None,
                    use_frontier: bool = False) -> ScanState:
    requested = init.requested if r_sel is None else init.requested[:, r_sel]
    return ScanState(
        requested=jnp.asarray(requested),
        nonzero_requested=jnp.asarray(init.nonzero_requested),
        pod_count=jnp.asarray(init.pod_count),
        ports_used=jnp.asarray(init.ports_used),
        spread_counts=jnp.asarray(init.spread_counts),
        round_robin=jnp.asarray(init.round_robin, dtype=jnp.int32),
        dm=jnp.asarray(init.dm),
        downer=jnp.asarray(init.downer),
        total_match=jnp.asarray(init.total_match),
        vol_any=jnp.asarray(init.vol_any),
        vol_ns=jnp.asarray(init.vol_ns),
        nk=jnp.asarray(init.nk),
        still_ok=(jnp.asarray(init.still_ok)
                  if use_frontier and init.still_ok is not None else None),
    )


# -- fixed-point scoring pieces (must mirror scheduler/priorities.py) -------


def _idiv(a, b):
    """int32 floor division, bit-identical to ``a // b`` on every lane the
    scoring formulas SELECT, computed as an f32 division plus a one-step
    integer fixup — variable-divisor int32 division has no SIMD lowering
    on CPU and scalarized into the single most expensive scoring op.

    Exactness: every selected lane of every caller has divisor 1 <= b <=
    2^24 (node capacities, normalization maxima) and true quotient |q| <=
    MAX_PRIORITY * FIXED_POINT_ONE = 10 * 1024 = 10240 < 2^23 (any
    quotient below 2^23 keeps the argument; the current scale has 64x
    headroom), so the f32 estimate (one input rounding of a, one
    correctly-rounded divide; b exact) is within |q| * 2^-22 < 1 of q —
    its floor is off by at most one, and the remainder fixup lands
    exactly on floor(a / b).  Masked-out lanes (infeasible nodes, guard
    branches of jnp.where) may hold garbage either way; they are never
    selected."""
    q0 = jnp.floor(a.astype(jnp.float32) / b.astype(jnp.float32)).astype(jnp.int32)
    r = a - q0 * b
    return q0 - (r < 0).astype(jnp.int32) + (r >= b).astype(jnp.int32)


def _usage_score(requested, capacity, most: bool):
    """least/most-requested per-resource score with the reference's guards
    (capacity==0 -> 0, requested > capacity -> 0)."""
    safe_cap = jnp.maximum(capacity, 1)
    if most:
        raw = _idiv(requested * MAX_PRIORITY, safe_cap)
    else:
        raw = _idiv((capacity - requested) * MAX_PRIORITY, safe_cap)
    return jnp.where((capacity == 0) | (requested > capacity), 0, raw)


def _balanced_score(cpu_req, cpu_cap, mem_req, mem_cap):
    f_cpu = _idiv(cpu_req * FIXED_POINT_ONE, jnp.maximum(cpu_cap, 1))
    f_mem = _idiv(mem_req * FIXED_POINT_ONE, jnp.maximum(mem_cap, 1))
    diff = jnp.abs(f_cpu - f_mem)
    score = (MAX_PRIORITY * FIXED_POINT_ONE - diff * MAX_PRIORITY) // FIXED_POINT_ONE
    bad = (cpu_cap == 0) | (mem_cap == 0) | (cpu_req >= cpu_cap) | (mem_req >= mem_cap)
    return jnp.where(bad, 0, score)


# -- cross-shard collective seams -------------------------------------------
# Identity when ``axis_name`` is None (the single-device path): the same
# step serves both the plain jit and the shard_map-wrapped wave loop, and
# these helpers are the ONLY points where shards communicate — everything
# else in the step is elementwise on the local node columns.


def _ax_sum(x, axis_name):
    return x if axis_name is None else jax.lax.psum(x, axis_name)


def _ax_max(x, axis_name):
    return x if axis_name is None else jax.lax.pmax(x, axis_name)


def _ax_min(x, axis_name):
    return x if axis_name is None else jax.lax.pmin(x, axis_name)


def _ax_any(mask, axis_name):
    """``jnp.any`` over the (possibly sharded) trailing node axis."""
    if axis_name is None:
        return jnp.any(mask, axis=-1)
    return _ax_sum(jnp.sum(mask.astype(jnp.int32), axis=-1), axis_name) > 0


def _ax_first_true(mask, offset, axis_name):
    """Global node index of the FIRST true column in GLOBAL node order:
    ``argmax`` on one device, a deterministic min-over-global-index tree
    reduce across shards (each shard offers ``offset + local_argmax`` or
    INT32_MAX when it has no hit).  Ordering by global index — never by
    shard arrival — is what keeps round-robin tie rotation bit-exact
    against the CPU oracle.  All-false masks yield INT32_MAX (sharded) /
    0 (single device); every caller guards on feasibility counts before
    consuming the result."""
    local = jnp.argmax(mask).astype(jnp.int32)
    if axis_name is None:
        return local
    cand = jnp.where(jnp.any(mask), offset + local, INT32_MAX)
    return _ax_min(cand, axis_name)


def _normalized_max(raw, feasible, reverse: bool, axis_name=None):
    """NormalizeReduce: 10*raw//max over feasible (0 if max==0); reversed
    variant returns 10 when max==0."""
    max_c = _ax_max(jnp.max(jnp.where(feasible, raw, 0)), axis_name)
    if reverse:
        return jnp.where(
            max_c > 0, _idiv(MAX_PRIORITY * (max_c - raw), jnp.maximum(max_c, 1)), MAX_PRIORITY
        )
    return jnp.where(max_c > 0, _idiv(MAX_PRIORITY * raw, jnp.maximum(max_c, 1)), 0)


def make_step(
    dev: StaticArrays, num_zones: int, w: dict, use_terms: bool = True,
    use_vols: bool = True, use_ports: bool = True, use_frontier: bool = False,
    axis_name: "str | None" = None,
):
    """Builds the scan step: (state, xs) -> (state', chosen_node).

    ``use_terms`` / ``use_vols`` / ``use_ports`` are compile-time flags
    (part of the cached runner key): segments whose batch carries no
    (anti)affinity terms, no direct-disk volumes, or no host ports skip
    those blocks entirely instead of paying the gather/scatter cost on
    inert state every step.

    ``use_frontier`` additionally maintains the ``still_ok`` carry plane
    (see ScanState): the current signature's row is ANDed with the
    monotone filter components each step, so a chunked caller can read
    the G-union between chunks and compact the node axis (frontier
    scan).  Off, the plane stays None and the step is unchanged.

    ``axis_name`` names the node-axis mesh dimension when the step runs
    under ``shard_map``: ``dev``/``state`` node planes are then per-shard
    slices and every whole-axis reduce below goes through the ``_ax_*``
    collectives so scores, tie sets, and the chosen GLOBAL node index are
    identical to the single-device trace.  None (the default) keeps every
    reduce local and the step byte-for-byte equivalent to the unsharded
    kernel."""

    n_local = dev.node_exists.shape[0]  # per-shard width under shard_map
    if axis_name is None:
        offset = jnp.int32(0)
    else:
        # global index of this shard's first column: shards are laid out
        # in node order along the 1-D mesh, so offset + local index IS
        # the original node-axis position
        offset = jax.lax.axis_index(axis_name).astype(jnp.int32) * n_local
    col_ids = offset + jnp.arange(n_local, dtype=jnp.int32)  # [N] global ids

    # Zone membership as a [Z, N] one-hot contraction matrix, hoisted out
    # of the step (scan treats closed-over values as loop constants): the
    # per-step `.at[zone_idx].add` scatter plus `zsum[zone_idx]` gather
    # scalarize on CPU and were the single most expensive ops of the plain
    # step (~300us/pod at N=5120); the matvec form is SIMD-friendly and
    # bit-identical (int32 adds in a different association order — exact).
    has_zone = dev.node_zone >= 0
    zone_idx = jnp.where(has_zone, dev.node_zone, 0)
    zone_onehot = (
        (jnp.arange(num_zones, dtype=jnp.int32)[:, None] == zone_idx[None, :])
        & has_zone[None, :]
    ).astype(jnp.int32)  # [Z, N]

    def step(state: ScanState, xs):
        # per-pod inputs: signature id, validity (False = scan-length
        # padding), and the pod's volume slots
        gid, pvalid, vol_ids, vol_valid, vol_ro_ok, vol_kind, vol_count_only = xs
        g_req = dev.g_request[gid]  # [R]
        g_nz = dev.g_nonzero[gid]  # [2]
        g_ports = dev.g_ports[gid]  # [Pv]

        # -- feasibility (filters) ------------------------------------
        # kernel: implements GeneralPredicates
        # (resources/pod-count/ports live here; the host/selector parts and
        # the node-condition predicates ride static_ok — models/snapshot.py)
        fit = jnp.all(
            jnp.where(g_req > 0, state.requested + g_req <= dev.node_alloc, True), axis=1
        )
        pods_ok = state.pod_count + 1 <= dev.node_alloc_pods

        feasible = dev.static_ok[gid] & fit & pods_ok & dev.node_exists
        if use_ports:
            ports_ok = ~jnp.any(state.ports_used & g_ports, axis=1)
            feasible = feasible & ports_ok

        if use_terms:
            # kernel: implements MatchInterPodAffinity
            # inter-pod affinity vs ALREADY-PLACED batch pods (the static_ok
            # mask covers existing pods; these domain counters cover the scan
            # carry — the batch generalization of the oracle's work_map feedback)
            m_g = dev.term_matches_sig[:, gid]  # [T] bool: pod in term t's scope
            dm = state.dm  # [T, N] int32 (already key-masked; see InitialState)
            downer = state.downer  # [T, N]
            # symmetry: placed pods' required anti-affinity forbids their
            # domains for matching candidates (predicates.go:1146)
            sym_anti_bad = jnp.any((m_g & dev.is_raa)[:, None] & (downer > 0), axis=0)
            # the pod's own required affinity: some matching pod in-domain, or
            # the first-pod rule (no matching pod anywhere + self-match,
            # predicates.go:1196-1216)
            first_ok = (state.total_match == 0) & dev.self_match  # [T]
            ra_ok = (dm > 0) | first_ok[:, None]  # [T, N]
            own_ra_bad = jnp.any(dev.own_ra[gid][:, None] & ~ra_ok, axis=0)
            # the pod's own required anti-affinity: no matching pod in-domain
            own_raa_bad = jnp.any(dev.own_raa[gid][:, None] & (dm > 0), axis=0)
            feasible = feasible & ~sym_anti_bad & ~own_ra_bad & ~own_raa_bad

        if use_vols:
            # kernel: implements NoDiskConflict, MaxVolumeCount
            # volumes checked against placed state.
            # Only the pod's own <= W slots are touched: gather their [W, N]
            # occupancy rows instead of sweeping the whole [V, N] state.
            rows_any = state.vol_any[vol_ids]  # [W, N]
            rows_ns = state.vol_ns[vol_ids]  # [W, N]
            blocked = jnp.where(vol_ro_ok[:, None], rows_ns, rows_any)
            disk_bad = jnp.any(vol_valid[:, None] & blocked, axis=0)
            new_v = vol_valid[:, None] & ~rows_any  # [W, N] would-be-new instance
            k_range = jnp.arange(dev.vol_limits.shape[0], dtype=jnp.int32)
            k_onehot = (
                (k_range[:, None] == vol_kind[None, :]) & vol_valid[None, :]
            ).astype(jnp.int32)  # [K, W]
            count_new = k_onehot @ new_v.astype(jnp.int32)  # [K, N]
            has_kind = jnp.any(k_onehot > 0, axis=1)  # [K]
            over = has_kind[:, None] & (state.nk + count_new > dev.vol_limits[:, None])
            vol_bad = disk_bad | jnp.any(over, axis=0)
            feasible = feasible & ~vol_bad
        n_feasible = _ax_sum(jnp.sum(feasible.astype(jnp.int32)), axis_name)

        if use_frontier:
            # monotone components ONLY: fit/pods/ports can only get worse
            # as the carry grows, and the required-anti hits (downer / dm
            # only ever increase) likewise — a False here is False for
            # the rest of the segment.  Volume conflicts are per-POD
            # (disk ids are off the signature axis) and own required
            # affinity can RESURRECT (dm growth / first-pod rule), so
            # neither belongs in the plane.  Padded steps (pvalid False)
            # leave the plane untouched.
            mono = fit & pods_ok
            if use_ports:
                mono = mono & ports_ok
            if use_terms:
                mono = mono & ~sym_anti_bad & ~own_raa_bad
            row = state.still_ok[gid]
            still_ok_new = state.still_ok.at[gid].set(
                jnp.where(pvalid, row & mono, row))
        else:
            still_ok_new = state.still_ok

        # -- scores (priorities) --------------------------------------
        cpu_req = state.nonzero_requested[:, 0] + g_nz[0]
        mem_req = state.nonzero_requested[:, 1] + g_nz[1]
        cpu_cap = dev.node_alloc[:, 0]
        mem_cap = dev.node_alloc[:, 1]
        total = dev.static_score[gid]
        if w["least"]:
            s = (_usage_score(cpu_req, cpu_cap, False) + _usage_score(mem_req, mem_cap, False)) // 2
            total = total + w["least"] * s
        if w["most"]:
            s = (_usage_score(cpu_req, cpu_cap, True) + _usage_score(mem_req, mem_cap, True)) // 2
            total = total + w["most"] * s
        if w["balanced"]:
            total = total + w["balanced"] * _balanced_score(cpu_req, cpu_cap, mem_req, mem_cap)
        if w["spread"]:
            cnt = state.spread_counts[gid]  # [N]
            max_n = _ax_max(jnp.max(jnp.where(feasible, cnt, 0)), axis_name)
            node_fp = jnp.where(
                max_n > 0,
                _idiv((max_n - cnt) * (MAX_PRIORITY * FIXED_POINT_ONE), jnp.maximum(max_n, 1)),
                MAX_PRIORITY * FIXED_POINT_ONE,
            )
            # zone blend: counts aggregated over feasible nodes per zone
            # (one-hot matvec, not scatter/gather — see zone_onehot above)
            zsum = _ax_sum(
                zone_onehot @ jnp.where(feasible & has_zone, cnt, 0),
                axis_name)  # [Z], replicated across shards
            max_z = jnp.max(zsum)
            zcnt = zsum @ zone_onehot  # [N]: zsum[zone_idx] without the gather
            zone_fp = jnp.where(
                max_z > 0,
                _idiv((max_z - zcnt) * (MAX_PRIORITY * FIXED_POINT_ONE), jnp.maximum(max_z, 1)),
                MAX_PRIORITY * FIXED_POINT_ONE,
            )
            have_zones = dev.g_has_spread[gid] & _ax_any(
                feasible & has_zone, axis_name)
            total_fp = jnp.where(have_zones & has_zone, (node_fp + 2 * zone_fp) // 3, node_fp)
            total = total + w["spread"] * (total_fp // FIXED_POINT_ONE)
        if w["node_affinity"]:
            total = total + w["node_affinity"] * _normalized_max(
                dev.node_aff_raw[gid], feasible, reverse=False,
                axis_name=axis_name
            )
        if w["taint"]:
            total = total + w["taint"] * _normalized_max(
                dev.taint_intol_raw[gid], feasible, reverse=True,
                axis_name=axis_name
            )
        if w["interpod"]:
            # static (existing pods' symmetric terms) + dynamic: the pod's
            # own soft terms against all matching pods in-domain, and placed
            # batch owners' symmetric terms against this pod
            # (interpod_affinity.go:160-186)
            raw = dev.interpod_raw[gid]
            if use_terms:
                raw = raw + dev.own_w[gid] @ dm + (m_g.astype(jnp.int32) * dev.sym_w) @ downer
            max_c = jnp.maximum(0, _ax_max(
                jnp.max(jnp.where(feasible, raw, INT32_MIN)), axis_name))
            min_c = jnp.minimum(0, _ax_min(
                jnp.min(jnp.where(feasible, raw, INT32_MAX)), axis_name))
            rng = max_c - min_c
            s = jnp.where(rng > 0, _idiv(MAX_PRIORITY * (raw - min_c), jnp.maximum(rng, 1)), 0)
            total = total + w["interpod"] * s

        # -- selection (selectHost) -----------------------------------
        masked = jnp.where(feasible, total, INT32_MIN)
        max_score = _ax_max(jnp.max(masked), axis_name)
        ties = feasible & (total == max_score)
        t_count = _ax_sum(jnp.sum(ties.astype(jnp.int32)), axis_name)
        idx = state.round_robin % jnp.maximum(t_count, 1)
        cum = jnp.cumsum(ties.astype(jnp.int32))
        if axis_name is not None:
            # cross-shard exclusive prefix of tie counts: shifting shard
            # s's local cumsum by the ties on shards < s makes ``cum``
            # the GLOBAL running tie count in node-axis order, so the
            # round-robin pick rotates over the global tie set exactly
            # as the single-device kernel (and the oracle) rotate
            t_local = jnp.sum(ties.astype(jnp.int32))
            all_t = jax.lax.all_gather(t_local, axis_name)  # [S]
            me = jax.lax.axis_index(axis_name)
            shard_ids = jnp.arange(all_t.shape[0], dtype=jnp.int32)
            cum = cum + jnp.sum(jnp.where(shard_ids < me, all_t, 0))
        pick_among_ties = _ax_first_true(
            ties & (cum == idx + 1), offset, axis_name)
        only = _ax_first_true(feasible, offset, axis_name)
        chosen = jnp.where(
            (n_feasible == 0) | ~pvalid,
            jnp.int32(-1),
            jnp.where(n_feasible == 1, only, pick_among_ties).astype(jnp.int32),
        )
        # reference: selectHost (and its counter) runs only when >=2 feasible
        rr = state.round_robin + ((n_feasible >= 2) & pvalid).astype(jnp.int32)

        # -- commit (assume) ------------------------------------------
        landed = chosen >= 0
        safe = jnp.maximum(chosen, 0)
        # ``chosen``/``safe`` are GLOBAL node indices (replicated across
        # shards); comparing against ``col_ids`` lands the onehot on the
        # owning shard's local column and zeros everywhere else
        onehot = (col_ids == safe) & landed
        oh_i = onehot.astype(jnp.int32)
        # the chosen node's column, extracted by onehot CONTRACTION, never
        # by dynamic slice: a traced index into the SHARDED node axis makes
        # GSPMD all-gather the whole [T, N]/[W, N] plane every step (the
        # exact regression assert_collective_structure guards against); the
        # contraction is elementwise on the shard + an O(T) all-reduce
        safe_onehot = col_ids == safe
        if use_terms:
            # affinity domain counters, expanded over nodes: the landed pod
            # counts toward every node sharing the chosen node's topology
            # domain for each term it matches/owns — a scatter-free
            # elementwise same-domain mask (no-op when the chosen node lacks
            # the key, mirroring the old trash-slot semantics)
            d_at_safe = _ax_sum(
                (dev.node_domain
                 * safe_onehot[None, :].astype(jnp.int32)).sum(axis=1),
                axis_name)  # [T]
            valid_at_safe = _ax_any(
                dev.dom_valid & safe_onehot[None, :], axis_name)  # [T]
            same_dom = (
                (dev.node_domain == d_at_safe[:, None])
                & dev.dom_valid
                & valid_at_safe[:, None]
            )  # [T, N]
            m_i = (m_g & landed).astype(jnp.int32)
            own_i = (dev.own_all[gid] & landed).astype(jnp.int32)
            dm_new = state.dm + same_dom * m_i[:, None]
            downer_new = state.downer + same_dom * own_i[:, None]
            total_match = state.total_match + m_i
        else:
            dm_new, downer_new, total_match = state.dm, state.downer, state.total_match
        if use_vols:
            # volume occupancy on the chosen node: scatter the pod's slots
            # into the [V, N] maps (invalid AND count-only slots aim at the
            # sentinel row, which must stay empty — mask them to write False,
            # a no-op under max)
            vol_upd = (vol_valid & ~vol_count_only & landed)[:, None] & onehot[None, :]  # [W, N]
            newv_at_safe = _ax_any(new_v & safe_onehot[None, :], axis_name)  # [W]
            newv_chosen = (vol_valid & newv_at_safe & landed).astype(jnp.int32)  # [W]
            vol_any = state.vol_any.at[vol_ids].max(vol_upd)
            vol_ns = state.vol_ns.at[vol_ids].max(vol_upd & ~vol_ro_ok[:, None])
            nk = state.nk + (k_onehot @ newv_chosen)[:, None] * oh_i[None, :]
        else:
            vol_any, vol_ns, nk = state.vol_any, state.vol_ns, state.nk
        new_state = ScanState(
            requested=state.requested + oh_i[:, None] * g_req[None, :],
            nonzero_requested=state.nonzero_requested + oh_i[:, None] * g_nz[None, :],
            pod_count=state.pod_count + oh_i,
            ports_used=(state.ports_used | (onehot[:, None] & g_ports[None, :])
                        if use_ports else state.ports_used),
            spread_counts=state.spread_counts
            + dev.spread_inc[:, gid][:, None] * oh_i[None, :],
            round_robin=rr,
            dm=dm_new,
            downer=downer_new,
            total_match=total_match,
            vol_any=vol_any,
            vol_ns=vol_ns,
            nk=nk,
            still_ok=still_ok_new,
        )
        return new_state, chosen

    return step


def monotone_plane_device(dev: StaticArrays, state: ScanState,
                          use_terms: bool, use_ports: bool) -> jnp.ndarray:
    """Device twin of ``models.snapshot.monotone_plane``: the [G, N]
    monotone-component feasibility plane at the CURRENT carry state.
    ANDed into ``still_ok`` at chunk boundaries inside the device loop
    (the ROADMAP's periodic all-G refresh): the per-step update only
    tightens the current pod's signature row, so rows of signatures that
    stopped appearing would otherwise never learn that the carry grew
    past them.  Pure over-approximation tightening — every component
    here can only get WORSE as the carry grows, so a False is a
    permanent truth and compaction semantics are unchanged."""
    # kernel: implements GeneralPredicates
    # (same resource/pod-count/port masks as the step, vectorized [G, N])
    fit = jnp.all(
        (state.requested[None, :, :] + dev.g_request[:, None, :]
         <= dev.node_alloc[None, :, :]) | (dev.g_request[:, None, :] <= 0),
        axis=2)  # [G, N]
    pods_ok = state.pod_count + 1 <= dev.node_alloc_pods  # [N]
    mono = dev.static_ok & dev.node_exists[None, :] & fit & pods_ok[None, :]
    if use_ports:
        mono = mono & ~jnp.any(
            state.ports_used[None, :, :] & dev.g_ports[:, None, :], axis=2)
    if use_terms:
        raa_bad = (dev.own_raa.astype(jnp.int32)
                   @ (state.dm > 0).astype(jnp.int32)) > 0  # [G, N]
        sym = (dev.term_matches_sig & dev.is_raa[:, None]).astype(jnp.int32)
        sym_bad = (sym.T @ (state.downer > 0).astype(jnp.int32)) > 0  # [G, N]
        mono = mono & ~raa_bad & ~sym_bad
    return mono


@lru_cache(maxsize=64)
def _runner(num_zones: int, weights: tuple, use_terms: bool = True,
            use_vols: bool = True, use_ports: bool = True,
            use_frontier: bool = False):
    w = dict(zip(WEIGHT_KEYS, weights))

    @jax.jit
    def run(dev: StaticArrays, xs, state: ScanState):
        step = make_step(dev, num_zones, w, use_terms=use_terms,
                         use_vols=use_vols, use_ports=use_ports,
                         use_frontier=use_frontier)
        return jax.lax.scan(step, state, xs)

    return run


def _make_loop_run(num_zones: int, w: dict, use_terms: bool, use_vols: bool,
                   use_ports: bool, chunk_len: int,
                   axis_name: "str | None" = None):
    """The (unjitted) wave-loop body shared by the single-device and the
    shard_map runners.  ``axis_name`` threads through to ``make_step``:
    sharded, the in-loop still_ok/alive reduce and every score/tie reduce
    are per-shard collectives INSIDE the ``lax.while_loop`` — the shards
    advance in lockstep (cond consumes replicated scalars) with no host
    hop per chunk, and the per-shard ``alive`` slices concatenate back to
    the global mask at the loop exit."""

    def run(dev: StaticArrays, xs_full, state: ScanState, chosen_buf,
            start_chunk, n_chunks, compact_thresh):
        step = make_step(dev, num_zones, w, use_terms=use_terms,
                         use_vols=use_vols, use_ports=use_ports,
                         use_frontier=True, axis_name=axis_name)

        def alive_of(st):
            alive = jnp.any(st.still_ok, axis=0) & dev.node_exists
            return alive, _ax_sum(jnp.sum(alive.astype(jnp.int32)), axis_name)

        def cond(carry):
            _, _, c, want = carry
            return (c < n_chunks) & ~want

        def body(carry):
            st, buf, c, _ = carry
            start = c * jnp.int32(chunk_len)
            with jax.named_scope("ktpu.wave_chunk"):
                xs_c = tuple(
                    jax.lax.dynamic_slice_in_dim(a, start, chunk_len, axis=0)
                    for a in xs_full)
                st, chosen = jax.lax.scan(step, st, xs_c)
                buf = jax.lax.dynamic_update_slice(buf, chosen, (start,))
            with jax.named_scope("ktpu.still_ok_refresh"):
                st = st._replace(still_ok=st.still_ok & monotone_plane_device(
                    dev, st, use_terms, use_ports))
            _, n_alive = alive_of(st)
            return (st, buf, c + jnp.int32(1), n_alive <= compact_thresh)

        carry = (state, chosen_buf, start_chunk, jnp.bool_(False))
        state, chosen_buf, c, want = jax.lax.while_loop(cond, body, carry)
        alive, n_alive = alive_of(state)
        return state, chosen_buf, c, want, alive, n_alive

    return run


@lru_cache(maxsize=64)
def _loop_runner(num_zones: int, weights: tuple, use_terms: bool,
                 use_vols: bool, use_ports: bool, chunk_len: int):
    """The device-resident wave loop: a ``lax.while_loop`` that advances
    the frontier scan chunk by chunk entirely on device and exits only
    when the segment is done OR a compaction is worth taking — the host
    is re-entered O(compactions + 1) times per segment, independent of
    chunk count.

    Carry = (ScanState, chosen buffer [P_pad], chunk cursor, stop flag).
    ``state`` and ``chosen_buf`` are DONATED (the XLA executable reuses
    their buffers in place across iterations); callers must treat the
    passed-in arrays as consumed and must never fall back onto them —
    the backend's retry ladder re-derives everything from host arrays.
    The compaction decision is computed ON DEVICE: after each chunk the
    all-G ``still_ok`` refresh runs (see ``monotone_plane_device``) and
    the alive-union count is compared against ``compact_thresh`` (a
    host-precomputed int equivalent to the ``_pow2_width``/
    ``compact_frac`` rule; -1 = never fires).  ``n_chunks`` is a device
    operand, not a Python constant, so the pow-2 pod-axis bucket padding
    never adds loop trips."""
    w = dict(zip(WEIGHT_KEYS, weights))
    run = _make_loop_run(num_zones, w, use_terms, use_vols, use_ports,
                         chunk_len)
    return jax.jit(run, donate_argnums=(2, 3))


@lru_cache(maxsize=16)
def _sharded_loop_runner(num_zones: int, weights: tuple, use_terms: bool,
                         use_vols: bool, use_ports: bool, chunk_len: int,
                         mesh):
    """``_loop_runner``'s wave loop wrapped in ``shard_map`` over a 1-D
    node-axis mesh: every node-axis plane of StaticArrays/ScanState is
    partitioned (``parallel.mesh.loop_in_specs``), the pod-axis xs and
    the chosen buffer are replicated, and every whole-axis reduce inside
    the loop is a psum/pmax/pmin collective (see ``make_step``'s
    ``axis_name``) — the cross-host sync budget stays O(compactions + 1)
    per wave because the loop never leaves the device between chunks.

    Donation carries through shard_map unchanged (state and chosen
    buffer are reused in place across loop runs), which is what lets
    DC601's use-after-donate tracking extend through the sharded
    dispatch chain.  ``check_rep=False``: the replicated scalar outputs
    (cursor, stop flag, alive count) are provably identical on every
    shard — they are pure functions of psum/pmax results — but shard_map
    cannot prove it through ``lax.while_loop``."""
    from jax.experimental.shard_map import shard_map

    from ..parallel.mesh import NODE_AXIS, loop_in_specs, loop_out_specs

    w = dict(zip(WEIGHT_KEYS, weights))
    run = _make_loop_run(num_zones, w, use_terms, use_vols, use_ports,
                         chunk_len, axis_name=NODE_AXIS)
    sharded = shard_map(run, mesh=mesh, in_specs=loop_in_specs(),
                        out_specs=loop_out_specs(), check_rep=False)
    return jax.jit(sharded, donate_argnums=(2, 3))


def _sharded_loop_runner_for(static: BatchStatic, chunk_len: int, mesh):
    weights = tuple(int(static.weights.get(k, 0)) for k in WEIGHT_KEYS)
    return _sharded_loop_runner(  # device: static — mesh identity is a hashable per-device-set constant; one compile per (mesh, key)
        int(static.num_zones),
        weights,
        bool(static.terms),
        bool(static.use_vols),
        bool(getattr(static, "use_ports", True)),
        int(chunk_len),
        mesh,
    )


def _loop_runner_for(static: BatchStatic, chunk_len: int):
    weights = tuple(int(static.weights.get(k, 0)) for k in WEIGHT_KEYS)
    return _loop_runner(
        int(static.num_zones),
        weights,
        bool(static.terms),
        bool(static.use_vols),
        bool(getattr(static, "use_ports", True)),
        int(chunk_len),
    )


def _runner_for(static: BatchStatic, use_frontier: bool = False):
    weights = tuple(int(static.weights.get(k, 0)) for k in WEIGHT_KEYS)
    return _runner(
        int(static.num_zones),
        weights,
        use_terms=bool(static.terms),
        use_vols=bool(static.use_vols),
        use_ports=bool(getattr(static, "use_ports", True)),
        use_frontier=use_frontier,
    )


def dispatch_batch_arrays(static: BatchStatic, init: InitialState,
                          node_cache: "DeviceNodeCache | None" = None):
    """Async half: dispatch the scan and return the UNMATERIALIZED jax
    arrays (futures).  The caller may run host work while the device
    executes, then block via ``finalize_batch_arrays`` — the overlap seam
    the pipelined backend commits previous-segment bindings in."""
    dev = to_device(static, node_cache=node_cache)
    state = state_to_device(init, r_sel=getattr(static, "r_sel", None))
    xs = batch_xs(static)
    run = _runner_for(static)
    # XLA-profiler attribution: device time of this dispatch shows up
    # under this annotation (host-side trace spans stay as they are)
    with jax.profiler.TraceAnnotation("ktpu.wave_scan"):
        final_state, chosen = run(dev, xs, state)
    # enqueue the D2H transfer behind the scan (see dispatch_batch_pallas)
    chosen.copy_to_host_async()
    final_state.round_robin.copy_to_host_async()
    return chosen, final_state.round_robin


def finalize_batch_arrays(static: BatchStatic, chosen, rr) -> tuple[np.ndarray, int]:
    return np.asarray(chosen)[: len(static.group_of_pod)], int(rr)


def schedule_batch_arrays(static: BatchStatic, init: InitialState) -> tuple[np.ndarray, int]:
    """Run the kernel; returns (chosen node index per pod [-1 = unschedulable],
    final round-robin counter)."""
    chosen, rr = dispatch_batch_arrays(static, init)
    return finalize_batch_arrays(static, chosen, rr)


# -- frontier scan: chunked execution + mid-segment node-axis compaction ----

# StaticArrays fields carrying a node axis, with the axis position.
_STATIC_NODE_AXES = {
    "node_exists": 0, "node_alloc": 0, "node_alloc_pods": 0, "node_zone": 0,
    "static_ok": 1, "node_aff_raw": 1, "taint_intol_raw": 1,
    "static_score": 1, "interpod_raw": 1, "node_domain": 1, "dom_valid": 1,
}
# ScanState fields carrying a node axis (still_ok handled explicitly).
_STATE_NODE_AXES = {
    "requested": 0, "nonzero_requested": 0, "pod_count": 0, "ports_used": 0,
    "spread_counts": 1, "dm": 1, "downer": 1, "vol_any": 1, "vol_ns": 1,
    "nk": 1,
}


def _pow2_width(n: int, min_width: int) -> int:
    w = max(min_width, 1)
    while w < n:
        w *= 2
    return w


def gather_node_axis(dev: StaticArrays, state: ScanState, js: np.ndarray,
                     width: int) -> tuple[StaticArrays, ScanState]:
    """Device-side node-axis compaction: gather the kept columns ``js``
    (node-axis order preserved — the round-robin tie-break walks the axis
    in order, so relative order IS semantics) of every node-axis plane of
    the statics and the carry onto a ``width``-column buffer.  Positions
    past ``len(js)`` are padding: their ``node_exists`` / ``still_ok``
    are forced False, which makes every other plane's garbage there
    unreachable (feasible ≡ False).

    Parity: excluded columns are provably inert — every normalization,
    tie set, and n_feasible ranges over *feasible* columns only, and a
    column is dropped only when ``still_ok`` (the monotone
    over-approximation of every future pod's feasibility) has it False
    for ALL signatures.  The caller maps chosen indices back through its
    cumulative permutation."""
    # kernel: implements GeneralPredicates
    # (the compaction consumes the same monotone filter verdicts the step
    # computes; gathering them preserves each column's masks bit-for-bit)
    k = len(js)
    idx_host = np.zeros(width, dtype=np.int32)
    idx_host[:k] = js
    idx = jnp.asarray(idx_host)
    pad_mask = jnp.asarray(np.arange(width) < k)

    def take(arr, axis):
        return jnp.take(arr, idx, axis=axis)

    dev_new = dev._replace(**{
        f: take(getattr(dev, f), ax) for f, ax in _STATIC_NODE_AXES.items()
    })
    dev_new = dev_new._replace(node_exists=dev_new.node_exists & pad_mask)
    st_new = state._replace(**{
        f: take(getattr(state, f), ax) for f, ax in _STATE_NODE_AXES.items()
    })
    if state.still_ok is not None:
        st_new = st_new._replace(
            still_ok=take(state.still_ok, 1) & pad_mask[None, :])
    return dev_new, st_new


def _host_xs(static: BatchStatic):
    """The per-pod scan inputs as UNPADDED host numpy arrays — the
    frontier loop slices chunks out of these and pads each chunk to the
    chunk bucket (padding entries are pvalid=False, inert)."""
    p_real = len(static.group_of_pod)
    w = static.pod_vol_ids.shape[1]
    vco = np.zeros((p_real, w), dtype=bool)
    if static.pod_vol_count_only is not None:
        vco[:] = static.pod_vol_count_only
    return (
        np.asarray(static.group_of_pod, dtype=np.int32),
        np.ones(p_real, dtype=bool),
        np.asarray(static.pod_vol_ids, dtype=np.int32),
        np.asarray(static.pod_vol_valid, dtype=bool),
        np.asarray(static.pod_vol_ro_ok, dtype=bool),
        np.asarray(static.pod_vol_kind, dtype=np.int32),
        vco,
    )


def _chunk_xs(host_xs, start: int, chunk_len: int, v_sentinel: int):
    gids, pvalid, vids, vval, vro, vkind, vco = host_xs
    p_real = len(gids)
    end = min(start + chunk_len, p_real)
    n = end - start
    w = vids.shape[1]
    cg = np.zeros(chunk_len, dtype=np.int32)
    cg[:n] = gids[start:end]
    cp = np.zeros(chunk_len, dtype=bool)
    cp[:n] = True
    cv = np.full((chunk_len, w), v_sentinel, dtype=np.int32)
    cv[:n] = vids[start:end]
    cvv = np.zeros((chunk_len, w), dtype=bool)
    cvv[:n] = vval[start:end]
    cvr = np.zeros((chunk_len, w), dtype=bool)
    cvr[:n] = vro[start:end]
    cvk = np.zeros((chunk_len, w), dtype=np.int32)
    cvk[:n] = vkind[start:end]
    cvc = np.zeros((chunk_len, w), dtype=bool)
    cvc[:n] = vco[start:end]
    return tuple(jnp.asarray(a) for a in (cg, cp, cv, cvv, cvr, cvk, cvc))


class FrontierRun:
    """One segment's frontier execution.  Two drive modes share the same
    carry plane, compaction rule, and parity contract:

    - ``device_loop=True`` (the device-resident wave loop): ONE
      ``lax.while_loop`` dispatch advances every chunk on device with
      donated carries; the compaction decision is a device-computed
      flag checked inside the loop, so the host is re-entered only when
      a compaction is worth taking (it performs the dynamic-shape
      ``gather_node_axis`` and re-enters the loop at the new
      power-of-two width).  Host syncs per segment: one control read
      per loop run + the final result read = O(compactions + 1),
      independent of chunk count.
    - ``device_loop=False`` (the chunked host loop, also the fallback
      when the loop form fails): the host dispatches each chunk,
      reading the alive-union count back between chunks — O(chunks)
      syncs.

    ``__init__`` dispatches the first loop run / chunk and returns (the
    async seam the backend commits prior segments in — ``device_probe``
    polls it); ``finalize()`` drives the rest and returns chosen
    indices in the ORIGINAL node axis plus the final round-robin
    counter.  ``stats["host_syncs"]`` counts every blocking
    device→host round-trip this run performed — the seam the
    scheduler's per-wave ``host_syncs`` accounting deltas.

    Donation contract (loop mode): the ScanState and the chosen buffer
    are donated to each loop dispatch — after a dispatch the previous
    arrays are dead, and any failure path must rebuild from HOST data
    (the backend's full-width retry re-tensorizes from the original
    static/init, which donation never touches)."""

    def __init__(self, static: BatchStatic, init: InitialState,
                 node_cache: "DeviceNodeCache | None" = None,
                 chunk_len: int = 512, compact_frac: float = 0.5,
                 min_width: int = 128, on_compact=None,
                 device_loop: bool = False, on_loop=None, mesh=None):
        self.static = static
        self.chunk_len = chunk_len
        self.compact_frac = compact_frac
        self.min_width = min_width
        self.on_compact = on_compact
        self.on_loop = on_loop
        self.device_loop = bool(device_loop)
        self.mesh = mesh if device_loop else None
        self._p_real = len(static.group_of_pod)
        self._dev = to_device(static, node_cache=node_cache)
        self._state = state_to_device(
            init, r_sel=getattr(static, "r_sel", None), use_frontier=True)
        if self._state.still_ok is None:
            raise ValueError("frontier run requires init.still_ok (seed the "
                             "InitialState via models.snapshot.frontier_seed)")
        self._width = int(static.n_pad)
        # cumulative permutation: current column position -> original
        # full-axis index (chosen indices map back through the snapshot
        # of this array taken at each dispatch)
        self._map = np.arange(self._width, dtype=np.int64)
        self.stats = {"chunks": 0, "compactions": 0,
                      "alive_frac": [], "widths": [self._width],
                      "host_syncs": 0, "loop_runs": 0}
        if self.device_loop:
            if chunk_len <= 0 or chunk_len & (chunk_len - 1):
                raise ValueError(
                    "device_loop requires a power-of-two chunk_len (the "
                    "pod-axis bucket must be chunk-divisible)")
            # whole-segment xs uploaded ONCE: the pod axis is invariant
            # under node compaction, so every re-entry reuses this upload
            self._xs_full = batch_xs(static)
            p_pad = int(self._xs_full[0].shape[0])  # pow2, >= chunk bucket
            self._chunk_eff = min(chunk_len, p_pad)
            if self.mesh is not None:
                ns = int(self.mesh.size)
                if ns < 2 or ns & (ns - 1):
                    raise ValueError(
                        "mesh mode requires a power-of-two shard count >= 2")
                if self._width % ns:
                    raise ValueError(
                        f"segment width {self._width} not divisible by {ns} "
                        "shards (pad via snapshot.pad_segment_to_multiple)")
                from ..parallel import mesh as pmesh
                # compaction widths must stay shard-divisible: every
                # pow-2 width >= the pow-2 shard count divides evenly
                self.min_width = max(self.min_width, ns)
                self._dev = pmesh.place_static(self._dev, self.mesh)
                self._state = pmesh.place_state(self._state, self.mesh)
                self._loop = _sharded_loop_runner_for(
                    static, self._chunk_eff, self.mesh)
                self.stats["n_shards"] = ns
                self.stats["shard_alive_frac"] = []
            else:
                self._loop = _loop_runner_for(static, self._chunk_eff)
            self._n_chunks = -(-self._p_real // self._chunk_eff)
            self._buf = jnp.full((p_pad,), -1, dtype=jnp.int32)
            self._c = 0  # chunks completed (host mirror, updated at syncs)
            self._regions: list = []  # (start pod index, map snapshot)
            self._pending = None
            self._dispatch_loop()
        else:
            self._run = _runner_for(static, use_frontier=True)
            self._host_xs = _host_xs(static)
            self._chunks: list = []  # (chosen_dev, map_snapshot)
            self._next = 0
            self._dispatch_chunk()

    # -- device-resident loop drive ------------------------------------

    def _loop_thresh(self) -> int:
        """The device-side compaction trigger, as one int32: fire iff
        ``n_alive <= thresh``.  Exactly the host rule — ``_pow2_width``
        can shrink a pow-2 width iff n_alive <= width // 2 (and the
        floor allows it), and the frac gate is ``n_alive <=
        floor(compact_frac * width)`` for integer n_alive."""
        if self.min_width >= self._width:
            return -1  # width floor: no smaller pow-2 exists
        return min(self._width // 2, int(self.compact_frac * self._width))

    def _dispatch_loop(self) -> None:
        if self.on_loop is not None:
            # fault/trace seam BEFORE the dispatch: an injected loop
            # failure aborts the run and the segment falls back
            self.on_loop(self.stats["loop_runs"], self._width, self._c)
        tr = tracing.current()
        with (tr.span("frontier.loop", cat="frontier",
                      index=self.stats["loop_runs"], width=self._width,
                      start_chunk=self._c, n_chunks=self._n_chunks)
              if tr is not None else tracing.NULL_SPAN):
            with jax.profiler.TraceAnnotation("ktpu.frontier.loop"):
                out = self._loop(
                    self._dev, self._xs_full, self._state, self._buf,
                    jnp.int32(self._c), jnp.int32(self._n_chunks),
                    jnp.int32(self._loop_thresh()))
            # the donated state/buf are dead the moment the call returns;
            # rebind to the outputs before anything can raise
            self._state, self._buf = out[0], out[1]
            self._pending = out[2:]  # (c, want, alive, n_alive)
            self._regions.append((self._c * self._chunk_eff, self._map))
            self.stats["loop_runs"] += 1
            for a in self._pending[:2]:
                a.copy_to_host_async()

    def _sync_loop(self) -> tuple[bool, "jnp.ndarray", int]:
        """ONE blocking control read per loop run: the exit cursor, the
        compaction flag, and the alive count/mask arrive together (the
        loop already finished computing all of them — a single stall,
        then ready-buffer copies)."""
        c_dev, want_dev, alive, n_alive_dev = self._pending
        self._pending = None
        c_exit = int(c_dev)  # device: sync — blocks until the loop run completes; the one control stall per run
        self.stats["host_syncs"] += 1
        want = bool(want_dev)  # device: sync — compaction flag rides the same ready transfer as the cursor
        n_alive = int(n_alive_dev)  # device: sync — alive count, already host-side once the cursor read returned
        self.stats["chunks"] += c_exit - self._c
        self._c = c_exit
        frac = round(n_alive / max(self._width, 1), 4)
        self.stats["alive_frac"].append(frac)
        shard_fracs = None
        ns = self.stats.get("n_shards", 0)
        if ns and self._width % ns == 0:
            # per-shard alive split: the mask is shard-concatenated in
            # node order, so an even reshape recovers each shard's slice
            alive_h = np.asarray(alive)  # device: sync — rides the loop-exit transfer the cursor read above already stalled on
            n_loc = self._width // ns
            per = alive_h.reshape(ns, n_loc).sum(axis=1)
            shard_fracs = [round(int(c) / max(n_loc, 1), 4) for c in per]
            self.stats["shard_alive_frac"].append(shard_fracs)
        tr = tracing.current()
        if tr is not None:
            # one instant per loop EXIT (not per chunk): the pruning
            # trajectory at every host re-entry.  Per-shard fractions ride
            # the SAME instant as extra attrs — no second trace format.
            attrs = dict(frac=frac, width=self._width, chunk=self._c)
            if shard_fracs is not None:
                attrs["shards"] = shard_fracs
            tr.instant("frontier.alive", **attrs)
        return want, alive, n_alive

    def _finalize_loop(self) -> tuple[np.ndarray, int]:
        while True:
            want, alive, n_alive = self._sync_loop()
            if self._c >= self._n_chunks:
                break
            if want:
                width_new = _pow2_width(n_alive, self.min_width)  # device: static — pow2 buckets bound compiles to log2(N)
                if (width_new < self._width
                        and n_alive <= self.compact_frac * self._width):
                    if self.on_compact is not None:
                        self.on_compact(self._width, width_new, n_alive)
                    js = np.nonzero(np.asarray(alive))[0]
                    self._dev, self._state = gather_node_axis(
                        self._dev, self._state, js, width_new)
                    if self.mesh is not None:
                        # re-commit the compacted planes to the mesh: the
                        # gather ran under GSPMD and its output placement
                        # is whatever XLA chose, but the next loop run's
                        # in_specs demand clean node-axis partitions
                        from ..parallel import mesh as pmesh
                        self._dev = pmesh.place_static(self._dev, self.mesh)
                        self._state = pmesh.place_state(
                            self._state, self.mesh)
                    self._map = self._map[js]
                    self._width = width_new
                    self.stats["compactions"] += 1
                    self.stats["widths"].append(width_new)
            self._dispatch_loop()
        # final result read: the whole segment's chosen buffer at once
        buf_host = np.asarray(self._buf)  # device: sync — the whole segment's chosen buffer, once per wave
        rr = int(self._state.round_robin)  # device: sync — round-robin cursor rides the final result read
        self.stats["host_syncs"] += 1
        chosen_full = np.empty(self._p_real, dtype=np.int64)
        bounds = [start for start, _ in self._regions] + [self._p_real]
        for (start, map_snap), end in zip(self._regions, bounds[1:]):
            end = min(end, self._p_real)
            if end <= start:
                continue
            part = buf_host[start:end].astype(np.int64)
            safe = np.clip(part, 0, len(map_snap) - 1)
            chosen_full[start:end] = np.where(part >= 0, map_snap[safe], -1)
        return chosen_full, rr

    # -- chunked host-loop drive (and loop-failure fallback) -----------

    def _dispatch_chunk(self) -> None:
        tr = tracing.current()
        with (tr.span("frontier.chunk", cat="frontier",
                      index=self.stats["chunks"], width=self._width,
                      start=self._next)
              if tr is not None else tracing.NULL_SPAN):
            with jax.profiler.TraceAnnotation("ktpu.frontier.chunk"):
                xs = _chunk_xs(self._host_xs, self._next, self.chunk_len,
                               int(self.static.v_state) - 1)
                self._state, chosen = self._run(self._dev, xs, self._state)
                chosen.copy_to_host_async()
            self._chunks.append((chosen, self._map))
            self._next += self.chunk_len
            self.stats["chunks"] += 1

    @property
    def device_probe(self):
        cand = (self._pending[0] if self.device_loop and self._pending
                else self._chunks[0][0] if not self.device_loop
                else None)
        return cand if hasattr(cand, "is_ready") else None

    def _maybe_compact(self) -> None:
        alive = jnp.any(self._state.still_ok, axis=0) & self._dev.node_exists
        n_alive = int(jnp.sum(alive))  # device: sync — the one [N] reduce + sync per chunk
        self.stats["host_syncs"] += 1
        frac = round(n_alive / max(self._width, 1), 4)
        self.stats["alive_frac"].append(frac)
        tr = tracing.current()
        if tr is not None:
            # per-chunk alive fraction: the frontier's pruning trajectory
            # is readable straight off the wave trace
            tr.instant("frontier.alive", frac=frac, width=self._width,
                       chunk=self.stats["chunks"])
        width_new = _pow2_width(n_alive, self.min_width)  # device: static — pow2 buckets bound compiles to log2(N)
        if width_new >= self._width or n_alive > self.compact_frac * self._width:
            return
        if self.on_compact is not None:
            self.on_compact(self._width, width_new, n_alive)
        js = np.nonzero(np.asarray(alive))[0]  # device: sync — compaction gather indices (mask already reduced)
        self._dev, self._state = gather_node_axis(
            self._dev, self._state, js, width_new)
        self._map = self._map[js]
        self._width = width_new
        self.stats["compactions"] += 1
        self.stats["widths"].append(width_new)

    def finalize(self) -> tuple[np.ndarray, int]:
        if self.device_loop:
            return self._finalize_loop()
        while self._next < self._p_real:
            self._maybe_compact()
            self._dispatch_chunk()
        chosen_full = np.empty(self._p_real, dtype=np.int64)
        pos = 0
        for chosen_dev, map_snap in self._chunks:
            part = np.asarray(chosen_dev)  # device: sync — per-chunk result read; D2H copy was pre-staged async
            self.stats["host_syncs"] += 1
            n = min(len(part), self._p_real - pos)
            part = part[:n].astype(np.int64)
            safe = np.clip(part, 0, len(map_snap) - 1)
            chosen_full[pos:pos + n] = np.where(
                part >= 0, map_snap[safe], -1)
            pos += n
        return chosen_full, int(self._state.round_robin)  # device: sync — round-robin cursor, once per segment
