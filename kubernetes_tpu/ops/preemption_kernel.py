"""Preemption prefilter: the masked min-cost victim-threshold kernel.

SURVEY.md §7.4.7 — victim selection designed as a kernel rather than a
host scan.  For a failed cohort of priority pods, compute over the node
axis the smallest priority level v such that evicting every pod with
priority < v frees enough RESOURCES for the preemptor ("min priority
that frees enough").  That level is a provable lower bound on the exact
max-victim-priority on the node (any feasible victim set must free
enough resources, and resource feasibility is monotone in eviction even
where affinity is not), so ``scheduler/preemption.py``'s branch-and-bound
evaluates only the handful of nodes whose bound can win — instead of the
oracle's full O(nodes × pods) predicate sweep per preemptor.

State shape: levels L = sorted distinct priorities of placed pods
([Pd]); per node, cumulative freeable request vectors and counts at each
level ([Pd, N, R] / [Pd, N]).  One evicted node re-derives only its own
columns (``update_node``), so a preemption wave pays O(touched nodes).

Placement note (a deliberate TPU-systems judgment): the computation is
kernel-SHAPED — vectorized integer compares over the node axis — but it
executes in host numpy, not on the accelerator.  The operands are a few
MB and the outputs a few KB; on this platform a device round-trip costs
~0.5s of transfer latency through the tunnel while the whole compare is
sub-millisecond on host.  Putting sub-ms work across a high-latency
link would invert the win; the same arrays drop into a jnp ``jit`` 1:1
if a future topology changes that balance.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..scheduler.nodeinfo import NodeInfo
from ..scheduler.units import (
    NUM_RESOURCES,
    node_allocatable_pods,
    node_allocatable_vec,
    pod_request_vec,
)


class PreemptionState:
    """Per-snapshot victim-threshold arrays over (priority level, node)."""

    def __init__(self, node_info_map: dict[str, NodeInfo]):
        self.node_names = sorted(
            n for n, i in node_info_map.items() if i.node is not None)
        self.node_index = {n: j for j, n in enumerate(self.node_names)}
        n = len(self.node_names)
        levels: set[int] = set()
        for name in self.node_names:
            for q in node_info_map[name].pods:
                levels.add(q.spec.priority)
        self.levels = np.array(sorted(levels), dtype=np.int64)  # [Pd]
        pd = len(self.levels)
        self.alloc = np.zeros((n, NUM_RESOURCES), dtype=np.int64)
        self.alloc_pods = np.zeros(n, dtype=np.int64)
        self.requested = np.zeros((n, NUM_RESOURCES), dtype=np.int64)
        self.pod_count = np.zeros(n, dtype=np.int64)
        self.cum_req = np.zeros((pd, n, NUM_RESOURCES), dtype=np.int64)
        self.cum_cnt = np.zeros((pd, n), dtype=np.int64)
        # [N, M] reprieve-order pod arrays (lazy — see _ensure_pod_arrays)
        self._pa_built = False
        self.pp_prio = None
        self.pp_req = None
        self.pp_pods: list[list] = []
        self._vec_memo: dict = {}
        for name in self.node_names:
            self.update_node(name, node_info_map[name])

    def update_node(self, name: str, info: Optional[NodeInfo]) -> None:
        """(Re)derive one node's columns — called after its victims are
        evicted, so the next preemptor in the cohort sees the new truth."""
        j = self.node_index.get(name)
        if j is None:
            return
        if self._pa_built:
            self._refresh_pod_row(j, info)
        if info is None or info.node is None:
            # node vanished mid-cohort: zero capacity excludes it
            self.alloc[j] = 0
            self.alloc_pods[j] = 0
            self.cum_req[:, j] = 0
            self.cum_cnt[:, j] = 0
            return
        self.alloc[j] = node_allocatable_vec(info.node).units
        self.alloc_pods[j] = node_allocatable_pods(info.node)
        self.requested[j] = info.requested.units
        self.pod_count[j] = len(info.pods)
        self.cum_req[:, j] = 0
        self.cum_cnt[:, j] = 0
        if len(self.levels) == 0:
            return
        for q in info.pods:
            # pods at level L[k] are freed by any threshold > L[k]:
            # accumulate into the cumulative-≤ slot, prefix-summed below
            k = int(np.searchsorted(self.levels, q.spec.priority))
            if k >= len(self.levels) or self.levels[k] != q.spec.priority:
                continue  # priority level not in the frozen axis (new pod
                # class mid-cohort); conservative: it is never freeable
            self.cum_req[k, j] += pod_request_vec(q).units
            self.cum_cnt[k, j] += 1
        np.cumsum(self.cum_req[:, j], axis=0, out=self.cum_req[:, j])
        np.cumsum(self.cum_cnt[:, j], axis=0, out=self.cum_cnt[:, j])

    def _pod_vec(self, q) -> "np.ndarray":
        hit = self._vec_memo.get(id(q))
        if hit is None:
            hit = self._vec_memo[id(q)] = (
                q, np.asarray(pod_request_vec(q).units, dtype=np.int64))
        return hit[1]

    # -- [N, M] reprieve arrays (the vectorized greedy's operands) ------
    def _ensure_pod_arrays(self, node_info_map: dict) -> None:
        """Per-node resident pods in REPRIEVE ORDER (highest priority
        first, then key — exactly ``_evaluate_node``'s victim sort) as
        dense [N, M] arrays, so the greedy reprieve runs as M vectorized
        column passes over every node at once instead of a Python loop
        per (preemptor, node).  Rows refresh individually on eviction."""
        if self._pa_built:
            return
        n = len(self.node_names)
        self.pp_pods = [[] for _ in range(n)]
        m = 1
        for name in self.node_names:
            info = node_info_map.get(name)
            if info is not None and info.node is not None:
                m = max(m, len(info.pods))
        self.pp_prio = np.full((n, m), np.iinfo(np.int64).max, dtype=np.int64)
        self.pp_req = np.zeros((n, m, NUM_RESOURCES), dtype=np.int64)
        for name in self.node_names:
            self._refresh_pod_row(self.node_index[name], node_info_map.get(name))
        self._pa_built = True

    def _refresh_pod_row(self, j: int, info: Optional[NodeInfo]) -> None:
        pods = [] if info is None or info.node is None else list(info.pods)
        if len(pods) > self.pp_prio.shape[1]:
            # row outgrew the M axis: rebuild lazily with a larger M
            self._pa_built = False
            return
        pods.sort(key=lambda q: (-q.spec.priority, q.meta.key))
        self.pp_pods[j] = pods
        self.pp_prio[j, :] = np.iinfo(np.int64).max
        self.pp_req[j, :, :] = 0
        for c, q in enumerate(pods):
            self.pp_prio[j, c] = q.spec.priority
            self.pp_req[j, c] = self._pod_vec(q)

    def rank_arrays(self, req_units: list[int], priority: int,
                    node_info_map: dict):
        """Exact per-node preemption ranks for a FAST-ELIGIBLE preemptor
        (victim-dependent predicates = resources+count), vectorized over
        every node: the greedy reprieve runs as M sequential column
        passes (column order = reprieve order), identical decisions to
        ``scheduler/preemption._evaluate_node``.

        Returns (ok[N], max_prio[N], n_vict[N], total_req[N], victim
        mask [N, M]); the caller materializes the winner's victim list
        from ``pp_pods`` + the mask row and applies the node-static
        predicate gate."""
        self._ensure_pod_arrays(node_info_map)
        req = np.asarray(req_units, dtype=np.int64)
        lower = self.pp_prio < priority  # [N, M]
        slot_checked = req > 0  # [R]
        need = (self.requested + req[None, :] - self.alloc)  # [N, R]
        need_cnt = self.pod_count + 1 - self.alloc_pods  # [N]
        freed = (self.pp_req * lower[:, :, None]).sum(axis=1)  # [N, R]
        count_lower = lower.sum(axis=1)  # [N]
        ok = (
            np.all((freed >= need) | ~slot_checked[None, :], axis=1)
            & (count_lower >= need_cnt)
            & (count_lower > 0)
        )
        victim = lower.copy()
        nvict = count_lower.copy()
        m = self.pp_prio.shape[1]
        for c in range(m):  # reprieve in column (= priority, key) order
            v = self.pp_req[:, c]  # [N, R]
            can = (
                victim[:, c]
                & (nvict - 1 >= need_cnt)
                & np.all((freed - v >= need) | ~slot_checked[None, :], axis=1)
            )
            victim[:, c] &= ~can
            freed -= v * can[:, None]
            nvict -= can
        ok &= nvict > 0
        max_prio = np.max(
            np.where(victim, self.pp_prio, np.iinfo(np.int64).min), axis=1)
        total = (self.pp_req.sum(axis=2) * victim).sum(axis=1)
        return ok, max_prio, nvict, total, victim

    # -- the prefilter --------------------------------------------------
    def candidates_for(self, req_units: list[int], priority: int) -> list[tuple[int, str]]:
        """(bound, node_name) for every node where evicting all pods below
        some level < ``priority`` makes the preemptor resource-feasible.
        bound = the smallest sufficient level's value = the lower bound on
        exact max victim priority."""
        bounds, ok = self._bounds_numpy(
            np.asarray([req_units], dtype=np.int64),
            np.asarray([priority], dtype=np.int64))
        return self._to_candidates(bounds[0], ok[0])

    def _to_candidates(self, bounds: "np.ndarray", ok: "np.ndarray") -> list[tuple[int, str]]:
        idx = np.flatnonzero(ok)
        return [(int(bounds[j]), self.node_names[j]) for j in idx]

    def _fit_masks(self, xp, u_req, u_pri):
        """Shared arithmetic of both paths (xp = numpy | jax.numpy):
        ok[u, k, n] — evicting every pod with priority ≤ L[k] on node n
        makes preemptor u resource-feasible with at least one victim."""
        levels = xp.asarray(self.levels)
        allowed = levels[None, :] < u_pri[:, None]  # [U, Pd]
        head = (self.alloc[None, :, :] - self.requested[None, :, :]
                + xp.asarray(self.cum_req))  # [Pd, N, R] broadcast below
        fits_r = xp.all(
            (u_req[:, None, None, :] <= head[None, :, :, :])
            | (u_req[:, None, None, :] == 0),
            axis=-1,
        )  # [U, Pd, N]
        fits_p = (self.pod_count[None, :] - xp.asarray(self.cum_cnt) + 1
                  <= self.alloc_pods[None, :])  # [Pd, N]
        ok = (fits_r & fits_p[None, :, :]
              & (xp.asarray(self.cum_cnt)[None, :, :] > 0)
              & allowed[:, :, None])  # [U, Pd, N]
        return ok

    def _bounds_numpy(self, u_req, u_pri):
        if len(self.levels) == 0 or not self.node_names:
            u = len(u_pri)
            n = len(self.node_names)
            return np.zeros((u, n), dtype=np.int64), np.zeros((u, n), dtype=bool)
        ok = self._fit_masks(np, u_req, u_pri)
        any_ok = ok.any(axis=1)  # [U, N]
        kmin = ok.argmax(axis=1)  # first True along Pd (argmax of bool)
        bounds = self.levels[kmin]
        return bounds, any_ok

