"""TPU kernels: feasibility masks, scoring, batched assignment."""

import logging
import os
import sys

# XLA's CPU thunk runtime pays a per-op dispatch cost that dominates the
# scan step at scheduler shapes (~150 small [N] ops per pod): the legacy
# runtime runs the same step in ~half the time (353 vs 656 us/pod at
# N=5120).  Opt the CPU client into it unless the operator already chose
# — harmless for TPU execution (CPU-only flag), and it must be set
# before the first JAX computation initializes the CPU client.
if "xla_cpu_use_thunk_runtime" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") + " --xla_cpu_use_thunk_runtime=false"
    ).strip()
    if "jax" in sys.modules:
        # the flag is read when the CPU client initializes; an embedding
        # app that already ran a JAX computation keeps the default
        # runtime — the scan then runs ~2x slower per step, so say so
        # instead of silently missing the bench floor
        logging.getLogger("kubernetes_tpu.ops").info(
            "jax was imported before kubernetes_tpu.ops: the legacy CPU "
            "runtime flag may not apply if the CPU client is already "
            "initialized (scan steps ~2x slower; set XLA_FLAGS="
            "--xla_cpu_use_thunk_runtime=false yourself to be sure)")

from .backend import TPUBatchBackend
from .batch_kernel import ScanState, StaticArrays, schedule_batch_arrays
