"""TPU kernels: feasibility masks, scoring, batched assignment."""

from .backend import TPUBatchBackend
from .batch_kernel import ScanState, StaticArrays, schedule_batch_arrays
