"""Per-shape circuit breaker for the kernel degradation ladder.

The batch backend has three ways to execute a segment, ordered by speed
and by blast radius of a failure:

    pallas (fused Mosaic) → interpret (XLA scan) → oracle (per-pod CPU)

The seed behavior was a per-shape failure *budget*: ``pallas_max_failures``
strikes and the shape silently never tried Pallas again — degradation was
permanent and invisible.  This breaker makes the ladder explicit and
reversible (the classic closed → open → half-open protocol, per shape):

- ``failure_threshold`` **consecutive** failures at a level trips the
  shape one rung down (a transient Mosaic hiccup doesn't; r3 VERDICT
  Weak #5);
- a tripped shape **re-probes** one rung up after ``cooldown`` seconds
  (half-open): success restores the better level, failure re-opens with
  a doubled cool-down (capped) so a permanently broken shape asymptotes
  to rare, cheap probes;
- every transition is observable: the ``on_transition`` hook feeds the
  scheduler's ``kernel_breaker_transitions_total`` counter and the
  backend's stats, so "this cluster is quietly running on the slow path"
  is a metric, not a surprise.

The clock is injected for deterministic tests (tests/test_faults.py
drives the full degrade → cool-down → re-probe → restore cycle with a
fake clock).
"""

from __future__ import annotations

import time
from typing import Callable, Optional

LEVELS = ("pallas", "interpret", "oracle")
ORACLE = len(LEVELS) - 1


class _ShapeState:
    __slots__ = ("level", "fails", "reprobe_at", "cooldown")

    def __init__(self, cooldown: float):
        self.level = 0  # current operating rung (index into LEVELS)
        # consecutive-failure streak PER RUNG: a segment that fails at
        # pallas and then also at interpret must advance both streaks —
        # one shared counter would let each rung's failures reset the
        # other's and never trip either
        self.fails = [0] * len(LEVELS)
        self.reprobe_at: Optional[float] = None
        self.cooldown = cooldown


class KernelCircuitBreaker:
    def __init__(
        self,
        failure_threshold: int = 2,
        cooldown: float = 30.0,
        cooldown_max: float = 480.0,
        clock: Callable[[], float] = time.monotonic,
        on_transition: Optional[Callable[[str, tuple, int, int], None]] = None,
    ):
        self.failure_threshold = failure_threshold
        self.base_cooldown = cooldown
        self.cooldown_max = cooldown_max
        self._clock = clock
        self._on_transition = on_transition
        self._shapes: dict[tuple, _ShapeState] = {}

    def _state(self, key: tuple) -> _ShapeState:
        st = self._shapes.get(key)
        if st is None:
            st = self._shapes[key] = _ShapeState(self.base_cooldown)
        return st

    def _notify(self, kind: str, key: tuple, frm: int, to: int) -> None:
        if self._on_transition is not None:
            self._on_transition(kind, key, frm, to)

    # -- the three verbs ---------------------------------------------------
    def plan_level(self, key: tuple, floor: int = 0) -> int:
        """The rung to ATTEMPT for the next segment of this shape.

        ``floor`` is the best rung the environment supports at all (1
        when Pallas is not eligible: CPU platform, unsupported shape,
        feature gate off) — the breaker never plans above it.  When the
        shape is degraded below the floor and its cool-down has elapsed,
        the returned rung is one better than the operating rung: the
        half-open probe.  The caller reports the outcome via
        record_success/record_failure; until then the operating rung is
        unchanged."""
        st = self._state(key)
        eff = max(st.level, floor)
        if (eff > floor and st.reprobe_at is not None
                and self._clock() >= st.reprobe_at):
            # half-open probe.  No notification here — plan_level is a
            # read-only query (probes announce themselves through their
            # outcome: restore or probe_failed)
            return eff - 1
        return eff

    def record_success(self, key: tuple, attempted: int) -> None:
        st = self._state(key)
        if attempted < st.level:
            # successful half-open probe: restore the better rung
            self._notify("restore", key, st.level, attempted)
            st.level = attempted
            st.cooldown = self.base_cooldown
            # keep climbing: a restored-but-still-degraded rung re-probes
            # again after a fresh cool-down; fully healthy clears the timer
            st.reprobe_at = (None if attempted == 0
                             else self._clock() + st.cooldown)
            st.fails[attempted] = 0
            return
        # only a success at the SAME rung clears that rung's streak: a
        # fallback succeeding one rung down says nothing about whether
        # the rung above is healthy again
        st.fails[attempted] = 0

    def record_failure(self, key: tuple, attempted: int) -> None:
        st = self._state(key)
        if attempted < st.level:
            # failed half-open probe: stay where we are, back off harder
            st.cooldown = min(st.cooldown * 2, self.cooldown_max)
            st.reprobe_at = self._clock() + st.cooldown
            self._notify("probe_failed", key, attempted, st.level)
            return
        st.fails[attempted] += 1
        if st.fails[attempted] >= self.failure_threshold and attempted < ORACLE:
            # report the rung that actually failed (st.level may sit above
            # a floor-clamped attempt: CPU floors pallas-level state out)
            frm = max(st.level, attempted)
            st.level = attempted + 1
            st.fails[attempted] = 0
            st.reprobe_at = self._clock() + st.cooldown
            self._notify("degrade", key, frm, st.level)

    # -- introspection -----------------------------------------------------
    def level_name(self, key: tuple, floor: int = 0) -> str:
        return LEVELS[max(self._state(key).level, floor)]

    def snapshot(self) -> dict:
        """{shape_key: (level_name, per-rung fail streaks, reprobe_at)}."""
        return {
            k: (LEVELS[st.level], list(st.fails), st.reprobe_at)
            for k, st in self._shapes.items()
        }
