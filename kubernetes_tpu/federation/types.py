"""Federation API types (reference ``federation/apis/federation/types.go``):
the Cluster registry object — one row per member cluster, carrying its
API endpoint + credential reference and health conditions maintained by
the cluster controller."""

from __future__ import annotations

import time
from dataclasses import dataclass, field

from ..api.meta import ObjectMeta
from ..api.types import register_kind

CLUSTER_READY = "Ready"
CLUSTER_OFFLINE = "Offline"

# placement annotation on a federated object: JSON list of member cluster
# names (reference used per-kind preferences; an explicit cluster list is
# the capability essential)
PLACEMENT_ANNOTATION = "federation.kubernetes.io/clusters"


@dataclass
class Cluster:
    """A member cluster (reference ``federation/apis/federation``
    Cluster: serverAddressByClientCIDRs + secretRef + status.conditions)."""

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    server_address: str = ""
    token: str = ""  # credential for the member apiserver ("" = none)
    conditions: list[dict] = field(default_factory=list)
    # zone/region the member reports — consumed by cross-cluster DNS
    zone: str = ""
    region: str = ""

    KIND = "Cluster"

    def __post_init__(self):
        self.meta.namespace = ""

    def condition(self, ctype: str) -> dict | None:
        for c in self.conditions:
            if c.get("type") == ctype:
                return c
        return None

    @property
    def ready(self) -> bool:
        c = self.condition(CLUSTER_READY)
        return c is not None and c.get("status") == "True"

    def set_condition(self, ctype: str, status: str, clock=time.time) -> None:
        c = self.condition(ctype)
        if c is None:
            self.conditions.append(
                {"type": ctype, "status": status, "lastProbeTime": clock()})
        else:
            c["status"] = status
            c["lastProbeTime"] = clock()

    def to_dict(self) -> dict:
        return {
            "kind": self.KIND,
            "metadata": self.meta.to_dict(),
            "spec": {
                "serverAddress": self.server_address,
                "token": self.token,
                "zone": self.zone,
                "region": self.region,
            },
            "status": {"conditions": [dict(c) for c in self.conditions]},
        }

    @classmethod
    def from_dict(cls, d: dict) -> "Cluster":
        spec = d.get("spec") or {}
        status = d.get("status") or {}
        return cls(
            meta=ObjectMeta.from_dict(d.get("metadata") or {}),
            server_address=spec.get("serverAddress", ""),
            token=spec.get("token", ""),
            zone=spec.get("zone", ""),
            region=spec.get("region", ""),
            conditions=[dict(c) for c in status.get("conditions") or []],
        )


register_kind(Cluster, cluster_scoped=True)
