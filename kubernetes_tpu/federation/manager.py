"""federation-controller-manager (reference
``federation/cmd/federation-controller-manager``): cluster health +
per-kind sync controllers + service DNS over one shared informer set."""

from __future__ import annotations

from typing import Callable, Optional

from ..client.clientset import Clientset
from ..client.informer import InformerFactory
from ..controllers.manager import ControllerManager
from .controllers import (
    ClusterController,
    FederatedSyncController,
    MemberRegistry,
    ServiceDNSController,
)

DEFAULT_FEDERATED_KINDS = ("Deployment", "ConfigMap", "Secret", "Service")


class FederationControllerManager(ControllerManager):
    def __init__(self, clientset: Clientset,
                 kinds: tuple = DEFAULT_FEDERATED_KINDS,
                 member_factory: Optional[Callable] = None,
                 federation_name: str = "myfed",
                 dns_zone: str = "example.com",
                 clock=None, **kw):
        # hand-built registry: every controller shares ONE MemberRegistry
        # (and through it one member clientset per cluster)
        self.clientset = clientset
        self.informers = InformerFactory(clientset)
        if member_factory is not None:
            members = MemberRegistry(clientset, factory=member_factory)
        else:
            members = MemberRegistry(clientset)
        self.members = members
        common = {"informers": self.informers, "members": members}
        if clock is not None:
            common["clock"] = clock
        self.controllers = {
            "cluster": ClusterController(clientset, **common),
            "service-dns": ServiceDNSController(
                clientset, federation_name=federation_name,
                dns_zone=dns_zone, **common),
        }
        for kind in kinds:
            c = FederatedSyncController(clientset, kind, **common)
            self.controllers[c.name] = c

    @property
    def dns(self) -> ServiceDNSController:
        return self.controllers["service-dns"]
