"""kubefed CLI (reference ``federation/pkg/kubefed``): init / join /
unjoin / get-clusters against a federation apiserver."""

from __future__ import annotations

import argparse
import sys
from typing import Optional

from ..api.meta import ObjectMeta
from ..client.clientset import Clientset
from ..client.remote import RemoteStore
from ..store.store import AlreadyExistsError, NotFoundError
from .types import Cluster


def join(cs: Clientset, name: str, server: str, token: str = "",
         zone: str = "", region: str = "", out=None) -> int:
    out = out or sys.stdout
    try:
        cs.client_for("Cluster").create(Cluster(
            meta=ObjectMeta(name=name), server_address=server, token=token,
            zone=zone, region=region))
    except AlreadyExistsError:
        out.write(f'Error: cluster "{name}" already joined\n')
        return 1
    out.write(f"cluster/{name} joined\n")
    return 0


def unjoin(cs: Clientset, name: str, out=None) -> int:
    out = out or sys.stdout
    try:
        cs.client_for("Cluster").delete(name, "")
    except NotFoundError:
        out.write(f'Error: cluster "{name}" not found\n')
        return 1
    out.write(f"cluster/{name} unjoined\n")
    return 0


def get_clusters(cs: Clientset, out=None) -> int:
    out = out or sys.stdout
    rows = [("NAME", "SERVER", "READY", "ZONE")]
    for c in cs.client_for("Cluster").list("")[0]:
        rows.append((c.meta.name, c.server_address, str(c.ready), c.zone))
    widths = [max(len(str(r[i])) for r in rows) for i in range(4)]
    for r in rows:
        out.write("  ".join(str(v).ljust(w) for v, w in zip(r, widths)).rstrip() + "\n")
    return 0


def main(argv: Optional[list] = None, clientset: Optional[Clientset] = None,
         out=None) -> int:
    ap = argparse.ArgumentParser(prog="kubefed")
    ap.add_argument("--host", default="http://127.0.0.1:8080",
                    help="federation apiserver")
    ap.add_argument("--token", default=None)
    sub = ap.add_subparsers(dest="verb", required=True)
    p = sub.add_parser("join")
    p.add_argument("name")
    p.add_argument("--cluster-server", required=True)
    p.add_argument("--cluster-token", default="")
    p.add_argument("--zone", default="")
    p.add_argument("--region", default="")
    p = sub.add_parser("unjoin")
    p.add_argument("name")
    sub.add_parser("get-clusters")
    args = ap.parse_args(argv)
    cs = clientset or Clientset(RemoteStore(args.host, token=args.token))
    if args.verb == "join":
        return join(cs, args.name, args.cluster_server, args.cluster_token,
                    args.zone, args.region, out)
    if args.verb == "unjoin":
        return unjoin(cs, args.name, out)
    return get_clusters(cs, out)


if __name__ == "__main__":
    sys.exit(main())
