"""Federation control plane (reference ``federation/pkg/
federation-controller``): cluster health, per-kind sync fan-out with
status rollup, and cross-cluster service DNS.

The federation apiserver IS the ordinary wire apiserver over its own
store (the reference's federation-apiserver is likewise a trimmed
kube-apiserver) — what makes it a federation is this controller set
running against it, with a member clientset per registered Cluster.
"""

from __future__ import annotations

import json
import logging
import threading
from typing import Callable, Optional

from ..api import types as api
from ..client.clientset import Clientset
from ..controllers.base import Controller
from ..store.store import AlreadyExistsError, NotFoundError
from .types import CLUSTER_OFFLINE, CLUSTER_READY, PLACEMENT_ANNOTATION, Cluster

logger = logging.getLogger("kubernetes_tpu.federation")


def default_member_factory(cluster: Cluster) -> Clientset:
    from ..client.remote import RemoteStore

    return Clientset(RemoteStore(cluster.server_address,
                                 token=cluster.token or None))


class MemberRegistry:
    """Shared cluster -> member-clientset resolution with caching; the
    factory is injectable so tests can wire in-proc clusters."""

    def __init__(self, clientset: Clientset,
                 factory: Callable[[Cluster], Clientset] = default_member_factory):
        self.clientset = clientset
        self.factory = factory
        # name -> ((server_address, token), clientset)
        self._cache: dict[str, tuple[tuple[str, str], Clientset]] = {}
        # one registry is shared by the cluster-probe and federated-sync
        # controllers' workers; the get-or-create below is a
        # check-then-act on the cache
        self._mu = threading.Lock()

    def clusters(self, only_ready: bool = True) -> list[Cluster]:
        out = []
        for c in self.clientset.client_for("Cluster").list("")[0]:
            if not only_ready or c.ready:
                out.append(c)
        return out

    def client(self, cluster: Cluster) -> Clientset:
        # cache keyed on the full connection identity: a rejoined or
        # re-addressed cluster must get a fresh clientset, never keep
        # syncing to the old endpoint
        ident = (cluster.server_address, cluster.token)
        with self._mu:
            entry = self._cache.get(cluster.meta.name)
            if entry is None or entry[0] != ident:
                entry = (ident, self.factory(cluster))
                self._cache[cluster.meta.name] = entry
            return entry[1]


class ClusterController(Controller):
    """``federation-controller/cluster``: probe member /healthz on every
    monitor tick, maintain Ready/Offline conditions."""

    name = "federation-cluster"

    def __init__(self, clientset, informers=None, members: MemberRegistry = None, **kw):
        super().__init__(clientset, informers, **kw)
        self.members = members or MemberRegistry(clientset)
        self.watch("Cluster")

    def _probe(self, cluster: Cluster) -> bool:
        try:
            member = self.members.client(cluster)
            raw = getattr(member.store, "raw", None)
            if raw is not None:
                return json.loads(raw("GET", "/healthz")).get("status") == "ok"
            member.nodes.list()  # in-proc member: a live store IS healthy
            return True
        except Exception:
            return False

    def sync(self, key: str) -> None:
        name = key.split("/", 1)[-1]
        try:
            cluster = self.clientset.client_for("Cluster").get(name, "")
        except NotFoundError:
            self.members._cache.pop(name, None)
            return
        healthy = self._probe(cluster)
        want = {CLUSTER_READY: "True" if healthy else "False",
                CLUSTER_OFFLINE: "False" if healthy else "True"}
        # write ONLY on a state transition: an unconditional write (fresh
        # lastProbeTime) would emit MODIFIED, re-enqueue this key via our
        # own Cluster watch, and livelock the sync loop
        current = {t: (cluster.condition(t) or {}).get("status") for t in want}
        if current == want:
            return

        def _set(cur):
            for ctype, status in want.items():
                cur.set_condition(ctype, status, clock=self.clock)
            return cur

        self.clientset.client_for("Cluster").guaranteed_update(name, _set, "")

    def monitor(self) -> None:
        for c in self.members.clusters(only_ready=False):
            self.queue.add(c.meta.key)


class FederatedSyncController(Controller):
    """``federation-controller/sync`` essential: for ONE kind, fan every
    federated object out to its placement clusters, reconcile drift, and
    delete from members when the federated object is gone.  Deployment
    status rolls up as the sum of member statuses."""

    # member-owned metadata that must not be propagated
    _STRIP = ("uid", "resourceVersion", "creationRevision")

    def __init__(self, clientset, kind: str, informers=None,
                 members: MemberRegistry = None, **kw):
        super().__init__(clientset, informers, **kw)
        self.kind = kind
        self.name = f"federated-{kind.lower()}"
        self.members = members or MemberRegistry(clientset)
        self.watch(kind)
        from ..client.informer import Handler

        # re-reconcile everything when cluster membership/health changes
        self.informers.informer("Cluster").add_handler(Handler(
            on_add=lambda c: self._requeue_all(),
            on_update=lambda old, new: (
                self._requeue_all() if old.ready != new.ready else None),
            on_delete=lambda c: self._requeue_all(),
        ))

    def _requeue_all(self) -> None:
        for obj in self.informer(self.kind).list():
            self.queue.add(obj.meta.key)

    def monitor(self) -> None:
        """Periodic full resync: member-side drift and member status
        changes are invisible to the federation store's watches (the
        reference runs per-member informers; a tick-driven resync is the
        same level-triggered contract)."""
        self._requeue_all()

    def _placement(self, obj) -> Optional[set]:
        raw = obj.meta.annotations.get(PLACEMENT_ANNOTATION)
        if raw is None:
            return None  # all ready clusters
        try:
            return set(json.loads(raw))
        except (ValueError, TypeError):
            logger.warning("%s: bad placement annotation on %s", self.name,
                           obj.meta.key)
            return None

    def _wire_for_member(self, obj) -> dict:
        d = obj.to_dict()
        meta = d.get("metadata") or {}
        for k in self._STRIP:
            meta.pop(k, None)
        d.pop("status", None)  # member-owned
        return d

    def sync(self, key: str) -> None:
        namespace, _, name = key.rpartition("/")
        client = self.clientset.client_for(self.kind)
        try:
            fed_obj = client.get(name, namespace)
        except NotFoundError:
            fed_obj = None
        clusters = self.members.clusters()
        placement = self._placement(fed_obj) if fed_obj is not None else set()
        want_wire = self._wire_for_member(fed_obj) if fed_obj is not None else None

        totals = {"replicas": 0, "ready": 0, "updated": 0}
        for cluster in clusters:
            member = self.members.client(cluster).client_for(self.kind)
            targeted = fed_obj is not None and (
                placement is None or cluster.meta.name in placement)
            try:
                existing = member.get(name, namespace)
            except NotFoundError:
                existing = None
            if not targeted:
                if existing is not None:
                    member.delete(name, namespace)
                continue
            if existing is None:
                try:
                    member.create(type(fed_obj).from_dict(want_wire))
                except AlreadyExistsError:
                    pass
                existing = member.get(name, namespace)
            elif self._wire_for_member(existing) != want_wire:
                def _overwrite(cur):
                    new = type(cur).from_dict(want_wire)
                    new.meta.uid = cur.meta.uid
                    new.meta.resource_version = cur.meta.resource_version
                    if hasattr(cur, "status"):
                        new.status = cur.status
                    return new

                existing = member.guaranteed_update(name, _overwrite, namespace)
            if self.kind == "Deployment":
                totals["replicas"] += existing.status_replicas
                totals["ready"] += existing.status_ready_replicas
                totals["updated"] += existing.status_updated_replicas

        if fed_obj is not None and self.kind == "Deployment":
            # skip the no-op write: it would MODIFIED-requeue this key
            # through our own watch forever (the livelock the deployment
            # controller also guards against)
            if (fed_obj.status_replicas, fed_obj.status_ready_replicas,
                    fed_obj.status_updated_replicas) == (
                    totals["replicas"], totals["ready"], totals["updated"]):
                return

            def _rollup(cur):
                cur.status_replicas = totals["replicas"]
                cur.status_ready_replicas = totals["ready"]
                cur.status_updated_replicas = totals["updated"]
                return cur

            client.guaranteed_update(name, _rollup, namespace)


class ServiceDNSController(Controller):
    """``federation-controller/service``'s DNS half: synthesize
    cross-cluster records ``<svc>.<ns>.<federation>.svc.<zone>`` from the
    member clusters' published LoadBalancer ingress IPs, with per-zone /
    per-region scoping (the reference's three-level fallback chain).
    Records land in an in-memory zone table standing in for the cloud
    ``dnsprovider``."""

    name = "federation-service-dns"

    def __init__(self, clientset, informers=None, members: MemberRegistry = None,
                 federation_name: str = "myfed", dns_zone: str = "example.com", **kw):
        super().__init__(clientset, informers, **kw)
        self.members = members or MemberRegistry(clientset)
        self.federation_name = federation_name
        self.dns_zone = dns_zone
        self.records: dict[str, list[str]] = {}
        # sync() runs on worker threads (possibly several), resolve() on
        # whoever serves DNS: the multi-step record rebuild must be
        # atomic against both (ktpu-analyze RL301/RL303, ISSUE 2 scope
        # extension triage — a resolver between the filter and the
        # re-insert saw the service briefly vanish)
        self._records_mu = threading.Lock()
        self.watch("Service")

    def monitor(self) -> None:
        """Member LB ingress IPs appear asynchronously (cloud controllers
        in the members); re-derive all records each tick."""
        for svc in self.informer("Service").list():
            self.queue.add(svc.meta.key)

    def sync(self, key: str) -> None:
        namespace, _, name = key.rpartition("/")
        base = f"{name}.{namespace}.{self.federation_name}.svc.{self.dns_zone}"
        try:
            self.clientset.services.get(name, namespace)
        except NotFoundError:
            with self._records_mu:
                self.records = {k: v for k, v in self.records.items()
                                if k != base and not k.endswith("." + base)}
            return
        global_ips: list[str] = []
        by_scope: dict[str, list[str]] = {}
        for cluster in self.members.clusters():
            member = self.members.client(cluster)
            try:
                svc = member.services.get(name, namespace)
            except NotFoundError:
                continue
            ips = list(svc.status_load_balancer)
            global_ips.extend(ips)
            for scope in (cluster.zone, cluster.region):
                if scope:
                    by_scope.setdefault(scope, []).extend(ips)
        # rebuild this service's record set ATOMICALLY: stale scoped
        # records (a zone whose member dropped the service) must vanish,
        # so a scoped lookup falls back up the chain instead of serving a
        # dead IP — and a concurrent resolve()/sibling sync() must never
        # observe the half-rebuilt table
        with self._records_mu:
            rebuilt = {k: v for k, v in self.records.items()
                       if k != base and not k.endswith("." + base)}
            rebuilt[base] = sorted(global_ips)
            for scope, ips in by_scope.items():
                if ips:  # an empty scope is NO record, so lookups fall back
                    rebuilt[f"{scope}.{base}"] = sorted(ips)
            self.records = rebuilt

    def resolve(self, fqdn: str) -> list[str]:
        """Three-level chain: exact record, else strip the leading scope
        label (zone -> region -> global) like the reference's CNAME
        fallback chain."""
        # one snapshot for the whole walk: writers only ever PUBLISH a
        # fully-built table (atomic rebind under _records_mu), so the
        # chain below can never mix two generations of records
        records = self.records
        probe = fqdn
        while True:
            ips = records.get(probe)
            if ips:
                return ips
            if "." not in probe:
                return []
            head, rest = probe.split(".", 1)
            if rest in records or "." in rest:
                probe = rest
            else:
                return []
