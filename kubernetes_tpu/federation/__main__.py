"""The federation control plane daemon: apiserver + controller manager.

Capability of ``federation/cmd/federation-apiserver`` +
``federation-controller-manager`` (reference federation/): one process
serving the federation-scoped API over HTTP (Cluster + the federated
kinds, through the same generic apiserver machinery — the reference's
federation-apiserver is likewise a genericapiserver instantiation) and
running the federation control loops against it: cluster health, fan-out
sync with placement, status rollup, cross-cluster service DNS.

    python -m kubernetes_tpu.federation --port 18500 \
        [--federation-name myfed --dns-zone example.com]

Members join over the wire (``kubefed join NAME --server URL``); the
member factory dials each cluster's own apiserver.
"""

from __future__ import annotations

import argparse
import logging
import time


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(prog="federation-apiserver")
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--federation-name", default="myfed")
    parser.add_argument("--dns-zone", default="example.com")
    parser.add_argument("--sync-interval", type=float, default=1.0)
    parser.add_argument("--healthz-port", type=int, default=-1,
                        help="serve /healthz + /metrics + /debug/* for the "
                             "federation control plane; -1 = off")
    parser.add_argument("--timeseries", action="store_true",
                        help="scrape the apiserver registry into "
                             "time-series rings (/debug/timeseries)")
    parser.add_argument("--timeseries-interval", type=float, default=1.0)
    parser.add_argument("--telemetry-sink", default=None,
                        help="ship flight dumps + time-series deltas "
                             "off-box (collector URL or JSON-lines path)")
    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.INFO)

    from ..apiserver import APIServer
    from ..store import Store
    from ..client import Clientset
    from ..daemon import serve_health
    from .manager import FederationControllerManager

    server = APIServer(Store(), port=args.port)
    server.start()
    logging.info("federation-apiserver serving at %s", server.url)

    # the shared daemon health surface over the embedded apiserver's
    # registry (the apiserver port serves the same routes; this one
    # stays answerable even while the API is saturated)
    health = serve_health(args.healthz_port, server.registry)
    if health is not None:
        logging.info("healthz/metrics on :%d", health.local_port)
    if args.timeseries or args.telemetry_sink:
        from ..daemon import enable_continuous_telemetry

        enable_continuous_telemetry(
            server.registry, interval_s=args.timeseries_interval,
            sink_spec=args.telemetry_sink)

    cs = Clientset(server.store)
    mgr = FederationControllerManager(
        cs, federation_name=args.federation_name, dns_zone=args.dns_zone)
    mgr.start()
    try:
        while True:
            mgr.reconcile_all()
            for c in mgr.controllers.values():
                monitor = getattr(c, "monitor", None)
                if monitor is not None:
                    monitor()
            time.sleep(args.sync_interval)
    except KeyboardInterrupt:
        server.stop()
        if health is not None:
            health.stop()


if __name__ == "__main__":
    main()
