"""Federation: the multi-cluster control plane (SURVEY.md §1-L9 /
§2.10, reference ``federation/``).  A federation = an ordinary wire
apiserver over its own store + the controllers here + ``kubefed``.

Lazy attribute loading (PEP 562): the apiserver imports
``federation.types`` just to register the Cluster kind on the wire — it
must not drag the full controller tree (and through it every core
controller) into its import graph."""

from .types import PLACEMENT_ANNOTATION, Cluster  # noqa: F401  (import-light)

_LAZY = {
    "ClusterController": "controllers",
    "FederatedSyncController": "controllers",
    "MemberRegistry": "controllers",
    "ServiceDNSController": "controllers",
    "FederationControllerManager": "manager",
}


def __getattr__(name):
    mod = _LAZY.get(name)
    if mod is None:
        raise AttributeError(name)
    import importlib

    return getattr(importlib.import_module(f".{mod}", __name__), name)
