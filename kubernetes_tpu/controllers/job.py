"""Job controller: run pods to completion.

Capability of ``pkg/controller/job/jobcontroller.go`` (741 LoC):
``syncJob`` counts active/succeeded/failed pods owned by the Job, creates
up to ``parallelism`` active pods while fewer than ``completions`` have
succeeded, marks the Complete condition when done, and the Failed
condition when ``backoffLimit`` restarts are exhausted or
``activeDeadlineSeconds`` passes (measured from the Job's creation using
the controller's injected clock)."""

from __future__ import annotations

import itertools

from ..api import types as api
from ..api.apps import Job
from ..api.meta import ObjectMeta, OwnerReference
from ..store.store import AlreadyExistsError, NotFoundError
from .base import Controller
from .replicaset import Expectations

_suffix = itertools.count(1)


class JobController(Controller):
    name = "job"

    def __init__(self, clientset, informers=None, **kw):
        super().__init__(clientset, informers, **kw)
        self.expectations = Expectations()
        self.watch("Job")
        from ..client.informer import Handler, PodOwnerIndex

        self.pod_index = PodOwnerIndex(self.informers.informer("Pod"))
        self.informers.informer("Pod").add_handler(Handler(
            on_add=lambda pod: self._pod_event(pod, "add"),
            on_update=lambda old, new: self._pod_event(new, "update"),
            on_delete=lambda pod: self._pod_event(pod, "delete"),
        ))

    def _pod_event(self, pod: api.Pod, event: str) -> None:
        ref = pod.meta.controller_ref()
        if ref is None or ref.kind != "Job":
            return
        key = f"{pod.meta.namespace}/{ref.name}"
        if event == "add":
            self.expectations.observe_create(key)
        elif event == "delete":
            self.expectations.observe_delete(key)
        self.queue.add(key)

    def sync(self, key: str) -> None:
        namespace, name = key.split("/", 1)
        try:
            job = self.clientset.jobs.get(name, namespace)
        except NotFoundError:
            self.expectations.forget(key)
            return
        if job.complete or job.failed:
            return
        if not self.expectations.satisfied(key):
            return
        # persist startTime so the deadline survives controller restarts
        # (reference jobcontroller.go sets job.Status.StartTime once)
        if not job.status_start_time:
            start = self.clock()

            def _stamp(cur: Job) -> Job:
                if not cur.status_start_time:
                    cur.status_start_time = start
                return cur

            job = self.clientset.jobs.guaranteed_update(name, _stamp, namespace)

        owned = [p for p in self.pod_index.owned_by(job.meta.uid)
                 if p.meta.namespace == namespace]
        active = [p for p in owned if p.status.phase in (api.PENDING, api.RUNNING)]
        succeeded = sum(1 for p in owned if p.status.phase == api.SUCCEEDED)
        failed = sum(1 for p in owned if p.status.phase == api.FAILED)

        conditions = list(job.status_conditions)
        deadline_exceeded = (
            job.active_deadline_seconds is not None
            and self.clock() - job.status_start_time >= job.active_deadline_seconds
        )
        if failed > job.backoff_limit or deadline_exceeded:
            reason = "DeadlineExceeded" if deadline_exceeded else "BackoffLimitExceeded"
            conditions.append({"type": "Failed", "status": "True", "reason": reason})
            for p in active:  # kill remaining pods on failure
                try:
                    self.clientset.pods.delete(p.meta.name, namespace)
                except NotFoundError:
                    pass
            active = []
        elif self._done(job, succeeded):
            conditions.append({"type": "Complete", "status": "True"})
        else:
            want_active = self._wanted_active(job, succeeded)
            diff = want_active - len(active)
            if diff > 0:
                self.expectations.expect(key, diff, 0)
                for _ in range(diff):
                    self._create_pod(job)
            elif diff < 0:
                victims = sorted(active, key=lambda p: (bool(p.spec.node_name), p.meta.name))[:-diff]
                self.expectations.expect(key, 0, len(victims))
                for p in victims:
                    try:
                        self.clientset.pods.delete(p.meta.name, namespace)
                    except NotFoundError:
                        self.expectations.observe_delete(key)

        def _status(cur: Job) -> Job:
            cur.status_active = len(active)
            cur.status_succeeded = succeeded
            cur.status_failed = failed
            cur.status_conditions = conditions
            return cur

        self.clientset.jobs.guaranteed_update(name, _status, namespace)

    def _done(self, job: Job, succeeded: int) -> bool:
        if job.completions is None:
            # work-queue style: done when any pod succeeded
            return succeeded > 0
        return succeeded >= job.completions

    def _wanted_active(self, job: Job, succeeded: int) -> int:
        if job.completions is None:
            return job.parallelism
        return min(job.parallelism, max(0, job.completions - succeeded))

    def _create_pod(self, job: Job) -> None:
        spec = api.PodSpec.from_dict(job.template.spec.to_dict())
        if spec.restart_policy == "Always":
            spec.restart_policy = "OnFailure"  # jobs never restart-forever
        pod = api.Pod(
            meta=ObjectMeta(
                name=f"{job.meta.name}-{next(_suffix):06d}",
                namespace=job.meta.namespace,
                labels=dict(job.template.labels),
                owner_references=[OwnerReference(
                    kind="Job", name=job.meta.name, uid=job.meta.uid, controller=True)],
            ),
            spec=spec,
        )
        try:
            self.clientset.pods.create(pod)
        except AlreadyExistsError:
            self.expectations.observe_create(job.meta.key)
