"""Namespace controller: cascading teardown of terminating namespaces.

Capability of ``pkg/controller/namespace`` (796 LoC): when a namespace is
marked deleting, flip it to Terminating, discovery-walk every namespaced
kind, delete all contained resources, and only then clear the
``kubernetes`` finalizer so the store finishes the delete
(``namespace/deletion/namespaced_resources_deleter.go``).

Discovery here is the type registry (``KINDS`` minus cluster-scoped) —
the same role the reference's discovery client plays, so CRD-registered
kinds are swept too."""

from __future__ import annotations

from ..api.cluster import Namespace
from ..api.types import CLUSTER_SCOPED_KINDS, KINDS
from ..store.store import NotFoundError
from .base import Controller

FINALIZER = "kubernetes"


class NamespaceController(Controller):
    name = "namespace"

    def __init__(self, clientset, informers=None, **kw):
        super().__init__(clientset, informers, **kw)
        self.watch("Namespace", key_fn=lambda ns: ns.meta.name)

    def sync(self, key: str) -> None:
        try:
            ns = self.clientset.namespaces.get(key)
        except NotFoundError:
            return
        if ns.meta.deletion_revision is None:
            # live namespace: make sure the finalizer is armed so a future
            # delete is gated on our sweep
            if FINALIZER not in ns.meta.finalizers:
                def _arm(cur: Namespace) -> Namespace:
                    if FINALIZER not in cur.meta.finalizers:
                        cur.meta.finalizers.append(FINALIZER)
                    return cur

                self.clientset.namespaces.guaranteed_update(key, _arm)
            return

        # deleting: phase -> Terminating (admission now refuses new content)
        if ns.phase != "Terminating":
            def _term(cur: Namespace) -> Namespace:
                cur.phase = "Terminating"
                return cur

            self.clientset.namespaces.guaranteed_update(key, _term)

        remaining = self._delete_contents(key)
        if remaining:
            # try again on a later sync (informer events from the deletes
            # will not requeue us, so self-requeue like the reference's
            # rate-limited retry)
            self.queue.add_rate_limited(key)
            return

        def _finish(cur: Namespace) -> Namespace:
            cur.meta.finalizers = [f for f in cur.meta.finalizers if f != FINALIZER]
            cur.spec_finalizers = [f for f in cur.spec_finalizers if f != FINALIZER]
            return cur

        try:
            self.clientset.namespaces.guaranteed_update(key, _finish)
        except NotFoundError:
            pass  # someone else finished it

    def _delete_contents(self, namespace: str) -> int:
        """Delete every namespaced object; returns how many still remain."""
        remaining = 0
        for kind in KINDS:
            if kind in CLUSTER_SCOPED_KINDS or kind == "Namespace":
                continue
            objs, _ = self.clientset.store.list(kind, namespace)
            for obj in objs:
                meta = obj.get("metadata") or {}
                try:
                    self.clientset.store.delete(kind, namespace, meta.get("name", ""))
                except NotFoundError:
                    continue
                if meta.get("finalizers"):
                    remaining += 1  # delete only marked it; wait for owners
        return remaining
