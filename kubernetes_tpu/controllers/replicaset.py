"""ReplicaSet controller: keep N replicas of a pod template alive.

Capability of ``pkg/controller/replicaset`` (861 LoC; the expectations and
adoption patterns from ``controller_utils.go`` / ``controller_ref_manager.go``):

- reconciles |owned pods| to ``spec.replicas`` by creating/deleting pods;
- **adoption**: selector-matching pods with no controller owner are
  claimed by stamping an ownerReference;
- **expectations**: in-flight creates/deletes are remembered so a sync
  storm doesn't double-create before the informer catches up;
- deletion preference: unbound (pending) pods die first, mirroring the
  reference's pod-deletion cost ranking.
"""

from __future__ import annotations

import itertools
import threading
from typing import Optional

from ..api import types as api
from ..api.meta import ObjectMeta, OwnerReference
from ..store.store import AlreadyExistsError, NotFoundError
from .base import Controller

_suffix = itertools.count(1)


class Expectations:
    """Per-RS counters of in-flight creates/deletes (controller_utils.go).

    Locked like the reference's ControllerExpectations (a ThreadSafeStore):
    ``expect``/``forget``/``satisfied`` run on sync workers while
    ``observe_create``/``observe_delete`` run on the informer's pod-event
    thread — the read-decrement-write pairs lose counts without the lock.
    """

    def __init__(self):
        self._exp: dict[str, tuple[int, int]] = {}
        self._mu = threading.Lock()

    def expect(self, key: str, creates: int, deletes: int) -> None:
        with self._mu:
            self._exp[key] = (creates, deletes)

    def observe_create(self, key: str) -> None:
        with self._mu:
            c, d = self._exp.get(key, (0, 0))
            if c > 0:
                self._exp[key] = (c - 1, d)

    def observe_delete(self, key: str) -> None:
        with self._mu:
            c, d = self._exp.get(key, (0, 0))
            if d > 0:
                self._exp[key] = (c, d - 1)

    def satisfied(self, key: str) -> bool:
        with self._mu:
            c, d = self._exp.get(key, (0, 0))
            return c <= 0 and d <= 0

    def forget(self, key: str) -> None:
        with self._mu:
            self._exp.pop(key, None)


class ReplicaSetController(Controller):
    name = "replicaset"
    KIND = "ReplicaSet"  # subclassed for ReplicationController, whose
    # semantics are this controller with a map selector (pkg/controller/
    # replication is the same code pattern in the reference)

    def __init__(self, clientset, informers=None, burst_replicas: int = 500, **kw):
        super().__init__(clientset, informers, **kw)
        self.expectations = Expectations()
        self.burst_replicas = burst_replicas
        self.watch(self.KIND)
        from ..client.informer import Handler, PodOwnerIndex

        self.pod_index = PodOwnerIndex(self.informers.informer("Pod"))
        self.informers.informer("Pod").add_handler(
            Handler(
                on_add=lambda pod: self._pod_event(pod, "add"),
                on_update=lambda old, new: self._pod_event(new, "update"),
                on_delete=lambda pod: self._pod_event(pod, "delete"),
            )
        )

    def _pod_event(self, pod: api.Pod, event: str) -> None:
        key = self._rs_key_for_pod(pod)
        if key is None:
            return
        # expectations observe only the event kinds they count
        if event == "add":
            self.expectations.observe_create(key)
        elif event == "delete":
            self.expectations.observe_delete(key)
        self.queue.add(key)

    def _rs_key_for_pod(self, pod: api.Pod) -> Optional[str]:
        ref = pod.meta.controller_ref()
        if ref is not None:
            if ref.kind != self.KIND:
                return None
            return f"{pod.meta.namespace}/{ref.name}"
        # orphan: wake every RS in the namespace whose selector matches
        for rs in self.informer(self.KIND).list():
            if rs.meta.namespace == pod.meta.namespace and rs.selector.matches(pod.meta.labels):
                return rs.meta.key
        return None

    # -- reconcile ---------------------------------------------------------
    def _owned_and_orphans(self, rs: api.ReplicaSet):
        """O(pods-of-this-RS) via the owner-uid index, not O(cluster-pods)."""
        owned = [
            p
            for p in self.pod_index.owned_by(rs.meta.uid)
            if p.meta.namespace == rs.meta.namespace
            and p.status.phase not in (api.SUCCEEDED, api.FAILED)
        ]
        orphans = []
        if not rs.selector.is_empty():
            for pod in self.pod_index.orphans_in(rs.meta.namespace):
                if pod.status.phase in (api.SUCCEEDED, api.FAILED):
                    continue
                if rs.selector.matches(pod.meta.labels):
                    orphans.append(pod)
        return owned, orphans

    def sync(self, key: str) -> None:
        namespace, name = key.split("/", 1)
        try:
            rs = self.clientset.client_for(self.KIND).get(name, namespace)
        except NotFoundError:
            self.expectations.forget(key)
            return
        if not self.expectations.satisfied(key):
            return  # wait for the informer to observe in-flight changes

        owned, orphans = self._owned_and_orphans(rs)
        # adoption (controller_ref_manager.go): claim matching orphans
        for pod in orphans:
            try:
                self.clientset.pods.guaranteed_update(
                    pod.meta.name,
                    lambda p: self._stamp_owner(p, rs),
                    pod.meta.namespace,
                )
                owned.append(pod)
            except NotFoundError:
                continue

        diff = len(owned) - rs.replicas
        if diff < 0:
            n = min(-diff, self.burst_replicas)
            self.expectations.expect(key, n, 0)
            for _ in range(n):
                self._create_pod(rs)
        elif diff > 0:
            n = min(diff, self.burst_replicas)
            # prefer deleting pods that aren't running yet (unbound first)
            victims = sorted(owned, key=lambda p: (bool(p.spec.node_name), p.meta.name))[:n]
            self.expectations.expect(key, 0, n)
            for pod in victims:
                try:
                    self.clientset.pods.delete(pod.meta.name, pod.meta.namespace)
                except NotFoundError:
                    self.expectations.observe_delete(key)

        # status
        ready = sum(1 for p in owned if p.status.phase == api.RUNNING)
        if (
            rs.status_replicas != len(owned)
            or rs.status_ready_replicas != ready
            or rs.status_observed_generation != rs.meta.generation
        ):
            def _status(cur: api.ReplicaSet) -> api.ReplicaSet:
                cur.status_replicas = len(owned)
                cur.status_ready_replicas = ready
                cur.status_observed_generation = cur.meta.generation
                return cur

            self.clientset.client_for(self.KIND).guaranteed_update(name, _status, namespace)

    def _stamp_owner(self, pod: api.Pod, rs: api.ReplicaSet) -> api.Pod:
        if pod.meta.controller_ref() is None:
            pod.meta.owner_references.append(
                OwnerReference(kind=self.KIND, name=rs.meta.name, uid=rs.meta.uid, controller=True)
            )
        return pod

    def _create_pod(self, rs: api.ReplicaSet) -> None:
        pod = api.Pod(
            meta=ObjectMeta(
                name=f"{rs.meta.name}-{next(_suffix):06d}",
                namespace=rs.meta.namespace,
                labels=dict(rs.template.labels),
                owner_references=[
                    OwnerReference(kind=self.KIND, name=rs.meta.name, uid=rs.meta.uid, controller=True)
                ],
            ),
            spec=api.PodSpec.from_dict(rs.template.spec.to_dict()),
        )
        try:
            self.clientset.pods.create(pod)
        except AlreadyExistsError:
            self.expectations.observe_create(rs.meta.key)


class ReplicationControllerController(ReplicaSetController):
    """``pkg/controller/replication``: identical reconcile over the RC
    kind (map selector; ``ReplicationController.selector`` adapts)."""

    name = "replication"
    KIND = "ReplicationController"
