"""Endpoints controller: service selector -> backend pod addresses.

Capability of ``pkg/controller/endpoint/endpoints_controller.go`` (613
LoC): for every Service with a selector, maintain an Endpoints object of
the same name whose subsets hold the pod IPs of Running+ready matching
pods (not-ready pods land in ``notReadyAddresses``), with the service's
target ports."""

from __future__ import annotations

from ..api import types as api
from ..api.cluster import EndpointAddress, EndpointPort, Endpoints, EndpointSubset
from ..api.meta import ObjectMeta
from ..store.store import AlreadyExistsError, NotFoundError
from .base import Controller


def _pod_ready(pod: api.Pod) -> bool:
    if pod.status.phase != api.RUNNING:
        return False
    for c in pod.status.conditions:
        if c.get("type") == "Ready":
            return c.get("status") == "True"
    return True  # no Ready condition recorded -> assume ready when Running


class EndpointController(Controller):
    name = "endpoint"

    def __init__(self, clientset, informers=None, **kw):
        super().__init__(clientset, informers, **kw)
        self.watch("Service")
        from ..client.informer import Handler

        # updates requeue services matching the OLD labels too, so a pod
        # relabeled away from a selector is removed from its endpoints
        self.informers.informer("Pod").add_handler(Handler(
            on_add=self._pod_event,
            on_update=lambda old, new: (self._pod_event(old), self._pod_event(new)),
            on_delete=self._pod_event,
        ))

    def _pod_event(self, pod: api.Pod) -> None:
        for svc in self.informer("Service").list():
            if svc.meta.namespace != pod.meta.namespace or not svc.selector:
                continue
            if all(pod.meta.labels.get(k) == v for k, v in svc.selector.items()):
                self.queue.add(svc.meta.key)

    def sync(self, key: str) -> None:
        namespace, name = key.split("/", 1)
        try:
            svc = self.clientset.services.get(name, namespace)
        except NotFoundError:
            # service gone: remove its endpoints
            try:
                self.clientset.endpoints.delete(name, namespace)
            except NotFoundError:
                pass
            return
        if not svc.selector:
            return  # manual endpoints (headless external): hands off

        ready: list[EndpointAddress] = []
        not_ready: list[EndpointAddress] = []
        for pod in self.clientset.pods.list(namespace)[0]:
            if pod.status.phase in (api.SUCCEEDED, api.FAILED):
                continue
            if not all(pod.meta.labels.get(k) == v for k, v in svc.selector.items()):
                continue
            if not pod.status.pod_ip:
                continue
            addr = EndpointAddress(
                ip=pod.status.pod_ip,
                node_name=pod.spec.node_name,
                target_pod=pod.meta.key,
            )
            (ready if _pod_ready(pod) else not_ready).append(addr)

        ports = [
            EndpointPort(name=p.name, port=(p.target_port or p.port), protocol=p.protocol)
            for p in svc.ports
        ]
        subsets = []
        if ready or not_ready:
            subsets = [EndpointSubset(
                addresses=sorted(ready, key=lambda a: a.ip),
                not_ready_addresses=sorted(not_ready, key=lambda a: a.ip),
                ports=ports,
            )]

        desired = Endpoints(
            meta=ObjectMeta(name=name, namespace=namespace, labels=dict(svc.meta.labels)),
            subsets=subsets,
        )
        try:
            cur = self.clientset.endpoints.get(name, namespace)
        except NotFoundError:
            try:
                self.clientset.endpoints.create(desired)
            except AlreadyExistsError:
                pass
            return
        if [s.to_dict() for s in cur.subsets] != [s.to_dict() for s in subsets]:
            def _update(obj: Endpoints) -> Endpoints:
                obj.subsets = subsets
                return obj

            self.clientset.endpoints.guaranteed_update(name, _update, namespace)
