"""TTL controller: anneal node object-cache TTL annotations with cluster
size (capability of ``pkg/controller/ttl/ttlcontroller.go`` — kubelets
read ``node.alpha.kubernetes.io/ttl`` to decide how long secrets/
configmaps may be cached; bigger clusters get longer TTLs to shed
apiserver load)."""

from __future__ import annotations

from ..api import types as api
from ..store.store import NotFoundError
from .base import Controller

TTL_ANNOTATION = "node.alpha.kubernetes.io/ttl"

# (cluster-size threshold, ttl seconds) — reference ttlcontroller.go
_BOUNDARIES = [(0, 0), (100, 15), (500, 30), (1000, 60), (2000, 300)]


def ttl_for(num_nodes: int) -> int:
    ttl = 0
    for threshold, seconds in _BOUNDARIES:
        if num_nodes >= threshold:
            ttl = seconds
    return ttl


class TTLController(Controller):
    name = "ttl"

    def __init__(self, clientset, informers=None, **kw):
        super().__init__(clientset, informers, **kw)
        self.watch("Node", key_fn=lambda n: n.meta.name)

    def sync(self, key: str) -> None:
        nodes, _ = self.clientset.nodes.list()
        want = str(ttl_for(len(nodes)))
        try:
            node = self.clientset.nodes.get(key)
        except NotFoundError:
            return
        if node.meta.annotations.get(TTL_ANNOTATION) == want:
            return

        def _stamp(cur: api.Node) -> api.Node:
            cur.meta.annotations[TTL_ANNOTATION] = want
            return cur

        self.clientset.nodes.guaranteed_update(key, _stamp)
