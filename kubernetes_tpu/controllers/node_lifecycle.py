"""Node lifecycle controller: failure detection and pod eviction.

Capability of ``pkg/controller/node`` (3,192 LoC;
``node_controller.go:189,468 monitorNodeStatus``, zone-aware eviction
queues in ``node/scheduler/rate_limited_queue.go``, ``zoneStates :170``):

- kubelet heartbeats refresh the Ready condition; staleness past
  ``grace_period`` marks the node Unknown (the controller, not the
  kubelet, declares death — level-triggered from observed state);
- pods on dead nodes are evicted (deleted) after ``pod_eviction_timeout``
  through a **per-zone token bucket**, with the reference's zone-outage
  damping: when more than ``unhealthy_zone_threshold`` of a zone is down,
  the zone is treated as partitioned and evictions slow/stop — a network
  partition must not mass-delete every workload (SURVEY.md §5.2).

Driven by an explicit ``monitor()`` tick with an injected clock, so every
timing behavior is deterministic under test (the reference's fake-clock
pattern)."""

from __future__ import annotations

import logging
from typing import Callable, Optional

from ..api import types as api
from ..store.store import NotFoundError
from .base import Controller

logger = logging.getLogger("kubernetes_tpu.controllers.node")

ZONE_NORMAL = "Normal"
ZONE_PARTIAL = "PartialDisruption"
ZONE_FULL = "FullDisruption"


class RateLimiter:
    """Token bucket (the reference's flowcontrol.NewTokenBucketRateLimiter)."""

    def __init__(self, qps: float, burst: int, clock: Callable[[], float]):
        self.qps = qps
        self.burst = burst
        self._clock = clock
        self._tokens = float(burst)
        self._last = clock()

    def try_accept(self) -> bool:
        now = self._clock()
        self._tokens = min(self.burst, self._tokens + (now - self._last) * self.qps)
        self._last = now
        if self._tokens >= 1.0:
            self._tokens -= 1.0
            return True
        return False

    def set_qps(self, qps: float) -> None:
        self.qps = qps


class NodeLifecycleController(Controller):
    name = "node-lifecycle"

    def __init__(
        self,
        clientset,
        informers=None,
        grace_period: float = 40.0,
        pod_eviction_timeout: float = 300.0,
        eviction_qps: float = 0.1,
        secondary_eviction_qps: float = 0.01,
        unhealthy_zone_threshold: float = 0.55,
        large_zone_size: int = 50,
        use_taint_based_evictions: bool = False,
        **kw,
    ):
        super().__init__(clientset, informers, **kw)
        self.use_taint_based_evictions = use_taint_based_evictions
        self.grace_period = grace_period
        self.pod_eviction_timeout = pod_eviction_timeout
        self.eviction_qps = eviction_qps
        self.secondary_eviction_qps = secondary_eviction_qps
        self.unhealthy_zone_threshold = unhealthy_zone_threshold
        self.large_zone_size = large_zone_size
        self._zone_limiters: dict[str, RateLimiter] = {}
        self._not_ready_since: dict[str, float] = {}
        self.zone_states: dict[str, str] = {}
        self.informers.informer("Node")
        # by-node pod index (fieldSelector analogue) so eviction is
        # O(pods-on-node), not O(cluster-pods) per dead node per tick
        from ..client.informer import PodNodeIndex

        self._pod_index = PodNodeIndex(self.informers.informer("Pod"))

    def sync(self, key: str) -> None:  # queue unused; monitor() drives
        pass

    # -- the monitor tick --------------------------------------------------
    def monitor(self) -> dict:
        """One monitorNodeStatus pass; returns a summary for observability."""
        self.informers.pump_all()
        now = self.clock()
        nodes = self.informer("Node").list()
        summary = {"marked_unknown": 0, "evicted_pods": 0, "zones": {}}

        # 1. staleness -> Ready=Unknown
        for node in nodes:
            ready = node.status.condition(api.NODE_READY)
            hb = ready.heartbeat_time if ready else 0.0
            if ready is None or (ready.status == "True" and now - hb > self.grace_period):
                self._mark_unknown(node, now)
                summary["marked_unknown"] += 1

        # 2. zone census
        self.informers.pump_all()
        nodes = self.informer("Node").list()
        zone_members: dict[str, list[api.Node]] = {}
        for node in nodes:
            zone = node.meta.labels.get(api.ZONE_LABEL, "")
            zone_members.setdefault(zone, []).append(node)
        for zone, members in zone_members.items():
            not_ready = [n for n in members if not self._is_ready(n)]
            frac = len(not_ready) / len(members) if members else 0.0
            if frac >= 1.0:
                state = ZONE_FULL
            elif frac >= self.unhealthy_zone_threshold:
                state = ZONE_PARTIAL
            else:
                state = ZONE_NORMAL
            self.zone_states[zone] = state
            summary["zones"][zone] = state
            limiter = self._zone_limiters.get(zone)
            if limiter is None:
                limiter = RateLimiter(self.eviction_qps, burst=1, clock=self.clock)
                self._zone_limiters[zone] = limiter
            # reference zoneStates damping: partial outage in a large zone →
            # slow eviction; small zone or full outage → stop entirely
            if state == ZONE_NORMAL:
                limiter.set_qps(self.eviction_qps)
            elif state == ZONE_PARTIAL and len(members) > self.large_zone_size:
                limiter.set_qps(self.secondary_eviction_qps)
            else:
                limiter.set_qps(0.0)

        # 3. evictions — either direct pod deletes after the grace window,
        # or (taint mode) NoExecute taints applied at once: the taint
        # manager then enforces each pod's own tolerationSeconds instead of
        # one controller-wide timeout (taint_controller.go)
        for zone, members in zone_members.items():
            limiter = self._zone_limiters[zone]
            for node in members:
                if self._is_ready(node):
                    self._not_ready_since.pop(node.meta.name, None)
                    if self.use_taint_based_evictions:
                        self._set_failure_taints(node, ready=True)
                    continue
                if limiter.qps <= 0.0:
                    continue  # zone damped: leave state as-is
                if self.use_taint_based_evictions:
                    if limiter.try_accept():
                        self._set_failure_taints(node, ready=False)
                        summary["tainted"] = summary.get("tainted", 0) + 1
                    continue
                since = self._not_ready_since.setdefault(node.meta.name, now)
                if now - since < self.pod_eviction_timeout:
                    continue
                summary["evicted_pods"] += self._evict_pods(node, limiter)
        return summary

    def _set_failure_taints(self, node: api.Node, ready: bool) -> None:
        """Reconcile the notReady/unreachable NoExecute taints to the
        node's observed condition (reference ``zoneNoExecuteTainer``)."""
        from .taint import TAINT_NOT_READY, TAINT_UNREACHABLE

        cond = node.status.condition(api.NODE_READY)
        status = cond.status if cond else "Unknown"
        want_key = None
        if not ready:
            want_key = TAINT_UNREACHABLE if status == "Unknown" else TAINT_NOT_READY
        ours = {TAINT_NOT_READY, TAINT_UNREACHABLE}
        have = {t.key for t in node.spec.taints if t.key in ours}
        if have == ({want_key} if want_key else set()):
            return

        def _mutate(cur: api.Node) -> api.Node:
            cur.spec.taints = [t for t in cur.spec.taints if t.key not in ours]
            if want_key:
                cur.spec.taints.append(api.Taint(key=want_key, effect=api.NO_EXECUTE))
            return cur

        try:
            self.clientset.nodes.guaranteed_update(node.meta.name, _mutate, "")
        except NotFoundError:
            pass

    # -- helpers -----------------------------------------------------------
    def _is_ready(self, node: api.Node) -> bool:
        c = node.status.condition(api.NODE_READY)
        return c is not None and c.status == "True"

    def _mark_unknown(self, node: api.Node, now: float) -> None:
        def _mutate(cur: api.Node) -> api.Node:
            c = cur.status.condition(api.NODE_READY)
            if c is None:
                c = api.NodeCondition(type=api.NODE_READY)
                cur.status.conditions.append(c)
            c.status = "Unknown"
            return cur

        try:
            self.clientset.nodes.guaranteed_update(node.meta.name, _mutate, "")
        except NotFoundError:
            pass

    def _evict_pods(self, node: api.Node, limiter: RateLimiter) -> int:
        evicted = 0
        for pod in self._pod_index.pods_on(node.meta.name):
            if not limiter.try_accept():
                break
            try:
                self.clientset.pods.delete(pod.meta.name, pod.meta.namespace)
                evicted += 1
            except NotFoundError:
                continue
        return evicted
