"""ServiceAccount + token controllers.

Capability of ``pkg/controller/serviceaccount``: ensure every namespace
has a "default" ServiceAccount (``serviceaccounts_controller.go``), and
mint a token Secret for each ServiceAccount that lacks one
(``tokens_controller.go``, tokens signed by ``pkg/serviceaccount`` — here
the HMAC minter from the auth stack)."""

from __future__ import annotations

from ..api.cluster import Secret, ServiceAccount
from ..api.meta import ObjectMeta
from ..auth.authn import ServiceAccountTokenMinter
from ..store.store import AlreadyExistsError, NotFoundError
from .base import Controller


class ServiceAccountController(Controller):
    name = "serviceaccount"

    def __init__(self, clientset, informers=None,
                 minter: ServiceAccountTokenMinter | None = None, **kw):
        super().__init__(clientset, informers, **kw)
        self.minter = minter or ServiceAccountTokenMinter()
        self.watch("Namespace", key_fn=lambda ns: f"ns/{ns.meta.name}")
        self.watch("ServiceAccount", key_fn=lambda sa: f"sa/{sa.meta.key}")

    def sync(self, key: str) -> None:
        what, _, rest = key.partition("/")
        if what == "ns":
            self._ensure_default_sa(rest)
        elif what == "sa":
            namespace, name = rest.split("/", 1)
            self._ensure_token(namespace, name)

    def _ensure_default_sa(self, namespace: str) -> None:
        try:
            ns = self.clientset.namespaces.get(namespace)
        except NotFoundError:
            return
        if ns.phase == "Terminating":
            return
        try:
            self.clientset.serviceaccounts.get("default", namespace)
        except NotFoundError:
            try:
                self.clientset.serviceaccounts.create(
                    ServiceAccount(meta=ObjectMeta(name="default", namespace=namespace)))
            except AlreadyExistsError:
                pass

    def _ensure_token(self, namespace: str, name: str) -> None:
        try:
            sa = self.clientset.serviceaccounts.get(name, namespace)
        except NotFoundError:
            return
        if sa.secrets:
            return
        secret_name = f"{name}-token"
        token = self.minter.mint(namespace, name)
        try:
            self.clientset.secrets.create(Secret(
                meta=ObjectMeta(name=secret_name, namespace=namespace),
                type="kubernetes.io/service-account-token",
                data={"token": token},
            ))
        except AlreadyExistsError:
            pass

        def _link(cur: ServiceAccount) -> ServiceAccount:
            if secret_name not in cur.secrets:
                cur.secrets.append(secret_name)
            return cur

        self.clientset.serviceaccounts.guaranteed_update(name, _link, namespace)
