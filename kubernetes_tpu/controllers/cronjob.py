"""CronJob controller: create Jobs on a cron schedule.

Capability of ``pkg/controller/cronjob/cronjob_controller.go`` (935 LoC).
The reference polls every 10s rather than watching; here the controller is
level-triggered the same way — ``tick()`` (or a queued sync) evaluates
every CronJob against the injected clock, creates Jobs for unmet schedule
times, applies the concurrency policy, and prunes finished Jobs beyond the
history limits."""

from __future__ import annotations

from ..api.apps import CronJob, Job
from ..api.meta import ObjectMeta, OwnerReference
from ..api.selectors import LabelSelector
from ..api.types import PodTemplateSpec
from ..store.store import AlreadyExistsError, NotFoundError
from ..utils.cron import CronSchedule
from .base import Controller


class CronJobController(Controller):
    name = "cronjob"

    def __init__(self, clientset, informers=None, **kw):
        super().__init__(clientset, informers, **kw)
        self.watch("CronJob")
        self.watch("Job", key_fn=self._job_owner_key)

    def _job_owner_key(self, job):
        ref = job.meta.controller_ref()
        if ref is None or ref.kind != "CronJob":
            return None
        return f"{job.meta.namespace}/{ref.name}"

    def tick(self) -> None:
        """Enqueue every CronJob (the reference's 10s ``syncAll`` poll) —
        from the informer cache, not a wire LIST per poll."""
        for cj in self.informer("CronJob").list():
            self.queue.add(cj.meta.key)

    def sync(self, key: str) -> None:
        namespace, name = key.split("/", 1)
        try:
            cj = self.clientset.cronjobs.get(name, namespace)
        except NotFoundError:
            return
        if cj.suspend:
            return
        now = self.clock()
        schedule = CronSchedule.parse(cj.schedule)

        owned = [j for j in self.clientset.jobs.list(namespace)[0]
                 if any(r.kind == "CronJob" and r.uid == cj.meta.uid
                        for r in j.meta.owner_references)]
        running = [j for j in owned if not j.complete and not j.failed]

        # reconcile status.active against observed running jobs
        active_names = sorted(j.meta.name for j in running)

        last = cj.status_last_schedule_time
        if not last:
            last = now - 61.0  # first sync: look one schedule window back
        unmet = schedule.unmet_since(last, now)
        started = None
        if unmet:
            run_time = unmet[-1]  # most recent unmet time wins (reference)
            too_late = (
                cj.starting_deadline_seconds is not None
                and now - run_time > cj.starting_deadline_seconds
            )
            if not too_late:
                if running and cj.concurrency_policy == "Forbid":
                    pass  # skip this run
                else:
                    if running and cj.concurrency_policy == "Replace":
                        for j in running:
                            self._delete_job(j)
                            if j.meta.name in active_names:
                                active_names.remove(j.meta.name)
                        running = []
                    started = self._create_job(cj, run_time)
                    if started:
                        active_names.append(started)

        self._prune_history(cj, owned)

        def _status(cur: CronJob) -> CronJob:
            cur.status_active = sorted(set(active_names))
            if started is not None:
                cur.status_last_schedule_time = now
            return cur

        self.clientset.cronjobs.guaranteed_update(name, _status, namespace)

    def _create_job(self, cj: CronJob, run_time: float) -> str | None:
        tpl = cj.job_template or {}
        # deterministic name from the scheduled minute (reference
        # getJobFromTemplate: <cronjob>-<minute-epoch>)
        job_name = f"{cj.meta.name}-{int(run_time) // 60}"
        job = Job(
            meta=ObjectMeta(
                name=job_name,
                namespace=cj.meta.namespace,
                labels=dict((tpl.get("labels") or {}) or cj.meta.labels),
                owner_references=[OwnerReference(
                    kind="CronJob", name=cj.meta.name, uid=cj.meta.uid, controller=True)],
            ),
            parallelism=int(tpl.get("parallelism", 1)),
            completions=tpl.get("completions", 1),
            backoff_limit=int(tpl.get("backoffLimit", 6)),
            selector=LabelSelector.from_dict(tpl.get("selector")),
            template=PodTemplateSpec.from_dict(tpl.get("template")),
        )
        try:
            self.clientset.jobs.create(job)
        except AlreadyExistsError:
            return None  # this schedule time already ran
        return job_name

    def _delete_job(self, job: Job) -> None:
        try:
            self.clientset.jobs.delete(job.meta.name, job.meta.namespace)
        except NotFoundError:
            pass
        # cascade to the job's pods (the GC would also get these via
        # ownerRefs; doing it inline keeps Replace semantics immediate)
        for p in self.clientset.pods.list(job.meta.namespace)[0]:
            ref = p.meta.controller_ref()
            if ref is not None and ref.kind == "Job" and ref.name == job.meta.name:
                try:
                    self.clientset.pods.delete(p.meta.name, p.meta.namespace)
                except NotFoundError:
                    pass

    def _prune_history(self, cj: CronJob, owned: list[Job]) -> None:
        done_ok = sorted((j for j in owned if j.complete), key=lambda j: j.meta.creation_revision)
        done_bad = sorted((j for j in owned if j.failed), key=lambda j: j.meta.creation_revision)
        for j in done_ok[: max(0, len(done_ok) - cj.successful_jobs_history_limit)]:
            self._delete_job(j)
        for j in done_bad[: max(0, len(done_bad) - cj.failed_jobs_history_limit)]:
            self._delete_job(j)
