"""StatefulSet controller: ordered, identity-preserving replicas.

Capability of ``pkg/controller/statefulset/stateful_set.go`` (+
``stateful_set_control.go``): pods are named ``<set>-<ordinal>``; with the
default OrderedReady policy, ordinal N is created only after 0..N-1 are
Running, scale-down removes the highest ordinal first and one at a time,
and RollingUpdate replaces outdated pods from the highest ordinal down
(respecting ``partition``)."""

from __future__ import annotations

import re

from ..api import types as api
from ..api.apps import StatefulSet
from ..api.meta import ObjectMeta, OwnerReference
from ..store.store import AlreadyExistsError, NotFoundError
from .base import Controller
from .deployment import template_hash

HASH_LABEL = "pod-template-hash"


def ordinal_of(set_name: str, pod_name: str) -> int | None:
    m = re.fullmatch(re.escape(set_name) + r"-(\d+)", pod_name)
    return int(m.group(1)) if m else None


class StatefulSetController(Controller):
    name = "statefulset"

    def __init__(self, clientset, informers=None, **kw):
        super().__init__(clientset, informers, **kw)
        self.watch("StatefulSet")
        from ..client.informer import Handler, PodOwnerIndex

        self.pod_index = PodOwnerIndex(self.informers.informer("Pod"))
        self.informers.informer("Pod").add_handler(Handler(
            on_add=self._pod_event,
            on_update=lambda old, new: self._pod_event(new),
            on_delete=self._pod_event,
        ))

    def _pod_event(self, pod: api.Pod) -> None:
        ref = pod.meta.controller_ref()
        if ref is not None and ref.kind == "StatefulSet":
            self.queue.add(f"{pod.meta.namespace}/{ref.name}")

    def sync(self, key: str) -> None:
        namespace, name = key.split("/", 1)
        try:
            ss = self.clientset.statefulsets.get(name, namespace)
        except NotFoundError:
            return
        owned = {}
        for p in self.pod_index.owned_by(ss.meta.uid):
            if p.meta.namespace != namespace:
                continue
            o = ordinal_of(name, p.meta.name)
            if o is not None:
                owned[o] = p

        want_hash = template_hash(ss.template)
        ordered = ss.pod_management_policy == "OrderedReady"

        # -- replace failed replicas (stateful_set_control.go: failed pods
        # are deleted and recreated with the same identity) ------------------
        for o in list(owned):
            if owned[o].status.phase in (api.FAILED, api.SUCCEEDED):
                self._delete_pod(owned[o])
                del owned[o]

        # -- scale up: create missing ordinals [0, replicas) -----------------
        created_blocking = False
        for i in range(ss.replicas):
            if i in owned:
                if ordered and owned[i].status.phase != api.RUNNING:
                    created_blocking = True  # wait for this ordinal first
                    break
                continue
            self._create_pod(ss, i, want_hash)
            created_blocking = True
            if ordered:
                break  # one at a time, wait for Running
        # -- scale down: delete highest ordinal first ------------------------
        extra = sorted((o for o in owned if o >= ss.replicas), reverse=True)
        if extra and not created_blocking:
            victims = extra if not ordered else extra[:1]
            for o in victims:
                self._delete_pod(owned[o])

        # -- rolling update: replace outdated, highest ordinal first ---------
        if (
            ss.update_strategy == "RollingUpdate"
            and not created_blocking
            and not extra
            and all(owned[o].status.phase == api.RUNNING
                    for o in owned if o < ss.replicas)
        ):
            for o in sorted((o for o in owned if o < ss.replicas), reverse=True):
                if o < ss.partition:
                    continue
                if owned[o].meta.labels.get(HASH_LABEL) != want_hash:
                    # delete; the next sync recreates the ordinal with the
                    # new template (identity preserved through the name)
                    self._delete_pod(owned[o])
                    break  # one at a time

        in_range = [owned[o] for o in owned if o < ss.replicas]
        ready = sum(1 for p in in_range if p.status.phase == api.RUNNING)
        updated = sum(1 for p in in_range if p.meta.labels.get(HASH_LABEL) == want_hash)

        def _status(cur: StatefulSet) -> StatefulSet:
            cur.status_replicas = len(in_range)
            cur.status_ready_replicas = ready
            cur.status_current_replicas = len(in_range)
            cur.status_updated_replicas = updated
            cur.status_observed_generation = cur.meta.generation
            return cur

        self.clientset.statefulsets.guaranteed_update(name, _status, namespace)

    def _create_pod(self, ss: StatefulSet, ordinal: int, want_hash: str) -> None:
        labels = dict(ss.template.labels)
        labels[HASH_LABEL] = want_hash
        labels["statefulset.kubernetes.io/pod-name"] = f"{ss.meta.name}-{ordinal}"
        pod = api.Pod(
            meta=ObjectMeta(
                name=f"{ss.meta.name}-{ordinal}",
                namespace=ss.meta.namespace,
                labels=labels,
                owner_references=[OwnerReference(
                    kind="StatefulSet", name=ss.meta.name, uid=ss.meta.uid, controller=True)],
            ),
            spec=api.PodSpec.from_dict(ss.template.spec.to_dict()),
        )
        try:
            self.clientset.pods.create(pod)
        except AlreadyExistsError:
            pass

    def _delete_pod(self, pod: api.Pod) -> None:
        try:
            self.clientset.pods.delete(pod.meta.name, pod.meta.namespace)
        except NotFoundError:
            pass
