"""NoExecute taint manager: timed, toleration-aware evictions.

Capability of the reference's ``NoExecuteTaintManager``
(``pkg/controller/node/scheduler/taint_controller.go`` +
``timed_workers.go``):

- a pod on a node carrying NoExecute taints is evicted **immediately**
  if it does not tolerate every such taint;
- if it tolerates them all but some toleration carries
  ``tolerationSeconds``, a timed eviction fires at the MINIMUM such
  value (``getMinTolerationTime``), measured from when the taint was
  first observed for that pod;
- tolerating with no ``tolerationSeconds`` means it stays forever;
- removing the taints (or deleting the pod / moving the node back to
  Ready) cancels the pending timer (``timed_workers.go CancelWork``).

The companion piece is taint-based failure marking: with
``use_taint_based_evictions``, ``NodeLifecycleController`` applies the
era's ``node.alpha.kubernetes.io/notReady`` / ``unreachable`` NoExecute
taints instead of deleting pods itself, and the DefaultTolerationSeconds
admission plugin (``admission/plugins.py``) gives every pod the 300s
grace the reference does — so this manager is what actually enforces
those timers.

Time is an injected clock + explicit ``tick()`` (the reference's timed
workers collapsed into a deterministic heap scan)."""

from __future__ import annotations

import heapq
import logging
import threading
from typing import Callable, Optional

from ..api import types as api
from ..store.store import NotFoundError
from .base import Controller

logger = logging.getLogger("kubernetes_tpu.controllers.taint")

# single-sourced from the API package (shared with the
# DefaultTolerationSeconds admission plugin)
TAINT_NOT_READY = api.TAINT_NODE_NOT_READY
TAINT_UNREACHABLE = api.TAINT_NODE_UNREACHABLE


def _no_execute_taints(node: api.Node) -> list[api.Taint]:
    return [t for t in node.spec.taints if t.effect == api.NO_EXECUTE]


def min_toleration_seconds(pod: api.Pod, taints: list[api.Taint]) -> Optional[float]:
    """None = evict now; float('inf') = tolerated forever; else seconds.

    Reference ``getMatchingTolerations`` + ``getMinTolerationTime``: the
    pod must tolerate EVERY NoExecute taint; the timer is the minimum
    ``tolerationSeconds`` across the tolerations used."""
    if not taints:
        return float("inf")
    used: list[api.Toleration] = []
    for taint in taints:
        match = next((tol for tol in pod.spec.tolerations if tol.tolerates(taint)), None)
        if match is None:
            return None
        used.append(match)
    secs = [t.toleration_seconds for t in used if t.toleration_seconds is not None]
    if not secs:
        return float("inf")
    return float(max(0, min(secs)))


class NoExecuteTaintManager(Controller):
    name = "taint-manager"

    def __init__(self, clientset, informers=None, **kw):
        super().__init__(clientset, informers, **kw)
        self.watch("Node", key_fn=lambda n: f"node/{n.meta.name}")
        self.watch("Pod", key_fn=self._pod_key)
        from ..client.informer import PodNodeIndex

        self._pod_index = PodNodeIndex(self.informers.informer("Pod"))
        # pod key -> (deadline, node_name); a heap mirrors the deadlines.
        # Guarded by _mu: sync() runs on run_workers() threads while tick()
        # pumps the heap from the manager loop (ktpu-analyze RL303).
        self._mu = threading.Lock()
        self._pending: dict[str, tuple[float, str]] = {}
        self._heap: list[tuple[float, str]] = []
        self.stats = {"evicted_now": 0, "evicted_timed": 0, "cancelled": 0}

    def _pod_key(self, pod: api.Pod):
        return f"pod/{pod.meta.key}" if pod.spec.node_name else None

    # -- reconcile ---------------------------------------------------------
    def sync(self, key: str) -> None:
        kind, _, rest = key.partition("/")
        if kind == "node":
            self._sync_node(rest)
        else:
            self._sync_pod(rest)

    def _sync_node(self, name: str) -> None:
        node = self.informer("Node").get(name)
        taints = _no_execute_taints(node) if node is not None else []
        if not taints:
            # taint gone (or node gone): cancel every timer for this node
            with self._mu:
                for pod_key, (_, node_name) in list(self._pending.items()):
                    if node_name == name:
                        del self._pending[pod_key]
                        self.stats["cancelled"] += 1
            return
        for pod in self._pod_index.pods_on(name):
            self._process(pod, taints)

    def _sync_pod(self, pod_key: str) -> None:
        pod = self.informer("Pod").get(pod_key)
        if pod is None or not pod.spec.node_name:
            with self._mu:
                if self._pending.pop(pod_key, None) is not None:
                    self.stats["cancelled"] += 1
            return
        node = self.informer("Node").get(pod.spec.node_name)
        taints = _no_execute_taints(node) if node is not None else []
        self._process(pod, taints)

    def _process(self, pod: api.Pod, taints: list[api.Taint]) -> None:
        key = pod.meta.key
        wait = min_toleration_seconds(pod, taints)
        if wait is None:
            with self._mu:
                self._pending.pop(key, None)
            self._evict(pod.meta.name, pod.meta.namespace, timed=False)
            return
        if wait == float("inf"):
            with self._mu:
                if self._pending.pop(key, None) is not None:
                    self.stats["cancelled"] += 1
            return
        deadline = self.clock() + wait
        with self._mu:
            cur = self._pending.get(key)
            if cur is not None and cur[1] == pod.spec.node_name:
                return  # timer already armed from first observation; keep it
            self._pending[key] = (deadline, pod.spec.node_name)
            heapq.heappush(self._heap, (deadline, key))

    # -- the timer pump ----------------------------------------------------
    def tick(self) -> int:
        """Fire due evictions (timed_workers collapsed to a heap scan)."""
        self.informers.pump_all()
        while self.sync_once():
            pass
        now = self.clock()
        # drain due keys under the lock, evict outside it (the delete is an
        # API round-trip; holding _mu across it would stall sync workers)
        due: list[tuple[str, str]] = []  # (pod key, node name)
        with self._mu:
            while self._heap and self._heap[0][0] <= now:
                deadline, key = heapq.heappop(self._heap)
                cur = self._pending.get(key)
                if cur is None or cur[0] != deadline:
                    continue  # cancelled or re-armed
                del self._pending[key]
                due.append((key, cur[1]))
        fired = 0
        for key, node_name in due:
            ns, _, name = key.partition("/")
            try:
                self._evict(name, ns, timed=True)
            except Exception:  # noqa: BLE001 - transient API failure
                # re-arm as already-due so the NEXT tick retries; without
                # this a failed delete mid-batch would silently drop every
                # drained timer (they are gone from _pending and _heap)
                with self._mu:
                    self._pending[key] = (now, node_name)
                    heapq.heappush(self._heap, (now, key))
            else:
                fired += 1
        return fired

    def _evict(self, name: str, namespace: str, timed: bool) -> None:
        try:
            self.clientset.pods.delete(name, namespace)
            with self._mu:
                self.stats["evicted_timed" if timed else "evicted_now"] += 1
        except NotFoundError:
            pass

    def pending_count(self) -> int:
        with self._mu:
            return len(self._pending)
