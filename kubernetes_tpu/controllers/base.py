"""Generic controller runtime: informers → workqueue → reconcile.

The shape every reference controller shares (SURVEY.md §2.5, P3; exemplar
``deployment_controller.go:112,147,458``): watch events enqueue object
keys into a rate-limited dedup workqueue; N workers pop keys and run a
level-triggered ``sync(key)`` that reconciles desired vs observed state
through the API only.  Failures requeue with exponential backoff; success
forgets the backoff.

Drive modes mirror the informers: ``run_workers`` (threads, production
shape) or ``sync_once``/``reconcile_all`` (deterministic, for tests and
single-threaded composition)."""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Optional

from ..client.clientset import Clientset
from ..client.informer import Handler, InformerFactory
from ..client.workqueue import WorkQueue

logger = logging.getLogger("kubernetes_tpu.controllers")


class Controller:
    """Base: subclasses set ``name``, call ``watch(kind, ...)`` in
    ``__init__``, and implement ``sync(key)``."""

    name = "controller"
    max_retries = 15

    def __init__(self, clientset: Clientset, informers: Optional[InformerFactory] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.clientset = clientset
        self.informers = informers or InformerFactory(clientset)
        self.queue = WorkQueue(clock=clock)
        self.clock = clock
        self._stopped = threading.Event()
        self._threads: list[threading.Thread] = []

    # -- wiring ------------------------------------------------------------
    def watch(self, kind: str, key_fn: Optional[Callable] = None) -> None:
        """Subscribe to a kind; enqueue key_fn(obj) (default: the object's
        own key) on every add/update/delete."""
        key_fn = key_fn or (lambda obj: obj.meta.key)

        def enqueue(obj):
            key = key_fn(obj)
            if key is not None:
                self.queue.add(key)

        self.informers.informer(kind).add_handler(
            Handler(
                on_add=enqueue,
                on_update=lambda old, new: enqueue(new),
                on_delete=enqueue,
            )
        )

    def informer(self, kind: str):
        return self.informers.informer(kind)

    # -- reconcile ---------------------------------------------------------
    def sync(self, key: str) -> None:  # pragma: no cover - abstract
        raise NotImplementedError

    def _process_one(self, key) -> None:
        try:
            self.sync(key)
        except Exception as e:  # noqa: BLE001 - controller loops never die
            if self.queue.num_requeues(key) < self.max_retries:
                logger.warning("%s: sync %s failed (requeue): %s", self.name, key, e)
                self.queue.add_rate_limited(key)
            else:
                logger.error("%s: sync %s dropped after retries: %s", self.name, key, e)
                self.queue.forget(key)
        else:
            self.queue.forget(key)
        finally:
            self.queue.done(key)

    def sync_once(self, timeout: float = 0.0) -> bool:
        """Process one queued key synchronously; False if queue empty."""
        key = self.queue.get(timeout=timeout)
        if key is None:
            return False
        self._process_one(key)
        return True

    def reconcile_all(self, max_rounds: int = 50) -> int:
        """Pump informers + drain the queue until quiescent (tests)."""
        total = 0
        for _ in range(max_rounds):
            self.informers.pump_all()
            progressed = 0
            while self.sync_once():
                progressed += 1
            total += progressed
            self.informers.pump_all()
            if len(self.queue) == 0 and progressed == 0:
                break
        return total

    # -- threaded ----------------------------------------------------------
    def run_workers(self, n: int = 1) -> None:
        for _ in range(n):
            t = threading.Thread(target=self._worker_loop, daemon=True)
            t.start()
            self._threads.append(t)

    def _worker_loop(self) -> None:
        while not self._stopped.is_set():
            key = self.queue.get(timeout=0.2)
            if key is None:
                continue
            self._process_one(key)

    def stop(self) -> None:
        self._stopped.set()
        self.queue.shut_down()
        for t in self._threads:
            t.join(timeout=5)
