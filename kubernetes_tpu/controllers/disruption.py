"""Disruption controller: maintain PodDisruptionBudget status.

Capability of ``pkg/controller/disruption`` (765 LoC): for each PDB, count
healthy (Running) pods matching its selector, compute
``disruptionsAllowed = max(0, healthy - minAvailable)``, and keep the
counts fresh so the eviction subresource can gate voluntary evictions."""

from __future__ import annotations

from ..api import types as api
from ..api.cluster import PodDisruptionBudget
from ..store.store import NotFoundError
from .base import Controller


class DisruptionController(Controller):
    name = "disruption"

    def __init__(self, clientset, informers=None, **kw):
        super().__init__(clientset, informers, **kw)
        self.watch("PodDisruptionBudget")
        from ..client.informer import Handler

        # old labels requeue too (label moved off a PDB's selector)
        self.informers.informer("Pod").add_handler(Handler(
            on_add=self._pod_event,
            on_update=lambda old, new: (self._pod_event(old), self._pod_event(new)),
            on_delete=self._pod_event,
        ))

    def _pod_event(self, pod: api.Pod) -> None:
        for pdb in self.informer("PodDisruptionBudget").list():
            if pdb.meta.namespace == pod.meta.namespace and pdb.selector.matches(pod.meta.labels):
                self.queue.add(pdb.meta.key)

    def sync(self, key: str) -> None:
        namespace, name = key.split("/", 1)
        try:
            pdb = self.clientset.poddisruptionbudgets.get(name, namespace)
        except NotFoundError:
            return
        matching = [p for p in self.clientset.pods.list(namespace)[0]
                    if pdb.selector.matches(p.meta.labels)
                    and p.status.phase not in (api.SUCCEEDED, api.FAILED)]
        healthy = sum(1 for p in matching if p.status.phase == api.RUNNING)
        expected = len(matching)
        desired = pdb.min_available
        allowed = max(0, healthy - desired)

        def _status(cur: PodDisruptionBudget) -> PodDisruptionBudget:
            cur.status_current_healthy = healthy
            cur.status_desired_healthy = desired
            cur.status_expected_pods = expected
            cur.status_disruptions_allowed = allowed
            return cur

        self.clientset.poddisruptionbudgets.guaranteed_update(name, _status, namespace)
