"""IPAM: node pod-CIDR allocation + bootstrap token housekeeping.

Capabilities of three reference pieces grouped here:

- ``NodeIpamController`` (``pkg/controller/node/ipam``): carve the
  cluster CIDR into fixed-size per-node ranges and assign each node a
  ``spec.podCIDR``; released when the node goes away.
- ``BootstrapSigner`` (``pkg/controller/bootstrap/bootstrapsigner.go``):
  keep the ``kube-public/cluster-info`` ConfigMap signed with every
  active bootstrap token (HMAC stands in for JWS — the capability is a
  discovery document joiners can verify with nothing but their token).
- ``TokenCleaner`` (``tokencleaner.go``): delete expired bootstrap
  token Secrets.
"""

from __future__ import annotations

import hashlib
import hmac
import ipaddress
import logging
import threading

from ..api import types as api
from ..api.cluster import ConfigMap, Secret
from ..api.meta import ObjectMeta
from ..store.store import AlreadyExistsError, NotFoundError
from .base import Controller

logger = logging.getLogger("kubernetes_tpu.controllers.ipam")

BOOTSTRAP_TOKEN_PREFIX = "bootstrap-token-"
CLUSTER_INFO = "cluster-info"
KUBE_PUBLIC = "kube-public"
KUBE_SYSTEM = "kube-system"


class NodeIpamController(Controller):
    """reference ``pkg/controller/node/ipam`` range allocator."""

    name = "node-ipam"

    def __init__(self, clientset, informers=None,
                 cluster_cidr: str = "10.8.0.0/14", node_cidr_mask: int = 24, **kw):
        super().__init__(clientset, informers, **kw)
        self.network = ipaddress.ip_network(cluster_cidr)
        self.node_cidr_mask = node_cidr_mask
        # in-flight allocations (the reference's CidrSet): the informer
        # cache lags our own writes within a sync burst, so the
        # controller's view of "used" must include what IT just assigned.
        # Guarded by _mu: sync() runs on worker threads while _release()
        # fires on the informer thread (ktpu-analyze RL303).
        self._mu = threading.Lock()
        self._allocated: set[str] = set()
        from ..client.informer import Handler

        self.informers.informer("Node").add_handler(Handler(
            on_add=lambda n: self.queue.add(n.meta.name),
            on_update=lambda old, new: self.queue.add(new.meta.name),
            on_delete=self._release,
        ))

    def _release(self, node: api.Node) -> None:
        # node gone: its range returns to the pool (docstring contract)
        if node.spec.pod_cidr:
            with self._mu:
                self._allocated.discard(node.spec.pod_cidr)

    def _used(self) -> set[str]:
        with self._mu:
            allocated = set(self._allocated)
        return allocated | {
            n.spec.pod_cidr for n in self.informer("Node").list() if n.spec.pod_cidr
        }

    def sync(self, key: str) -> None:
        node = self.informer("Node").get(key)
        if node is None or node.spec.pod_cidr:
            return  # gone, or already allocated (CIDRs are sticky)
        used = self._used()
        for subnet in self.network.subnets(new_prefix=self.node_cidr_mask):
            cidr = str(subnet)
            if cidr in used:
                continue

            def _assign(cur: api.Node) -> api.Node:
                if not cur.spec.pod_cidr:  # lost race: keep first writer's
                    cur.spec.pod_cidr = cidr
                return cur

            try:
                got = self.clientset.nodes.guaranteed_update(key, _assign, "")
                if got.spec.pod_cidr == cidr:  # lost races must not leak
                    with self._mu:
                        self._allocated.add(cidr)
            except NotFoundError:
                pass
            return
        logger.error("node-ipam: cluster CIDR %s exhausted", self.network)


def sign_cluster_info(payload: str, token_secret: str) -> str:
    return hmac.new(token_secret.encode(), payload.encode(), hashlib.sha256).hexdigest()


def parse_token_expiration(raw) -> float:
    """Epoch-seconds or RFC3339; malformed values mean ALREADY EXPIRED —
    a broken token must fail closed, not crash the auth path."""
    if raw is None or raw == "inf":
        return float("inf")
    try:
        return float(raw)
    except (TypeError, ValueError):
        pass
    try:
        from datetime import datetime

        return datetime.fromisoformat(str(raw).replace("Z", "+00:00")).timestamp()
    except ValueError:
        return float("-inf")


def _bootstrap_tokens(secrets) -> list[tuple[str, str, float]]:
    """[(token_id, token_secret, expiration)] from bootstrap Secrets."""
    out = []
    for s in secrets:
        if not s.meta.name.startswith(BOOTSTRAP_TOKEN_PREFIX):
            continue
        data = s.data
        tid = data.get("token-id", s.meta.name[len(BOOTSTRAP_TOKEN_PREFIX):])
        out.append((tid, data.get("token-secret", ""),
                    parse_token_expiration(data.get("expiration"))))
    return out


class BootstrapSignerController(Controller):
    """Signs kube-public/cluster-info with every live bootstrap token."""

    name = "bootstrapsigner"

    def __init__(self, clientset, informers=None, cluster_info_payload: str = "", **kw):
        super().__init__(clientset, informers, **kw)
        self.payload = cluster_info_payload
        self.watch("Secret", key_fn=self._secret_key)

    def _secret_key(self, secret):
        if secret.meta.namespace == KUBE_SYSTEM and secret.meta.name.startswith(
            BOOTSTRAP_TOKEN_PREFIX
        ):
            return "sign"
        return None

    def sync(self, key: str) -> None:
        secrets = [
            s for s in self.informer("Secret").list() if s.meta.namespace == KUBE_SYSTEM
        ]
        payload = self.payload
        if not payload:
            # a signer started without its own payload (the default
            # controller set) signs the EXISTING discovery document —
            # it must never clobber what cluster init published
            try:
                payload = self.clientset.configmaps.get(
                    CLUSTER_INFO, KUBE_PUBLIC
                ).data.get("kubeconfig", "")
            except NotFoundError:
                return  # nothing to sign yet
        now = self.clock()
        sigs = {
            f"jws-kubeconfig-{tid}": sign_cluster_info(payload, tok)
            for tid, tok, exp in _bootstrap_tokens(secrets)
            if tok and exp > now
        }
        body = {"kubeconfig": payload, **sigs}

        try:
            def _update(cur: ConfigMap) -> ConfigMap:
                cur.data = dict(body)
                return cur

            self.clientset.configmaps.guaranteed_update(CLUSTER_INFO, _update, KUBE_PUBLIC)
        except NotFoundError:
            try:
                self.clientset.configmaps.create(ConfigMap(
                    meta=ObjectMeta(name=CLUSTER_INFO, namespace=KUBE_PUBLIC),
                    data=dict(body),
                ))
            except AlreadyExistsError:
                pass


class TokenCleanerController(Controller):
    """Deletes expired bootstrap token Secrets (tokencleaner.go)."""

    name = "tokencleaner"

    def __init__(self, clientset, informers=None, **kw):
        super().__init__(clientset, informers, **kw)
        self.informers.informer("Secret")

    def sync(self, key: str) -> None:  # tick-driven
        pass

    def tick(self) -> int:
        self.informers.pump_all()
        now = self.clock()
        deleted = 0
        for s in list(self.informer("Secret").list()):
            if s.meta.namespace != KUBE_SYSTEM:
                continue
            if not s.meta.name.startswith(BOOTSTRAP_TOKEN_PREFIX):
                continue
            exp = parse_token_expiration(s.data.get("expiration"))
            if exp <= now:
                try:
                    self.clientset.secrets.delete(s.meta.name, KUBE_SYSTEM)
                    deleted += 1
                except NotFoundError:
                    pass
        return deleted
