"""Pod garbage collector.

Capability of ``pkg/controller/podgc/gc_controller.go``: delete (a) the
oldest terminated pods beyond ``terminated_pod_threshold``, (b) pods bound
to nodes that no longer exist, (c) unscheduled pods already marked
deleting.  Driven by ``tick()`` (the reference runs it on a 20s timer)."""

from __future__ import annotations

from ..api import types as api
from ..store.store import NotFoundError
from .base import Controller


class PodGCController(Controller):
    name = "podgc"

    def __init__(self, clientset, informers=None, terminated_pod_threshold: int = 12500, **kw):
        super().__init__(clientset, informers, **kw)
        self.terminated_pod_threshold = terminated_pod_threshold
        # cache-fed scans: a GC pass must not LIST the cluster over the wire
        self.informers.informer("Pod")
        self.informers.informer("Node")

    def tick(self) -> int:
        """One GC pass; returns pods deleted."""
        self.informers.pump_all()  # no-op under threaded informers
        pods = self.informer("Pod").list()
        node_names = {n.meta.name for n in self.informer("Node").list()}
        deleted = 0

        terminated = [p for p in pods if p.status.phase in (api.SUCCEEDED, api.FAILED)]
        excess = len(terminated) - self.terminated_pod_threshold
        if excess > 0:
            oldest = sorted(terminated, key=lambda p: p.meta.creation_revision)[:excess]
            deleted += self._delete_all(oldest)

        orphaned = [p for p in pods
                    if p.spec.node_name and p.spec.node_name not in node_names]
        deleted += self._delete_all(orphaned)

        unscheduled_terminating = [
            p for p in pods
            if not p.spec.node_name and p.meta.deletion_revision is not None
        ]
        deleted += self._delete_all(unscheduled_terminating)
        return deleted

    def sync(self, key: str) -> None:  # queue-driven path just re-ticks
        self.tick()

    def _delete_all(self, pods: list[api.Pod]) -> int:
        n = 0
        for p in pods:
            try:
                self.clientset.pods.delete(p.meta.name, p.meta.namespace)
                n += 1
            except NotFoundError:
                continue
        return n
