"""CRD registrar: establish custom kinds from CustomResourceDefinitions.

The controller half of ``apiextensions-apiserver``'s establishing
controller: watch CRD objects, register the named kind into the live
type registry (making it wire-addressable, informable, GC-visible, and
kubectl-visible), mark the CRD Established, and unregister on delete."""

from __future__ import annotations

import logging
import threading

from ..api.crd import (
    CustomResourceDefinition,
    register_custom_kind,
    unregister_custom_kind,
)
from ..store.store import NotFoundError
from .base import Controller

logger = logging.getLogger("kubernetes_tpu.controllers.crd")


class CRDRegistrar(Controller):
    name = "crd-registrar"

    def __init__(self, clientset, informers=None, **kw):
        super().__init__(clientset, informers, **kw)
        self.watch("CustomResourceDefinition")
        # name -> established kind, for unregistration on delete.  Guarded
        # by _mu: two workers syncing CRDs that name the same kind must not
        # both pass the claimant check (ktpu-analyze RL303).
        self._mu = threading.Lock()
        self._established: dict[str, str] = {}

    def sync(self, key: str) -> None:
        crd = self.informer("CustomResourceDefinition").get(key)
        if crd is None:
            with self._mu:
                kind = self._established.pop(key, None)
                # only the CRD that claimed the kind may unregister it — a
                # duplicate CRD naming the same kind must not pull the rug
                # out from under the claimant on its own deletion.  The
                # unregister itself stays under _mu: outside it, a worker
                # re-claiming the kind between the check and the call would
                # get its fresh registration torn down (TOCTOU).
                if kind is not None and kind not in self._established.values():
                    unregister_custom_kind(kind)
                    logger.info("crd %s deleted: kind %s unregistered", key, kind)
            return
        with self._mu:
            claimant = next(
                (n for n, k in self._established.items() if k == crd.kind_name), None
            )
            if claimant is not None and claimant != key:
                return  # another CRD already owns this kind: never established
            cls = register_custom_kind(crd)
            if cls is None:
                return  # name collision with a built-in: never established
            self._established[key] = crd.kind_name
        if not crd.established:
            def _mark(cur: CustomResourceDefinition) -> CustomResourceDefinition:
                cur.established = True
                return cur

            try:
                self.clientset.customresourcedefinitions.guaranteed_update(
                    crd.meta.name, _mark
                )
            except NotFoundError:
                pass
