"""DaemonSet controller: one pod per matching node.

Capability of ``pkg/controller/daemon/daemoncontroller.go`` (1,971 LoC).
Distinctive reference behavior reproduced here: the daemon controller does
its OWN scheduling — it imports the scheduler's predicates
(``daemoncontroller.go`` nodeShouldRunDaemonPod runs GeneralPredicates +
taint checks against a simulated pod) and writes ``spec.nodeName``
directly instead of leaving pods to the scheduler.  RollingUpdate deletes
up to ``maxUnavailable`` outdated pods per sync; their replacements are
created with the new template on the next pass."""

from __future__ import annotations

from ..api import types as api
from ..api.apps import DaemonSet
from ..api.meta import ObjectMeta, OwnerReference
from ..scheduler.nodeinfo import NodeInfo
from ..scheduler.predicates import (
    PredicateContext,
    compute_metadata,
    general_predicates,
    pod_fits_on_node,
    pod_tolerates_node_taints,
)
from ..store.store import AlreadyExistsError, NotFoundError
from .base import Controller
from .deployment import template_hash

# the subset the reference's nodeShouldRunDaemonPod evaluates
_DAEMON_PREDICATES = {
    "GeneralPredicates": general_predicates,
    "PodToleratesNodeTaints": pod_tolerates_node_taints,
}

HASH_LABEL = "pod-template-hash"


class DaemonSetController(Controller):
    name = "daemonset"

    def __init__(self, clientset, informers=None, **kw):
        super().__init__(clientset, informers, **kw)
        self.watch("DaemonSet")
        self.watch("Node", key_fn=lambda node: self._all_ds_keys())
        from ..client.informer import Handler, PodOwnerIndex

        self.pod_index = PodOwnerIndex(self.informers.informer("Pod"))
        self.informers.informer("Pod").add_handler(Handler(
            on_add=self._pod_event,
            on_update=lambda old, new: self._pod_event(new),
            on_delete=self._pod_event,
        ))

    def _all_ds_keys(self):
        for ds in self.informer("DaemonSet").list():
            self.queue.add(ds.meta.key)
        return None  # keys already enqueued

    def _pod_event(self, pod: api.Pod) -> None:
        ref = pod.meta.controller_ref()
        if ref is not None and ref.kind == "DaemonSet":
            self.queue.add(f"{pod.meta.namespace}/{ref.name}")

    # -- scheduling check --------------------------------------------------
    def _node_should_run(self, ds: DaemonSet, node: api.Node,
                         node_infos: dict[str, NodeInfo]) -> bool:
        if node.spec.unschedulable:
            # daemon pods ignore unschedulable (reference: they tolerate it)
            pass
        sim = self._new_pod(ds, node.meta.name, persist=False)
        info = node_infos.get(node.meta.name) or NodeInfo(node)
        ctx = PredicateContext(node_infos)
        meta = compute_metadata(sim, ctx)
        ok, _ = pod_fits_on_node(sim, meta, info, ctx, predicates=_DAEMON_PREDICATES)
        return ok

    def sync(self, key: str) -> None:
        namespace, name = key.split("/", 1)
        try:
            ds = self.clientset.daemonsets.get(name, namespace)
        except NotFoundError:
            return
        nodes, _ = self.clientset.nodes.list()
        # node -> NodeInfo with current pods for the resource-fit check,
        # EXCLUDING this DaemonSet's own pods — simulating the daemon pod on
        # a node that already runs it must not fail the fit and evict the
        # healthy pod (reference daemoncontroller.go simulate())
        node_infos: dict[str, NodeInfo] = {n.meta.name: NodeInfo(n) for n in nodes}
        for p in self.clientset.pods.list(None)[0]:
            ref = p.meta.controller_ref()
            if ref is not None and ref.kind == "DaemonSet" and ref.uid == ds.meta.uid:
                continue
            if p.spec.node_name in node_infos and p.status.phase not in (api.SUCCEEDED, api.FAILED):
                node_infos[p.spec.node_name].add_pod(p)

        owned = [p for p in self.pod_index.owned_by(ds.meta.uid)
                 if p.meta.namespace == namespace
                 and p.status.phase not in (api.SUCCEEDED, api.FAILED)]
        by_node: dict[str, list[api.Pod]] = {}
        for p in owned:
            by_node.setdefault(p.spec.node_name, []).append(p)

        want_hash = template_hash(ds.template)
        desired = current = ready = updated = mis = 0
        to_delete: list[api.Pod] = []
        outdated: list[api.Pod] = []

        for node in nodes:
            should = self._node_should_run(ds, node, node_infos)
            have = by_node.pop(node.meta.name, [])
            if should:
                desired += 1
                if not have:
                    self._create_pod(ds, node.meta.name, want_hash)
                    continue
                current += 1
                keep, extra = have[0], have[1:]
                to_delete.extend(extra)  # duplicates on one node
                if keep.status.phase == api.RUNNING:
                    ready += 1
                if keep.meta.labels.get(HASH_LABEL) == want_hash:
                    updated += 1
                else:
                    outdated.append(keep)
            else:
                mis += len(have)
                to_delete.extend(have)

        # pods on nodes that no longer exist
        for orphan_pods in by_node.values():
            to_delete.extend(orphan_pods)

        if ds.update_strategy == "RollingUpdate":
            # deletion budget = maxUnavailable minus already-unavailable
            # daemons (reference rollingUpdate.go getUnavailableNumbers):
            # never take down more than maxUnavailable nodes at once
            unavailable = desired - ready
            budget = max(0, ds.max_unavailable - unavailable)
            to_delete.extend(outdated[:budget])
        for p in to_delete:
            try:
                self.clientset.pods.delete(p.meta.name, p.meta.namespace)
            except NotFoundError:
                pass

        def _status(cur: DaemonSet) -> DaemonSet:
            cur.status_desired = desired
            cur.status_current = current
            cur.status_ready = ready
            cur.status_updated = updated
            cur.status_mis_scheduled = mis
            return cur

        self.clientset.daemonsets.guaranteed_update(name, _status, namespace)

    def _new_pod(self, ds: DaemonSet, node_name: str, persist: bool, want_hash: str = "") -> api.Pod:
        labels = dict(ds.template.labels)
        if want_hash:
            labels[HASH_LABEL] = want_hash
        spec = api.PodSpec.from_dict(ds.template.spec.to_dict())
        spec.node_name = node_name
        return api.Pod(
            meta=ObjectMeta(
                name=f"{ds.meta.name}-{node_name}",
                namespace=ds.meta.namespace,
                labels=labels,
                owner_references=[OwnerReference(
                    kind="DaemonSet", name=ds.meta.name, uid=ds.meta.uid, controller=True)],
            ),
            spec=spec,
        )

    def _create_pod(self, ds: DaemonSet, node_name: str, want_hash: str) -> None:
        try:
            self.clientset.pods.create(self._new_pod(ds, node_name, persist=True, want_hash=want_hash))
        except AlreadyExistsError:
            pass
