"""The HPA's metrics source: node stats scraped into per-pod utilization.

Capability of ``pkg/controller/podautoscaler/metrics/metrics_client.go``
(the heapster REST client): scrape every node's kubelet stats-summary
document (``pkg/kubelet/server/stats/summary.go``), keep the last two
CPU samples per pod, and answer *CPU utilization as percent of request*
— cumulative CPU deltas over wall time, exactly how a rate is derived
from cadvisor counters.  The scrape dials the node's kubeletURL
directly, falling back to the apiserver's node proxy
(``/api/v1/nodes/<n>/proxy/stats/summary``) when the direct dial fails
or no kubeletURL is published — so tunnel-only nodes still feed the HPA.
"""

from __future__ import annotations

import json
import logging
import threading
import time
import urllib.request
from typing import Callable, Optional

from ..api import types as api

logger = logging.getLogger("kubernetes_tpu.metrics")


class MetricsClient:
    def __init__(self, clientset, scrape_interval: float = 5.0,
                 clock: Callable[[], float] = time.monotonic,
                 wall_clock: Callable[[], float] = time.monotonic):
        self.clientset = clientset
        self.scrape_interval = scrape_interval
        self.clock = clock
        # rates need REAL elapsed time even under a fake test clock
        self.wall_clock = wall_clock
        self._last_scrape = -1e18
        # pod key -> (wall_t, cumulative cpu ms); two generations for rates
        self._prev: dict[str, tuple[float, float]] = {}
        self._cur: dict[str, tuple[float, float]] = {}
        # generations roll only when at least this much wall time passed:
        # /proc CPU counters tick at ~10ms, so a near-zero window reads a
        # spurious zero rate
        self.min_rate_window = 0.25
        self._memory: dict[str, int] = {}
        self._pod_node: dict[str, str] = {}  # last node each pod reported from
        # node -> scrape counter at demotion: scrape via proxy only until
        # DIRECT_RETRY_SWEEPS pass, then retry the direct dial (a node
        # that recovers gets its direct path back; entries for deleted
        # nodes are pruned each sweep)
        self._direct_bad: dict[str, int] = {}
        self.stats = {"scrapes": 0, "nodes_ok": 0, "nodes_failed": 0}
        # utilization() runs on EVERY HPA worker thread (run_workers
        # defaults to 2): without this lock, concurrent scrapes lose
        # stat updates, double-roll the sample generations (defeating
        # min_rate_window), and the eviction comprehensions can raise
        # "dictionary changed size during iteration" mid-sync.  Held
        # across the whole sweep — the throttle means at most one sweep
        # per interval actually dials nodes; contenders return fast.
        self._mu = threading.Lock()

    # how many sweeps a node stays demoted to the proxy before the
    # direct dial is retried (~1 min at the default 5s interval)
    DIRECT_RETRY_SWEEPS = 12

    # -- scraping ------------------------------------------------------------
    def _fetch_summary(self, node: api.Node) -> Optional[dict]:
        url = node.status.kubelet_url
        raw = getattr(self.clientset.store, "raw", None)
        demoted_at = self._direct_bad.get(node.meta.name)
        if demoted_at is not None and (
                self.stats["scrapes"] - demoted_at >= self.DIRECT_RETRY_SWEEPS):
            self._direct_bad.pop(node.meta.name)
            demoted_at = None
        # a node whose direct dial recently failed goes straight to the
        # proxy — otherwise every sweep pays the full direct timeout per
        # tunnel-only node before the call that actually works
        if url and demoted_at is None:
            try:
                # blocking-ok — the sweep dials under _mu by design (see _mu's init comment): the interval throttle means contenders return fast instead of racing duplicate sweeps
                with urllib.request.urlopen(f"{url}/stats/summary", timeout=5) as r:
                    return json.loads(r.read())
            except Exception as e:  # noqa: BLE001 — a down node must not stop the sweep
                logger.debug("direct stats scrape of %s failed: %s",
                             node.meta.name, e)
                if raw is not None:  # demote only when a proxy path exists
                    self._direct_bad[node.meta.name] = self.stats["scrapes"]
        # fall back to the apiserver node proxy when the clientset is
        # remote (RemoteStore carries .raw): nodes reachable only through
        # the tunneler still feed the HPA pipeline
        if raw is None:
            return None
        try:
            body = raw("GET",
                       f"/api/v1/nodes/{node.meta.name}/proxy/stats/summary")
            return json.loads(body)
        except Exception as e:  # noqa: BLE001
            logger.debug("proxied stats scrape of %s failed: %s",
                         node.meta.name, e)
            # both paths down: let the next sweep retry the direct dial
            self._direct_bad.pop(node.meta.name, None)
            return None

    def _scrapeable(self, node: api.Node) -> bool:
        return bool(node.status.kubelet_url
                    or getattr(self.clientset.store, "raw", None))

    def scrape(self, force: bool = False) -> None:
        """One sweep over every node with a kubelet endpoint; throttled
        to ``scrape_interval`` unless forced."""
        with self._mu:
            self._scrape_locked(force)

    def _scrape_locked(self, force: bool) -> None:
        now = self.clock()
        if not force and now - self._last_scrape < self.scrape_interval:
            return
        self._last_scrape = now
        wall = self.wall_clock()
        self.stats["scrapes"] += 1
        sample: dict[str, tuple[float, float]] = {}
        memory: dict[str, int] = {}
        pod_node: dict[str, str] = {}
        ok_nodes: set[str] = set()
        all_nodes: set[str] = set()
        for node in self.clientset.nodes.list()[0]:
            all_nodes.add(node.meta.name)
            summary = self._fetch_summary(node)
            if summary is None:
                if self._scrapeable(node):
                    self.stats["nodes_failed"] += 1
                continue
            self.stats["nodes_ok"] += 1
            ok_nodes.add(node.meta.name)
            for entry in summary.get("pods", []):
                ref = entry.get("podRef") or {}
                key = f"{ref.get('namespace', 'default')}/{ref.get('name', '')}"
                pod_node[key] = node.meta.name
                memory[key] = int((entry.get("memory") or {}).get("usageBytes", 0))
                cpu = entry.get("cpu") or {}
                if "cumulativeCpuMillis" in cpu:
                    sample[key] = (wall, float(cpu["cumulativeCpuMillis"]))
        # generations roll only when the new sweep actually sampled CPU
        # (a sweep of down nodes must not wipe the rate window) AND the
        # current generation is old enough to anchor a meaningful rate —
        # back-to-back scrapes otherwise collapse the window below the
        # counter tick and read a spurious zero
        if sample:
            ref_wall = max((t for t, _ in self._cur.values()), default=None)
            if ref_wall is None or wall - ref_wall >= self.min_rate_window:
                self._prev = {k: v for k, v in self._cur.items() if k in sample}
            self._cur.update(sample)
        # evict ONLY pods whose node was scraped successfully this sweep
        # and no longer reports them — a down node's pods keep their rate
        # window until the node answers again (partial-outage safety)
        for gone in [k for k in self._cur
                     if k not in sample and self._pod_node.get(k) in ok_nodes]:
            self._cur.pop(gone)
            self._prev.pop(gone, None)
        for gone in [k for k in self._memory
                     if k not in memory and self._pod_node.get(k) in ok_nodes]:
            self._memory.pop(gone)
            self._pod_node.pop(gone, None)
        self._pod_node.update(pod_node)
        self._memory.update(memory)
        # deleted nodes must not accumulate in the demotion ledger
        for gone in [n for n in self._direct_bad if n not in all_nodes]:
            self._direct_bad.pop(gone)

    # -- queries -------------------------------------------------------------
    def pod_cpu_millicores(self, pod_key: str) -> Optional[float]:
        """Observed CPU rate in millicores, from the last two samples;
        None until two samples exist."""
        with self._mu:
            cur = self._cur.get(pod_key)
            prev = self._prev.get(pod_key)
        if cur is None or prev is None:
            return None
        dt = cur[0] - prev[0]
        if dt <= 0:
            return None
        return max(0.0, (cur[1] - prev[1]) / dt) / 1000.0 * 1000.0  # ms/s = millicores

    def pod_memory_bytes(self, pod_key: str) -> Optional[int]:
        with self._mu:
            return self._memory.get(pod_key)

    def utilization(self, pod: api.Pod) -> Optional[float]:
        """CPU utilization as percent of the pod's CPU request — the
        number the HPA's replica calculator consumes
        (``replica_calculator.go GetResourceReplicas``).  Scrapes lazily
        (throttled) so the HPA needs no separate pump.

        Returns **None** when no rate exists yet (fewer than two samples,
        node down, or no CPU request): missing data must read as
        "unknown", never as "idle" — the reference HPA skips scaling on
        missing metrics rather than scaling to min."""
        self.scrape()
        rate = self.pod_cpu_millicores(pod.meta.key)
        if rate is None:
            return None
        request_m = 0
        for c in pod.spec.containers:
            q = c.resources.requests.get("cpu")
            if q is not None:
                request_m += int(q.milli_value())
        if request_m <= 0:
            return None
        return rate / request_m * 100.0
