"""Certificates controller: approve + sign CSRs.

Capability of ``pkg/controller/certificates`` (CSR signing/approving for
kubelet TLS bootstrap).  The signer issues an opaque certificate payload
for approved CSRs; the approver (optional, mirroring
``gke-certificates-controller``'s auto-approval of node client certs)
auto-approves CSRs from known bootstrap users."""

from __future__ import annotations

import hashlib

from ..api.cluster import CertificateSigningRequest
from ..store.store import NotFoundError
from .base import Controller


class CertificateController(Controller):
    name = "certificates"

    def __init__(self, clientset, informers=None, auto_approve_users: set[str] | None = None, **kw):
        super().__init__(clientset, informers, **kw)
        self.auto_approve_users = auto_approve_users or set()
        self.watch("CertificateSigningRequest", key_fn=lambda csr: csr.meta.name)

    def sync(self, key: str) -> None:
        try:
            csr = self.clientset.certificatesigningrequests.get(key)
        except NotFoundError:
            return
        if csr.denied or (csr.approved and csr.certificate):
            return

        def _update(cur: CertificateSigningRequest) -> CertificateSigningRequest:
            if not cur.approved and not cur.denied:
                if cur.username in self.auto_approve_users:
                    cur.conditions.append({
                        "type": "Approved", "reason": "AutoApproved",
                        "message": f"bootstrap user {cur.username}",
                    })
            if cur.approved and not cur.certificate:
                # opaque issued-cert payload (the reference calls a real
                # x509 signer; the capability is the state machine)
                digest = hashlib.sha256(cur.request.encode()).hexdigest()[:32]
                cur.certificate = f"signed:{cur.username}:{digest}"
            return cur

        self.clientset.certificatesigningrequests.guaranteed_update(key, _update)
