"""Controller manager: the registry/runner for all control loops.

Capability of ``cmd/kube-controller-manager``
(``controllermanager.go:107 Run``, ``:435 StartControllers``, registry at
``:315-339``): construct every enabled controller over ONE shared informer
factory (one watch per kind total — the reference's shared-informer
economy), run them, and expose a deterministic ``reconcile_all`` for
single-threaded drives."""

from __future__ import annotations

import logging
from typing import Callable, Optional

logger = logging.getLogger("kubernetes_tpu.controllers.manager")

from ..client.clientset import Clientset
from ..client.informer import InformerFactory
from .base import Controller
from .certificates import CertificateController
from .crdregistrar import CRDRegistrar
from .cronjob import CronJobController
from .daemonset import DaemonSetController
from .deployment import DeploymentController
from .disruption import DisruptionController
from .endpoint import EndpointController
from .garbagecollector import GarbageCollector
from .horizontal import HorizontalPodAutoscalerController
from .ipam import (BootstrapSignerController, NodeIpamController,
    TokenCleanerController)
from .job import JobController
from .namespace import NamespaceController
from .node_lifecycle import NodeLifecycleController
from .podgc import PodGCController
from .replicaset import (ReplicaSetController,
                         ReplicationControllerController)
from .resourcequota import ResourceQuotaController
from .serviceaccounts import ServiceAccountController
from .statefulset import StatefulSetController
from .taint import NoExecuteTaintManager
from .ttl import TTLController
from .volume import AttachDetachController, PersistentVolumeController

# registry of startable loops (reference controllermanager.go:315-339)
DEFAULT_CONTROLLERS: dict[str, Callable] = {
    "deployment": DeploymentController,
    "replicaset": ReplicaSetController,
    "replication": ReplicationControllerController,
    "garbagecollector": GarbageCollector,
    "node-lifecycle": NodeLifecycleController,
    "job": JobController,
    "cronjob": CronJobController,
    "daemonset": DaemonSetController,
    "statefulset": StatefulSetController,
    "endpoint": EndpointController,
    "namespace": NamespaceController,
    "resourcequota": ResourceQuotaController,
    "podgc": PodGCController,
    "ttl": TTLController,
    "disruption": DisruptionController,
    "taint-manager": NoExecuteTaintManager,
    "crd-registrar": CRDRegistrar,
    "persistentvolume": PersistentVolumeController,
    "attachdetach": AttachDetachController,
    "horizontalpodautoscaler": HorizontalPodAutoscalerController,
    "serviceaccount": ServiceAccountController,
    "certificates": CertificateController,
    "node-ipam": NodeIpamController,
    "bootstrapsigner": BootstrapSignerController,
    "tokencleaner": TokenCleanerController,
}


class ControllerManager:
    registry: dict[str, Callable] = DEFAULT_CONTROLLERS

    def __init__(
        self,
        clientset: Clientset,
        enabled: Optional[list[str]] = None,
        clock=None,
        registry: Optional[dict[str, Callable]] = None,
        **controller_kw,
    ):
        import inspect

        registry = registry or type(self).registry
        self.clientset = clientset
        self.informers = InformerFactory(clientset)
        self.controllers: dict[str, Controller] = {}
        kw = dict(controller_kw)
        if clock is not None:
            kw["clock"] = clock
        consumed: set[str] = {"clock"}
        for name in enabled or list(registry):
            ctor = registry[name]
            accepted = set(inspect.signature(ctor.__init__).parameters)
            # pass each controller only the options it declares ("clock" is
            # universal via the Controller base)
            sub_kw = {k: v for k, v in kw.items() if k in accepted or k == "clock"}
            consumed |= set(sub_kw)
            self.controllers[name] = ctor(clientset, informers=self.informers, **sub_kw)
        leftover = set(kw) - consumed
        if leftover:
            raise TypeError(
                f"options {sorted(leftover)} not accepted by any enabled controller "
                f"({sorted(self.controllers)}) — typo or missing controller?"
            )

    def start(self, manual: bool = True, workers_per_controller: int = 1) -> None:
        if manual:
            self.informers.start_all_manual()
        else:
            self.informers.start_all()
            for c in self.controllers.values():
                c.run_workers(workers_per_controller)

    def reconcile_all(self, max_rounds: int = 50) -> int:
        """Drive every controller to quiescence (single-threaded drive)."""
        total = 0
        for _ in range(max_rounds):
            self.informers.pump_all()
            progressed = 0
            for c in self.controllers.values():
                while c.sync_once():
                    progressed += 1
                self.informers.pump_all()
            total += progressed
            if progressed == 0 and all(len(c.queue) == 0 for c in self.controllers.values()):
                break
        return total

    def tick(self) -> None:
        """Drive the clock-based loops (the reference runs these on
        wait.Until timers): node-lifecycle monitor, taint-manager timers,
        cronjob schedule checks."""
        for c in self.controllers.values():
            fn = getattr(c, "monitor", None) or getattr(c, "tick", None)
            if fn is not None:
                try:
                    fn()
                except Exception:  # controller loops never die
                    logger.exception("%s tick failed", c.name)

    def stop(self) -> None:
        for c in self.controllers.values():
            c.stop()
        self.informers.stop_all()
