"""Deployment controller: declarative rollouts over ReplicaSets.

Capability of ``pkg/controller/deployment`` (3,175 LoC;
``syncDeployment :559``, strategies in ``rolling.go``/``sync.go``):

- one ReplicaSet per pod-template hash; template change → new RS;
- RollingUpdate: scale the new RS up and old RSes down within
  maxSurge/maxUnavailable; Recreate: old to zero first, then new up;
- status aggregation (replicas/updated/ready/observedGeneration).

Rollback = applying an old template again (hash matches the old RS, which
becomes "new" — the reference models it the same way, ``rollback.go``).
"""

from __future__ import annotations

import hashlib
import json

from ..api import types as api
from ..api.meta import ObjectMeta, OwnerReference
from ..store.store import AlreadyExistsError, NotFoundError
from .base import Controller


def template_hash(template: api.PodTemplateSpec) -> str:
    payload = json.dumps(template.to_dict(), sort_keys=True)
    return hashlib.sha256(payload.encode()).hexdigest()[:10]


class DeploymentController(Controller):
    name = "deployment"
    REVISION_ANNOTATION = api.DEPLOYMENT_REVISION_ANNOTATION

    def __init__(self, clientset, informers=None, **kw):
        super().__init__(clientset, informers, **kw)
        self.watch("Deployment")
        self.watch("ReplicaSet", key_fn=self._dep_key_for_rs)

    def _dep_key_for_rs(self, rs: api.ReplicaSet):
        ref = rs.meta.controller_ref()
        if ref is not None and ref.kind == "Deployment":
            return f"{rs.meta.namespace}/{ref.name}"
        return None

    # -- helpers -----------------------------------------------------------
    def _owned_rses(self, dep: api.Deployment) -> list[api.ReplicaSet]:
        out = []
        for rs in self.informer("ReplicaSet").list():
            ref = rs.meta.controller_ref()
            if (
                rs.meta.namespace == dep.meta.namespace
                and ref is not None
                and ref.kind == "Deployment"
                and ref.uid == dep.meta.uid
            ):
                out.append(rs)
        return out

    def _new_rs(self, dep: api.Deployment, rses: list[api.ReplicaSet]):
        want = template_hash(dep.template)
        for rs in rses:
            if rs.meta.labels.get("pod-template-hash") == want:
                return rs
        return None

    def _create_new_rs(self, dep: api.Deployment, replicas: int,
                       rses: list[api.ReplicaSet]) -> api.ReplicaSet:
        h = template_hash(dep.template)
        next_rev = 1 + max(
            (
                int(rs.meta.annotations.get(self.REVISION_ANNOTATION, "0"))
                for rs in rses
            ),
            default=0,
        )
        labels = dict(dep.template.labels)
        labels["pod-template-hash"] = h
        template = api.PodTemplateSpec(labels=labels, spec=api.PodSpec.from_dict(dep.template.spec.to_dict()))
        selector = api.LabelSelector.from_dict(dep.selector.to_dict())
        selector.match_labels["pod-template-hash"] = h
        rs = api.ReplicaSet(
            meta=ObjectMeta(
                name=f"{dep.meta.name}-{h}",
                namespace=dep.meta.namespace,
                labels=labels,
                annotations={self.REVISION_ANNOTATION: str(next_rev)},
                owner_references=[
                    OwnerReference(kind="Deployment", name=dep.meta.name, uid=dep.meta.uid, controller=True)
                ],
            ),
            replicas=replicas,
            selector=selector,
            template=template,
        )
        try:
            return self.clientset.replicasets.create(rs)
        except AlreadyExistsError:
            return self.clientset.replicasets.get(rs.meta.name, rs.meta.namespace)

    def _ensure_revision(self, new_rs, rses: list[api.ReplicaSet]) -> None:
        """The reference's revision bookkeeping (``deployment/sync.go``
        getNewReplicaSet): the RS matching the current template carries the
        HIGHEST revision; re-applying an old template (rollback-by-reapply)
        bumps that RS's revision rather than minting a new RS — rollout
        history/undo read these annotations."""
        revisions = [
            int(rs.meta.annotations.get(self.REVISION_ANNOTATION, "0")) for rs in rses
        ]
        max_rev = max(revisions, default=0)
        if new_rs is None:
            return  # _create_new_rs stamps max+1
        cur = int(new_rs.meta.annotations.get(self.REVISION_ANNOTATION, "0"))
        if cur == max_rev and cur != 0:
            return

        def _stamp(r: api.ReplicaSet) -> api.ReplicaSet:
            r.meta.annotations[self.REVISION_ANNOTATION] = str(max_rev + 1)
            return r

        self.clientset.replicasets.guaranteed_update(
            new_rs.meta.name, _stamp, new_rs.meta.namespace
        )

    def _scale_rs(self, rs: api.ReplicaSet, replicas: int) -> None:
        if rs.replicas == replicas:
            return

        def _scale(cur: api.ReplicaSet) -> api.ReplicaSet:
            cur.replicas = replicas
            return cur

        self.clientset.replicasets.guaranteed_update(rs.meta.name, _scale, rs.meta.namespace)

    # -- reconcile ---------------------------------------------------------
    def sync(self, key: str) -> None:
        namespace, name = key.split("/", 1)
        try:
            dep = self.clientset.deployments.get(name, namespace)
        except NotFoundError:
            return
        rses = self._owned_rses(dep)
        new_rs = self._new_rs(dep, rses)
        self._ensure_revision(new_rs, rses)
        old_rses = [rs for rs in rses if new_rs is None or rs.meta.uid != new_rs.meta.uid]
        old_total = sum(rs.replicas for rs in old_rses)

        if dep.paused:
            # rollout pause (deployment/sync.go): SCALE still reconciles,
            # the rollout does not — no new RS for a template change, no
            # old→new shifting.  The delta lands on the newest RS (the
            # single-RS steady state is the dominant paused case).
            if rses:
                total = sum(rs.replicas for rs in rses)
                if total != dep.replicas:
                    newest = max(
                        rses,
                        key=lambda rs: int(rs.meta.annotations.get(
                            self.REVISION_ANNOTATION, "0") or 0))
                    self._scale_rs(
                        newest,
                        max(0, newest.replicas + dep.replicas - total))
        elif dep.strategy == "Recreate":
            for rs in old_rses:
                self._scale_rs(rs, 0)
            old_active = sum(rs.status_replicas for rs in old_rses)
            if old_active == 0:
                if new_rs is None:
                    new_rs = self._create_new_rs(dep, dep.replicas, rses)
                self._scale_rs(new_rs, dep.replicas)
        else:  # RollingUpdate
            if new_rs is None:
                # surge head-room for the first step of the rollout
                initial = max(min(dep.replicas, dep.replicas + dep.max_surge - old_total), 0)
                new_rs = self._create_new_rs(dep, initial, rses)
            else:
                # scale new up within maxSurge
                max_total = dep.replicas + dep.max_surge
                allowed_up = max(max_total - (old_total + new_rs.replicas), 0)
                want_new = min(new_rs.replicas + allowed_up, dep.replicas)
                if want_new != new_rs.replicas:
                    self._scale_rs(new_rs, want_new)
                # scale old down within maxUnavailable, counting only READY
                # new replicas as available coverage
                min_available = dep.replicas - dep.max_unavailable
                available = new_rs.status_ready_replicas + sum(
                    rs.status_ready_replicas for rs in old_rses
                )
                can_remove = max(available - min_available, 0)
                for rs in sorted(old_rses, key=lambda r: r.meta.name):
                    if can_remove <= 0:
                        break
                    step = min(rs.replicas, can_remove)
                    if step > 0:
                        self._scale_rs(rs, rs.replicas - step)
                        can_remove -= step

        # status
        all_rses = self._owned_rses(dep)
        new_rs_now = self._new_rs(dep, all_rses)
        total = sum(rs.status_replicas for rs in all_rses)
        ready = sum(rs.status_ready_replicas for rs in all_rses)
        updated = new_rs_now.status_replicas if new_rs_now else 0
        if (
            dep.status_replicas != total
            or dep.status_ready_replicas != ready
            or dep.status_updated_replicas != updated
            or dep.status_observed_generation != dep.meta.generation
        ):
            def _status(cur: api.Deployment) -> api.Deployment:
                cur.status_replicas = total
                cur.status_ready_replicas = ready
                cur.status_updated_replicas = updated
                cur.status_observed_generation = cur.meta.generation
                return cur

            self.clientset.deployments.guaranteed_update(name, _status, namespace)
