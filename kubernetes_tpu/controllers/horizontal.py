"""Horizontal pod autoscaler controller.

Capability of ``pkg/controller/podautoscaler/horizontal.go`` (1,449 LoC):
per HPA, read the target workload's pods' CPU utilization from a metrics
source (the reference scrapes heapster; here any callable
``metrics(pod) -> percent-of-request``), compute

    desired = ceil(current * observed / target)

(``replica_calculator.go``), clamp to [min,max], apply a tolerance band
(±10%) and scale the target via its scale client.  Driven by ``tick()``
(the reference polls every 30s)."""

from __future__ import annotations

import math
from typing import Callable, Optional

from ..api import types as api
from ..api.cluster import HorizontalPodAutoscaler
from ..store.store import NotFoundError
from .base import Controller

TOLERANCE = 0.1  # reference defaultTestingTolerance / horizontal.go tolerance


class HorizontalPodAutoscalerController(Controller):
    name = "horizontalpodautoscaler"

    def __init__(self, clientset, informers=None,
                 metrics: Optional[Callable[[api.Pod], float]] = None,
                 metrics_client=None, **kw):
        super().__init__(clientset, informers, **kw)
        # metrics source: per-pod CPU as percent of request.  Default is
        # the REAL pipeline — kubelet stats-summary scraped by the
        # MetricsClient (metrics_client.go) — not an injected stub; an
        # explicit callable still overrides for tests
        if metrics is None:
            from .metrics_client import MetricsClient

            self.metrics_client = metrics_client or MetricsClient(clientset)
            self.metrics = self.metrics_client.utilization
        else:
            self.metrics_client = metrics_client
            self.metrics = metrics
        self.watch("HorizontalPodAutoscaler")

    def tick(self) -> None:
        # informer cache, not a wire LIST per resync period
        for hpa in self.informer("HorizontalPodAutoscaler").list():
            self.queue.add(hpa.meta.key)

    def _target_client(self, hpa: HorizontalPodAutoscaler):
        return self.clientset.client_for(hpa.target_kind)

    def sync(self, key: str) -> None:
        namespace, name = key.split("/", 1)
        try:
            hpa = self.clientset.horizontalpodautoscalers.get(name, namespace)
        except NotFoundError:
            return
        try:
            target = self._target_client(hpa).get(hpa.target_name, namespace)
        except (NotFoundError, KeyError):
            return
        selector = target.selector
        pods = [p for p in self.clientset.pods.list(namespace)[0]
                if selector.matches(p.meta.labels)
                and p.status.phase == api.RUNNING]
        current = target.replicas
        # None = metrics MISSING for that pod (metrics client warming up,
        # node down) — distinct from an explicit 0.0 (observed idle).
        # Missing data must never read as "idle": the reference HPA skips
        # the scaling decision when it cannot get metrics.
        samples = [self.metrics(p) for p in pods]
        known = [s for s in samples if s is not None]
        observed = sum(known) / len(known) if known else 0.0

        desired = current
        if known and hpa.target_cpu_utilization > 0:
            ratio = observed / hpa.target_cpu_utilization
            if abs(ratio - 1.0) > TOLERANCE:  # inside the band: no scale
                # scale from the pod count metrics exist for, not
                # spec.replicas (replica_calculator.go uses
                # readyPodCount) — repeated syncs with unchanged metrics
                # then converge instead of compounding; fully idle
                # (ratio 0) clamps to minReplicas
                desired = math.ceil(len(known) * ratio)
        # pods exist but ALL metrics are missing (or target<=0): hold the
        # metric-driven decision as-is — but the reference always bounds
        # desiredReplicas, so the [min,max] clamp is unconditional
        desired = max(hpa.min_replicas, min(hpa.max_replicas, desired))

        if desired != current:
            def _scale(obj):
                obj.replicas = desired
                return obj

            self._target_client(hpa).guaranteed_update(hpa.target_name, _scale, namespace)

        def _status(cur: HorizontalPodAutoscaler) -> HorizontalPodAutoscaler:
            cur.status_current_replicas = current
            cur.status_desired_replicas = desired
            cur.status_current_utilization = int(observed)
            if desired != current:
                cur.status_last_scale_time = self.clock()
            return cur

        self.clientset.horizontalpodautoscalers.guaranteed_update(name, _status, namespace)
