"""kube-controller-manager daemon (reference
``cmd/kube-controller-manager/app/controllermanager.go:107 Run``).

    python -m kubernetes_tpu.controllers --apiserver http://host:6443 \
        [--leader-elect] [--controllers deployment,replicaset,...] \
        [--node-monitor-period 5]

Runs every registered control loop threaded (informer watch threads +
per-controller workers) plus the tick-driven loops (node lifecycle
monitor, taint manager, cronjob clock)."""

from __future__ import annotations

import argparse
import logging
import os
import sys
import threading

from ..daemon import install_signal_stop, remote_clientset, run_with_leader_election
from .manager import DEFAULT_CONTROLLERS, ControllerManager


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="kubernetes_tpu.controllers")
    ap.add_argument("--apiserver", default=None)
    ap.add_argument("--token", default=None)
    ap.add_argument("--kubeconfig", default=None,
                    help="connection document from the kubeadm kubeconfig "
                    "phase (server + CA pin + client cert); --apiserver/"
                    "--token override its fields")
    ap.add_argument("--leader-elect", action="store_true")
    ap.add_argument("--controllers", default="*",
                    help="comma list or * (default set: %s)" % ",".join(DEFAULT_CONTROLLERS))
    ap.add_argument("--workers", type=int, default=2)
    ap.add_argument("--node-monitor-period", type=float, default=5.0)
    ap.add_argument("--feature-gates", default="")
    ap.add_argument("--healthz-port", type=int, default=-1,
                    help="serve /healthz + /metrics + /debug/* (reference "
                         ":10252); -1 = off")
    ap.add_argument("--timeseries", action="store_true",
                    help="scrape the client-metrics registry into "
                         "time-series rings (served at /debug/timeseries)")
    ap.add_argument("--timeseries-interval", type=float, default=1.0)
    ap.add_argument("--telemetry-sink", default=None,
                    help="ship flight dumps + time-series deltas off-box "
                         "(collector URL or JSON-lines file path)")
    args = ap.parse_args(argv)
    from ..utils.features import DEFAULT_FEATURE_GATES

    if args.feature_gates:
        DEFAULT_FEATURE_GATES.set_from_string(args.feature_gates)
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s %(name)s %(levelname)s %(message)s")

    if not args.apiserver and not args.kubeconfig:
        ap.error("one of --apiserver or --kubeconfig is required")
    cs = remote_clientset(args.apiserver, args.token,
                          kubeconfig=args.kubeconfig)
    names = None if args.controllers == "*" else args.controllers.split(",")

    def run(payload_stop: threading.Event) -> None:
        kw = {}
        if DEFAULT_FEATURE_GATES.enabled("TaintBasedEvictions"):
            kw["use_taint_based_evictions"] = True
        mgr = ControllerManager(cs, enabled=names, **kw)
        mgr.start(manual=False, workers_per_controller=args.workers)
        logging.info("controller manager running: %s", ", ".join(mgr.controllers))
        while not payload_stop.is_set():
            mgr.tick()  # clock-driven loops (node monitor, taints, cron)
            payload_stop.wait(args.node_monitor_period)
        mgr.stop()

    stop = install_signal_stop()
    # health BEFORE leader election: standbys must answer liveness probes.
    # The controller manager's observable surface is the client transport
    # (retries, relists, watch gaps) — the process-wide client registry.
    from ..daemon import serve_health
    from ..utils.metrics import DEFAULT_CLIENT_METRICS

    health = serve_health(args.healthz_port,
                          DEFAULT_CLIENT_METRICS.registry)
    if health is not None:
        logging.info("healthz/metrics on :%d", health.local_port)
    if args.timeseries or args.telemetry_sink:
        from ..daemon import enable_continuous_telemetry

        enable_continuous_telemetry(
            DEFAULT_CLIENT_METRICS.registry,
            interval_s=args.timeseries_interval,
            sink_spec=args.telemetry_sink)
    try:
        run_with_leader_election(
            cs, "kube-controller-manager", f"kcm-{os.getpid()}", run, stop,
            leader_elect=args.leader_elect,
        )
    finally:
        if health is not None:
            health.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
