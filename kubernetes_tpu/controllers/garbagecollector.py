"""Garbage collector: ownerReference graph + cascading deletion.

Capability of ``pkg/controller/garbagecollector`` (2,748 LoC):

- the owner graph spans EVERY kind in the type registry
  (``graph_builder.go:317`` builds from discovery + dynamic watches; here
  the registry is the discovery source), so Job→Pod, StatefulSet→Pod, or
  any CRD-style late-registered kind participates with no per-kind code;
- **background cascading deletion**: a dependent whose owners are ALL
  gone is deleted; a dependent with a mix of live and dangling owners
  gets the dangling references patched away (``attemptToDeleteItem``);
- UID-checked: an owner deleted and recreated under the same name does
  NOT keep old dependents alive;
- **orphan propagation**: deleting an owner that carries the ``orphan``
  finalizer makes the GC strip its ownerReferences from all dependents
  and then remove the finalizer (releasing the tombstoned delete) —
  dependents survive ownerless (``orphanDependents``, the
  DeleteOptions.propagationPolicy=Orphan path).

A reverse index (owner → dependents) makes owner-deletion wakeups
O(dependents-of-owner), not O(cluster)."""

from __future__ import annotations

import logging
import threading

from ..api import types as api
from ..client.informer import Handler
from ..store.store import ConflictError, NotFoundError
from .base import Controller

logger = logging.getLogger("kubernetes_tpu.controllers.gc")

ORPHAN_FINALIZER = "orphan"

# kinds that never own or get owned usefully and churn at high volume
_EXCLUDED_KINDS = {"Event"}


def _owner_index_key(ref: api.OwnerReference, dependent_namespace: str) -> tuple:
    ns = "" if ref.kind in api.CLUSTER_SCOPED_KINDS else dependent_namespace
    return (ref.kind, ns, ref.name, ref.uid)


class GarbageCollector(Controller):
    name = "garbagecollector"

    def __init__(self, clientset, informers=None, kinds=None, **kw):
        super().__init__(clientset, informers, **kw)
        self._fixed_kinds = list(kinds) if kinds is not None else None
        # Graph state is written by per-kind watch threads and read by
        # workers; the reference serializes all graph changes through one
        # graph-builder goroutine — a lock is the equivalent here.
        self._graph_mu = threading.Lock()
        # owner identity -> {(dependent kind, dependent key)}
        self._dependents: dict[tuple, set[tuple[str, str]]] = {}
        # dependent (kind, key) -> owner identities it is indexed under
        self._owners_of: dict[tuple[str, str], set[tuple]] = {}
        self.kinds: list[str] = []
        self.refresh_kinds()

    def refresh_kinds(self) -> None:
        """Wire handlers for every registry kind not yet watched — called
        at construction and again whenever a CRD establishes a new kind,
        so late-registered kinds join the owner graph."""
        wanted = self._fixed_kinds if self._fixed_kinds is not None else list(api.KINDS)
        for kind in wanted:
            # membership check + append under the graph lock: this runs
            # from informer callbacks (CRD establishment) as well as the
            # constructing thread, and a check-then-act race would wire
            # duplicate handlers (= duplicate graph events per object)
            with self._graph_mu:
                if kind in self.kinds or kind in _EXCLUDED_KINDS:
                    continue
                # handler wiring is permanent by design (shared informers
                # are never unwired in the reference either)
                # bounded: one entry per registry/CRD kind ever established
                self.kinds.append(kind)
            self.informers.informer(kind).add_handler(Handler(
                on_add=lambda obj, k=kind: self._observe(k, obj),
                on_update=lambda old, new, k=kind: self._observe(k, new),
                on_delete=lambda obj, k=kind: self._observe_delete(k, obj),
            ))

    # -- graph maintenance (graph_builder processGraphChanges) --------------
    def _observe(self, kind: str, obj) -> None:
        if kind == "CustomResourceDefinition":
            # a CRD may have just established a new kind: wire it in
            self.refresh_kinds()
        dep = (kind, obj.meta.key)
        new_idx = {
            _owner_index_key(ref, obj.meta.namespace)
            for ref in obj.meta.owner_references
        }
        with self._graph_mu:
            old_idx = self._owners_of.get(dep, set())
            for gone in old_idx - new_idx:
                members = self._dependents.get(gone)
                if members:
                    members.discard(dep)
                    if not members:
                        del self._dependents[gone]
            for added in new_idx - old_idx:
                self._dependents.setdefault(added, set()).add(dep)
            if new_idx:
                self._owners_of[dep] = new_idx
            else:
                self._owners_of.pop(dep, None)
        if new_idx:
            self.queue.add(f"dep|{kind}|{obj.meta.key}")
        if obj.meta.deletion_revision is not None and ORPHAN_FINALIZER in obj.meta.finalizers:
            self.queue.add(f"orphan|{kind}|{obj.meta.key}")

    def _observe_delete(self, kind: str, obj) -> None:
        dep = (kind, obj.meta.key)
        ns = "" if kind in api.CLUSTER_SCOPED_KINDS else obj.meta.namespace
        idx = (kind, ns, obj.meta.name, obj.meta.uid)
        with self._graph_mu:
            for owner_idx in self._owners_of.pop(dep, set()):
                members = self._dependents.get(owner_idx)
                if members:
                    members.discard(dep)
                    if not members:
                        del self._dependents[owner_idx]
            # this object may have been an owner: wake exactly its dependents
            waiters = list(self._dependents.get(idx, ()))
        for dkind, dkey in waiters:
            self.queue.add(f"dep|{dkind}|{dkey}")

    # -- liveness ------------------------------------------------------------
    def _owner_alive(self, namespace: str, ref: api.OwnerReference) -> bool:
        if ref.kind not in api.KINDS:
            return True  # unregistered kinds are never collected against
        ns = "" if ref.kind in api.CLUSTER_SCOPED_KINDS else namespace
        inf = self.informers.informer(ref.kind) if ref.kind in self.kinds else None
        if inf is not None:
            owner = inf.get(f"{ns}/{ref.name}" if ns else ref.name)
            if owner is not None and owner.meta.uid == ref.uid:
                # a deleting owner with the orphan finalizer will release
                # its dependents; treat as alive until the orphan pass runs
                return True
        # Informer caches race in threaded mode (a dependent's add can land
        # before its owner's add on a different watch thread).  Absence must
        # be confirmed against the LIVE API before deleting — the reference
        # GC does the same quarantine re-check.
        try:
            live = self.clientset.client_for(ref.kind).get(ref.name, ns)
            return live.meta.uid == ref.uid
        except NotFoundError:
            return False

    # -- reconcile (attemptToDeleteItem / orphanDependents) ------------------
    def sync(self, key: str) -> None:
        mode, kind, obj_key = key.split("|", 2)
        if mode == "orphan":
            self._sync_orphan(kind, obj_key)
            return
        obj = self.informers.informer(kind).get(obj_key)
        if obj is None or not obj.meta.owner_references:
            return
        dangling = [
            ref for ref in obj.meta.owner_references
            if not self._owner_alive(obj.meta.namespace, ref)
        ]
        if not dangling:
            return
        client = self.clientset.client_for(kind)
        if len(dangling) == len(obj.meta.owner_references):
            logger.info("gc: deleting %s %s (all owners gone)", kind, obj_key)
            try:
                client.delete(obj.meta.name, obj.meta.namespace)
            except NotFoundError:
                pass
            return
        # mixed: live owners keep the object; dangling refs are patched away
        gone_uids = {ref.uid for ref in dangling}

        def _strip(cur):
            cur.meta.owner_references = [
                r for r in cur.meta.owner_references if r.uid not in gone_uids
            ]
            return cur

        try:
            client.guaranteed_update(obj.meta.name, _strip, obj.meta.namespace)
        except NotFoundError:
            pass

    def _sync_orphan(self, kind: str, obj_key: str) -> None:
        """Strip this deleting owner's refs from every dependent, then drop
        the orphan finalizer so the tombstoned delete completes."""
        obj = self.informers.informer(kind).get(obj_key)
        if obj is None:
            return
        ns = "" if kind in api.CLUSTER_SCOPED_KINDS else obj.meta.namespace
        idx = (kind, ns, obj.meta.name, obj.meta.uid)
        with self._graph_mu:
            dependents = list(self._dependents.get(idx, ()))
        for dkind, dkey in dependents:
            dclient = self.clientset.client_for(dkind)
            dns, _, dname = dkey.rpartition("/")

            def _strip(cur, uid=obj.meta.uid):
                cur.meta.owner_references = [
                    r for r in cur.meta.owner_references if r.uid != uid
                ]
                return cur

            try:
                dclient.guaranteed_update(dname, _strip, dns)
            except NotFoundError:
                continue

        def _drop_finalizer(cur):
            cur.meta.finalizers = [
                f for f in cur.meta.finalizers if f != ORPHAN_FINALIZER
            ]
            return cur

        try:
            self.clientset.client_for(kind).guaranteed_update(
                obj.meta.name, _drop_finalizer, obj.meta.namespace
            )
        except (NotFoundError, ConflictError):
            pass
