"""Garbage collector: ownerReference graph + cascading deletion.

Capability of ``pkg/controller/garbagecollector`` (2,748 LoC;
``graph_builder.go:317``): maintain the cluster-wide owner graph from
watches over every kind, and delete dependents whose owner is gone
(background cascading deletion).  UID-checked: an owner that was deleted
and recreated under the same name does NOT keep old dependents alive."""

from __future__ import annotations

import logging

from ..api import types as api
from ..store.store import NotFoundError
from .base import Controller

logger = logging.getLogger("kubernetes_tpu.controllers.gc")

# kinds participating in ownership, in dependency order
OWNED_KINDS = ["Deployment", "ReplicaSet", "Pod"]


class GarbageCollector(Controller):
    name = "garbagecollector"

    def __init__(self, clientset, informers=None, **kw):
        super().__init__(clientset, informers, **kw)
        # live owner uids per kind, rebuilt from informer caches
        for kind in OWNED_KINDS:
            self.watch(kind, key_fn=lambda obj, k=kind: f"{k}|{obj.meta.key}")
            # an owner's deletion must wake its dependents
            self.informers.informer(kind)

    def _owner_alive(self, namespace: str, ref) -> bool:
        inf = self.informers.informer(ref.kind) if ref.kind in OWNED_KINDS else None
        if inf is None:
            return True  # unknown kinds are never collected against
        owner = inf.get(f"{namespace}/{ref.name}")
        if owner is not None and owner.meta.uid == ref.uid:
            return True
        # Informer caches race in threaded mode (a dependent's add can land
        # before its owner's add on a different watch thread).  Absence must
        # be confirmed against the LIVE API before deleting — the reference
        # GC does the same quarantine re-check.
        try:
            live = self.clientset.client_for(ref.kind).get(ref.name, namespace)
            return live.meta.uid == ref.uid
        except NotFoundError:
            return False

    def sync(self, key: str) -> None:
        kind, obj_key = key.split("|", 1)
        obj = self.informers.informer(kind).get(obj_key)
        if obj is None:
            # object deleted: its dependents may now be orphans — enqueue
            # everything that could have referenced it (cheap: dependents of
            # this kind's children kinds in the same namespace)
            idx = OWNED_KINDS.index(kind) if kind in OWNED_KINDS else -1
            if 0 <= idx < len(OWNED_KINDS) - 1:
                child_kind = OWNED_KINDS[idx + 1]
                for child in self.informers.informer(child_kind).list():
                    ref = child.meta.controller_ref()
                    if ref is not None and ref.kind == kind:
                        self.queue.add(f"{child_kind}|{child.meta.key}")
            return
        ref = obj.meta.controller_ref()
        if ref is None:
            return
        if not self._owner_alive(obj.meta.namespace, ref):
            logger.info("gc: deleting %s %s (owner %s/%s gone)", kind, obj_key, ref.kind, ref.name)
            try:
                self.clientset.client_for(kind).delete(obj.meta.name, obj.meta.namespace)
            except NotFoundError:
                pass
