"""Reconciling control loops (SURVEY.md L6)."""

from .base import Controller
from .certificates import CertificateController
from .cronjob import CronJobController
from .daemonset import DaemonSetController
from .deployment import DeploymentController, template_hash
from .disruption import DisruptionController
from .endpoint import EndpointController
from .garbagecollector import GarbageCollector
from .horizontal import HorizontalPodAutoscalerController
from .job import JobController
from .manager import ControllerManager, DEFAULT_CONTROLLERS
from .namespace import NamespaceController
from .node_lifecycle import NodeLifecycleController, RateLimiter
from .podgc import PodGCController
from .replicaset import (Expectations, ReplicaSetController,
                         ReplicationControllerController)
from .resourcequota import ResourceQuotaController
from .serviceaccounts import ServiceAccountController
from .statefulset import StatefulSetController
from .ttl import TTLController
