"""Reconciling control loops (SURVEY.md L6)."""

from .base import Controller
from .deployment import DeploymentController, template_hash
from .garbagecollector import GarbageCollector
from .manager import ControllerManager
from .node_lifecycle import NodeLifecycleController, RateLimiter
from .replicaset import Expectations, ReplicaSetController
