"""Volume lifecycle controllers: PV↔PVC binding + attach/detach.

Capability of ``pkg/controller/volume`` (5,517 LoC):

- ``PersistentVolumeController`` — the claim↔volume binder
  (``persistentvolume/pv_controller.go`` / ``pv_controller_base.go``):
  a phase machine driving PVCs Pending→Bound(→Lost) and PVs
  Available→Bound→Released(→deleted/Available), with best-match binding
  (smallest satisfying volume), pre-binding via ``claim.volume_name``,
  dynamic provisioning through StorageClass provisioners, and the
  Retain/Delete/Recycle reclaim policies.

- ``AttachDetachController`` — the desired-vs-actual attachment
  reconciler (``attachdetach/attach_detach_controller.go``): computes
  which bound PVs each node needs from the pods scheduled there and
  writes ``node.status.volumesAttached``; volumes no longer used by any
  pod on the node are detached.

Both are standard informer→workqueue→sync loops (SURVEY.md §2.5 / P3).
"""

from __future__ import annotations

from ..api import types as api
from ..api.cluster import PersistentVolume, PersistentVolumeClaim
from ..store.store import ConflictError, NotFoundError
from .base import Controller


def _modes_satisfied(want: list[str], have: list[str]) -> bool:
    return set(want) <= set(have)


class _VolumeTakenError(Exception):
    """Bind raced another claim to the same PV; the loser stays Pending."""


class PersistentVolumeController(Controller):
    """Reference ``pv_controller.go``: syncClaim/syncVolume phase machine."""

    name = "persistentvolume"

    def __init__(self, clientset, informers=None, **kw):
        super().__init__(clientset, informers, **kw)
        # claims drive binding; volume/class churn re-syncs affected claims
        self.watch("PersistentVolumeClaim")
        self.watch("PersistentVolume", key_fn=self._volume_key)
        self.watch("StorageClass", key_fn=self._class_key)

    def _requeue_pending_claims(self) -> None:
        for pvc in self.informer("PersistentVolumeClaim").list():
            if pvc.phase == "Pending":
                self.queue.add(pvc.meta.key)

    def _volume_key(self, pv: PersistentVolume):
        # a PV event re-syncs its bound claim if any, else all pending claims
        # get a chance to bind to it (cheap at control-plane scale)
        if pv.claim_ref:
            return pv.claim_ref
        self._requeue_pending_claims()
        return f"\x00volume/{pv.meta.name}"

    def _class_key(self, sc):
        # a class appearing/changing may unblock provisioning of any
        # pending claim naming it (or none, for the default class)
        self._requeue_pending_claims()
        return None

    # -- claim side --------------------------------------------------------
    def sync(self, key: str) -> None:
        if key.startswith("\x00volume/"):
            self._sync_volume(key.split("/", 1)[1])
            return
        namespace, name = key.split("/", 1)
        try:
            pvc = self.clientset.persistentvolumeclaims.get(name, namespace)
        except NotFoundError:
            # deleted claim: release any PV still pointing at it
            self._release_volumes_of(f"{namespace}/{name}")
            return
        if pvc.phase == "Bound":
            self._check_bound(pvc)
        else:
            self._bind_pending(pvc)

    def _bind_pending(self, pvc: PersistentVolumeClaim) -> None:
        pvs = self.clientset.persistentvolumes.list()[0]
        match = None
        if pvc.volume_name:
            # pre-bound claim (reference: claim.Spec.VolumeName set by user)
            match = next((pv for pv in pvs if pv.meta.name == pvc.volume_name), None)
            if match is None or (match.claim_ref and match.claim_ref != pvc.meta.key):
                return  # wait for the named volume
        else:
            # smallest satisfying Available volume of the same class
            candidates = [
                pv
                for pv in pvs
                if pv.phase == "Available"
                and not pv.claim_ref
                and pv.storage_class == pvc.storage_class
                and _modes_satisfied(pvc.access_modes, pv.access_modes)
                and pv.capacity.get("storage", api.Quantity(0)) >= pvc.request_storage
            ]
            if candidates:
                match = min(candidates, key=lambda pv: pv.capacity.get("storage", api.Quantity(0)))
        if match is None:
            match = self._provision(pvc)
        if match is None:
            return  # stays Pending; a future PV/class event re-queues
        self._bind(pvc, match)

    def _provision(self, pvc: PersistentVolumeClaim):
        """Dynamic provisioning (reference ``pv_controller.go
        provisionClaim``): a StorageClass with a provisioner mints a PV
        sized to the request.  A claim naming no class uses the default
        class (reference: the DefaultStorageClass admission plugin)."""
        classes = self.clientset.storageclasses.list()[0]
        if pvc.storage_class:
            sc = next((c for c in classes if c.meta.name == pvc.storage_class), None)
        else:
            sc = next((c for c in classes if c.is_default), None)
        if sc is None or not sc.provisioner:
            return None
        name = f"pvc-{pvc.meta.namespace}-{pvc.meta.name}"
        pv = PersistentVolume(
            meta=api.ObjectMeta(name=name, annotations={"pv.kubernetes.io/provisioned-by": sc.provisioner}),
            capacity={"storage": pvc.request_storage},
            access_modes=list(pvc.access_modes),
            storage_class=pvc.storage_class or sc.meta.name,
            reclaim_policy=sc.reclaim_policy,
            phase="Available",
        )
        try:
            return self.clientset.persistentvolumes.create(pv)
        except ConflictError:
            # name collision ("a-b"/"c" vs "a"/"b-c") or an idempotent
            # re-provision: reuse only a PV that is ours or unclaimed
            existing = self.clientset.persistentvolumes.get(name)
            if existing.claim_ref in ("", pvc.meta.key):
                return existing
            return None

    def _bind(self, pvc: PersistentVolumeClaim, pv: PersistentVolume) -> None:
        claim_key = pvc.meta.key

        def _set_pv(cur: PersistentVolume) -> PersistentVolume:
            if cur.claim_ref not in ("", claim_key):
                # lost the race to another claim (reference syncUnboundClaim
                # re-verifies claimRef before binding)
                raise _VolumeTakenError(cur.meta.name)
            cur.claim_ref = claim_key
            cur.phase = "Bound"
            return cur

        try:
            self.clientset.persistentvolumes.guaranteed_update(pv.meta.name, _set_pv)
        except _VolumeTakenError:
            return  # claim stays Pending; next PV event retries

        def _set_pvc(cur: PersistentVolumeClaim) -> PersistentVolumeClaim:
            cur.volume_name = pv.meta.name
            cur.phase = "Bound"
            return cur

        self.clientset.persistentvolumeclaims.guaranteed_update(
            pvc.meta.name, _set_pvc, pvc.meta.namespace
        )

    def _check_bound(self, pvc: PersistentVolumeClaim) -> None:
        """Bound claim whose PV vanished goes Lost (reference
        syncClaim's bound-claim verification)."""
        try:
            pv = self.clientset.persistentvolumes.get(pvc.volume_name)
        except NotFoundError:
            pv = None
        if pv is None or pv.claim_ref != pvc.meta.key:
            def _lost(cur: PersistentVolumeClaim) -> PersistentVolumeClaim:
                cur.phase = "Lost"
                return cur

            self.clientset.persistentvolumeclaims.guaranteed_update(
                pvc.meta.name, _lost, pvc.meta.namespace
            )

    # -- volume side -------------------------------------------------------
    def _release_volumes_of(self, claim_key: str) -> None:
        for pv in self.clientset.persistentvolumes.list()[0]:
            if pv.claim_ref == claim_key:
                self._sync_volume(pv.meta.name)

    def _sync_volume(self, name: str) -> None:
        try:
            pv = self.clientset.persistentvolumes.get(name)
        except NotFoundError:
            return
        if not pv.claim_ref:
            return
        try:
            ns, claim_name = pv.claim_ref.split("/", 1)
            pvc = self.clientset.persistentvolumeclaims.get(claim_name, ns)
        except (NotFoundError, ValueError):
            pvc = None
        if pvc is not None and pvc.volume_name in ("", pv.meta.name):
            return  # claim still around (or pre-bind in progress): nothing to do
        # claim gone: apply the reclaim policy (reference reclaimVolume)
        if pv.reclaim_policy == "Delete":
            try:
                self.clientset.persistentvolumes.delete(pv.meta.name)
            except NotFoundError:
                pass
            return
        def _reclaim(cur: PersistentVolume) -> PersistentVolume:
            if cur.reclaim_policy == "Recycle":
                cur.claim_ref = ""
                cur.phase = "Available"
            else:  # Retain
                cur.phase = "Released"
            return cur

        self.clientset.persistentvolumes.guaranteed_update(pv.meta.name, _reclaim)


class AttachDetachController(Controller):
    """Reference ``attachdetach``: desired attachments per node from the
    scheduled pods' bound claims; actual = node.status.volumesAttached."""

    name = "attachdetach"

    def __init__(self, clientset, informers=None, **kw):
        super().__init__(clientset, informers, **kw)
        self.watch("Node")
        self.watch("Pod", key_fn=self._pod_key)
        self.watch("PersistentVolumeClaim", key_fn=self._claim_key)

    def _pod_key(self, pod: api.Pod):
        return pod.spec.node_name or None  # only scheduled pods matter

    def _claim_key(self, pvc: PersistentVolumeClaim):
        # a claim binding/unbinding changes the desired set of every node
        # running a pod that references it
        for pod in self.informer("Pod").list():
            if not pod.spec.node_name:
                continue
            if pod.meta.namespace == pvc.meta.namespace and any(
                vol.pvc_name == pvc.meta.name for vol in pod.spec.volumes
            ):
                self.queue.add(pod.spec.node_name)
        return None

    def _desired_for(self, node_name: str) -> list[str]:
        pvcs = {c.meta.key: c for c in self.informer("PersistentVolumeClaim").list()}
        want: list[str] = []
        for pod in self.informer("Pod").list():
            if pod.spec.node_name != node_name or pod.status.phase in (api.SUCCEEDED, api.FAILED):
                continue
            for vol in pod.spec.volumes:
                if not vol.pvc_name:
                    continue
                pvc = pvcs.get(f"{pod.meta.namespace}/{vol.pvc_name}")
                if pvc is not None and pvc.phase == "Bound" and pvc.volume_name:
                    if pvc.volume_name not in want:
                        want.append(pvc.volume_name)
        return sorted(want)

    def sync(self, key: str) -> None:
        try:
            node = self.clientset.nodes.get(key)
        except NotFoundError:
            return
        desired = self._desired_for(key)
        # unmount-before-detach (the reference reconciler consults
        # node.status.volumesInUse): a volume the kubelet still has
        # mounted stays attached even when no pod wants it anymore
        in_use = set(node.status.volumes_in_use)
        keep = [v for v in node.status.volumes_attached
                if v in in_use and v not in desired]
        want = sorted(set(desired) | set(keep))
        if sorted(node.status.volumes_attached) == want:
            return

        def _set(cur: api.Node) -> api.Node:
            cur.status.volumes_attached = list(want)
            return cur

        self.clientset.nodes.guaranteed_update(key, _set)
