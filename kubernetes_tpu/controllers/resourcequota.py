"""ResourceQuota controller: full usage recalculation.

Capability of ``pkg/controller/resourcequota`` (632 LoC): periodically (and
on watched-object churn) recompute each quota's ``status.used`` from the
live objects in its namespace using the shared evaluators, healing any
drift from admission-time charge leaks (failed writes, out-of-band
deletes) — the reference's ``resource_quota_controller.go`` replenishment
loop."""

from __future__ import annotations

from ..admission import quota as quotalib
from ..api.cluster import ResourceQuota
from ..api.quantity import Quantity
from ..store.store import NotFoundError
from .base import Controller


class ResourceQuotaController(Controller):
    name = "resourcequota"

    def __init__(self, clientset, informers=None, **kw):
        super().__init__(clientset, informers, **kw)
        self.watch("ResourceQuota")
        from ..client.informer import Handler

        # churn on tracked kinds re-syncs the namespace's quotas
        # (the reference's replenishment controller watches the same set)
        for kind in ("Pod", *quotalib.COUNTED_KINDS):
            self.informers.informer(kind).add_handler(Handler(
                on_add=lambda obj: self._object_event(obj),
                on_update=lambda old, new: self._object_event(new),
                on_delete=lambda obj: self._object_event(obj),
            ))

    def _object_event(self, obj) -> None:
        for rq in self.informer("ResourceQuota").list():
            if rq.meta.namespace == obj.meta.namespace:
                self.queue.add(rq.meta.key)

    def sync(self, key: str) -> None:
        namespace, name = key.split("/", 1)
        try:
            rq = self.clientset.resourcequotas.get(name, namespace)
        except NotFoundError:
            return
        scopes = rq.scopes
        used: dict[str, Quantity] = {}
        for kind in ("Pod", *quotalib.COUNTED_KINDS):
            for obj in self.clientset.store.list(kind, namespace)[0]:
                if not quotalib.matches_scopes(scopes, kind, obj):
                    continue
                used = quotalib.add_usage(used, quotalib.usage_for(kind, obj))
        # only report resources the quota constrains (reference behavior)
        tracked = {k: used.get(k, Quantity(0)) for k in rq.hard}

        if tracked != rq.used:
            def _update(cur: ResourceQuota) -> ResourceQuota:
                cur.used = tracked
                return cur

            self.clientset.resourcequotas.guaranteed_update(name, _update, namespace)
