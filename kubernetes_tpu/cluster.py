"""Cluster lifecycle: init / join / up / down (the kubeadm +
local-up-cluster capability, ``cmd/kubeadm`` + ``hack/``).

    python -m kubernetes_tpu.cluster up   --nodes 10        # whole cluster
    python -m kubernetes_tpu.cluster init --port 6443       # control plane
    python -m kubernetes_tpu.cluster join --apiserver URL \
        --token <id>.<secret> --name node-7                 # one hollow node
    python -m kubernetes_tpu.cluster down

``init`` mirrors kubeadm's phases at this control plane's depth: start
the apiserver, create the system namespaces, mint a bootstrap token
Secret, publish the signed ``kube-public/cluster-info`` discovery
document, then start the scheduler and controller manager (leader
elected). ``join`` performs the token-verified discovery handshake
(fetch cluster-info WITHOUT credentials, verify its HMAC signature with
the shared token — the reference's JWS check) before starting a kubelet.
Process state lives in ``.kubernetes-tpu-cluster.json`` for ``down``."""

from __future__ import annotations

import argparse
import json
import os
import secrets as pysecrets
import subprocess
import sys
import time
import urllib.request

STATE_FILE = ".kubernetes-tpu-cluster.json"


def _spawn(mod: str, *args: str) -> int:
    env = dict(os.environ)
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = root + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.Popen(
        [sys.executable, "-m", mod, *args],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        start_new_session=True,
    )
    return proc.pid


def _wait_healthy(url: str, timeout: float = 15.0, ca_file: str = None) -> None:
    import ssl

    ctx = None
    if url.startswith("https://"):
        ctx = ssl.create_default_context(cafile=ca_file)
        ctx.check_hostname = False  # IP-addressed; chain still verified
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            with urllib.request.urlopen(f"{url}/healthz", timeout=1,
                                        context=ctx) as r:
                if r.status == 200:
                    return
        except Exception:
            time.sleep(0.2)
    raise SystemExit(f"apiserver at {url} did not become healthy")


def _clientset(url: str):
    from .client import Clientset
    from .client.remote import RemoteStore

    return Clientset(RemoteStore(url))


def _bootstrap_phase(cs, url: str, token_ttl: float,
                     ca_data: str = "") -> str:
    """kubeadm phase: system namespaces + bootstrap token + the signed
    cluster-info discovery document.  ``ca_data`` (PEM) rides in the
    payload so a TLS join can learn the cluster CA through the
    token-verified channel (the reference embeds the CA in the
    cluster-info kubeconfig the same way)."""
    from .api import Namespace, ObjectMeta
    from .api.cluster import Secret
    from .controllers.ipam import BootstrapSignerController
    from .store.store import AlreadyExistsError

    for ns in ("kube-system", "kube-public"):
        try:
            cs.namespaces.create(Namespace(meta=ObjectMeta(name=ns)))
        except AlreadyExistsError:
            pass
    token_id = pysecrets.token_hex(3)
    token_secret = pysecrets.token_hex(8)
    cs.secrets.create(Secret(
        meta=ObjectMeta(name=f"bootstrap-token-{token_id}", namespace="kube-system"),
        type="bootstrap.kubernetes.io/token",
        data={"token-id": token_id, "token-secret": token_secret,
              "expiration": str(time.time() + token_ttl),
              "usage-bootstrap-authentication": "true"},
    ))
    payload = json.dumps({"server": url,
                          "certificate-authority-data": ca_data})
    signer = BootstrapSignerController(cs, cluster_info_payload=payload)
    signer.informers.start_all_manual()
    signer.informers.pump_all()
    while signer.sync_once():
        pass
    return f"{token_id}.{token_secret}"


def cmd_init(args) -> dict:
    if getattr(args, "self_hosted", False):
        return cmd_init_selfhosted(args)
    pids = {}
    pids["apiserver"] = _spawn(
        "kubernetes_tpu.apiserver", "--host", "127.0.0.1", "--port", str(args.port)
    )
    # persist immediately: if health-wait fails, `down` can still reap it
    _save({"pids": dict(pids)})
    url = f"http://127.0.0.1:{args.port}"
    _wait_healthy(url)
    cs = _clientset(url)

    token = _bootstrap_phase(cs, url, args.token_ttl)

    pids["scheduler"] = _spawn(
        "kubernetes_tpu.scheduler", "--apiserver", url,
        "--backend", args.backend, "--leader-elect",
    )
    pids["controller-manager"] = _spawn(
        "kubernetes_tpu.controllers", "--apiserver", url, "--leader-elect",
    )
    if getattr(args, "dns_port", 0):
        # the kube-dns addon (cluster/addons/dns): part of standard
        # turn-up, serving the cluster zone over UDP
        pids["kube-dns"] = _spawn(
            "kubernetes_tpu.dns", "--apiserver", url,
            "--port", str(args.dns_port),
        )
    print(f"control plane up at {url}")
    print(f"join token: {token}")
    print(f"  python -m kubernetes_tpu.cluster join --apiserver {url} "
          f"--token {token} --name node-1")
    return {"url": url, "pids": pids, "token": token}


CONTROL_PLANE_NODE = "control-plane"


def _write_control_plane_manifests(cluster_dir: str, port: int,
                                   paths: dict, backend: str) -> str:
    """kubeadm ``phases/controlplane/manifests.go:45
    CreateInitStaticPodManifestFiles``: one static-pod manifest per
    control-plane component, consumed by the control-plane kubelet's
    file source and run as REAL processes."""
    import yaml

    manifests = os.path.join(cluster_dir, "manifests")
    os.makedirs(manifests, exist_ok=True)
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    inherited = os.environ.get("PYTHONPATH", "")
    env = {"PYTHONPATH": (root + os.pathsep + inherited) if inherited else root,
           "JAX_PLATFORMS": os.environ.get("JAX_PLATFORMS", "cpu")}

    def manifest(name: str, argv: list[str]) -> None:
        doc = {
            "kind": "Pod",
            "metadata": {"name": name, "namespace": "kube-system",
                         "labels": {"component": name, "tier": "control-plane"}},
            "spec": {
                "restartPolicy": "Always",
                "containers": [{
                    "name": name,
                    "image": f"ktpu/{name}",
                    "command": [sys.executable, "-m", *argv],
                    "env": env,
                }],
            },
        }
        with open(os.path.join(manifests, f"{name}.yaml"), "w") as f:
            yaml.safe_dump(doc, f)

    manifest("kube-apiserver", [
        "kubernetes_tpu.apiserver", "--host", "127.0.0.1",
        "--port", str(port),
        "--tls-cert-file", paths["apiserver"],
        "--tls-private-key-file", paths["apiserver_key"],
        "--client-ca-file", paths["ca"],
    ])
    manifest("kube-scheduler", [
        "kubernetes_tpu.scheduler",
        "--kubeconfig", paths["kubeconfig_kube-scheduler"],
        "--backend", backend, "--leader-elect",
    ])
    manifest("kube-controller-manager", [
        "kubernetes_tpu.controllers",
        "--kubeconfig", paths["kubeconfig_kube-controller-manager"],
        "--leader-elect",
    ])
    return manifests


def cmd_init_selfhosted(args) -> dict:
    """``init --self-hosted``: certs phase → kubeconfig phase →
    control-plane static-pod manifests → ONE real-container kubelet that
    bootstraps the control plane from its manifest dir (standalone until
    its own apiserver pod answers, then mirrored).  The control plane
    serves TLS with the generated CA; components authenticate with
    client certificates."""
    from .pki import create_cluster_pki, write_kubeconfig

    cluster_dir = os.path.abspath(args.cluster_dir)
    os.makedirs(cluster_dir, exist_ok=True)
    url = f"https://127.0.0.1:{args.port}"
    paths = create_cluster_pki(cluster_dir, node_name=CONTROL_PLANE_NODE)
    for component in ("admin", "kube-scheduler", "kube-controller-manager"):
        paths[f"kubeconfig_{component}"] = write_kubeconfig(
            cluster_dir, component, url, paths["ca"],
            client_cert=paths[component], client_key=paths[f"{component}_key"])
    paths["kubeconfig_kubelet"] = write_kubeconfig(
        cluster_dir, "kubelet", url, paths["ca"],
        client_cert=paths["kubelet"], client_key=paths["kubelet_key"])
    manifests = _write_control_plane_manifests(
        cluster_dir, args.port, paths, args.backend)

    pids = {"control-plane-kubelet": _spawn(
        "kubernetes_tpu.kubelet",
        "--kubeconfig", paths["kubeconfig_kubelet"],
        "--name", CONTROL_PLANE_NODE,
        "--real-containers", "--static-pod-dir", manifests,
    )}
    _save({"pids": dict(pids)})
    _wait_healthy(url, timeout=60.0, ca_file=paths["ca"])

    from .client import Clientset
    from .client.remote import RemoteStore

    with open(paths["ca"]) as f:
        ca_data = f.read()
    cs = Clientset(RemoteStore(url, ca_file=paths["ca"],
                               client_cert=paths["admin"],
                               client_key=paths["admin_key"]))
    token = _bootstrap_phase(cs, url, args.token_ttl, ca_data=ca_data)
    if getattr(args, "dns_port", 0):
        # the kube-dns addon rides the admin kubeconfig (TLS + client cert)
        pids["kube-dns"] = _spawn(
            "kubernetes_tpu.dns",
            "--kubeconfig", paths["kubeconfig_admin"],
            "--port", str(args.dns_port),
        )
        _save({"pids": dict(pids)})
    print(f"self-hosted control plane up at {url}")
    print(f"  pki + kubeconfigs: {cluster_dir}")
    print(f"join token: {token}")
    print(f"  python -m kubernetes_tpu.cluster join --apiserver {url} "
          f"--token {token} --name node-1")
    return {"url": url, "pids": pids, "token": token,
            "cluster_dir": cluster_dir}


def verify_cluster_info(url: str, token: str) -> str:
    """The join-side discovery handshake: fetch cluster-info anonymously,
    verify the signature for OUR token id with OUR token secret.

    Over https the FETCH is deliberately unverified (the joiner does not
    know the cluster CA yet); trust comes from the HMAC signature shared
    through the token — after which the payload's embedded CA becomes
    the pinned trust root (the reference's token-based TLS bootstrap,
    ``kubeadm join`` discovery)."""
    import ssl

    from .controllers.ipam import sign_cluster_info

    ctx = None
    if url.startswith("https://"):
        ctx = ssl._create_unverified_context()  # noqa: S323 — see docstring
    token_id, _, token_secret = token.partition(".")
    with urllib.request.urlopen(
        f"{url}/api/v1/namespaces/kube-public/configmaps/cluster-info",
        timeout=5, context=ctx
    ) as r:
        info = json.loads(r.read())
    data = info.get("data") or {}
    payload = data.get("kubeconfig", "")
    sig = data.get(f"jws-kubeconfig-{token_id}", "")
    want = sign_cluster_info(payload, token_secret)
    if not sig or sig != want:
        raise SystemExit("cluster-info signature verification FAILED "
                         "(wrong token or tampered discovery document)")
    return payload


def cmd_join(args) -> dict:
    payload = verify_cluster_info(args.apiserver, args.token)
    print(f"discovery verified: {payload!r}")
    ca_data = ""
    try:
        ca_data = (json.loads(payload) or {}).get(
            "certificate-authority-data", "")
    except (ValueError, AttributeError):
        pass  # pre-TLS payloads are plain text
    if ca_data:
        # TLS cluster: pin the token-verified CA and join with the
        # bootstrap token as the credential.  Credentials live NEXT TO
        # the cluster state file (not a leaked mkdtemp) so `down` reaps
        # them with everything else
        join_dir = os.path.abspath(f".kubernetes-tpu-join-{args.name}")
        os.makedirs(join_dir, exist_ok=True)
        ca_path = os.path.join(join_dir, "ca.crt")
        with open(ca_path, "w") as f:
            f.write(ca_data)
        from .pki import write_kubeconfig

        kubeconfig = write_kubeconfig(join_dir, f"kubelet-{args.name}",
                                      args.apiserver, ca_path,
                                      token=args.token)
        pid = _spawn(
            "kubernetes_tpu.kubelet", "--kubeconfig", kubeconfig,
            "--name", args.name, "--proxy",
        )
        print(f"node {args.name} joining (pid {pid})")
        return {"pids": {f"kubelet-{args.name}": pid},
                "join_dirs": [join_dir]}
    pid = _spawn(
        "kubernetes_tpu.kubelet", "--apiserver", args.apiserver,
        "--name", args.name, "--proxy",
    )
    print(f"node {args.name} joining (pid {pid})")
    return {"pids": {f"kubelet-{args.name}": pid}}


def _save(state: dict) -> None:
    old = {}
    if os.path.exists(STATE_FILE):
        with open(STATE_FILE) as f:
            old = json.load(f)
    old.setdefault("pids", {}).update(state.get("pids", {}))
    old.setdefault("join_dirs", [])
    old["join_dirs"] = sorted(
        set(old["join_dirs"]) | set(state.get("join_dirs", [])))
    for k, v in state.items():
        if k not in ("pids", "join_dirs"):
            old[k] = v
    with open(STATE_FILE, "w") as f:
        json.dump(old, f, indent=2)


def cmd_down(_args) -> None:
    import signal

    if not os.path.exists(STATE_FILE):
        print("no cluster state found")
        return
    with open(STATE_FILE) as f:
        state = json.load(f)
    for name, pid in state.get("pids", {}).items():
        try:
            os.kill(pid, signal.SIGTERM)
            print(f"stopped {name} (pid {pid})")
        except ProcessLookupError:
            pass
    import shutil

    for d in state.get("join_dirs", []):
        shutil.rmtree(d, ignore_errors=True)  # token-bearing credentials
    os.remove(STATE_FILE)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="kubernetes_tpu.cluster")
    sub = ap.add_subparsers(dest="cmd", required=True)
    p = sub.add_parser("init")
    p.add_argument("--port", type=int, default=6443)
    p.add_argument("--backend", choices=["tpu", "oracle"], default="tpu")
    p.add_argument("--token-ttl", type=float, default=24 * 3600)
    p.add_argument("--dns-port", type=int, default=10053,
                   help="0 disables the kube-dns addon")
    p.add_argument("--self-hosted", action="store_true",
                   help="certs + kubeconfig phases, control plane as "
                   "static pods under a real-container kubelet, TLS "
                   "throughout (the kubeadm shape)")
    p.add_argument("--cluster-dir", default=".kubernetes-tpu",
                   help="where --self-hosted writes pki/, kubeconfigs, "
                   "and manifests/")
    p = sub.add_parser("join")
    p.add_argument("--apiserver", required=True)
    p.add_argument("--token", required=True)
    p.add_argument("--name", required=True)
    p = sub.add_parser("up")
    p.add_argument("--port", type=int, default=6443)
    p.add_argument("--backend", choices=["tpu", "oracle"], default="oracle")
    p.add_argument("--nodes", type=int, default=5)
    p.add_argument("--token-ttl", type=float, default=24 * 3600)
    p.add_argument("--dns-port", type=int, default=10053,
                   help="0 disables the kube-dns addon")
    sub.add_parser("down")
    args = ap.parse_args(argv)

    if args.cmd == "init":
        _save(cmd_init(args))
        return 0
    if args.cmd == "join":
        _save(cmd_join(args))
        return 0
    if args.cmd == "up":
        state = cmd_init(args)
        url, token = state["url"], state["token"]
        for i in range(args.nodes):
            verify_cluster_info(url, token)
            state["pids"][f"kubelet-{i}"] = _spawn(
                "kubernetes_tpu.kubelet", "--apiserver", url,
                "--name", f"node-{i:03d}", "--proxy",
            )
        _save(state)
        print(f"{args.nodes} nodes joining")
        return 0
    if args.cmd == "down":
        cmd_down(args)
        return 0
    return 2


if __name__ == "__main__":
    sys.exit(main())
