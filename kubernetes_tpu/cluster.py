"""Cluster lifecycle: init / join / up / down (the kubeadm +
local-up-cluster capability, ``cmd/kubeadm`` + ``hack/``).

    python -m kubernetes_tpu.cluster up   --nodes 10        # whole cluster
    python -m kubernetes_tpu.cluster init --port 6443       # control plane
    python -m kubernetes_tpu.cluster join --apiserver URL \
        --token <id>.<secret> --name node-7                 # one hollow node
    python -m kubernetes_tpu.cluster down

``init`` mirrors kubeadm's phases at this control plane's depth: start
the apiserver, create the system namespaces, mint a bootstrap token
Secret, publish the signed ``kube-public/cluster-info`` discovery
document, then start the scheduler and controller manager (leader
elected). ``join`` performs the token-verified discovery handshake
(fetch cluster-info WITHOUT credentials, verify its HMAC signature with
the shared token — the reference's JWS check) before starting a kubelet.
Process state lives in ``.kubernetes-tpu-cluster.json`` for ``down``."""

from __future__ import annotations

import argparse
import json
import os
import secrets as pysecrets
import subprocess
import sys
import time
import urllib.request

STATE_FILE = ".kubernetes-tpu-cluster.json"


def _spawn(mod: str, *args: str) -> int:
    env = dict(os.environ)
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = root + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.Popen(
        [sys.executable, "-m", mod, *args],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        start_new_session=True,
    )
    return proc.pid


def _wait_healthy(url: str, timeout: float = 15.0) -> None:
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            with urllib.request.urlopen(f"{url}/healthz", timeout=1) as r:
                if r.status == 200:
                    return
        except Exception:
            time.sleep(0.2)
    raise SystemExit(f"apiserver at {url} did not become healthy")


def _clientset(url: str):
    from .client import Clientset
    from .client.remote import RemoteStore

    return Clientset(RemoteStore(url))


def cmd_init(args) -> dict:
    pids = {}
    pids["apiserver"] = _spawn(
        "kubernetes_tpu.apiserver", "--host", "127.0.0.1", "--port", str(args.port)
    )
    # persist immediately: if health-wait fails, `down` can still reap it
    _save({"pids": dict(pids)})
    url = f"http://127.0.0.1:{args.port}"
    _wait_healthy(url)
    cs = _clientset(url)

    # kubeadm phase: system namespaces + bootstrap token + cluster-info
    from .api import Namespace, ObjectMeta
    from .api.cluster import Secret
    from .controllers.ipam import BootstrapSignerController
    from .store.store import AlreadyExistsError

    for ns in ("kube-system", "kube-public"):
        try:
            cs.namespaces.create(Namespace(meta=ObjectMeta(name=ns)))
        except AlreadyExistsError:
            pass
    token_id = pysecrets.token_hex(3)
    token_secret = pysecrets.token_hex(8)
    cs.secrets.create(Secret(
        meta=ObjectMeta(name=f"bootstrap-token-{token_id}", namespace="kube-system"),
        type="bootstrap.kubernetes.io/token",
        data={"token-id": token_id, "token-secret": token_secret,
              "expiration": str(time.time() + args.token_ttl),
              "usage-bootstrap-authentication": "true"},
    ))
    signer = BootstrapSignerController(cs, cluster_info_payload=f"server: {url}")
    signer.informers.start_all_manual()
    signer.informers.pump_all()
    while signer.sync_once():
        pass

    pids["scheduler"] = _spawn(
        "kubernetes_tpu.scheduler", "--apiserver", url,
        "--backend", args.backend, "--leader-elect",
    )
    pids["controller-manager"] = _spawn(
        "kubernetes_tpu.controllers", "--apiserver", url, "--leader-elect",
    )
    if getattr(args, "dns_port", 0):
        # the kube-dns addon (cluster/addons/dns): part of standard
        # turn-up, serving the cluster zone over UDP
        pids["kube-dns"] = _spawn(
            "kubernetes_tpu.dns", "--apiserver", url,
            "--port", str(args.dns_port),
        )
    token = f"{token_id}.{token_secret}"
    print(f"control plane up at {url}")
    print(f"join token: {token}")
    print(f"  python -m kubernetes_tpu.cluster join --apiserver {url} "
          f"--token {token} --name node-1")
    return {"url": url, "pids": pids, "token": token}


def verify_cluster_info(url: str, token: str) -> str:
    """The join-side discovery handshake: fetch cluster-info anonymously,
    verify the signature for OUR token id with OUR token secret."""
    from .controllers.ipam import sign_cluster_info

    token_id, _, token_secret = token.partition(".")
    with urllib.request.urlopen(
        f"{url}/api/v1/namespaces/kube-public/configmaps/cluster-info", timeout=5
    ) as r:
        info = json.loads(r.read())
    data = info.get("data") or {}
    payload = data.get("kubeconfig", "")
    sig = data.get(f"jws-kubeconfig-{token_id}", "")
    want = sign_cluster_info(payload, token_secret)
    if not sig or sig != want:
        raise SystemExit("cluster-info signature verification FAILED "
                         "(wrong token or tampered discovery document)")
    return payload


def cmd_join(args) -> dict:
    payload = verify_cluster_info(args.apiserver, args.token)
    print(f"discovery verified: {payload!r}")
    pid = _spawn(
        "kubernetes_tpu.kubelet", "--apiserver", args.apiserver,
        "--name", args.name, "--proxy",
    )
    print(f"node {args.name} joining (pid {pid})")
    return {"pids": {f"kubelet-{args.name}": pid}}


def _save(state: dict) -> None:
    old = {}
    if os.path.exists(STATE_FILE):
        with open(STATE_FILE) as f:
            old = json.load(f)
    old.setdefault("pids", {}).update(state.get("pids", {}))
    for k, v in state.items():
        if k != "pids":
            old[k] = v
    with open(STATE_FILE, "w") as f:
        json.dump(old, f, indent=2)


def cmd_down(_args) -> None:
    import signal

    if not os.path.exists(STATE_FILE):
        print("no cluster state found")
        return
    with open(STATE_FILE) as f:
        state = json.load(f)
    for name, pid in state.get("pids", {}).items():
        try:
            os.kill(pid, signal.SIGTERM)
            print(f"stopped {name} (pid {pid})")
        except ProcessLookupError:
            pass
    os.remove(STATE_FILE)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="kubernetes_tpu.cluster")
    sub = ap.add_subparsers(dest="cmd", required=True)
    p = sub.add_parser("init")
    p.add_argument("--port", type=int, default=6443)
    p.add_argument("--backend", choices=["tpu", "oracle"], default="tpu")
    p.add_argument("--token-ttl", type=float, default=24 * 3600)
    p.add_argument("--dns-port", type=int, default=10053,
                   help="0 disables the kube-dns addon")
    p = sub.add_parser("join")
    p.add_argument("--apiserver", required=True)
    p.add_argument("--token", required=True)
    p.add_argument("--name", required=True)
    p = sub.add_parser("up")
    p.add_argument("--port", type=int, default=6443)
    p.add_argument("--backend", choices=["tpu", "oracle"], default="oracle")
    p.add_argument("--nodes", type=int, default=5)
    p.add_argument("--token-ttl", type=float, default=24 * 3600)
    p.add_argument("--dns-port", type=int, default=10053,
                   help="0 disables the kube-dns addon")
    sub.add_parser("down")
    args = ap.parse_args(argv)

    if args.cmd == "init":
        _save(cmd_init(args))
        return 0
    if args.cmd == "join":
        _save(cmd_join(args))
        return 0
    if args.cmd == "up":
        state = cmd_init(args)
        url, token = state["url"], state["token"]
        for i in range(args.nodes):
            verify_cluster_info(url, token)
            state["pids"][f"kubelet-{i}"] = _spawn(
                "kubernetes_tpu.kubelet", "--apiserver", url,
                "--name", f"node-{i:03d}", "--proxy",
            )
        _save(state)
        print(f"{args.nodes} nodes joining")
        return 0
    if args.cmd == "down":
        cmd_down(args)
        return 0
    return 2


if __name__ == "__main__":
    sys.exit(main())
