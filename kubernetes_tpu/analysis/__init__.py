"""ktpu-analyze: project-native static analysis.

Three AST/call-graph passes guard the two silent-failure classes this
codebase is most exposed to (ISSUE 1):

- ``trace_safety`` (TS1xx): host Python semantics leaking into traced
  JAX/Pallas code under ``ops/`` — Python branching on kernel-derived
  values, host escapes (``float()``, ``.item()``, ``np.`` calls) inside
  jitted bodies, and nondeterministic set iteration feeding tensor
  builders.
- ``parity`` (PC2xx): every predicate/priority registered in the host
  oracle (``scheduler/predicates.py`` / ``scheduler/priorities.py``)
  must either carry a ``# kernel: implements <Name>`` marker at its
  kernel implementation site or an explicit
  ``# kernel: host-fallback — <why>`` marker at its oracle definition,
  so oracle↔kernel drift fails loudly instead of surfacing as a parity
  mismatch at 5k-node scale.
- ``races`` (RL3xx): ``threading.Thread`` target call graphs over
  ``controllers/`` and ``kubelet/`` — instance attributes written from
  worker threads without holding the owning object's lock, and
  lock-acquisition-order cycles.

Run ``python -m kubernetes_tpu.analysis`` (exits nonzero on unbaselined
findings); suppressions live in ``analysis/baseline.json`` and each
requires a justification string.
"""

from .core import (  # noqa: F401
    Finding,
    Report,
    load_baseline,
    repo_root,
    run_analysis,
)
