"""Pass 2 — oracle↔kernel parity coverage (PC2xx).

The host oracle (``scheduler/predicates.py`` / ``scheduler/priorities.py``)
is the behavioral spec the TPU kernels must reproduce bit-for-bit.  The
drift mode that hurts is silent: a predicate or priority added to the
oracle with no matching kernel mask schedules correctly in unit tests and
diverges only as a parity mismatch at scale (the backend falls back to
all-oracle when the *configured* set is unsupported — but a new entry in
DEFAULT_PREDICATES silently widens what "supported" claims to mean).

The contract this pass enforces:

- every registered oracle entity (a ``DEFAULT_PREDICATES`` key, a
  ``make_*`` predicate factory, a priority class carrying a ``name``
  attribute) must either
  (a) appear in a ``# kernel: implements <Name>[, <Name>…]`` marker in a
  kernel file (``ops/batch_kernel.py``, ``ops/pallas_kernel.py``,
  ``ops/backend.py``, ``models/snapshot.py`` — the mask may live in the
  tensorizer), or
  (b) carry an explicit ``# kernel: host-fallback — <why>`` marker inside
  its oracle definition block.

Findings:

- PC201 unmapped predicate (neither implemented nor marked host-fallback)
- PC202 unmapped priority
- PC203 ``implements`` marker names an unknown oracle entity (a rename or
  removal on the oracle side left a stale kernel claim — exactly the
  drift this pass exists to catch, in the other direction)
- PC204 entity both kernel-implemented and marked host-fallback (stale
  fallback marker: the kernel caught up, the oracle annotation didn't)
- PC205 host-fallback marker with no justification text
- PC206 ``implements`` marker outside the kernel call graph (module-level
  comment, or inside a private function no public kernel entry point
  reaches) — the marker is IGNORED: a claim next to deleted or orphaned
  code must not keep counting as coverage (ROADMAP "Parity markers are
  comment-level").  Such a marker's entity reverts to PC201/PC202 unless
  mapped elsewhere.

Reachability: the units are module-level functions and class methods of
the kernel files; roots are the public ones (no leading underscore —
the kernel API surface); edges follow any referenced name, bare or
attribute (``self._kernel_weights()``, ``tensorizer.build_static``,
callbacks passed by reference), resolved against unit names across the
whole kernel file set.  Nested functions belong to their enclosing
unit's span, so markers inside closures of reachable functions count.
"""

from __future__ import annotations

import ast
import re
from typing import Optional

from .core import Finding, iter_py_files

DEFAULT_ORACLE_PATHS = [
    "kubernetes_tpu/scheduler/predicates.py",
    "kubernetes_tpu/scheduler/priorities.py",
]
DEFAULT_KERNEL_PATHS = [
    "kubernetes_tpu/ops/batch_kernel.py",
    "kubernetes_tpu/ops/pallas_kernel.py",
    "kubernetes_tpu/ops/backend.py",
    "kubernetes_tpu/models/snapshot.py",
]

_IMPLEMENTS_RE = re.compile(r"#\s*kernel:\s*implements\s+(?P<names>[A-Za-z0-9_,\s]+)")
_FALLBACK_RE = re.compile(r"#\s*kernel:\s*host-fallback\s*(?:[-—–:]+\s*(?P<reason>.*))?$")


class OracleEntity:
    def __init__(self, name: str, kind: str, path: str, line: int, end_line: int):
        self.name = name
        self.kind = kind  # "predicate" | "priority"
        self.path = path
        self.line = line
        self.end_line = end_line
        self.fallback_line: Optional[int] = None
        self.fallback_reason: Optional[str] = None


def _collect_oracle_entities(abs_path: str, rel: str) -> list[OracleEntity]:
    with open(abs_path, "r", encoding="utf-8") as f:
        src = f.read()
    tree = ast.parse(src, filename=rel)
    entities: list[OracleEntity] = []
    for node in tree.body:
        # registry dicts: DEFAULT_PREDICATES = {"Name": fn, …}
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) else [node.target]
            value = node.value
            if (
                isinstance(value, ast.Dict)
                and any(
                    isinstance(t, ast.Name) and "PREDICATES" in t.id for t in targets
                )
            ):
                for key in value.keys:
                    if isinstance(key, ast.Constant) and isinstance(key.value, str):
                        entities.append(
                            OracleEntity(key.value, "predicate", rel, key.lineno, key.lineno)
                        )
        elif isinstance(node, ast.FunctionDef) and node.name.startswith("make_"):
            entities.append(
                OracleEntity(
                    node.name, "predicate", rel, node.lineno,
                    node.end_lineno or node.lineno,
                )
            )
        elif isinstance(node, ast.ClassDef):
            for item in node.body:
                if (
                    isinstance(item, ast.Assign)
                    and any(isinstance(t, ast.Name) and t.id == "name" for t in item.targets)
                    and isinstance(item.value, ast.Constant)
                    and isinstance(item.value.value, str)
                ):
                    entities.append(
                        OracleEntity(
                            node.name, "priority", rel, node.lineno,
                            node.end_lineno or node.lineno,
                        )
                    )
                    break
    _attach_fallback_markers(src, entities)
    return entities


def _attach_fallback_markers(src: str, entities: list[OracleEntity]) -> None:
    for lineno, line in enumerate(src.splitlines(), start=1):
        m = _FALLBACK_RE.search(line)
        if not m:
            continue
        reason = (m.group("reason") or "").strip()
        # attach to the innermost (smallest) enclosing entity block
        best: Optional[OracleEntity] = None
        for e in entities:
            if e.line <= lineno <= e.end_line:
                if best is None or (e.end_line - e.line) < (best.end_line - best.line):
                    best = e
        if best is not None:
            best.fallback_line = lineno
            best.fallback_reason = reason


class _KernelUnit:
    """One call-graph node: a module-level function or a class method of
    a kernel file.  Nested defs are folded into the enclosing unit (their
    lines fall inside its span; their references count as its calls)."""

    def __init__(self, name: str, path: str, line: int, end_line: int,
                 refs: set, owner_class: Optional[str] = None):
        self.name = name
        self.path = path
        self.line = line
        self.end_line = end_line
        self.refs = refs  # every bare/attribute name the body references
        self.owner_class = owner_class  # None for module-level functions


def _unit_refs(node: ast.AST) -> set:
    refs: set = set()
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name):
            refs.add(sub.id)
        elif isinstance(sub, ast.Attribute):
            refs.add(sub.attr)
    return refs


def _collect_kernel_units(abs_path: str, rel: str) -> list[_KernelUnit]:
    with open(abs_path, "r", encoding="utf-8") as f:
        src = f.read()
    tree = ast.parse(src, filename=rel)
    units: list[_KernelUnit] = []

    def add(node: ast.AST, owner: Optional[str] = None) -> None:
        units.append(_KernelUnit(
            node.name, rel, node.lineno, node.end_lineno or node.lineno,
            _unit_refs(node), owner_class=owner))

    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            add(node)
        elif isinstance(node, ast.ClassDef):
            for item in node.body:
                if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    add(item, owner=node.name)
    return units


def _reachable_spans(units: list[_KernelUnit]) -> dict[str, list[tuple[int, int]]]:
    """BFS from the public units over name-reference edges; returns the
    reachable line spans per file.  A reference to a CLASS name reaches
    that class's dunder methods (instantiation runs ``__init__``; the
    public methods are roots in their own right) — a marker inside the
    constructor of an instantiated kernel class must not be flagged."""
    by_name: dict[str, list[_KernelUnit]] = {}
    dunders_by_class: dict[str, list[_KernelUnit]] = {}
    for u in units:
        by_name.setdefault(u.name, []).append(u)
        if (u.owner_class is not None
                and u.name.startswith("__") and u.name.endswith("__")):
            dunders_by_class.setdefault(u.owner_class, []).append(u)
    work = [u for u in units if not u.name.startswith("_")]
    seen = set(id(u) for u in work)
    while work:
        u = work.pop()
        for ref in u.refs:
            for target in by_name.get(ref, ()):
                if id(target) not in seen:
                    seen.add(id(target))
                    work.append(target)
            for target in dunders_by_class.get(ref, ()):
                if id(target) not in seen:
                    seen.add(id(target))
                    work.append(target)
    spans: dict[str, list[tuple[int, int]]] = {}
    for u in units:
        if id(u) in seen:
            spans.setdefault(u.path, []).append((u.line, u.end_line))
    return spans


def _collect_implements(
    abs_path: str, rel: str, spans: Optional[list[tuple[int, int]]]
) -> tuple[list[tuple[str, str, int]], list[tuple[str, str, int]]]:
    """(counted, ignored): implements-marker mentions inside vs outside
    the reachable kernel spans of this file."""
    counted: list[tuple[str, str, int]] = []
    ignored: list[tuple[str, str, int]] = []
    with open(abs_path, "r", encoding="utf-8") as f:
        for lineno, line in enumerate(f, start=1):
            m = _IMPLEMENTS_RE.search(line)
            if not m:
                continue
            in_graph = spans is not None and any(
                lo <= lineno <= hi for lo, hi in spans)
            for name in m.group("names").split(","):
                name = name.strip()
                if name:
                    (counted if in_graph else ignored).append((name, rel, lineno))
    return counted, ignored


def run(
    root: str,
    oracle_paths: Optional[list[str]] = None,
    kernel_paths: Optional[list[str]] = None,
) -> list[Finding]:
    findings: list[Finding] = []
    entities: list[OracleEntity] = []
    for abs_path, rel in iter_py_files(root, oracle_paths or DEFAULT_ORACLE_PATHS):
        try:
            entities.extend(_collect_oracle_entities(abs_path, rel))
        except SyntaxError as e:
            findings.append(
                Finding("PC200", rel, e.lineno or 1, "syntax", f"unparseable oracle file: {e.msg}")
            )
    kernel_files = list(iter_py_files(root, kernel_paths or DEFAULT_KERNEL_PATHS))
    units: list[_KernelUnit] = []
    unparseable: set[str] = set()
    for abs_path, rel in kernel_files:
        try:
            units.extend(_collect_kernel_units(abs_path, rel))
        except SyntaxError as e:
            findings.append(
                Finding("PC200", rel, e.lineno or 1, "syntax",
                        f"unparseable kernel file: {e.msg}")
            )
            unparseable.add(rel)
    spans_by_file = _reachable_spans(units)
    implements: list[tuple[str, str, int]] = []
    for abs_path, rel in kernel_files:
        # an unparseable file has no call graph — count its markers as
        # before rather than mass-reporting PC206 on top of PC200
        counted, ignored = _collect_implements(
            abs_path, rel, spans_by_file.get(rel, []))
        if rel in unparseable:
            implements.extend(counted)
            implements.extend(ignored)
            continue
        implements.extend(counted)
        for name, _rel, lineno in ignored:
            findings.append(
                Finding(
                    code="PC206",
                    path=rel,
                    line=lineno,
                    symbol=f"marker.{name}",
                    message=(
                        f"implements marker for {name!r} sits outside every "
                        f"function the kernel call graph reaches (module-level "
                        f"comment or orphaned private code) — it does NOT "
                        f"count as kernel coverage; move it into the "
                        f"implementing function or delete it"
                    ),
                )
            )

    by_name: dict[str, OracleEntity] = {}
    for e in entities:
        # a name registered twice (dict entry + factory) keeps the first
        by_name.setdefault(e.name, e)
    implemented: dict[str, tuple[str, int]] = {}
    for name, rel, lineno in implements:
        implemented.setdefault(name, (rel, lineno))
        if name not in by_name:
            findings.append(
                Finding(
                    code="PC203",
                    path=rel,
                    line=lineno,
                    symbol=f"implements.{name}",
                    message=(
                        f"kernel claims to implement {name!r} but no such "
                        f"predicate/priority is registered in the oracle — "
                        f"renamed or removed without updating the marker?"
                    ),
                )
            )

    for e in by_name.values():
        is_impl = e.name in implemented
        has_fb = e.fallback_line is not None
        if is_impl and has_fb:
            findings.append(
                Finding(
                    code="PC204",
                    path=e.path,
                    line=e.fallback_line,
                    symbol=f"fallback.{e.name}",
                    message=(
                        f"{e.name} is marked host-fallback but a kernel implements "
                        f"marker exists at {implemented[e.name][0]}:"
                        f"{implemented[e.name][1]} — remove the stale marker"
                    ),
                )
            )
        elif has_fb and not (e.fallback_reason or "").strip():
            findings.append(
                Finding(
                    code="PC205",
                    path=e.path,
                    line=e.fallback_line,
                    symbol=f"fallback.{e.name}",
                    message=(
                        f"host-fallback marker on {e.name} has no justification — "
                        f"write why the kernel path doesn't cover it "
                        f"(`# kernel: host-fallback — <why>`)"
                    ),
                )
            )
        elif not is_impl and not has_fb:
            code = "PC201" if e.kind == "predicate" else "PC202"
            findings.append(
                Finding(
                    code=code,
                    path=e.path,
                    line=e.line,
                    symbol=f"unmapped.{e.name}",
                    message=(
                        f"registered {e.kind} {e.name!r} has no kernel implementation "
                        f"marker and no `# kernel: host-fallback` annotation — the "
                        f"batch path will silently diverge from the oracle"
                    ),
                )
            )
    return findings
